"""Pallas flash attention — the hot-op kernel for transformer training.

Why a hand kernel: attention is the one op where XLA's automatic fusion
leaves MXU/HBM performance on the table — materializing the [T, T] score
matrix costs O(T^2) HBM traffic. This kernel streams K/V blocks through
VMEM with an online-softmax accumulator (running max + denominator in
VMEM scratch), so scores never leave the chip: the flash-attention
formulation mapped onto the TPU memory hierarchy per
/opt/skills/guides/pallas_guide.md (grid iterates the K dimension
innermost; scratch carries the accumulator across grid steps).

Backward: recompute-based custom_vjp (the reference-attention vjp), the
standard memory/compute trade for flash kernels — no O(T^2) residuals.

On CPU (tests, virtual meshes) the kernel runs in interpreter mode.

STATUS (measured 2026-07-31, v5e, BENCH_FLASH_SWEEP.jsonl): 0.96-1.06x
vs XLA attention at seq 1024/2048/4096 — XLA's own attention fusion has
closed the gap on this hardware/JAX version, so the transformer uses the
kernel only when MXNET_FLASH_ATTENTION=1 (opt-in) and falls back to the
pure-XLA reference otherwise; the bench keeps measuring both so a future
JAX/Pallas upgrade that re-opens the gap is caught.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp


def _kernel(q_ref, k_ref, v_ref, o_ref, m_ref, l_ref, m_scr, l_scr,
            acc_scr, *, scale, causal, block_q, block_k, nk):
    from jax.experimental import pallas as pl

    qi = pl.program_id(1)
    ki = pl.program_id(2)

    @pl.when(ki == 0)
    def _init():
        m_scr[...] = jnp.full_like(m_scr, -jnp.inf)
        l_scr[...] = jnp.zeros_like(l_scr)
        acc_scr[...] = jnp.zeros_like(acc_scr)

    # causal: K/V blocks strictly above the block diagonal contribute
    # nothing — skip their MXU work entirely (~2x for long sequences)
    live = (ki * block_k <= qi * block_q + block_q - 1) if causal else True

    @pl.when(live)
    def _accumulate():
        q = q_ref[0].astype(jnp.float32)              # [bq, D]
        k = k_ref[0].astype(jnp.float32)              # [bk, D]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        if causal:
            tq = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            tk = ki * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(tk <= tq, s, -jnp.inf)

        m_prev = m_scr[...]                            # [bq, 1]
        l_prev = l_scr[...]
        m_cur = jnp.max(s, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        m_safe = jnp.where(jnp.isfinite(m_new), m_new, 0.0)
        p = jnp.exp(s - m_safe)
        p = jnp.where(jnp.isfinite(s), p, 0.0)         # fully-masked guard
        alpha = jnp.where(jnp.isfinite(m_prev), jnp.exp(m_prev - m_safe),
                          0.0)
        l_new = alpha * l_prev + jnp.sum(p, axis=-1, keepdims=True)
        v = v_ref[0].astype(jnp.float32)               # [bk, D]
        acc = alpha * acc_scr[...] + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_scr[...] = m_new
        l_scr[...] = l_new
        acc_scr[...] = acc

    @pl.when(ki == nk - 1)
    def _emit():
        o_ref[0] = (acc_scr[...] / jnp.maximum(l_scr[...], 1e-20)) \
            .astype(o_ref.dtype)
        m_ref[0] = m_scr[...]
        l_ref[0] = l_scr[...]


def _flash_fwd(q, k, v, scale, causal, block_q, block_k, interpret):
    """q/k/v: [BH, T, D] -> (out [BH, T, D], m [BH, T, 1], l [BH, T, 1]).
    The softmax stats feed the blockwise backward."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    from .pallas_fused import _cost

    BH, T, D = q.shape
    Tk = k.shape[1]
    nq = T // block_q
    nk = Tk // block_k
    kern = functools.partial(_kernel, scale=scale, causal=causal,
                             block_q=block_q, block_k=block_k, nk=nk)
    itemsize = q.dtype.itemsize
    # declared cost (house invariant: every pallas_call under ops/ says
    # what the TPU cost model should count for the opaque custom call):
    # 2 MACs/element for each of the QK^T and PV matmuls; bytes = q/out
    # streamed once per (b, i) row, K/V blocks re-walked once per query
    # row (the j grid), plus the f32 softmax stats
    return pl.pallas_call(
        kern,
        out_shape=[jax.ShapeDtypeStruct((BH, T, D), q.dtype),
                   jax.ShapeDtypeStruct((BH, T, 1), jnp.float32),
                   jax.ShapeDtypeStruct((BH, T, 1), jnp.float32)],
        grid=(BH, nq, nk),
        in_specs=[
            pl.BlockSpec((1, block_q, D), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_k, D), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, block_k, D), lambda b, i, j: (b, j, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, D), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_q, 1), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_q, 1), lambda b, i, j: (b, i, 0)),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, D), jnp.float32),
        ],
        interpret=interpret,
        **_cost(4 * BH * T * Tk * D,
                2 * BH * T * D * itemsize
                + 2 * BH * nq * Tk * D * itemsize
                + 2 * BH * T * 4,
                transcendentals=BH * T * Tk),
    )(q, k, v)


def _flash_bwd_blockwise(q, k, v, o, m, l, g, scale, causal, bq, bk):
    """Flash backward in pure lax, blockwise: recompute each [bq, bk] score
    tile from the saved softmax stats, so no O(T^2) matrix is ever live —
    the long-context memory property holds through the backward too.

    Standard flash-attention backward: with delta_i = sum(dO_i * O_i),
    ds = p * (dO V^T - delta) * scale; dq += ds K; dk += ds^T Q;
    dv += p^T dO.
    """
    from jax import lax

    BH, T, D = q.shape
    Tk = k.shape[1]
    nq, nk = T // bq, Tk // bk
    f32 = jnp.float32
    delta = jnp.sum(g.astype(f32) * o.astype(f32), axis=-1)      # [BH, T]
    qb = q.reshape(BH, nq, bq, D)
    gb = g.reshape(BH, nq, bq, D)
    mb = m.reshape(BH, nq, bq)
    lb = l.reshape(BH, nq, bq)
    db = delta.reshape(BH, nq, bq)
    kb = k.reshape(BH, nk, bk, D)
    vb = v.reshape(BH, nk, bk, D)

    def outer(carry, qi):
        dk_acc, dv_acc = carry
        qq = qb[:, qi].astype(f32)
        gg = gb[:, qi].astype(f32)
        mm = mb[:, qi]
        m_safe = jnp.where(jnp.isfinite(mm), mm, 0.0)[..., None]
        ll = jnp.maximum(lb[:, qi], 1e-20)[..., None]
        dd = db[:, qi][..., None]

        def inner(carry, ki):
            def live_block(carry):
                dq_blk, dk_acc, dv_acc = carry
                kk = kb[:, ki].astype(f32)
                vv = vb[:, ki].astype(f32)
                s = jnp.einsum("bqd,bkd->bqk", qq, kk,
                               preferred_element_type=f32) * scale
                if causal:
                    tq = qi * bq + jnp.arange(bq)[:, None]
                    tk_ = ki * bk + jnp.arange(bk)[None, :]
                    s = jnp.where((tk_ <= tq)[None], s, -jnp.inf)
                p = jnp.where(jnp.isfinite(s), jnp.exp(s - m_safe) / ll,
                              0.0)
                dv_acc = dv_acc.at[:, ki].add(
                    jnp.einsum("bqk,bqd->bkd", p, gg,
                               preferred_element_type=f32))
                dp = jnp.einsum("bqd,bkd->bqk", gg, vv,
                                preferred_element_type=f32)
                ds = p * (dp - dd) * scale
                dq_blk = dq_blk + jnp.einsum("bqk,bkd->bqd", ds, kk,
                                             preferred_element_type=f32)
                dk_acc = dk_acc.at[:, ki].add(
                    jnp.einsum("bqk,bqd->bkd", ds, qq,
                               preferred_element_type=f32))
                return dq_blk, dk_acc, dv_acc

            if causal:
                # skip fully-masked above-diagonal tiles, mirroring the
                # forward's `live` predicate (~2x fewer backward FLOPs)
                live = ki * bk <= qi * bq + bq - 1
                carry = lax.cond(live, live_block, lambda c: c, carry)
            else:
                carry = live_block(carry)
            return carry, None

        (dq_blk, dk_acc, dv_acc), _ = lax.scan(
            inner, (jnp.zeros((BH, bq, D), f32), dk_acc, dv_acc),
            jnp.arange(nk))
        return (dk_acc, dv_acc), dq_blk

    (dk_acc, dv_acc), dq_blocks = lax.scan(
        outer, (jnp.zeros((BH, nk, bk, D), f32),
                jnp.zeros((BH, nk, bk, D), f32)), jnp.arange(nq))
    dq = dq_blocks.transpose(1, 0, 2, 3).reshape(BH, T, D).astype(q.dtype)
    dk = dk_acc.reshape(BH, Tk, D).astype(k.dtype)
    dv = dv_acc.reshape(BH, Tk, D).astype(v.dtype)
    return dq, dk, dv


def _reference(q, k, v, scale, causal):
    """3-D wrapper over the one dense attention reference
    (parallel.ring_attention.attention_reference) — a single source of
    truth for masking/upcast/scale semantics."""
    from ..parallel.ring_attention import attention_reference
    return attention_reference(q[:, None], k[:, None], v[:, None],
                               causal=causal, scale=scale)[:, 0]


@functools.lru_cache(maxsize=None)
def _make_flash(scale, causal, block_q, block_k, interpret):
    @jax.custom_vjp
    def fa(q, k, v):
        out, _m, _l = _flash_fwd(q, k, v, scale, causal, block_q, block_k,
                                 interpret)
        return out

    def fwd(q, k, v):
        out, m, l = _flash_fwd(q, k, v, scale, causal, block_q, block_k,
                               interpret)
        return out, (q, k, v, out, m, l)

    def bwd(res, g):
        q, k, v, o, m, l = res
        return _flash_bwd_blockwise(q, k, v, o, m, l, g, scale, causal,
                                    block_q, block_k)

    fa.defvjp(fwd, bwd)
    return fa


def default_interpret():
    """Interpreter mode off only on real TPU backends."""
    try:
        return jax.default_backend() != "tpu"
    except Exception:
        return True


def flash_attention(q, k, v, causal=False, scale=None, block_q=None,
                    block_k=None, interpret=None):
    """Flash attention over [B, H, T, D] (or [BH, T, D]) q/k/v.

    Falls back to the pure-XLA reference when T doesn't tile into the
    block sizes (shape-polymorphic callers keep working). Block sizes
    default to 128x128 (the MXU/VMEM sweet spot on v5e) and are
    overridable per-run with MXNET_FLASH_BLOCK_Q/MXNET_FLASH_BLOCK_K for
    on-hardware A/B without code edits.
    """
    import os
    if block_q is None:
        block_q = int(os.environ.get("MXNET_FLASH_BLOCK_Q", "128"))
    if block_k is None:
        block_k = int(os.environ.get("MXNET_FLASH_BLOCK_K", "128"))
    squeeze = q.ndim == 4
    if squeeze:
        B, H, T, D = q.shape
        q3 = q.reshape(B * H, T, D)
        k3 = k.reshape(B * H, k.shape[2], D)
        v3 = v.reshape(B * H, v.shape[2], D)
    else:
        q3, k3, v3 = q, k, v
    scale = (1.0 / (q.shape[-1] ** 0.5)) if scale is None else float(scale)
    T, Tk = q3.shape[1], k3.shape[1]
    D = q3.shape[2]
    bq = min(block_q, T)
    bk = min(block_k, Tk)
    if interpret is None:
        interpret = default_interpret()
    use_kernel = not (T % bq or Tk % bk or (causal and bq != bk))
    if use_kernel and not interpret and \
            (D % 128 != 0 or bq % 8 != 0 or bk % 8 != 0):
        # conservative on real hardware: blocks off the (8,128) VMEM tiling
        # grid (head dim or sublane-unaligned block sizes from short
        # sequences) go through XLA (which pads) instead of the kernel
        use_kernel = False
    if not use_kernel:
        out3 = _reference(q3, k3, v3, scale, causal)
    else:
        out3 = _make_flash(scale, causal, bq, bk, bool(interpret))(q3, k3,
                                                                   v3)
    if squeeze:
        return out3.reshape(q.shape)
    return out3
