"""Tensor ops: elementwise, broadcast, reduce, dot, indexing, matrix
manipulation, ordering, init.

Parity: reference `src/operator/tensor/` (~35k LoC of C++/CUDA across
elemwise_*, broadcast_reduce, dot, indexing_op, init_op, matrix_op,
ordering_op, la_op). TPU-native redesign: every op is a pure jax.numpy/lax
expression — XLA does the tiling/fusion the reference hand-wrote kernels for;
gradients come from jax.vjp instead of registered FGradient entries.
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from .registry import register

# ---------------------------------------------------------------------------
# elementwise binary (parity: src/operator/tensor/elemwise_binary_op_basic.cc)
# ---------------------------------------------------------------------------


@register("elemwise_add", aliases=("_plus", "_add"))
def elemwise_add(lhs, rhs):
    return jnp.add(lhs, rhs)


@register("elemwise_sub", aliases=("_minus", "_sub"))
def elemwise_sub(lhs, rhs):
    return jnp.subtract(lhs, rhs)


@register("elemwise_mul", aliases=("_mul",))
def elemwise_mul(lhs, rhs):
    return jnp.multiply(lhs, rhs)


@register("elemwise_div", aliases=("_div",))
def elemwise_div(lhs, rhs):
    return jnp.divide(lhs, rhs)


@register("_mod")
def _mod(lhs, rhs):
    return jnp.mod(lhs, rhs)


@register("_power", aliases=("pow",))
def _power(lhs, rhs):
    return jnp.power(lhs, rhs)


@register("_maximum")
def _maximum(lhs, rhs):
    return jnp.maximum(lhs, rhs)


@register("_minimum")
def _minimum(lhs, rhs):
    return jnp.minimum(lhs, rhs)


@register("_hypot")
def _hypot(lhs, rhs):
    return jnp.hypot(lhs, rhs)


# comparison ops (non-differentiable; parity: elemwise_binary_op_logic.cc)
for _name, _fn in [
    ("_equal", jnp.equal), ("_not_equal", jnp.not_equal),
    ("_greater", jnp.greater), ("_greater_equal", jnp.greater_equal),
    ("_lesser", jnp.less), ("_lesser_equal", jnp.less_equal),
    ("_logical_and", jnp.logical_and), ("_logical_or", jnp.logical_or),
    ("_logical_xor", jnp.logical_xor),
]:
    def _mk(fn):
        def cmp_op(lhs, rhs):
            return fn(lhs, rhs).astype(jnp.result_type(lhs))
        return cmp_op
    register(_name, differentiable=False)(_mk(_fn))


# ---------------------------------------------------------------------------
# scalar variants (parity: elemwise_binary_scalar_op_*.cc — mxnet keeps
# tensor∘scalar as separate ops so the scalar stays a static attribute)
# ---------------------------------------------------------------------------


@register("_plus_scalar")
def _plus_scalar(data, scalar=0.0):
    return data + jnp.asarray(scalar, dtype=data.dtype)


@register("_minus_scalar")
def _minus_scalar(data, scalar=0.0):
    return data - jnp.asarray(scalar, dtype=data.dtype)


@register("_rminus_scalar")
def _rminus_scalar(data, scalar=0.0):
    return jnp.asarray(scalar, dtype=data.dtype) - data


@register("_mul_scalar")
def _mul_scalar(data, scalar=1.0):
    return data * jnp.asarray(scalar, dtype=data.dtype)


@register("_div_scalar")
def _div_scalar(data, scalar=1.0):
    return data / jnp.asarray(scalar, dtype=data.dtype)


@register("_rdiv_scalar")
def _rdiv_scalar(data, scalar=1.0):
    return jnp.asarray(scalar, dtype=data.dtype) / data


@register("_mod_scalar")
def _mod_scalar(data, scalar=1.0):
    return jnp.mod(data, jnp.asarray(scalar, dtype=data.dtype))


@register("_rmod_scalar")
def _rmod_scalar(data, scalar=1.0):
    return jnp.mod(jnp.asarray(scalar, dtype=data.dtype), data)


@register("_power_scalar")
def _power_scalar(data, scalar=1.0):
    return jnp.power(data, jnp.asarray(scalar, dtype=data.dtype))


@register("_rpower_scalar")
def _rpower_scalar(data, scalar=1.0):
    return jnp.power(jnp.asarray(scalar, dtype=data.dtype), data)


@register("_maximum_scalar")
def _maximum_scalar(data, scalar=0.0):
    return jnp.maximum(data, jnp.asarray(scalar, dtype=data.dtype))


@register("_hypot_scalar")
def _hypot_scalar(data, scalar=0.0):
    return jnp.hypot(data, jnp.asarray(scalar, dtype=data.dtype))


# scalar logical ops (parity: elemwise_binary_scalar_op_logic.cc)
for _lname, _lfn in [("_logical_and_scalar", jnp.logical_and),
                     ("_logical_or_scalar", jnp.logical_or),
                     ("_logical_xor_scalar", jnp.logical_xor)]:
    def _mkl(fn):
        def logical_scalar(data, scalar=0.0):
            return fn(data != 0, bool(scalar)).astype(data.dtype)
        return logical_scalar
    register(_lname, differentiable=False)(_mkl(_lfn))


# _scatter_* ops: in the reference these write only the stored rows of a
# row_sparse output (elemwise_scatter_op.cc); dense storage makes them the
# plain elementwise op, and the sparse frontend routes stored-values-only
# updates through the same kernels.
@register("_scatter_plus_scalar")
def _scatter_plus_scalar(data, scalar=0.0):
    return data + jnp.asarray(scalar, dtype=data.dtype)


@register("_scatter_minus_scalar")
def _scatter_minus_scalar(data, scalar=0.0):
    return data - jnp.asarray(scalar, dtype=data.dtype)


@register("_scatter_elemwise_div")
def _scatter_elemwise_div(lhs, rhs):
    return lhs / rhs


@register("_identity_with_attr_like_rhs")
def _identity_with_attr_like_rhs(lhs, rhs):
    """Identity on lhs; rhs only donates shape/stype attrs in the reference's
    graph passes (elemwise_op_common.h) — returned value is lhs."""
    return lhs


@register("_minimum_scalar")
def _minimum_scalar(data, scalar=0.0):
    return jnp.minimum(data, jnp.asarray(scalar, dtype=data.dtype))


for _name, _fn in [
    ("_equal_scalar", jnp.equal), ("_not_equal_scalar", jnp.not_equal),
    ("_greater_scalar", jnp.greater), ("_greater_equal_scalar", jnp.greater_equal),
    ("_lesser_scalar", jnp.less), ("_lesser_equal_scalar", jnp.less_equal),
]:
    def _mks(fn):
        def cmp_scalar(data, scalar=0.0):
            return fn(data, jnp.asarray(scalar, dtype=data.dtype)).astype(data.dtype)
        return cmp_scalar
    register(_name, differentiable=False)(_mks(_fn))


# ---------------------------------------------------------------------------
# elementwise unary (parity: elemwise_unary_op_basic.cc + mshadow_op.h's 64
# scalar functors — here each is one jnp call XLA fuses into neighbors)
# ---------------------------------------------------------------------------

_UNARY = {
    "abs": jnp.abs, "sign": jnp.sign, "rint": jnp.rint,
    # mxnet round = half away from zero (mshadow_op.h round), NOT
    # banker's rounding — keeps it distinct from rint
    "round": lambda x: jnp.sign(x) * jnp.floor(jnp.abs(x) + 0.5),
    "ceil": jnp.ceil,
    "floor": jnp.floor, "trunc": jnp.trunc, "fix": jnp.trunc,
    "square": jnp.square, "sqrt": jnp.sqrt,
    "cbrt": jnp.cbrt, "exp": jnp.exp, "log": jnp.log, "log10": jnp.log10,
    "log2": jnp.log2, "log1p": jnp.log1p, "expm1": jnp.expm1,
    "sin": jnp.sin, "cos": jnp.cos, "tan": jnp.tan, "arcsin": jnp.arcsin,
    "arccos": jnp.arccos, "arctan": jnp.arctan, "degrees": jnp.degrees,
    "radians": jnp.radians, "sinh": jnp.sinh, "cosh": jnp.cosh,
    "tanh": jnp.tanh, "arcsinh": jnp.arcsinh, "arccosh": jnp.arccosh,
    "arctanh": jnp.arctanh, "negative": jnp.negative,
    "gamma": lambda x: jnp.exp(jax.scipy.special.gammaln(x)),
    "gammaln": jax.scipy.special.gammaln,
    "erf": jax.scipy.special.erf,
    "erfinv": jax.scipy.special.erfinv,
}
for _name, _fn in _UNARY.items():
    def _mku(fn):
        def unary_op(data):
            return fn(data)
        return unary_op
    register(_name)(_mku(_fn))


@register("reciprocal")
def reciprocal(data):
    return 1.0 / data


@register("rsqrt")
def rsqrt(data):
    return lax.rsqrt(data)


@register("rcbrt")
def rcbrt(data):
    return 1.0 / jnp.cbrt(data)


@register("_copy", aliases=("identity",))
def _copy(data):
    return data + jnp.zeros((), dtype=data.dtype)  # force a fresh buffer


@register("BlockGrad", aliases=("stop_gradient", "make_loss_identity"))
def BlockGrad(data):
    return lax.stop_gradient(data)


@register("clip")
def clip(data, a_min=0.0, a_max=1.0):
    return jnp.clip(data, a_min, a_max)


@register("Cast", aliases=("cast",), differentiable=False)
def Cast(data, dtype="float32"):
    from ..base import dtype_np
    return data.astype(dtype_np(dtype))


@register("logical_not", differentiable=False)
def logical_not(data):
    return jnp.logical_not(data).astype(data.dtype)


@register("isnan", differentiable=False)
def isnan(data):
    return jnp.isnan(data)


@register("isinf", differentiable=False)
def isinf(data):
    return jnp.isinf(data)


# ---------------------------------------------------------------------------
# broadcast binary (parity: broadcast_reduce_op + elemwise w/ broadcasting;
# jnp broadcasts natively so these alias the elemwise impls)
# ---------------------------------------------------------------------------

for _bname, _efn in [
    ("broadcast_add", jnp.add), ("broadcast_plus", jnp.add),
    ("broadcast_sub", jnp.subtract), ("broadcast_minus", jnp.subtract),
    ("broadcast_mul", jnp.multiply), ("broadcast_div", jnp.divide),
    ("broadcast_mod", jnp.mod), ("broadcast_power", jnp.power),
    ("broadcast_maximum", jnp.maximum), ("broadcast_minimum", jnp.minimum),
    ("broadcast_hypot", jnp.hypot),
]:
    def _mkb(fn):
        def bcast_op(lhs, rhs):
            return fn(lhs, rhs)
        return bcast_op
    register(_bname)(_mkb(_efn))

for _bname, _efn in [
    ("broadcast_equal", jnp.equal), ("broadcast_not_equal", jnp.not_equal),
    ("broadcast_greater", jnp.greater),
    ("broadcast_greater_equal", jnp.greater_equal),
    ("broadcast_lesser", jnp.less), ("broadcast_lesser_equal", jnp.less_equal),
    ("broadcast_logical_and", jnp.logical_and),
    ("broadcast_logical_or", jnp.logical_or),
    ("broadcast_logical_xor", jnp.logical_xor),
]:
    def _mkbc(fn):
        def bcast_cmp(lhs, rhs):
            return fn(lhs, rhs).astype(jnp.result_type(lhs))
        return bcast_cmp
    register(_bname, differentiable=False)(_mkbc(_efn))


@register("broadcast_to")
def broadcast_to(data, shape=()):
    shape = tuple(int(s) if int(s) != 0 else int(d)
                  for s, d in zip(shape, data.shape))
    return jnp.broadcast_to(data, shape)


@register("broadcast_axis", aliases=("broadcast_axes",))
def broadcast_axis(data, axis=(), size=()):
    axis = (axis,) if np.isscalar(axis) else tuple(axis)
    size = (size,) if np.isscalar(size) else tuple(size)
    shape = list(data.shape)
    for a, s in zip(axis, size):
        shape[a] = int(s)
    return jnp.broadcast_to(data, tuple(shape))


@register("broadcast_like")
def broadcast_like(lhs, rhs):
    return jnp.broadcast_to(lhs, rhs.shape)


# ---------------------------------------------------------------------------
# reductions (parity: broadcast_reduce-inl.h)
# ---------------------------------------------------------------------------

def _norm_axis(axis):
    if axis is None or axis == ():
        return None
    if np.isscalar(axis):
        return int(axis)
    return tuple(int(a) for a in axis)


def _make_reduce(jfn):
    def reduce_op(data, axis=None, keepdims=False, exclude=False):
        ax = _norm_axis(axis)
        if exclude and ax is not None:
            all_ax = set(range(data.ndim))
            inc = {ax} if isinstance(ax, int) else set(a % data.ndim for a in ax)
            ax = tuple(sorted(all_ax - inc))
        return jfn(data, axis=ax, keepdims=bool(keepdims))
    return reduce_op


for _rname, _rfn in [
    ("sum", jnp.sum), ("mean", jnp.mean), ("prod", jnp.prod),
    ("max", jnp.max), ("min", jnp.min),
]:
    register(_rname)(_make_reduce(_rfn))

register("nansum")(_make_reduce(jnp.nansum))
register("nanprod")(_make_reduce(jnp.nanprod))


@register("argmax", differentiable=False)
def argmax(data, axis=None, keepdims=False):
    ax = _norm_axis(axis)
    out = jnp.argmax(data, axis=ax)
    if keepdims and ax is not None:
        out = jnp.expand_dims(out, ax)
    return out.astype(jnp.float32)


@register("argmin", differentiable=False)
def argmin(data, axis=None, keepdims=False):
    ax = _norm_axis(axis)
    out = jnp.argmin(data, axis=ax)
    if keepdims and ax is not None:
        out = jnp.expand_dims(out, ax)
    return out.astype(jnp.float32)


@register("argmax_channel", differentiable=False)
def argmax_channel(data):
    return jnp.argmax(data, axis=-1).astype(jnp.float32)


@register("norm")
def norm(data, ord=2, axis=None, keepdims=False):
    ax = _norm_axis(axis)
    if ord == 1:
        return jnp.sum(jnp.abs(data), axis=ax, keepdims=bool(keepdims))
    return jnp.sqrt(jnp.sum(jnp.square(data), axis=ax, keepdims=bool(keepdims)))


@register("L2Normalization")
def L2Normalization(data, eps=1e-10, mode="instance"):
    """Parity: src/operator/l2_normalization-inl.h."""
    if mode == "instance":
        ax = tuple(range(1, data.ndim))
    elif mode == "channel":
        ax = (1,)
    elif mode == "spatial":
        ax = tuple(range(2, data.ndim))
    else:
        raise ValueError("unknown mode %s" % mode)
    nrm = jnp.sqrt(jnp.sum(jnp.square(data), axis=ax, keepdims=True) + eps)
    return data / nrm


@register("square_sum", aliases=("_square_sum",))
def square_sum(data, axis=None, keepdims=False):
    """Parity: src/operator/tensor/square_sum-inl.h (sparse fused square+sum)."""
    return jnp.sum(jnp.square(data), axis=_norm_axis(axis), keepdims=bool(keepdims))


# ---------------------------------------------------------------------------
# dot / linalg (parity: dot-inl.h, la_op.h — MXU territory)
# ---------------------------------------------------------------------------


@register("dot")
def dot(lhs, rhs, transpose_a=False, transpose_b=False):
    a = lhs.T if transpose_a else lhs
    b = rhs.T if transpose_b else rhs
    if a.ndim == 1 and b.ndim == 1:
        return jnp.dot(a, b)
    # mxnet dot: contract last axis of a with first axis of b
    return jnp.tensordot(a, b, axes=([a.ndim - 1], [0]))


@register("_matmul")
def _matmul(lhs, rhs):
    """The Python @ operator: numpy matmul semantics (2-D dot, batched
    for higher ranks). Shared by NDArray.__matmul__ and
    Symbol.__matmul__ so eager and traced code agree."""
    if lhs.ndim < 2 or rhs.ndim < 2:
        raise TypeError(
            "@ needs operands of rank >= 2; got %s @ %s"
            % (lhs.shape, rhs.shape))
    return jnp.matmul(lhs, rhs)


@register("batch_dot")
def batch_dot(lhs, rhs, transpose_a=False, transpose_b=False):
    a = jnp.swapaxes(lhs, -1, -2) if transpose_a else lhs
    b = jnp.swapaxes(rhs, -1, -2) if transpose_b else rhs
    return jnp.matmul(a, b)


@register("khatri_rao")
def khatri_rao(*mats):
    """Column-wise Khatri-Rao product (parity: contrib krprod.cc)."""
    out = mats[0]
    for m in mats[1:]:
        out = (out[:, None, :] * m[None, :, :]).reshape(-1, out.shape[1])
    return out


@register("linalg_gemm", aliases=("_linalg_gemm",))
def linalg_gemm(A, B, C, transpose_a=False, transpose_b=False, alpha=1.0, beta=1.0):
    a = jnp.swapaxes(A, -1, -2) if transpose_a else A
    b = jnp.swapaxes(B, -1, -2) if transpose_b else B
    return alpha * jnp.matmul(a, b) + beta * C


@register("linalg_gemm2", aliases=("_linalg_gemm2",))
def linalg_gemm2(A, B, transpose_a=False, transpose_b=False, alpha=1.0):
    a = jnp.swapaxes(A, -1, -2) if transpose_a else A
    b = jnp.swapaxes(B, -1, -2) if transpose_b else B
    return alpha * jnp.matmul(a, b)


@register("linalg_potrf", aliases=("_linalg_potrf",))
def linalg_potrf(A):
    return jnp.linalg.cholesky(A)


@register("linalg_potri", aliases=("_linalg_potri",))
def linalg_potri(A):
    L = A
    inv = jnp.linalg.inv(jnp.matmul(L, jnp.swapaxes(L, -1, -2)))
    return inv


@register("linalg_trsm", aliases=("_linalg_trsm",))
def linalg_trsm(A, B, transpose=False, rightside=False, alpha=1.0, lower=True):
    a = jnp.swapaxes(A, -1, -2) if transpose else A
    low = bool(lower) != bool(transpose)
    if rightside:
        # X A = alpha B  ->  A^T X^T = alpha B^T
        xt = jax.scipy.linalg.solve_triangular(
            jnp.swapaxes(a, -1, -2), jnp.swapaxes(alpha * B, -1, -2), lower=not low)
        return jnp.swapaxes(xt, -1, -2)
    return jax.scipy.linalg.solve_triangular(a, alpha * B, lower=low)


@register("linalg_trmm", aliases=("_linalg_trmm",))
def linalg_trmm(A, B, transpose=False, rightside=False, alpha=1.0):
    a = jnp.swapaxes(A, -1, -2) if transpose else A
    return alpha * (jnp.matmul(B, a) if rightside else jnp.matmul(a, B))


@register("linalg_sumlogdiag", aliases=("_linalg_sumlogdiag",))
def linalg_sumlogdiag(A):
    return jnp.sum(jnp.log(jnp.diagonal(A, axis1=-2, axis2=-1)), axis=-1)


@register("linalg_syrk", aliases=("_linalg_syrk",))
def linalg_syrk(A, transpose=False, alpha=1.0):
    at = jnp.swapaxes(A, -1, -2)
    return alpha * (jnp.matmul(at, A) if transpose else jnp.matmul(A, at))


@register("linalg_gelqf", num_outputs=2, aliases=("_linalg_gelqf",))
def linalg_gelqf(A):
    q, r = jnp.linalg.qr(jnp.swapaxes(A, -1, -2))
    return jnp.swapaxes(r, -1, -2), jnp.swapaxes(q, -1, -2)


@register("linalg_syevd", num_outputs=2, aliases=("_linalg_syevd",))
def linalg_syevd(A):
    w, v = jnp.linalg.eigh(A)
    return jnp.swapaxes(v, -1, -2), w


# ---------------------------------------------------------------------------
# matrix manipulation (parity: matrix_op-inl.h)
# ---------------------------------------------------------------------------


@register("Reshape", aliases=("reshape",))
def Reshape(data, shape=(), reverse=False):
    return jnp.reshape(data, _infer_reshape(data.shape, shape, reverse))


def _infer_reshape(dshape, tshape, reverse=False):
    """Implements mxnet's reshape special codes 0,-1,-2,-3,-4
    (parity: matrix_op-inl.h InferReshapeShape)."""
    tshape = list(tshape)
    if reverse:
        dshape = tuple(reversed(dshape))
        tshape = list(reversed(tshape))
    out = []
    src = list(dshape)
    i = 0  # index into src
    j = 0
    while j < len(tshape):
        t = tshape[j]
        if t == 0:
            out.append(src[i]); i += 1
        elif t == -1:
            out.append(-1); i += 1
        elif t == -2:
            out.extend(src[i:]); i = len(src)
        elif t == -3:
            out.append(src[i] * src[i + 1]); i += 2
        elif t == -4:
            a, b = tshape[j + 1], tshape[j + 2]
            if a == -1:
                a = src[i] // b
            if b == -1:
                b = src[i] // a
            out.extend([a, b]); i += 1; j += 2
        else:
            out.append(int(t))
            # advance src cursor heuristically
            if i < len(src):
                i += 1
        j += 1
    if out.count(-1) == 1:
        known = int(np.prod([x for x in out if x != -1])) or 1
        total = int(np.prod(dshape)) if dshape else 1
        out[out.index(-1)] = total // known
    if reverse:
        out = list(reversed(out))
    return tuple(out)


@register("Flatten", aliases=("flatten",))
def Flatten(data):
    return jnp.reshape(data, (data.shape[0], -1))


@register("transpose")
def transpose(data, axes=()):
    if not axes:
        axes = None
    return jnp.transpose(data, axes)


@register("expand_dims")
def expand_dims(data, axis=0):
    return jnp.expand_dims(data, axis)


@register("squeeze")
def squeeze(data, axis=None):
    return jnp.squeeze(data, axis=_norm_axis(axis))


@register("slice", aliases=("crop",))
def slice_op(data, begin=(), end=(), step=()):
    return data[_slice_index(begin, end, step)]


def builtins_slice(b, e, s):
    b = None if b is None else int(b)
    e = None if e is None else int(e)
    s = None if s is None else int(s)
    return slice(b, e, s)


def _slice_index(begin, end, step):
    """begin/end/step attr triple -> an indexing tuple (shared by slice,
    _slice_assign, _slice_assign_scalar)."""
    step = tuple(step) if step else (None,) * len(begin)
    return tuple(builtins_slice(b, e, s)
                 for b, e, s in zip(begin, end, step))


@register("_slice_assign", aliases=("_crop_assign",))
def _slice_assign(lhs, rhs, begin=(), end=(), step=()):
    """Write rhs into lhs[begin:end:step] (parity: _slice_assign /
    _crop_assign, matrix_op.cc) — functional: returns the updated array."""
    return lhs.at[_slice_index(begin, end, step)].set(rhs)


@register("_slice_assign_scalar", aliases=("_crop_assign_scalar",))
def _slice_assign_scalar(data, scalar=0.0, begin=(), end=(), step=()):
    return data.at[_slice_index(begin, end, step)].set(
        jnp.asarray(scalar, dtype=data.dtype))


@register("slice_axis")
def slice_axis(data, axis=0, begin=0, end=None):
    idx = [slice(None)] * data.ndim
    idx[axis] = slice(begin, None if end is None else int(end))
    return data[tuple(idx)]


@register("slice_like")
def slice_like(data, shape_like, axes=()):
    axes = tuple(axes) if axes else tuple(range(shape_like.ndim))
    idx = [slice(None)] * data.ndim
    for a in axes:
        idx[a] = slice(0, shape_like.shape[a])
    return data[tuple(idx)]


@register("Concat", aliases=("concat",))
def Concat(*data, dim=1):
    return jnp.concatenate(data, axis=dim)


@register("stack")
def stack(*data, axis=0):
    return jnp.stack(data, axis=axis)


@register("SliceChannel", aliases=("split",), num_outputs=-1)
def SliceChannel(data, num_outputs=1, axis=1, squeeze_axis=False):
    parts = jnp.split(data, num_outputs, axis=axis)
    if squeeze_axis:
        parts = [jnp.squeeze(p, axis=axis) for p in parts]
    return tuple(parts) if num_outputs > 1 else parts[0]


@register("swapaxes", aliases=("SwapAxis",))
def swapaxes(data, dim1=0, dim2=0):
    return jnp.swapaxes(data, dim1, dim2)


@register("flip", aliases=("reverse",))
def flip(data, axis=0):
    ax = _norm_axis(axis)
    return jnp.flip(data, axis=ax)


@register("tile")
def tile(data, reps=()):
    return jnp.tile(data, tuple(int(r) for r in reps))


@register("repeat")
def repeat(data, repeats=1, axis=None):
    return jnp.repeat(data, repeats, axis=axis)


@register("Pad", aliases=("pad",))
def Pad(data, mode="constant", pad_width=(), constant_value=0.0):
    pw = np.asarray(pad_width, dtype=np.int64).reshape(-1, 2)
    pw = [tuple(p) for p in pw]
    if mode == "constant":
        return jnp.pad(data, pw, mode="constant", constant_values=constant_value)
    if mode == "edge":
        return jnp.pad(data, pw, mode="edge")
    if mode == "reflect":
        return jnp.pad(data, pw, mode="reflect")
    raise ValueError("unknown pad mode %s" % mode)


@register("diag")
def diag(data, k=0):
    if data.ndim == 1:
        return jnp.diag(data, k)
    return jnp.diagonal(data, offset=k, axis1=-2, axis2=-1)


@register("where")
def where(condition, x, y):
    return jnp.where(condition.astype(bool), x, y)


@register("depth_to_space")
def depth_to_space(data, block_size=1):
    n, c, h, w = data.shape
    b = block_size
    x = data.reshape(n, b, b, c // (b * b), h, w)
    x = x.transpose(0, 3, 4, 1, 5, 2)
    return x.reshape(n, c // (b * b), h * b, w * b)


@register("space_to_depth")
def space_to_depth(data, block_size=1):
    n, c, h, w = data.shape
    b = block_size
    x = data.reshape(n, c, h // b, b, w // b, b)
    x = x.transpose(0, 3, 5, 1, 2, 4)
    return x.reshape(n, c * b * b, h // b, w // b)


@register("reshape_like")
def reshape_like(lhs, rhs):
    return jnp.reshape(lhs, rhs.shape)


@register("shape_array", differentiable=False)
def shape_array(data):
    return jnp.asarray(data.shape, dtype=jnp.int64)


@register("size_array", differentiable=False)
def size_array(data):
    return jnp.asarray([data.size], dtype=jnp.int64)


# ---------------------------------------------------------------------------
# indexing / embedding / take / scatter (parity: indexing_op.h)
# ---------------------------------------------------------------------------


@register("Embedding", aliases=("_contrib_SparseEmbedding",))
def Embedding(data, weight, input_dim=0, output_dim=0, dtype="float32",
              sparse_grad=False):
    """Parity: src/operator/tensor/indexing_op.h Embedding.

    TPU note: gather lowers to a dynamic-gather HLO; sparse_grad maps to
    row-sparse grads in the reference — here grads stay dense (XLA scatter-add)
    with the row_sparse surface handled at the KVStore level.
    """
    idx = data.astype(jnp.int32)
    return jnp.take(weight, idx, axis=0)


@register("take")
def take(a, indices, axis=0, mode="clip"):
    idx = indices.astype(jnp.int32)
    return jnp.take(a, idx, axis=axis, mode="clip" if mode == "clip" else "wrap")


@register("batch_take")
def batch_take(a, indices):
    idx = indices.astype(jnp.int32)
    return jnp.take_along_axis(a, idx[:, None], axis=1)[:, 0]


@register("pick")
def pick(data, index, axis=-1, keepdims=False, mode="clip"):
    idx = jnp.clip(index.astype(jnp.int32), 0, data.shape[axis] - 1)
    out = jnp.take_along_axis(data, jnp.expand_dims(idx, axis), axis=axis)
    if not keepdims:
        out = jnp.squeeze(out, axis=axis)
    return out


@register("choose_element_0index")
def choose_element_0index(lhs, rhs):
    """out[i] = lhs[i, rhs[i]] — legacy name for pick along axis 1
    (parity: src/operator/tensor/indexing_op.cc choose_element_0index)."""
    return pick(lhs, rhs, axis=1)


@register("fill_element_0index")
def fill_element_0index(lhs, mhs, rhs):
    """out = lhs with out[i, rhs[i]] = mhs[i]
    (parity: src/operator/tensor/indexing_op.cc fill_element_0index)."""
    idx = jnp.clip(rhs.astype(jnp.int32), 0, lhs.shape[1] - 1)
    rows = jnp.arange(lhs.shape[0])
    return lhs.at[rows, idx].set(mhs.astype(lhs.dtype))


@register("one_hot", differentiable=False)
def one_hot(indices, depth=0, on_value=1.0, off_value=0.0, dtype="float32"):
    from ..base import dtype_np
    oh = jax.nn.one_hot(indices.astype(jnp.int32), depth)
    return (oh * (on_value - off_value) + off_value).astype(dtype_np(dtype))


@register("gather_nd")
def gather_nd(data, indices):
    idx = tuple(indices.astype(jnp.int32))
    return data[idx]


@register("scatter_nd")
def scatter_nd(data, indices, shape=()):
    idx = tuple(indices.astype(jnp.int32))
    out = jnp.zeros(tuple(int(s) for s in shape), dtype=data.dtype)
    return out.at[idx].add(data)


@register("_scatter_set_nd")
def _scatter_set_nd(lhs, rhs, indices, shape=()):
    idx = tuple(indices.astype(jnp.int32))
    return lhs.at[idx].set(rhs)


@register("SequenceMask")
def SequenceMask(data, sequence_length=None, use_sequence_length=False,
                 value=0.0, axis=0):
    """Parity: src/operator/sequence_mask-inl.h (time-major [T,N,...])."""
    if not use_sequence_length or sequence_length is None:
        return data
    T = data.shape[axis]
    steps = jnp.arange(T)
    if axis == 0:
        mask = steps[:, None] < sequence_length[None, :].astype(jnp.int32)
        mask = mask.reshape(mask.shape + (1,) * (data.ndim - 2))
    else:  # axis == 1, batch-major
        mask = steps[None, :] < sequence_length[:, None].astype(jnp.int32)
        mask = mask.reshape(mask.shape + (1,) * (data.ndim - 2))
    return jnp.where(mask, data, jnp.asarray(value, dtype=data.dtype))


@register("SequenceLast")
def SequenceLast(data, sequence_length=None, use_sequence_length=False, axis=0):
    if not use_sequence_length or sequence_length is None:
        idx = [slice(None)] * data.ndim
        idx[axis] = -1
        return data[tuple(idx)]
    last = (sequence_length.astype(jnp.int32) - 1)
    if axis == 0:
        return jnp.take_along_axis(
            data, last.reshape((1, -1) + (1,) * (data.ndim - 2)), axis=0)[0]
    return jnp.take_along_axis(
        data, last.reshape((-1, 1) + (1,) * (data.ndim - 2)), axis=1)[:, 0]


@register("SequenceReverse")
def SequenceReverse(data, sequence_length=None, use_sequence_length=False, axis=0):
    if not use_sequence_length or sequence_length is None:
        return jnp.flip(data, axis=0)
    T = data.shape[0]
    steps = jnp.arange(T)[:, None]
    L = sequence_length.astype(jnp.int32)[None, :]
    src = jnp.where(steps < L, L - 1 - steps, steps)
    src = src.reshape(src.shape + (1,) * (data.ndim - 2))
    return jnp.take_along_axis(data, jnp.broadcast_to(src, data.shape), axis=0)


# ---------------------------------------------------------------------------
# ordering (parity: ordering_op-inl.h)
# ---------------------------------------------------------------------------


@register("sort")
def sort(data, axis=-1, is_ascend=True):
    out = jnp.sort(data, axis=axis)
    return out if is_ascend else jnp.flip(out, axis=axis)


@register("argsort", differentiable=False)
def argsort(data, axis=-1, is_ascend=True, dtype="float32"):
    from ..base import dtype_np
    out = jnp.argsort(data, axis=axis)
    if not is_ascend:
        out = jnp.flip(out, axis=axis)
    return out.astype(dtype_np(dtype))


@register("topk", differentiable=False, num_outputs=-1)
def topk(data, axis=-1, k=1, ret_typ="indices", is_ascend=False, dtype="float32"):
    from ..base import dtype_np
    x = jnp.moveaxis(data, axis, -1)
    vals, idx = lax.top_k(-x if is_ascend else x, k)
    if is_ascend:
        vals = -vals
    vals = jnp.moveaxis(vals, -1, axis)
    idx = jnp.moveaxis(idx, -1, axis).astype(dtype_np(dtype))
    if ret_typ == "indices":
        return idx
    if ret_typ == "value":
        return vals
    if ret_typ == "both":
        return vals, idx
    if ret_typ == "mask":
        x2 = jnp.moveaxis(jnp.zeros_like(data), axis, -1).reshape(-1, data.shape[axis])
        ii = jnp.moveaxis(idx, axis, -1).reshape(-1, k).astype(jnp.int32)
        rows = jnp.arange(x2.shape[0])[:, None]
        x2 = x2.at[rows, ii].set(1)
        return jnp.moveaxis(x2.reshape(jnp.moveaxis(data, axis, -1).shape), -1, axis)
    raise ValueError("unknown ret_typ %s" % ret_typ)


# ---------------------------------------------------------------------------
# init ops (parity: init_op.h)
# ---------------------------------------------------------------------------

def _dt(dtype):
    from ..base import dtype_np
    return dtype_np(dtype)


@register("_zeros", differentiable=False)
def _zeros(shape=(), dtype="float32"):
    return jnp.zeros(tuple(int(s) for s in shape), dtype=_dt(dtype))


@register("_ones", differentiable=False)
def _ones(shape=(), dtype="float32"):
    return jnp.ones(tuple(int(s) for s in shape), dtype=_dt(dtype))


@register("_full", differentiable=False)
def _full(shape=(), value=0.0, dtype="float32"):
    return jnp.full(tuple(int(s) for s in shape), value, dtype=_dt(dtype))


@register("_arange", differentiable=False)
def _arange(start=0.0, stop=None, step=1.0, repeat=1, dtype="float32"):
    out = jnp.arange(start, stop, step, dtype=_dt(dtype))
    if repeat > 1:
        out = jnp.repeat(out, repeat)
    return out


@register("zeros_like", differentiable=False)
def zeros_like(data):
    return jnp.zeros_like(data)


@register("ones_like", differentiable=False)
def ones_like(data):
    return jnp.ones_like(data)


@register("_eye", differentiable=False)
def _eye(N=0, M=0, k=0, dtype="float32"):
    return jnp.eye(int(N), int(M) if M else None, k=int(k), dtype=_dt(dtype))


# ---------------------------------------------------------------------------
# misc / contrib-adjacent
# ---------------------------------------------------------------------------


@register("smooth_l1")
def smooth_l1(data, scalar=1.0):
    s2 = scalar * scalar
    return jnp.where(jnp.abs(data) < 1.0 / s2,
                     0.5 * s2 * jnp.square(data),
                     jnp.abs(data) - 0.5 / s2)


@register("quadratic", aliases=("_contrib_quadratic",))
def quadratic(data, a=0.0, b=0.0, c=0.0):
    """Parity: src/operator/contrib/quadratic_op-inl.h (the tutorial op)."""
    return a * jnp.square(data) + b * data + c


@register("softmax_cross_entropy")
def softmax_cross_entropy(data, label):
    """Fused softmax + CE against integer labels, summed over the batch
    (parity: softmax_cross_entropy, loss_binary_op.cc) — output shape (1,)."""
    logz = jax.scipy.special.logsumexp(data, axis=1)
    picked = jnp.take_along_axis(
        data, label.astype(jnp.int32)[:, None], axis=1)[:, 0]
    return jnp.sum(logz - picked).reshape(1)


@register("_grad_add")
def _grad_add(lhs, rhs):
    """Gradient aggregation add (parity: _grad_add — elemwise add that never
    runs in place; XLA owns buffers so it IS plain add here)."""
    return lhs + rhs


@register("add_n", aliases=("ElementWiseSum", "_sum"))
def add_n(*args):
    out = args[0]
    for a in args[1:]:
        out = out + a
    return out
