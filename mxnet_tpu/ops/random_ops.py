"""Random sampling ops.

Parity: reference `src/operator/random/` (sample_op.h uniform/normal/gamma/
exponential/poisson/negative_binomial/generalized_negative_binomial,
multisample_op.h per-distribution-parameter draws, shuffle_op, multinomial)
backed by per-device RandomGenerator (`src/common/random_generator.h`).

TPU-native redesign: jax.random counter-based PRNG; the global key lives in
mxnet_tpu.random and is threaded as a traced argument inside jit traces.
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from .registry import register
from ..random import next_key
from ..base import dtype_np


def _shp(shape):
    if shape is None:
        return ()
    if np.isscalar(shape):
        return (int(shape),)
    return tuple(int(s) for s in shape)


@register("_random_uniform", differentiable=False, stochastic=True,
          aliases=("uniform",))
def _random_uniform(low=0.0, high=1.0, shape=None, dtype="float32"):
    return jax.random.uniform(next_key(), _shp(shape), dtype=dtype_np(dtype),
                              minval=low, maxval=high)


@register("_random_normal", differentiable=False, stochastic=True,
          aliases=("normal",))
def _random_normal(loc=0.0, scale=1.0, shape=None, dtype="float32"):
    return loc + scale * jax.random.normal(next_key(), _shp(shape),
                                           dtype=dtype_np(dtype))


@register("_random_gamma", differentiable=False, stochastic=True)
def _random_gamma(alpha=1.0, beta=1.0, shape=None, dtype="float32"):
    return beta * jax.random.gamma(next_key(), alpha, _shp(shape),
                                   dtype=dtype_np(dtype))


@register("_random_exponential", differentiable=False, stochastic=True,
          aliases=("exponential",))
def _random_exponential(lam=1.0, shape=None, dtype="float32"):
    return jax.random.exponential(next_key(), _shp(shape),
                                  dtype=dtype_np(dtype)) / lam


@register("_random_poisson", differentiable=False, stochastic=True,
          aliases=("poisson",))
def _random_poisson(lam=1.0, shape=None, dtype="float32"):
    return jax.random.poisson(next_key(), lam, _shp(shape)).astype(dtype_np(dtype))


@register("_random_negative_binomial", differentiable=False, stochastic=True,
          aliases=("negative_binomial",))
def _random_negative_binomial(k=1, p=1.0, shape=None, dtype="float32"):
    lam = jax.random.gamma(next_key(), float(k), _shp(shape)) * (1 - p) / p
    return jax.random.poisson(next_key(), lam, _shp(shape)).astype(dtype_np(dtype))


@register("_random_generalized_negative_binomial", differentiable=False,
          stochastic=True, aliases=("generalized_negative_binomial",))
def _random_generalized_negative_binomial(mu=1.0, alpha=1.0, shape=None,
                                          dtype="float32"):
    if alpha == 0.0:
        return jax.random.poisson(next_key(), mu, _shp(shape)).astype(dtype_np(dtype))
    r = 1.0 / alpha
    p = r / (r + mu)
    lam = jax.random.gamma(next_key(), r, _shp(shape)) * (1 - p) / p
    return jax.random.poisson(next_key(), lam, _shp(shape)).astype(dtype_np(dtype))


@register("_random_randint", differentiable=False, stochastic=True)
def _random_randint(low=0, high=1, shape=None, dtype="int32"):
    return jax.random.randint(next_key(), _shp(shape), int(low), int(high),
                              dtype=dtype_np(dtype))


# sample_* variants: one draw per element of the parameter tensors
# (parity: multisample_op.h)


@register("_sample_uniform", differentiable=False, stochastic=True)
def _sample_uniform(low, high, shape=None, dtype=None):
    s = _shp(shape)
    u = jax.random.uniform(next_key(), low.shape + s, dtype=low.dtype)
    low_b = low.reshape(low.shape + (1,) * len(s))
    high_b = high.reshape(high.shape + (1,) * len(s))
    return (low_b + u * (high_b - low_b)).reshape(low.shape + s)


@register("_sample_normal", differentiable=False, stochastic=True)
def _sample_normal(mu, sigma, shape=None, dtype=None):
    s = _shp(shape)
    z = jax.random.normal(next_key(), mu.shape + s, dtype=mu.dtype)
    return mu.reshape(mu.shape + (1,) * len(s)) + \
        sigma.reshape(sigma.shape + (1,) * len(s)) * z


@register("_sample_gamma", differentiable=False, stochastic=True)
def _sample_gamma(alpha, beta, shape=None, dtype=None):
    s = _shp(shape)
    a = alpha.reshape(alpha.shape + (1,) * len(s))
    g = jax.random.gamma(next_key(), jnp.broadcast_to(a, alpha.shape + s))
    return g * beta.reshape(beta.shape + (1,) * len(s))


@register("_sample_exponential", differentiable=False, stochastic=True)
def _sample_exponential(lam, shape=None, dtype=None):
    s = _shp(shape)
    e = jax.random.exponential(next_key(), lam.shape + s, dtype=lam.dtype)
    return e / lam.reshape(lam.shape + (1,) * len(s))


@register("_sample_poisson", differentiable=False, stochastic=True)
def _sample_poisson(lam, shape=None, dtype=None):
    s = _shp(shape)
    l = jnp.broadcast_to(lam.reshape(lam.shape + (1,) * len(s)), lam.shape + s)
    return jax.random.poisson(next_key(), l).astype(lam.dtype)


@register("_sample_negative_binomial", differentiable=False, stochastic=True)
def _sample_negative_binomial(k, p, shape=None, dtype=None):
    """Per-element NB(k_i, p_i) draws via the gamma-Poisson mixture
    (parity: multisample_op.cc _sample_negative_binomial)."""
    s = _shp(shape)
    kb = jnp.broadcast_to(
        k.reshape(k.shape + (1,) * len(s)).astype(jnp.float32), k.shape + s)
    pb = jnp.broadcast_to(
        p.reshape(p.shape + (1,) * len(s)).astype(jnp.float32), p.shape + s)
    lam = jax.random.gamma(next_key(), kb) * (1 - pb) / pb
    return jax.random.poisson(next_key(), lam).astype(
        dtype_np(dtype or "float32"))


@register("_sample_generalized_negative_binomial", differentiable=False,
          stochastic=True)
def _sample_generalized_negative_binomial(mu, alpha, shape=None, dtype=None):
    """Per-element GNB(mu_i, alpha_i); alpha_i == 0 degenerates to
    Poisson(mu_i) (parity: multisample_op.cc)."""
    s = _shp(shape)
    mub = jnp.broadcast_to(
        mu.reshape(mu.shape + (1,) * len(s)).astype(jnp.float32),
        mu.shape + s)
    ab = jnp.broadcast_to(
        alpha.reshape(alpha.shape + (1,) * len(s)).astype(jnp.float32),
        alpha.shape + s)
    r = 1.0 / jnp.maximum(ab, 1e-6)
    pgb = r / (r + mub)
    lam = jnp.where(ab <= 1e-6, mub,
                    jax.random.gamma(next_key(), r) * (1 - pgb) / pgb)
    return jax.random.poisson(next_key(), lam).astype(
        dtype_np(dtype or "float32"))


@register("_sample_multinomial", differentiable=False, stochastic=True)
def _sample_multinomial(data, shape=None, get_prob=False, dtype="int32"):
    """data: [..., K] probabilities; returns [..., *shape] class indices."""
    s = _shp(shape) or ()
    n = int(np.prod(s)) if s else 1
    logits = jnp.log(jnp.maximum(data, 1e-37))
    flat = logits.reshape(-1, data.shape[-1])
    draws = jax.random.categorical(next_key(), flat[:, None, :].repeat(n, axis=1),
                                   axis=-1)  # [B, n]
    out = draws.reshape(data.shape[:-1] + (s if s else ()))
    out = out.astype(dtype_np(dtype))
    if get_prob:
        logp = jnp.take_along_axis(
            jax.nn.log_softmax(flat, axis=-1),
            draws.astype(jnp.int32), axis=1).reshape(out.shape)
        return out, logp
    return out


@register("_shuffle", differentiable=False, stochastic=True)
def _shuffle(data):
    """Shuffle along the first axis (parity: shuffle_op.cc)."""
    return jax.random.permutation(next_key(), data, axis=0)


@register("_sample_unique_zipfian", differentiable=False, stochastic=True)
def _sample_unique_zipfian(range_max=1, shape=None):
    s = _shp(shape)
    u = jax.random.uniform(next_key(), s)
    out = jnp.exp(u * jnp.log(float(range_max) + 1.0)) - 1.0
    return jnp.clip(out.astype(jnp.int64), 0, range_max - 1)
