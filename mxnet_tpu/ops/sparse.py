"""Sparse storage ops (row_sparse / CSR).

Parity: reference sparse support — `include/mxnet/ndarray.h:61-66` storage
types, `src/operator/tensor/cast_storage-inl.h`, `sparse_retain`,
`dot-inl.h` sparse×dense kernels.

TPU-native redesign: XLA has no native sparse tensors, so row_sparse is a
(indices[nnz], values[nnz, cols...]) dense pair and CSR is
(indptr, indices, values) — BCOO-style. Ops below work on these component
arrays; the user-facing RowSparseNDArray/CSRNDArray classes live in
`mxnet_tpu.ndarray.sparse`. Gathers/scatters lower to XLA gather/scatter;
perf cliffs differ from the CUDA kernels (documented in SURVEY §7 hard
part (c)).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .registry import register


@register("_rsp_to_dense")
def rsp_to_dense(indices, values, num_rows=0):
    shape = (int(num_rows),) + values.shape[1:]
    out = jnp.zeros(shape, dtype=values.dtype)
    return out.at[indices.astype(jnp.int32)].add(values)


@register("_dense_to_rsp", num_outputs=2, differentiable=False)
def dense_to_rsp(dense):
    """Full-row storage (all rows retained; zero rows stay zero rows).

    Note: for static shapes we keep nnz == num_rows; truly compacted storage
    happens host-side in RowSparseNDArray construction.
    """
    idx = jnp.arange(dense.shape[0], dtype=jnp.int64)
    return idx, dense


@register("_csr_to_dense")
def csr_to_dense(indptr, indices, values, num_rows=0, num_cols=0):
    nnz = values.shape[0]
    rows = jnp.searchsorted(indptr.astype(jnp.int32),
                            jnp.arange(nnz, dtype=jnp.int32), side="right") - 1
    out = jnp.zeros((int(num_rows), int(num_cols)), dtype=values.dtype)
    return out.at[rows, indices.astype(jnp.int32)].add(values)


@register("sparse_retain", num_outputs=2, aliases=("_sparse_retain",))
def sparse_retain(indices, values, new_idx):
    """Retain only rows listed in new_idx (parity: sparse_retain op).

    Rows of `new_idx` absent from `indices` produce zero rows.
    """
    pos = jnp.searchsorted(indices.astype(jnp.int64), new_idx.astype(jnp.int64))
    pos = jnp.clip(pos, 0, indices.shape[0] - 1)
    found = indices[pos].astype(jnp.int64) == new_idx.astype(jnp.int64)
    vals = jnp.where(found.reshape((-1,) + (1,) * (values.ndim - 1)),
                     values[pos], jnp.zeros((), dtype=values.dtype))
    return new_idx, vals


@register("_csr_dot_dense")
def csr_dot_dense(indptr, indices, values, rhs, num_rows=0, num_cols=0,
                  transpose_lhs=False):
    """dot(csr, dense) / dot(csr^T, dense) via segment-sum over nnz
    (parity: dot-inl.h csr kernels; the transposed form is the gradient
    path of sparse linear models)."""
    nnz = values.shape[0]
    rows = jnp.searchsorted(indptr.astype(jnp.int32),
                            jnp.arange(nnz, dtype=jnp.int32), side="right") - 1
    cols = indices.astype(jnp.int32)
    matvec = rhs.ndim == 1
    if matvec:
        rhs = rhs[:, None]
    if transpose_lhs:
        if int(num_cols) <= 0:
            raise ValueError(
                "csr_dot_dense(transpose_lhs=True) needs num_cols (the "
                "csr's column count) to size the output")
        # out[c, :] = sum_{nnz with col c} v * rhs[row, :]
        contrib = values[:, None] * rhs[rows]
        out = jax.ops.segment_sum(contrib, cols, num_segments=int(num_cols))
    else:
        # out[r, :] = sum_{nnz in row r} v * rhs[col, :]
        contrib = values[:, None] * rhs[cols]
        out = jax.ops.segment_sum(contrib, rows, num_segments=int(num_rows))
    if matvec:
        out = out[:, 0]
    return out.astype(rhs.dtype)


@register("_rsp_dot_dense")
def rsp_dot_dense(indices, values, rhs):
    return jnp.matmul(values, rhs)  # caller scatters rows back

