"""The `Custom` operator — user-defined Python ops callable from the
SYMBOLIC path (mx.sym.Custom) and inside jitted graphs.

Parity: reference `src/operator/custom/custom.cc` registers op "Custom"
whose forward/backward call back into frontend CustomOp code on a dedicated
worker thread (custom-inl.h:50-170), so symbols/CachedOps can embed Python
ops. TPU-native redesign: under tracing the Python callbacks escape via
`jax.pure_callback` (SURVEY §7 hard part (f)); gradients route through
`jax.custom_vjp`, whose backward re-enters the host to run
CustomOp.backward. The imperative `mx.nd.Custom` keeps the direct in-line
path (mxnet_tpu/operator.py) — this registered op is the traced/symbolic
seam.

Note: a fresh CustomOp instance is created per forward and per backward
call (the reference reuses one instance per executor binding); custom ops
that rely on instance state across forward->backward must carry it through
out_data instead.
"""
from __future__ import annotations

import numpy as np

from ..base import MXNetError
from .registry import register


def _prop_for(op_type, params):
    from .. import operator as _operator
    return _operator.get(op_type)(**params)


def _shapes_types(prop, ins):
    in_shapes = [tuple(x.shape) for x in ins]
    _, out_shapes, aux_shapes = prop.infer_shape(list(in_shapes))
    try:
        _, out_types, _ = prop.infer_type([x.dtype for x in ins])
    except NotImplementedError:
        # only the base-class "not implemented" signal falls back; genuine
        # errors in user infer_type overrides must surface
        out_types = [ins[0].dtype if ins else np.float32] * len(out_shapes)
    return in_shapes, out_shapes, aux_shapes, out_types


@register("Custom", num_outputs=-1)
def Custom(*inputs, op_type=None, **params):
    """Traced custom-op dispatch: host callbacks via pure_callback with a
    custom_vjp whose backward runs CustomOp.backward host-side."""
    import jax
    import jax.numpy as jnp
    from .. import autograd
    from ..ndarray import NDArray

    assert op_type is not None, "op_type is required"
    prop = _prop_for(op_type, params)
    ins = list(inputs)
    in_shapes, out_shapes, aux_shapes, out_types = _shapes_types(prop, ins)
    if aux_shapes:
        # persistent aux state would need executor-level threading (only
        # BatchNorm gets that in symbol._eval); zero-filled aux every call
        # would be silently wrong, so fail loudly instead
        raise MXNetError(
            "symbolic Custom op %r declares auxiliary states, which the "
            "traced path does not persist — carry state through out_data "
            "or use the imperative nd.Custom" % op_type)
    train = autograd.is_training()  # trace-time mode, like Dropout/BatchNorm
    n_in, n_out = len(ins), len(out_shapes)
    out_struct = tuple(jax.ShapeDtypeStruct(tuple(s), np.dtype(t))
                       for s, t in zip(out_shapes, out_types))
    in_struct = tuple(jax.ShapeDtypeStruct(tuple(s), np.dtype(v.dtype))
                      for s, v in zip(in_shapes, ins))

    def _nd(v):
        return NDArray(np.asarray(v))

    def host_forward(*vals):
        op = prop.create_operator(None, in_shapes,
                                  [v.dtype for v in vals])
        ins_nd = [_nd(v) for v in vals]
        outs = [_nd(np.zeros(s, t)) for s, t in zip(out_shapes, out_types)]
        with autograd.pause():
            op.forward(train, ["write"] * n_out, ins_nd, outs, [])
        return tuple(np.asarray(o.asnumpy(), dtype=t)
                     for o, t in zip(outs, out_types))

    def host_backward(*vals):
        gouts, vins, vouts = (vals[:n_out], vals[n_out:n_out + n_in],
                              vals[n_out + n_in:])
        op = prop.create_operator(None, in_shapes,
                                  [v.dtype for v in vins])
        ins_nd = [_nd(v) for v in vins]
        outs_nd = [_nd(v) for v in vouts]
        gouts_nd = [_nd(g) for g in gouts]
        gins = [_nd(np.zeros_like(np.asarray(v))) for v in vins]
        with autograd.pause():
            op.backward(["write"] * n_in, gouts_nd, ins_nd, outs_nd,
                        gins, [])
        return tuple(np.asarray(g.asnumpy(), dtype=v.dtype)
                     for g, v in zip(gins, vins))

    @jax.custom_vjp
    def run(*vals):
        return jax.pure_callback(host_forward, out_struct, *vals)

    def run_fwd(*vals):
        outs = jax.pure_callback(host_forward, out_struct, *vals)
        return outs, (vals, outs)

    def run_bwd(res, gouts):
        vals, outs = res
        return jax.pure_callback(host_backward, in_struct,
                                 *(tuple(gouts) + tuple(vals) +
                                   tuple(outs)))

    run.defvjp(run_fwd, run_bwd)
    outs = run(*ins)
    return outs if n_out > 1 else outs[0]
