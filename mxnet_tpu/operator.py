"""Custom operators defined in Python.

Parity: reference `src/operator/custom/` + `python/mxnet/operator.py:426,472,
692` — CustomOp/CustomOpProp/register let users write ops (forward+backward)
in Python; the reference runs callbacks on a dedicated worker thread so they
never block engine threads (custom-inl.h:50-170).

TPU-native redesign: eager custom ops run inline (XLA dispatch is already
async around them); inside jit traces a custom op can either be pure-JAX
(then it traces straight through) or host-bound (then wrap with
jax.pure_callback — the io_callback escape hatch of SURVEY §7(f)).
"""
from __future__ import annotations

import numpy as np

from .base import MXNetError
from .ndarray import NDArray
from . import autograd

_REGISTRY = {}


class CustomOp:
    """Base class for custom op execution (parity: operator.py CustomOp)."""

    def forward(self, is_train, req, in_data, out_data, aux):
        raise NotImplementedError

    def backward(self, req, out_grad, in_data, out_data, in_grad, aux):
        raise NotImplementedError

    def assign(self, dst, req, src):
        if req == "null":
            return
        if req in ("write", "inplace"):
            dst._data = src._data if isinstance(src, NDArray) else src
        elif req == "add":
            dst._data = dst._data + (src._data if isinstance(src, NDArray)
                                     else src)
        dst._version += 1


class CustomOpProp:
    """Op metadata: shapes, types, arity (parity: operator.py CustomOpProp)."""

    def __init__(self, need_top_grad=True):
        self.need_top_grad_ = need_top_grad

    def infer_shape(self, in_shape):
        return in_shape, [in_shape[0]] * len(self.list_outputs()), []

    def infer_type(self, in_type):
        return in_type, [in_type[0]] * len(self.list_outputs()), []

    def list_arguments(self):
        return ["data"]

    def list_outputs(self):
        return ["output"]

    def list_auxiliary_states(self):
        return []

    def declare_backward_dependency(self, out_grad, in_data, out_data):
        deps = []
        if self.need_top_grad_:
            deps.extend(out_grad)
        deps.extend(in_data)
        deps.extend(out_data)
        return deps

    def create_operator(self, ctx, in_shapes, in_dtypes):
        raise NotImplementedError


def register(reg_name):
    """Register a CustomOpProp subclass (parity: operator.py:692)."""

    def do_register(prop_cls):
        _REGISTRY[reg_name] = prop_cls
        return prop_cls

    return do_register


def get(name):
    if name not in _REGISTRY:
        raise MXNetError("custom op %s not registered" % name)
    return _REGISTRY[name]


def invoke(op_type, *inputs, **params):
    """Run a registered custom op imperatively (mx.nd.Custom equivalent)."""
    prop_cls = get(op_type)
    prop = prop_cls(**params)
    in_shapes = [i.shape for i in inputs]
    _, out_shapes, aux_shapes = prop.infer_shape(list(in_shapes))
    op = prop.create_operator(None, in_shapes, [i.dtype for i in inputs])
    import jax.numpy as jnp
    outs = [NDArray(jnp.zeros(s)) for s in out_shapes]
    aux = [NDArray(jnp.zeros(s)) for s in aux_shapes]
    with autograd.pause():
        op.forward(autograd.is_training(), ["write"] * len(outs),
                   list(inputs), outs, aux)
    if autograd.is_recording():
        n_in = len(inputs)

        def custom_backward(out_grads, input_vals, kwargs):
            in_grads = [NDArray(jnp.zeros_like(v)) for v in input_vals]
            with autograd.pause():
                op.backward(["write"] * n_in,
                            [NDArray(g) for g in out_grads],
                            list(inputs), outs, in_grads, aux)
            return [g._data for g in in_grads]

        class _OpDef:
            fn = None
            differentiable = True

        autograd.record_op(_OpDef, list(inputs), [i._data for i in inputs],
                           outs, {}, custom_backward=custom_backward)
    return outs[0] if len(outs) == 1 else outs


# expose as nd.Custom (parity: mx.nd.Custom)
def Custom(*inputs, op_type=None, **params):
    assert op_type is not None, "op_type is required"
    return invoke(op_type, *inputs, **params)


# ---------------------------------------------------------------------------
# deprecated pre-CustomOp interfaces (parity: operator.py PythonOp:42,
# NumpyOp:150, NDArrayOp:253) — kept working as thin adapters onto the
# CustomOp machinery so reference-era op code runs unchanged.
# ---------------------------------------------------------------------------

_DEPRECATED_SEQ = [0]


class PythonOp:
    """Deprecated base (parity: operator.py:42). Subclass NumpyOp or
    NDArrayOp; call get_symbol(*sym_args, name=...) to build the node."""

    def __init__(self, need_top_grad=True):
        self.need_top_grad_ = need_top_grad

    def get_symbol(self, *args, **kwargs):
        raise NotImplementedError("use NumpyOp or NDArrayOp")

    def forward(self, in_data, out_data):
        raise NotImplementedError

    def backward(self, out_grad, in_data, out_data, in_grad):
        raise NotImplementedError

    def infer_shape(self, in_shape):
        return in_shape, [in_shape[0]] * len(self.list_outputs())

    def list_outputs(self):
        return ["output"]

    def list_arguments(self):
        return ["data"]

    def need_top_grad(self):
        return self.need_top_grad_

    def _register_as_custom(self, shim_cls):
        """Register a CustomOpProp delegating to this instance; one
        registration per op instance (repeated get_symbol calls — common
        in sweep loops — must not grow the global registry unboundedly)."""
        cached = getattr(self, "_custom_reg_name", None)
        if cached is not None:
            return cached
        op = self
        _DEPRECATED_SEQ[0] += 1
        reg_name = "_deprecated_pyop_%d" % _DEPRECATED_SEQ[0]

        class _Prop(CustomOpProp):
            def __init__(self):
                super().__init__(need_top_grad=op.need_top_grad())

            def list_arguments(self):
                return op.list_arguments()

            def list_outputs(self):
                return op.list_outputs()

            def infer_shape(self, in_shape):
                ins, outs = op.infer_shape(in_shape)
                return ins, outs, []

            def create_operator(self, ctx, shapes, dtypes):
                return shim_cls()

        register(reg_name)(_Prop)
        self._custom_reg_name = reg_name
        return reg_name

    def _build(self, shim_cls, args, kwargs):
        import mxnet_tpu.symbol as S
        reg_name = self._register_as_custom(shim_cls)
        kwargs.pop("name", None)  # naming is cosmetic here
        return S.Custom(*args, op_type=reg_name, **kwargs)


class NumpyOp(PythonOp):
    """Deprecated numpy-operand custom op (parity: operator.py:150):
    forward/backward receive numpy arrays and write outputs in place."""

    def get_symbol(self, *args, **kwargs):
        op = self

        class _Shim(CustomOp):
            def forward(self, is_train, req, in_data, out_data, aux):
                ins = [d.asnumpy() for d in in_data]
                outs = [np.zeros(d.shape, dtype=np.float32)
                        for d in out_data]
                op.forward(in_data=ins, out_data=outs)
                for i, (dst, src) in enumerate(zip(out_data, outs)):
                    self.assign(dst, req[i], NDArray(src))

            def backward(self, req, out_grad, in_data, out_data, in_grad,
                         aux):
                grads = [np.zeros(g.shape, dtype=np.float32)
                         for g in in_grad]
                op.backward(out_grad=[g.asnumpy() for g in out_grad],
                            in_data=[d.asnumpy() for d in in_data],
                            out_data=[d.asnumpy() for d in out_data],
                            in_grad=grads)
                for i, (dst, src) in enumerate(zip(in_grad, grads)):
                    self.assign(dst, req[i], NDArray(src))

        return self._build(_Shim, args, kwargs)


class NDArrayOp(PythonOp):
    """Deprecated NDArray-operand custom op (parity: operator.py:253):
    forward/backward receive NDArrays and write outputs in place with
    framework ops (e.g. ``out[:] = ...``)."""

    def get_symbol(self, *args, **kwargs):
        op = self

        class _Shim(CustomOp):
            def forward(self, is_train, req, in_data, out_data, aux):
                op.forward(in_data=list(in_data), out_data=list(out_data))

            def backward(self, req, out_grad, in_data, out_data, in_grad,
                         aux):
                op.backward(out_grad=list(out_grad),
                            in_data=list(in_data),
                            out_data=list(out_data),
                            in_grad=list(in_grad))

        return self._build(_Shim, args, kwargs)
