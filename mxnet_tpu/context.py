"""Device contexts.

Parity: reference `python/mxnet/context.py` (Context class, cpu()/gpu(),
default-context scope). TPU-native redesign: a Context maps to a concrete
`jax.Device`; `gpu()` is accepted for script compatibility and aliases the
accelerator (TPU) when one is present. Placement of NDArrays is
`jax.device_put`; multi-device placement is handled by `mxnet_tpu.parallel`
(Mesh/NamedSharding), which the reference did per-executor-copy instead.
"""
from __future__ import annotations

import threading

import jax


class Context:
    """A device context (cpu/tpu; 'gpu' aliases the accelerator).

    Parity: reference `python/mxnet/context.py:23-141`.
    """

    devtype2str = {1: "cpu", 2: "gpu", 3: "cpu_pinned", 4: "cpu_shared", 5: "tpu"}
    devstr2type = {"cpu": 1, "gpu": 2, "cpu_pinned": 3, "cpu_shared": 4, "tpu": 5}
    _default_ctx = threading.local()

    def __init__(self, device_type, device_id=0):
        if isinstance(device_type, Context):
            self.device_typeid = device_type.device_typeid
            self.device_id = device_type.device_id
        else:
            self.device_typeid = Context.devstr2type[device_type]
            self.device_id = device_id
        self._old_ctx = None

    @property
    def device_type(self):
        return Context.devtype2str[self.device_typeid]

    @property
    def is_accelerator(self):
        return self.device_type in ("gpu", "tpu")

    def jax_device(self):
        """Resolve to a concrete jax.Device (accelerator if requested & present)."""
        if self.is_accelerator:
            accels = [d for d in jax.devices() if d.platform != "cpu"]
            if accels:
                return accels[self.device_id % len(accels)]
            # graceful fallback (e.g. CPU-only test mesh)
            return jax.devices()[self.device_id % len(jax.devices())]
        cpus = jax.devices("cpu") if any(
            d.platform == "cpu" for d in jax.local_devices()) else jax.devices()
        return cpus[self.device_id % len(cpus)]

    def __hash__(self):
        return hash((self.device_typeid, self.device_id))

    def __eq__(self, other):
        return (
            isinstance(other, Context)
            and self.device_typeid == other.device_typeid
            and self.device_id == other.device_id
        )

    def __str__(self):
        return "%s(%d)" % (self.device_type, self.device_id)

    def __repr__(self):
        return self.__str__()

    def __enter__(self):
        if not hasattr(Context._default_ctx, "value"):
            Context._default_ctx.value = Context("cpu", 0)
        self._old_ctx = Context._default_ctx.value
        Context._default_ctx.value = self
        return self

    def __exit__(self, ptype, value, trace):
        Context._default_ctx.value = self._old_ctx

    def empty_cache(self):  # parity: Context.empty_cache; XLA manages pools
        pass


Context._default_ctx.value = Context("cpu", 0)


def cpu(device_id=0):
    return Context("cpu", device_id)


def gpu(device_id=0):
    """Accepted for reference-script compatibility; aliases the accelerator."""
    return Context("gpu", device_id)


def tpu(device_id=0):
    return Context("tpu", device_id)


def cpu_pinned(device_id=0):
    return Context("cpu_pinned", device_id)


def num_gpus():
    """Number of accelerator chips visible (parity: mx.context.num_gpus)."""
    return len([d for d in jax.devices() if d.platform != "cpu"])


def num_tpus():
    return num_gpus()


def current_context():
    if not hasattr(Context._default_ctx, "value"):
        Context._default_ctx.value = Context("cpu", 0)
    return Context._default_ctx.value
