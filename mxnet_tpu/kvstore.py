"""KVStore: parameter synchronization.

Parity: reference `src/kvstore/` — types local/device/nccl/dist_sync/
dist_async/dist_device_sync (kvstore.cc:40-72), push/pull/row_sparse_pull
(python/mxnet/kvstore.py:158-307), server-side optimizer
(kvstore_dist_server.h:282), 2-bit gradient compression
(gradient_compression.h:37-127).

TPU-native redesign (SURVEY §5.8): there is no parameter server and no NCCL —
  * 'local'/'device': single-process aggregation; XLA async dispatch already
    overlaps the reduce with compute (the engine's priority-push capability).
  * 'tpu' (also accepted: 'nccl'): data-parallel over the chip mesh; the
    aggregate step is jit-compiled psum/all_reduce over jax devices. Inside a
    fused train step (gluon.Trainer/parallel.DataParallelStep) push/pull
    collapse into lax.psum over the ICI mesh.
  * 'dist_sync'/'dist_async'/'dist_device_sync': multi-host via
    jax.distributed; push = psum over the global mesh (DCN+ICI); 'async'
    semantics (Hogwild) are emulated by skipping the barrier — each host
    applies updates as they arrive (documented divergence: a synchronous
    mesh cannot reproduce truly unsynchronized PS clocks).
Server-side optimizer capability (set_optimizer) runs the optimizer inside
the store (sharded state), matching kvstore_dist_server.h:282-294.
2-bit gradient compression is implemented with the reference's error-feedback
residual algorithm in pure jnp (see _TwoBitCompressor).
"""
from __future__ import annotations

import os
import pickle

import numpy as np
import jax
import jax.numpy as jnp

from .base import MXNetError
from .ndarray import NDArray
from .ndarray.sparse import RowSparseNDArray
from . import optimizer as opt


class _TwoBitCompressor:
    """2-bit gradient quantization with error feedback.

    Parity: src/kvstore/gradient_compression.{h,cc} — values >= threshold
    quantize to +threshold, <= -threshold to -threshold, else 0; the
    quantization error is added to the next gradient (residual feedback).
    """

    def __init__(self, threshold=0.5):
        self.threshold = float(threshold)
        self.residual = {}

    def compress(self, key, grad):
        r = self.residual.get(key)
        g = grad if r is None else grad + r
        th = self.threshold
        q = jnp.where(g >= th, th, jnp.where(g <= -th, -th, 0.0)).astype(g.dtype)
        self.residual[key] = g - q
        return q


class KVStore:
    """Single-process store ('local'/'device') and base class."""

    def __init__(self, kv_type="local"):
        self.type = kv_type
        self._store = {}
        self._updater = None
        self._optimizer = None
        self._compressor = None
        self._str_keys = None

    # -- topology ----------------------------------------------------------
    @property
    def rank(self):
        return 0

    @property
    def num_workers(self):
        return 1

    # -- helpers ------------------------------------------------------------
    @staticmethod
    def _key_list(key, value):
        single = not isinstance(key, (list, tuple))
        keys = [key] if single else list(key)
        if single:
            values = [value]
        else:
            values = list(value)
        return keys, values

    @staticmethod
    def _merge_rowsparse(vlist):
        """Sparse-preserving reduce: see ndarray.sparse.merge_rowsparse."""
        from .ndarray.sparse import merge_rowsparse
        return merge_rowsparse(vlist)

    @staticmethod
    def _aggregate(vlist):
        """Sum a per-device list of values into one (the local reduce —
        parity: comm.h Reduce; on TPU XLA fuses/overlaps these adds)."""
        if not isinstance(vlist, (list, tuple)):
            return vlist
        if isinstance(vlist[0], RowSparseNDArray):
            if len(vlist) == 1:
                return vlist[0]
            return KVStore._merge_rowsparse(vlist)
        out = vlist[0]._data
        for v in vlist[1:]:
            out = out + v._data
        return NDArray(out, ctx=vlist[0]._ctx)

    # -- core API ------------------------------------------------------------
    def init(self, key, value):
        keys, values = self._key_list(key, value)
        for k, v in zip(keys, values):
            if k in self._store:
                continue
            if isinstance(v, RowSparseNDArray):
                self._store[k] = v
            else:
                self._store[k] = NDArray(v._data + 0, ctx=v._ctx)

    def push(self, key, value, priority=0):
        keys, values = self._key_list(key, value)
        for k, v in zip(keys, values):
            agg = self._aggregate(v)
            if self._compressor is not None and not isinstance(
                    agg, RowSparseNDArray):
                agg = NDArray(self._compressor.compress(k, agg._data))
            agg = self._reduce_global(agg, priority)
            if self._updater is not None:
                self._updater(self._resolve_key(k), agg, self._store[k])
            else:
                stored = self._store[k]
                if isinstance(stored, RowSparseNDArray) and \
                        isinstance(agg, RowSparseNDArray):
                    self._store[k] = self._merge_rowsparse([stored, agg])
                elif isinstance(stored, RowSparseNDArray) or \
                        isinstance(agg, RowSparseNDArray):
                    dense = (stored.todense()._data
                             if isinstance(stored, RowSparseNDArray)
                             else stored._data)
                    add = (agg.todense()._data
                           if isinstance(agg, RowSparseNDArray) else agg._data)
                    self._store[k] = RowSparseNDArray.from_dense(
                        NDArray(dense + add))
                else:
                    stored._data = stored._data + agg._data
                    stored._version += 1

    def pull(self, key, out=None, priority=0, ignore_sparse=True):
        keys, outs = self._key_list(key, out)
        for k, o in zip(keys, outs):
            stored = self._store[k]
            src = stored.todense() if isinstance(stored, RowSparseNDArray) \
                else stored
            if isinstance(o, (list, tuple)):
                for oo in o:
                    oo._data = src._data
                    oo._version += 1
            else:
                o._data = src._data
                o._version += 1

    def row_sparse_pull(self, key, out=None, priority=0, row_ids=None):
        """Pull only the requested rows (parity: kvstore.py:307 /
        kvstore_dist.h:437 — maps to a gather over the stored table)."""
        if row_ids is None:
            raise MXNetError("row_sparse_pull requires row_ids")
        if out is None and isinstance(key, (list, tuple)):
            keys, outs = list(key), [None] * len(key)
        else:
            keys, outs = self._key_list(key, out)
        if isinstance(row_ids, NDArray):
            row_ids = [row_ids] * len(keys)
        results = []
        for k, o, rid in zip(keys, outs, row_ids):
            stored = self._store[k]
            rsp = stored if isinstance(stored, RowSparseNDArray) else \
                RowSparseNDArray.from_dense(stored)
            if o is None:
                results.append(rsp.retain(rid))
                continue
            olist = o if isinstance(o, (list, tuple)) else [o]
            ridlist = rid if isinstance(rid, (list, tuple)) else [rid] * len(olist)
            for oo, rr in zip(olist, ridlist):
                ret = rsp.retain(rr)
                if isinstance(oo, RowSparseNDArray):
                    oo._indices = ret._indices
                    oo._values = ret._values
                else:
                    oo._data = ret.todense()._data
            results.append(o)
        if out is None:
            return results[0] if not isinstance(key, (list, tuple)) \
                else results

    # -- distributed hooks (overridden by the mesh-backed stores) -----------
    def _reduce_global(self, value, priority=0):
        return value

    def _resolve_key(self, k):
        return k

    # -- optimizer ----------------------------------------------------------
    def set_optimizer(self, optimizer):
        """Run the optimizer inside the store (parity: server-side optimizer,
        pickled to servers in kvstore.py:443-488)."""
        # round-trip through pickle like the reference to guarantee the
        # optimizer is serializable for multi-host use
        self._optimizer = pickle.loads(pickle.dumps(optimizer))
        self._updater = opt.get_updater(self._optimizer)

    def _set_updater(self, updater):
        self._updater = updater

    def set_gradient_compression(self, compression_params):
        ctype = compression_params.get("type", "2bit")
        if ctype != "2bit":
            raise MXNetError("unsupported compression type %s" % ctype)
        self._compressor = _TwoBitCompressor(
            compression_params.get("threshold", 0.5))

    # -- persistence / control ----------------------------------------------
    def save_optimizer_states(self, fname, dump_optimizer=False):
        if self._updater is None:
            raise MXNetError("there is no optimizer attached")
        with open(fname, "wb") as f:
            f.write(self._updater.get_states(dump_optimizer))

    def load_optimizer_states(self, fname):
        if self._updater is None:
            raise MXNetError("there is no optimizer attached")
        with open(fname, "rb") as f:
            self._updater.set_states(f.read())

    def barrier(self):
        pass

    def _send_command_to_servers(self, head, body):
        pass


class KVStoreTPU(KVStore):
    """Mesh-collective store: the reduce runs as a jitted all-sum over the
    visible chips (single-host) or the global mesh (multi-host). This is the
    KVStore('tpu') of BASELINE.json's north star; 'nccl' aliases here."""

    def __init__(self, kv_type="tpu"):
        super().__init__(kv_type)
        self.devices = jax.devices()
        self._reduce_jit = jax.jit(lambda xs: jax.tree.map(
            lambda *vs: sum(vs[1:], vs[0]), *xs)) if len(self.devices) > 1 else None

    @property
    def rank(self):
        return jax.process_index()

    @property
    def num_workers(self):
        return jax.process_count()

    def _reduce_global(self, value, priority=0):
        # single-process: per-device partial grads were already summed in
        # _aggregate; multi-host: psum over the process mesh
        if jax.process_count() > 1 and not isinstance(value, RowSparseNDArray):
            summed = _multihost_psum(value._data)
            return NDArray(summed, ctx=value._ctx)
        return value


def _bigarray_bound():
    """Element-count threshold above which cross-host transfers are chunked
    (parity: MXNET_KVSTORE_BIGARRAY_BOUND sharding big keys across servers,
    kvstore_dist.h:521 — here it bounds per-message allgather size)."""
    return int(os.environ.get("MXNET_KVSTORE_BIGARRAY_BOUND", 1000000))


def _multihost_psum(x):
    """All-reduce across hosts over ICI/DCN using the global process set.

    Arrays above MXNET_KVSTORE_BIGARRAY_BOUND elements are reduced in
    bounded chunks — the TPU-native analog of the reference splitting big
    keys across parameter servers so no single message/server sees the
    whole tensor.
    """
    from jax.experimental import multihost_utils
    bound = _bigarray_bound()
    if x.size <= bound:
        return multihost_utils.process_allgather(x).sum(axis=0)
    flat = x.reshape(-1)
    out = []
    for i in range(0, flat.size, bound):
        chunk = flat[i:i + bound]
        out.append(multihost_utils.process_allgather(chunk).sum(axis=0))
    return jnp.concatenate(out).reshape(x.shape)


def _multihost_rsp_sum(rsp, shape):
    """Cross-host sum of row-sparse values (parity: the dist kvstore's
    row_sparse key handling, kvstore_dist.h:437-476 — workers send only
    occupied rows; the merge scatter-adds them).

    Each worker pads its (indices, values) to the global max row count
    (one small allgather of counts first), allgathers both, and
    scatter-adds into the dense shape. Rows no worker touched stay zero.
    """
    from jax.experimental import multihost_utils
    idx = jnp.asarray(rsp._indices, dtype=jnp.int32)
    vals = rsp._values
    counts = multihost_utils.process_allgather(
        jnp.asarray([idx.shape[0]], dtype=jnp.int32))
    kmax = int(np.asarray(counts).max())
    pad = kmax - idx.shape[0]
    idx_p = jnp.pad(idx, (0, pad), constant_values=-1)
    vals_p = jnp.pad(vals, [(0, pad)] + [(0, 0)] * (vals.ndim - 1))
    gi = multihost_utils.process_allgather(idx_p).reshape(-1)
    gv = multihost_utils.process_allgather(vals_p).reshape(
        (-1,) + vals.shape[1:])
    mask = (gi >= 0).astype(gv.dtype).reshape((-1,) + (1,) * (gv.ndim - 1))
    dense = jnp.zeros(shape, dtype=gv.dtype)
    dense = dense.at[jnp.clip(gi, 0, shape[0] - 1)].add(gv * mask)
    return RowSparseNDArray.from_dense(NDArray(dense))


def _init_distributed():
    """Bring up jax.distributed from the launcher-provided environment.

    Parity: the reference worker reads DMLC_PS_ROOT_URI / DMLC_PS_ROOT_PORT /
    DMLC_NUM_WORKER / DMLC_WORKER_ID set by tools/launch.py and connects to
    the ps-lite scheduler (kvstore_dist.h:50). Here the same variables name
    the jax.distributed coordinator: process 0 hosts it, everyone connects
    over gRPC; collectives then ride gloo (CPU) or ICI/DCN (TPU).
    """
    num = int(os.environ.get("DMLC_NUM_WORKER", "1"))
    if num <= 1:
        return
    # NOTE: jax.process_count() would itself initialise the XLA backend,
    # which must not happen before jax.distributed.initialize — use the
    # distributed-state query, which does not touch the backend
    if jax.distributed.is_initialized():
        return
    uri = os.environ.get("DMLC_PS_ROOT_URI", "127.0.0.1")
    port = os.environ.get("DMLC_PS_ROOT_PORT", "9091")
    rank = int(os.environ.get("DMLC_WORKER_ID", "0"))
    try:
        # CPU multi-process collectives need gloo; harmless for TPU (the
        # flag only affects CPU client creation)
        jax.config.update("jax_cpu_collectives_implementation", "gloo")
    except Exception:
        pass
    try:
        jax.distributed.initialize(coordinator_address="%s:%s" % (uri, port),
                                   num_processes=num, process_id=rank)
    except RuntimeError as e:
        if "backend" in str(e).lower():
            raise MXNetError(
                "cannot join the distributed job: the XLA backend was "
                "already initialized before the dist kvstore was created. "
                "Create the kvstore (or import mxnet_tpu under "
                "tools/launch.py, which self-assembles at import) before "
                "any computation. Original error: %s" % e) from e
        raise  # connection/timeout errors keep their real cause


class KVStoreDist(KVStoreTPU):
    """dist_sync / dist_async / dist_device_sync over jax.distributed.

    Parity: kvstore_dist.h worker + kvstore_dist_server.h server collapsed
    into symmetric collectives; sync mode reduces with a barrier semantic
    (collectives are inherently synchronizing), async skips determinism by
    applying local updates immediately and folding remote contributions in
    at the next collective. The server-side optimizer (set_optimizer)
    becomes: every worker applies the optimizer to the identical global
    gradient sum, which reproduces the server's single authoritative update
    deterministically on all ranks.
    """

    def __init__(self, kv_type):
        _init_distributed()
        super().__init__(kv_type)
        self._sync = "async" not in kv_type

    def init(self, key, value):
        """Rank-0 value wins (parity: the first worker to init a key on the
        PS defines it; later inits are ignored)."""
        keys, values = self._key_list(key, value)
        if self.num_workers > 1:
            from jax.experimental import multihost_utils
            src = self.rank == 0
            bcast = []
            for v in values:
                if isinstance(v, RowSparseNDArray):
                    # shapes differ per rank: broadcast the rank-0 nnz
                    # first, then same-shaped (indices, values) buffers
                    n0 = int(multihost_utils.broadcast_one_to_all(
                        jnp.asarray([v._indices.shape[0]], jnp.int32))[0])
                    cols = v.shape[1:]
                    idx = v._indices if src else jnp.zeros((n0,), jnp.int32)
                    vals = v._values if src else \
                        jnp.zeros((n0,) + cols, v._values.dtype)
                    idx, vals = multihost_utils.broadcast_one_to_all(
                        (idx, vals))
                    bcast.append(RowSparseNDArray(idx, vals, v.shape,
                                                  ctx=v._ctx))
                else:
                    bcast.append(NDArray(
                        multihost_utils.broadcast_one_to_all(v._data),
                        ctx=v._ctx))
            values = bcast
        super().init(keys, values)

    def _reduce_global(self, value, priority=0):
        if self.num_workers <= 1:
            return value
        if isinstance(value, RowSparseNDArray):
            return _multihost_rsp_sum(value, value.shape)
        return NDArray(_multihost_psum(value._data), ctx=value._ctx)

    def barrier(self):
        if jax.process_count() > 1:
            from jax.experimental import multihost_utils
            multihost_utils.sync_global_devices("kvstore_barrier")


def create(name="local"):
    """Factory (parity: kvstore.cc:40-72 / python kvstore.py:628)."""
    if not isinstance(name, str):
        raise TypeError("name must be a string")
    if name in ("local", "local_update_cpu", "local_allreduce_cpu",
                "local_allreduce_device", "device"):
        return KVStore(name)
    if name in ("tpu", "nccl"):
        return KVStoreTPU(name)
    if name in ("dist_sync", "dist_async", "dist_device_sync", "dist"):
        return KVStoreDist(name)
    raise MXNetError("unknown KVStore type %s" % name)
