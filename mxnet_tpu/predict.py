"""Minimal inference/deployment path.

Parity: the reference's standalone predict ABI
(`include/mxnet/c_predict_api.h:78-200` — MXPredCreate from symbol JSON +
param bytes, SetInput/Forward/GetOutput/Reshape) and the amalgamation
single-artifact predict build (`amalgamation/mxnet_predict0.cc`).

TPU-native redesign: `Predictor` wraps a jitted inference executor;
`export_model` serializes the traced computation to portable **StableHLO**
via `jax.export` with the parameters baked in, packed in one `.mxtpu` zip.
`load_exported` runs that artifact through XLA alone — no symbol graph, op
registry, or parameter files needed at serving time (the amalgamation
capability, with the compiler as the runtime).
"""
from __future__ import annotations

import json
import zipfile

import numpy as np
import jax
import jax.export  # noqa: F401 — jax.export is not re-exported by `import jax`
import jax.numpy as jnp

from .base import MXNetError
from .ndarray import NDArray


def _load_param_payload(params):
    """Accept a dict of arrays, a .params path, or raw file bytes (the
    c_predict_api contract is a byte buffer, c_predict_api.h:96). Paths and
    bytes go through the one loader (bf16 tags, legacy format, list/dict
    duality all handled there)."""
    from .utils import serialization
    if isinstance(params, dict):
        return {k: (v if isinstance(v, NDArray) else NDArray(jnp.asarray(v)))
                for k, v in params.items()}
    loaded = serialization.load_ndarrays(params)
    if isinstance(loaded, list):
        raise MXNetError("the .params payload carries no names — a "
                         "predictor needs named parameters")
    return loaded


def _split_arg_aux(payload):
    from .utils.serialization import split_arg_aux
    # bare keys (plain npz saves) serve as arg params at predict time
    return split_arg_aux(payload, unprefixed="arg")


class Predictor:
    """Parity: MXPredCreate/MXPredSetInput/MXPredForward/MXPredGetOutput.

    Usage:
        pred = Predictor(open("m-symbol.json").read(), "m-0001.params",
                         {"data": (1, 3, 224, 224)})
        pred.set_input("data", x)      # or forward(data=x)
        pred.forward()
        out = pred.get_output(0)
    """

    def __init__(self, symbol_json, params, input_shapes, ctx=None):
        from . import symbol as sym_mod
        from .context import cpu
        sym = sym_mod.load_json(symbol_json) \
            if isinstance(symbol_json, str) else symbol_json
        self._sym = sym
        self._ctx = ctx or cpu()
        arg_params, aux_params = _split_arg_aux(_load_param_payload(params))
        self._input_names = [n for n in sym.list_arguments()
                             if n not in arg_params]
        missing = set(input_shapes) - set(self._input_names)
        if missing:
            raise MXNetError("input_shapes name(s) %s are bound parameters "
                             "or unknown" % sorted(missing))
        self._arg_params = arg_params
        self._aux_params = aux_params
        self._bind(dict(input_shapes))

    def _bind(self, input_shapes):
        self._input_shapes = input_shapes
        kwargs = dict(input_shapes)
        kwargs.update({k: v.shape for k, v in self._arg_params.items()})
        self._exec = self._sym.simple_bind(ctx=self._ctx, grad_req="null",
                                           **kwargs)
        self._exec.copy_params_from(self._arg_params, self._aux_params,
                                    allow_extra_params=True)
        self._inputs = {}

    def reshape(self, input_shapes):
        """Parity: MXPredReshape — rebind for new input shapes."""
        self._bind(dict(input_shapes))

    def set_input(self, name, value):
        if name not in self._input_names:
            raise MXNetError("unknown input %s (inputs: %s)"
                             % (name, self._input_names))
        # the caller's dtype is preserved — int inputs (token ids) must not
        # round-trip through float32
        v = value if isinstance(value, NDArray) else \
            NDArray(jnp.asarray(np.asarray(value)))
        self._inputs[name] = v

    def forward(self, **inputs):
        for n, v in inputs.items():
            self.set_input(n, v)
        self._exec.forward(is_train=False, **self._inputs)
        return self._exec.outputs

    def get_output(self, index=0):
        return self._exec.outputs[index]

    def predict(self, inputs):
        """Batch helper over the bound signature: see `batch_predict`."""
        name, sig = next(iter(self._input_shapes.items()))
        if len(self._input_shapes) != 1:
            raise MXNetError("predict(list) helps single-input models; "
                             "this predictor has inputs %s"
                             % sorted(self._input_shapes))
        return batch_predict(
            lambda x: self.forward(**{name: x})[0].asnumpy(), sig, inputs)


def batch_predict(forward, sig_shape, inputs):
    """Run a list of variable-length samples through a FIXED-shape
    forward: pad each sample to the signature (zeros), group into chunks
    of the signature batch, and trim outputs back per sample.

    `forward(x)` takes exactly `sig_shape` = (B, *rest) and returns one
    array (B, ...). Each sample may be shorter than `rest` along the
    FIRST feature axis (the ragged axis — token sequences); all other
    axes must match. Returns a list of per-sample outputs; when the
    output's axis 1 mirrors the padded ragged axis it is trimmed to the
    sample's true length, otherwise the row is returned whole.

    This replaces the old behavior (shape mismatch -> error) with the
    serving-friendly contract: any mix of lengths runs in
    ceil(len/B) fixed-shape calls — no recompiles, no rebinding.
    """
    B, rest = sig_shape[0], tuple(sig_shape[1:])
    arrs, lengths = [], []
    for i, s in enumerate(inputs):
        a = np.asarray(s)
        if a.shape == rest:
            arrs.append(a)
            lengths.append(rest[0] if rest else None)
            continue
        if not rest or a.ndim != len(rest) or a.shape[1:] != rest[1:] \
                or a.shape[0] > rest[0]:
            raise MXNetError(
                "sample %d shape %s doesn't fit signature %s (only the "
                "first feature axis may be shorter)"
                % (i, a.shape, (B,) + rest))
        pad = np.zeros(rest, a.dtype)
        pad[:a.shape[0]] = a
        arrs.append(pad)
        lengths.append(a.shape[0])
    outs = []
    for lo in range(0, len(arrs), B):
        chunk = arrs[lo:lo + B]
        batch = np.zeros((B,) + rest, chunk[0].dtype)
        for j, a in enumerate(chunk):
            batch[j] = a
        out = np.asarray(forward(batch))
        for j in range(len(chunk)):
            row = out[j]
            ln = lengths[lo + j]
            if ln is not None and row.ndim >= 1 and rest \
                    and row.shape[0] == rest[0]:
                row = row[:ln]
            outs.append(row)
    return outs


def quantize_lm_params(params, n_layers, mode="int8",
                       names=("wqkv", "wo", "w1", "w2")):
    """Quantize a transformer-LM parameter dict ONCE at load (ISSUE 20):
    per-output-channel symmetric int8 for each layer's 2-D matmul
    weights, each becoming a `{"q": int8, "s": f32-per-channel}` dict
    that `maybe_quant_matmul` consumes at serving time. Embeddings,
    positional table, layer norms, and the LM head stay f32 (small, and
    the final projection dominates the logit-error budget); 3-D MoE
    expert stacks stay f32 too. Returns a NEW dict — the caller keeps
    the f32 originals for the oracle / tp shard placement."""
    if str(mode) != "int8":
        raise MXNetError("weight quantization mode %r is not supported "
                         "(int8 or None)" % (mode,))
    from .ops.quantization import quantize_channelwise
    out = dict(params)
    for i in range(int(n_layers)):
        pre = "layer%d_" % i
        for name in names:
            w = out.get(pre + name)
            if w is None or getattr(w, "ndim", 0) != 2:
                continue
            q, s = quantize_channelwise(w, axis=1)
            out[pre + name] = {"q": q, "s": s}
    return out


def _pure_fn_from(model, params=None):
    """(fn(*raw_inputs) -> tuple of raw outputs, input_names)."""
    from .symbol import Symbol

    if isinstance(model, Symbol):
        arg_params, aux_params = _split_arg_aux(
            _load_param_payload(params or {}))
        input_names = [n for n in model.list_arguments()
                       if n not in arg_params]
        missing_aux = [n for n in model.list_auxiliary_states()
                       if n not in aux_params]
        if missing_aux:
            raise MXNetError("params payload is missing auxiliary state(s) "
                             "%s — export needs the trained aux values "
                             "('aux:<name>' entries)" % missing_aux)

        def fn(*xs):
            ex = model.bind(None, args=dict(
                {n: NDArray(x) for n, x in zip(input_names, xs)},
                **arg_params), grad_req="null", aux_states=aux_params)
            outs = ex.forward(is_train=False)
            return tuple(o._data for o in outs)

        return fn, input_names

    # Gluon block / callable: parameters are closed over as constants
    def fn(*xs):
        out = model(*[NDArray(x) for x in xs])
        if isinstance(out, (list, tuple)):
            return tuple(o._data for o in out)
        return (out._data,)

    return fn, None


def export_model(model, input_shapes, path, params=None,
                 input_dtypes=None):
    """Serialize `model` (Symbol + params, or an initialized Gluon block)
    to a standalone `.mxtpu` artifact: StableHLO bytes (params baked in as
    constants) + IO metadata. The artifact needs only jax/XLA to run.
    """
    shapes = list(input_shapes.items()) if isinstance(input_shapes, dict) \
        else list(input_shapes)
    dtypes = input_dtypes or {}
    fn, input_names = _pure_fn_from(model, params)
    if input_names is not None:
        shape_map = dict(shapes)
        missing = [n for n in input_names if n not in shape_map]
        extra = [n for n in shape_map if n not in input_names]
        if missing or extra:
            raise MXNetError(
                "input_shapes must name exactly the free inputs %s "
                "(missing: %s, unknown: %s)" % (input_names, missing, extra))
        shapes = [(n, shape_map[n]) for n in input_names]
    specs = [jax.ShapeDtypeStruct(tuple(s), jnp.dtype(
        dtypes.get(n, "float32"))) for n, s in shapes]
    from .telemetry import introspect
    with introspect.compile_region("predict.export", phase="export",
                                   path=str(path)):
        exported = jax.export.export(jax.jit(fn))(*specs)
    blob = exported.serialize()
    meta = {"inputs": [{"name": n, "shape": list(s),
                        "dtype": str(jnp.dtype(dtypes.get(n, "float32")))}
                       for n, s in shapes],
            "format": 1}
    # PJRT-facing entries for the C++ predictor (cpp-package/): the raw
    # StableHLO module bytecode PJRT_Client_Compile accepts, plus a
    # dependency-free one-line-per-tensor signature so C++ never parses
    # JSON or MLIR. zipfile defaults to STORE, which the C++ reader relies
    # on (predictor.cc rejects compressed entries).
    sig = ["in %s %s" % (_sig_dtype(a.dtype),
                         "x".join(str(d) for d in a.shape))
           for a in exported.in_avals]
    sig += ["out %s %s" % (_sig_dtype(a.dtype),
                           "x".join(str(d) for d in a.shape))
            for a in exported.out_avals]
    # atomic-rename publish (shared with the AOT executable cache): a
    # reader — or a serving fleet warm-booting off this artifact — never
    # observes a half-written zip
    from .aot import atomic_publish
    with atomic_publish(str(path)) as tmp:
        with zipfile.ZipFile(tmp, "w") as z:
            z.writestr("meta.json", json.dumps(meta))
            z.writestr("model.stablehlo", blob)
            z.writestr("model.mlir", exported.mlir_module_serialized)
            z.writestr("signature.txt", "\n".join(sig) + "\n")
    return path


def export_train_step(step, example_x, example_y, path):
    """Serialize a built `TrainStep` as a C++-drivable TRAINING artifact.

    Reference parity: the reference's cpp-package trains through
    Symbol/Executor bindings (cpp-package/include/mxnet-cpp/executor.h).
    TPU-native redesign: the whole fused train step (forward + backward +
    optimizer update) is ONE StableHLO program with training state
    threaded explicitly, so a dependency-free C++ loop
    (cpp-package mxtpu_train) can run real training against any PJRT
    plugin — no Python at train time.

    Artifact layout (on top of the export_model contract):
      signature.txt  in/out lines; inputs are [state..., x, y, seed, lr,
                     t] and outputs [loss, state...] (state chains:
                     output 1+i feeds input i of the next step)
      train.txt      "n_state <K>" — how many leading inputs are state
      state/<i>.bin  raw little-endian bytes of each state input's
                     initial value (the step's current state)
    """
    import numpy as _np

    if step._step_fn is None:
        step._build()
    if step._mesh is not None:
        raise MXNetError("export_train_step: mesh-sharded TrainSteps are "
                         "not exportable to the single-device C++ driver; "
                         "build the TrainStep without a mesh")
    xv = jnp.asarray(example_x._data if hasattr(example_x, "_data")
                     else example_x)
    yv = jnp.asarray(example_y._data if hasattr(example_y, "_data")
                     else example_y)
    grad_vals = tuple(step._grad_vals)
    nograd_vals = tuple(step._nograd_vals)
    opt_flat, opt_def = jax.tree.flatten(step._opt_state)
    n_g, n_n, n_o = len(grad_vals), len(nograd_vals), len(opt_flat)
    n_state = n_g + n_n + n_o
    state0 = list(grad_vals) + list(nograd_vals) + list(opt_flat)
    # the raw python step (pre-jit) — exporting through the donating jit
    # would bake donation into a calling convention the C++ driver then
    # has to honor; buffer reuse is the driver's decision, not the
    # artifact's
    raw_step = step._step_fn.__wrapped__

    def fn(*flat):
        state, rest = flat[:n_state], flat[n_state:]
        x, y, sd, lr, t = rest
        g = state[:n_g]
        n = state[n_g:n_g + n_n]
        o = jax.tree.unflatten(opt_def, state[n_g + n_n:])
        key = jax.random.PRNGKey(sd)
        # poison pinned to 0.0: the chaos grad-injection seam is a live
        # training concern, not part of the exported artifact. Guarded
        # steps also return (ok, gnorm); the artifact keeps the plain
        # (loss, state...) convention.
        out = raw_step(g, n, o, x, y, key, lr, t, jnp.float32(0.0))
        loss, g2, n2, o2 = out[:4]
        return (loss,) + tuple(g2) + tuple(n2) + \
            tuple(jax.tree.flatten(o2)[0])

    specs = [jax.ShapeDtypeStruct(jnp.shape(v), jnp.asarray(v).dtype)
             for v in state0]
    specs += [jax.ShapeDtypeStruct(xv.shape, xv.dtype),
              jax.ShapeDtypeStruct(yv.shape, yv.dtype),
              jax.ShapeDtypeStruct((), jnp.int32),    # seed
              jax.ShapeDtypeStruct((), jnp.float32),  # lr
              jax.ShapeDtypeStruct((), jnp.int32)]    # t
    from .telemetry import introspect
    with introspect.compile_region("predict.export", phase="export",
                                   path=str(path), train_step=True):
        exported = jax.export.export(jax.jit(fn))(*specs)
    sig = ["in %s %s" % (_sig_dtype(a.dtype),
                         "x".join(str(d) for d in a.shape))
           for a in exported.in_avals]
    sig += ["out %s %s" % (_sig_dtype(a.dtype),
                           "x".join(str(d) for d in a.shape))
            for a in exported.out_avals]
    meta = {"format": 1, "train": {"n_state": n_state, "n_grad": n_g,
                                   "n_nograd": n_n, "n_opt": n_o}}
    from .aot import atomic_publish
    with atomic_publish(str(path)) as tmp:
        with zipfile.ZipFile(tmp, "w") as z:
            z.writestr("meta.json", json.dumps(meta))
            z.writestr("model.stablehlo", exported.serialize())
            z.writestr("model.mlir", exported.mlir_module_serialized)
            z.writestr("signature.txt", "\n".join(sig) + "\n")
            z.writestr("train.txt", "n_state %d\n" % n_state)
            for i, v in enumerate(state0):
                z.writestr("state/%d.bin" % i, _np.asarray(v).tobytes())
    return path


def _sig_dtype(dt):
    """dtype -> the signature.txt/PJRT token (predictor.cc mirrors this).
    Unsupported dtypes fail HERE, at export — not at serving time."""
    name = jnp.dtype(dt).name
    token = {"float32": "f32", "float16": "f16", "float64": "f64",
             "bfloat16": "bf16", "int32": "s32", "int64": "s64",
             "int8": "s8", "uint8": "u8", "bool": "pred"}.get(name)
    if token is None:
        raise MXNetError(
            "export_model: dtype %s has no C++ predictor mapping (supported:"
            " f32/f16/f64/bf16/s32/s64/s8/u8/bool)" % name)
    return token


class ExportedPredictor:
    """Serving-side wrapper over a deserialized artifact — same predict
    surface, zero framework graph machinery."""

    def __init__(self, exported, meta):
        self._exported = exported
        self._meta = meta
        self._input_names = [i["name"] for i in meta["inputs"]]
        self._inputs = {}
        self._outputs = None

    @property
    def input_descs(self):
        return self._meta["inputs"]

    def set_input(self, name, value):
        if name not in self._input_names:
            raise MXNetError("unknown input %s" % name)
        self._inputs[name] = jnp.asarray(
            value._data if isinstance(value, NDArray) else np.asarray(value))

    def forward(self, **inputs):
        for n, v in inputs.items():
            self.set_input(n, v)
        unset = [n for n in self._input_names if n not in self._inputs]
        if unset:
            raise MXNetError("input(s) %s were never set" % unset)
        args = [self._inputs[n] for n in self._input_names]
        self._outputs = self._exported.call(*args)
        return [NDArray(o) for o in self._outputs]

    def get_output(self, index=0):
        if self._outputs is None:
            raise MXNetError("call forward() first")
        return NDArray(self._outputs[index])

    def predict(self, inputs):
        """Batch helper over the exported signature: see `batch_predict`.
        Variable-length samples pad/bucket into the artifact's fixed
        shape instead of erroring — every call replays the ONE compiled
        program."""
        if len(self._input_names) != 1:
            raise MXNetError("predict(list) helps single-input artifacts; "
                             "this one has inputs %s" % self._input_names)
        desc = self._meta["inputs"][0]
        sig = tuple(desc["shape"])

        def fwd(x):
            return np.asarray(self._exported.call(
                jnp.asarray(x, jnp.dtype(desc["dtype"])))[0])

        return batch_predict(fwd, sig, inputs)


def load_exported(path):
    """Load a `.mxtpu` artifact produced by export_model."""
    with zipfile.ZipFile(path) as z:
        meta = json.loads(z.read("meta.json"))
        blob = z.read("model.stablehlo")
    exported = jax.export.deserialize(blob)
    return ExportedPredictor(exported, meta)
