"""Utility subpackage: serialization, download, recovery, chaos, docs
helpers.

Parity: reference `python/mxnet/ndarray/utils.py` (save/load) and
`src/ndarray/ndarray.cc` legacy binary serialization — replaced by a
portable .npz-based container (see serialization.py). recovery.py and
chaos.py are the fault-tolerance subsystem (async checkpointing +
fault injection; see docs/FAULT_TOLERANCE.md).
"""
from . import serialization
from .serialization import save_ndarrays, load_ndarrays


def makedirs(d):
    import os
    os.makedirs(d, exist_ok=True)


def retry(fn, attempts=3, backoff=0.1, jitter=0.1, retry_on=(OSError,),
          on_retry=None, deadline_s=None):
    """Call `fn()` with exponential backoff on transient failures.

    attempts  total tries (>=1); the last failure re-raises.
    backoff   base delay in seconds; try i sleeps backoff * 2**i.
    jitter    fraction of the delay randomized (decorrelates a fleet of
              workers retrying the same overloaded endpoint).
    retry_on  exception class or tuple caught as retryable; anything
              else propagates immediately.
    on_retry  optional callback (exc, attempt_index) before each sleep —
              the logging/metrics hook.
    deadline_s  cap on the TOTAL seconds this call may spend sleeping
              between attempts (measured from entry on the monotonic
              clock). A sleep that would cross the deadline is clamped
              to the remainder; once the deadline is spent the current
              failure re-raises instead of retrying. The seam that lets
              a SIGTERM drain thread the PreemptionWatcher's
              `remaining_grace()` through checkpoint publish IO — the
              backoff can no longer sleep past MXNET_PREEMPT_GRACE_SECS
              and lose the final checkpoint. None = unbounded.

    Used by model-zoo downloads, the serving HTTP frontend's
    submit-on-QueueFull path, and `CheckpointManager._io_retry`;
    deliberately tiny so any transient-failure site can adopt it.
    """
    import random as _random
    import time as _time
    attempts = max(1, int(attempts))
    t0 = _time.monotonic()
    for i in range(attempts):
        try:
            return fn()
        except retry_on as e:
            if i == attempts - 1:
                raise
            remaining = None
            if deadline_s is not None:
                remaining = float(deadline_s) - (_time.monotonic() - t0)
                if remaining <= 0:
                    raise
            if on_retry is not None:
                on_retry(e, i)
            delay = backoff * (2 ** i)
            delay *= 1.0 + jitter * _random.random()
            if remaining is not None:
                delay = min(delay, remaining)
            if delay > 0:
                _time.sleep(delay)
