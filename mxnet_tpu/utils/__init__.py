"""Utility subpackage: serialization, download, docs helpers.

Parity: reference `python/mxnet/ndarray/utils.py` (save/load) and
`src/ndarray/ndarray.cc` legacy binary serialization — replaced by a
portable .npz-based container (see serialization.py).
"""
from . import serialization
from .serialization import save_ndarrays, load_ndarrays

def makedirs(d):
    import os
    os.makedirs(d, exist_ok=True)
