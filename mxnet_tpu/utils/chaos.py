"""Fault injection for the resilience test harness.

Nothing in a codebase that never *simulates* a failure can claim to
survive one. This module is the single place every injected fault flows
through: production code calls the tiny hook functions below (no-ops
when chaos is off), and tests / tools/chaos_train.py arm them either
programmatically (`configure(...)`) or via environment variables — the
env path is what lets a subprocess worker be faulted without any code
changes:

  MXNET_CHAOS_KILL_SAVE=<step>     hard-exit (os._exit) in the middle of
                                   the checkpoint write for <step>, after
                                   the temp file holds bytes but BEFORE
                                   the atomic publish — a preemption
                                   landing mid-save.
  MXNET_CHAOS_CORRUPT_CKPT=<step>  after checkpoint <step> publishes,
                                   truncate it to half its bytes (torn
                                   write / bitrot on restore).
  MXNET_CHAOS_NAN_STEP=<step>      poison step <step>'s gradients with
                                   NaN inside the jitted train step (the
                                   bad-step guard's quarry).
  MXNET_CHAOS_SIGTERM_AT=<step>    deliver SIGTERM to this process after
                                   step <step> completes (a preemption
                                   notice mid-epoch).
  MXNET_CHAOS_SIGKILL_AT=<step>    deliver SIGKILL to this process after
                                   step <step> completes — a host DYING
                                   with no drain, no checkpoint, no
                                   cleanup (the multi-host chaos drill
                                   kills one host of a pod this way).

Steps are 1-based and compare against the trainer's post-increment step
counter (`TrainStep._t`), i.e. the value `ResilientLoop` reports. Each
fault fires at most once per process (`_fired` latch) so a relaunched
worker with a stale environment does not re-kill itself — relaunch
scripts should still scrub `MXNET_CHAOS_*` when they can.
"""
from __future__ import annotations

import os
import signal


_FAULTS = ("kill_save", "corrupt_ckpt", "nan_step", "sigterm_at",
           "sigkill_at")

_conf = {}          # fault name -> step (int)
_fired = set()      # fault names that already triggered in this process
_env_loaded = False


def _load_env():
    global _env_loaded
    if _env_loaded:
        return
    _env_loaded = True
    for name in _FAULTS:
        val = os.environ.get("MXNET_CHAOS_" + name.upper())
        if val:
            try:
                _conf.setdefault(name, int(val))
            except ValueError:
                raise ValueError("MXNET_CHAOS_%s must be an integer step, "
                                 "got %r" % (name.upper(), val))


def configure(**faults):
    """Arm faults programmatically: configure(nan_step=7, sigterm_at=12).
    A value of None disarms. Returns the active config."""
    _load_env()
    for name, step in faults.items():
        if name not in _FAULTS:
            raise ValueError("unknown chaos fault %r (know %s)"
                             % (name, ", ".join(_FAULTS)))
        if step is None:
            _conf.pop(name, None)
            _fired.discard(name)
        else:
            _conf[name] = int(step)
    return dict(_conf)


def reset():
    """Disarm everything (test teardown)."""
    global _env_loaded
    _conf.clear()
    _fired.clear()
    _env_loaded = False


def active():
    _load_env()
    return dict(_conf)


def _should(name, step):
    _load_env()
    if name in _fired or _conf.get(name) != int(step):
        return False
    _fired.add(name)
    # the injected fault is itself a flight-recorder event: a post-mortem
    # timeline that cannot show the fault that caused it is useless
    from .. import telemetry
    telemetry.flight().record("fault", "chaos." + name, step=int(step))
    return True


# -- hooks (called from production code; no-ops when disarmed) --------------

def maybe_kill_during_save(step):
    """recovery.CheckpointManager._write calls this between writing the
    temp file and the atomic os.replace publish."""
    if _should("kill_save", step):
        os._exit(43)  # hard exit: no atexit, no flush — a real preemption


def maybe_corrupt_checkpoint(step, path):
    """recovery.CheckpointManager._write calls this after publishing
    ckpt for `step`; truncates the published file to half its size."""
    if _should("corrupt_ckpt", step):
        size = os.path.getsize(path)
        with open(path, "r+b") as f:
            f.truncate(max(1, size // 2))


def grad_poison(step):
    """TrainStep threads this scalar into the jitted step as `g + poison`
    on every gradient: 0.0 normally, NaN on the armed step. Passing it as
    a runtime argument keeps the injection retrace-free."""
    return float("nan") if _should("nan_step", step) else 0.0


def maybe_sigterm(step):
    """ResilientLoop calls this at each step boundary; delivers SIGTERM
    to this very process on the armed step — the preemption watcher must
    catch it, checkpoint, and exit with the relaunch code."""
    if _should("sigterm_at", step):
        os.kill(os.getpid(), signal.SIGTERM)
        return True
    return False


def maybe_sigkill(step):
    """ResilientLoop calls this at each step boundary; delivers SIGKILL
    on the armed step — uncatchable, so the process dies with NO drain
    checkpoint and NO cleanup. This is the dead-host fault of the
    multi-host chaos drill: the surviving hosts' next complete
    checkpoint step must exclude everything the dead host never
    published."""
    if _should("sigkill_at", step):
        os.kill(os.getpid(), signal.SIGKILL)
        return True
    return False
