"""Fault injection for the resilience test harness.

Nothing in a codebase that never *simulates* a failure can claim to
survive one. This module is the single place every injected fault flows
through: production code calls the tiny hook functions below (no-ops
when chaos is off), and tests / tools/chaos_train.py arm them either
programmatically (`configure(...)`) or via environment variables — the
env path is what lets a subprocess worker be faulted without any code
changes:

  MXNET_CHAOS_KILL_SAVE=<step>     hard-exit (os._exit) in the middle of
                                   the checkpoint write for <step>, after
                                   the temp file holds bytes but BEFORE
                                   the atomic publish — a preemption
                                   landing mid-save.
  MXNET_CHAOS_CORRUPT_CKPT=<step>  after checkpoint <step> publishes,
                                   truncate it to half its bytes (torn
                                   write / bitrot on restore).
  MXNET_CHAOS_NAN_STEP=<step>      poison step <step>'s gradients with
                                   NaN inside the jitted train step (the
                                   bad-step guard's quarry).
  MXNET_CHAOS_SIGTERM_AT=<step>    deliver SIGTERM to this process after
                                   step <step> completes (a preemption
                                   notice mid-epoch).
  MXNET_CHAOS_SIGKILL_AT=<step>    deliver SIGKILL to this process after
                                   step <step> completes — a host DYING
                                   with no drain, no checkpoint, no
                                   cleanup (the multi-host chaos drill
                                   kills one host of a pod this way).
  MXNET_CHAOS_SPIKE_STEP=<step>    poison step <step>'s gradients with a
                                   LARGE FINITE value (1e6) — the
                                   finite-but-wrong fault the anomaly
                                   detector (telemetry/anomaly.py)
                                   exists for: the NaN/Inf guard stays
                                   green while the grad norm explodes.
  MXNET_CHAOS_SLOW_HOST=<host>:<secs>[:<from_step>]
                                   sleep <secs> at EVERY step boundary
                                   (from <from_step>, default 1) on the
                                   process whose MXNET_HOST_ID equals
                                   <host> — the straggler fault.
                                   UNLATCHED (a straggler is slow every
                                   step); the first firing records one
                                   flight event.
  MXNET_CHAOS_SDC_AT=<host>:<step> flip the SDC parity probe's digest on
                                   the process whose MXNET_HOST_ID equals
                                   <host>, at the first probe with step
                                   >= <step> — silent data corruption: a
                                   finite-but-wrong result only the
                                   cross-host digest quorum
                                   (parallel/supervisor.py SDCProbe) can
                                   attribute to one chip.

SERVING faults (ISSUE 11; tools/chaos_serve.py drives them through a
multi-replica fleet) target one replica's serving loop and are keyed
`<replica>:<iteration>` — the loop iteration counter of THAT LMServer
instance, so a respawned replica re-counts from zero. A fault fires at
the FIRST opportunity with iteration >= the armed one (some hook sites
only run under load), then latches (serve_crash_loop excepted):

  MXNET_CHAOS_SERVE_KILL=<r>:<i>        raise inside replica r's serving
                                        loop at iteration i (outside the
                                        engine-fault isolation): the loop
                                        DIES — the thread-death fault the
                                        router's respawn path exists for.
  MXNET_CHAOS_SERVE_CRASH_LOOP=<r>:<i>  same, but NOT latched: every
                                        (re)spawned instance of replica r
                                        dies again at its iteration i —
                                        the crash loop that must open the
                                        respawn circuit breaker.
  MXNET_CHAOS_SERVE_WEDGE=<r>:<i>[:<s>] sleep s seconds (default 2.0)
                                        inside the loop: a stale beat
                                        with the thread alive — the
                                        drain-then-restore shape.
  MXNET_CHAOS_SERVE_POISON=<r>:<i>      poison one decode step (raises
                                        inside the engine-fault try):
                                        the batch's requests must be
                                        resumed, the loop must survive.
  MXNET_CHAOS_SERVE_SPEC_POISON=<r>:<i> NaN-fill one iteration's DRAFT
                                        logits on a speculating replica:
                                        the engine must degrade that
                                        batch to the non-speculative
                                        path, token-identical to the
                                        undisturbed oracle — no request
                                        fails, no resume is spent.
  MXNET_CHAOS_SERVE_EXHAUST=<r>:<i>[:<n>] steal every free block of the
                                        replica's pool for n loop
                                        iterations (default 20):
                                        transient exhaustion, requests
                                        queue instead of failing.
  MXNET_CHAOS_SERVE_ROLLOUT_CORRUPT=<step>:<file_index>
                                        bit-flip one byte mid-file in
                                        live-rollout candidate <step>'s
                                        payload file #<file_index>,
                                        AFTER its manifest published —
                                        bitrot landing between publish
                                        and canary, which the rollout
                                        verification / parity gate's
                                        digest probe must quarantine
                                        before any user traffic.
  MXNET_CHAOS_SERVE_ROLLOUT_SLOW_CANARY=<r>:<i>[:<secs>]
                                        sleep <secs> (default 0.05) on
                                        replica r's EVERY loop iteration
                                        >= i — a healthy-but-SLOW canary
                                        the rollout judge must roll back
                                        on per-replica SLO burn instead
                                        of promoting. UNLATCHED like
                                        slow_host; the first firing
                                        records one flight event.

Steps are 1-based and compare against the trainer's post-increment step
counter (`TrainStep._t`), i.e. the value `ResilientLoop` reports. Each
fault fires at most once per process (`_fired` latch, serve_crash_loop
excepted) so a relaunched worker with a stale environment does not
re-kill itself — relaunch scripts should still scrub `MXNET_CHAOS_*`
when they can.
"""
from __future__ import annotations

import os
import signal
import time


_FAULTS = ("kill_save", "corrupt_ckpt", "nan_step", "sigterm_at",
           "sigkill_at", "spike_step")

#: `<host>:<secs>[:<from_step>]` — per-step sleep on one emulated host
#: (parsed separately: the key is a HOST label, not a step)
_HOST_FAULTS = ("slow_host",)

#: `<host>:<step>` — faults targeting one host at one step (the key is
#: a HOST label + an integer step, unlike _HOST_FAULTS' float seconds)
_HOST_STEP_FAULTS = ("sdc_at",)

#: the finite gradient poison `spike_step` injects: big enough that the
#: EWMA z-score on the grad norm flags it unmissably, small enough that
#: squaring it in the norm stays finite (so the NaN/Inf guard does NOT
#: trip — that is the point: finite-but-wrong)
SPIKE_POISON = 1.0e6

#: serving faults: value is (replica, iteration[, extra]) — parsed from
#: "r:i[:x]" env strings or passed as tuples to configure()
_SERVE_FAULTS = ("serve_kill", "serve_crash_loop", "serve_wedge",
                 "serve_poison", "serve_spec_poison", "serve_exhaust",
                 "serve_rollout_corrupt", "serve_rollout_slow_canary")


class ChaosReplicaKilled(RuntimeError):
    """The injected serving-loop death (serve_kill / serve_crash_loop):
    raised from inside the loop, OUTSIDE the engine-fault isolation, so
    the loop's catch-all sees a dying thread exactly like a real bug."""

_conf = {}          # fault name -> step (int)
_fired = set()      # fault names that already triggered in this process
_env_loaded = False


def _parse_serve(name, val):
    """(replica, iteration[, extra]) out of an "r:i[:x]" string or a
    tuple/list; extra stays a float (wedge seconds / exhaust hold)."""
    if isinstance(val, (tuple, list)):
        parts = list(val)
    else:
        parts = str(val).split(":")
    if len(parts) not in (2, 3):
        raise ValueError(
            "%s must be <replica>:<iteration>[:<extra>], got %r"
            % (name, val))
    try:
        out = [int(parts[0]), int(parts[1])]
        if len(parts) == 3:
            out.append(float(parts[2]))
    except (TypeError, ValueError):
        raise ValueError(
            "%s must be <replica>:<iteration>[:<extra>], got %r"
            % (name, val))
    return tuple(out)


def _parse_host(name, val):
    """(host, secs[, from_step]) out of `<host>:<secs>[:<from_step>]`
    (host stays a string — MXNET_HOST_ID labels are strings)."""
    if isinstance(val, (tuple, list)):
        parts = list(val)
    else:
        parts = str(val).split(":")
    if len(parts) not in (2, 3):
        raise ValueError("%s must be <host>:<secs>[:<from_step>], got %r"
                         % (name, val))
    try:
        out = [str(parts[0]), float(parts[1])]
        if len(parts) == 3:
            out.append(int(parts[2]))
    except (TypeError, ValueError):
        raise ValueError("%s must be <host>:<secs>[:<from_step>], got %r"
                         % (name, val))
    return tuple(out)


def _parse_host_step(name, val):
    """(host, step) out of `<host>:<step>` (host stays a string —
    MXNET_HOST_ID labels are strings; step is a 1-based int)."""
    if isinstance(val, (tuple, list)):
        parts = list(val)
    else:
        parts = str(val).split(":")
    if len(parts) != 2:
        raise ValueError("%s must be <host>:<step>, got %r" % (name, val))
    try:
        return (str(parts[0]), int(parts[1]))
    except (TypeError, ValueError):
        raise ValueError("%s must be <host>:<step>, got %r" % (name, val))


def _load_env():
    global _env_loaded
    if _env_loaded:
        return
    _env_loaded = True
    for name in _FAULTS:
        val = os.environ.get("MXNET_CHAOS_" + name.upper())
        if val:
            try:
                _conf.setdefault(name, int(val))
            except ValueError:
                raise ValueError("MXNET_CHAOS_%s must be an integer step, "
                                 "got %r" % (name.upper(), val))
    for name in _SERVE_FAULTS:
        val = os.environ.get("MXNET_CHAOS_" + name.upper())
        if val:
            _conf.setdefault(name, _parse_serve(
                "MXNET_CHAOS_" + name.upper(), val))
    for name in _HOST_FAULTS:
        val = os.environ.get("MXNET_CHAOS_" + name.upper())
        if val:
            _conf.setdefault(name, _parse_host(
                "MXNET_CHAOS_" + name.upper(), val))
    for name in _HOST_STEP_FAULTS:
        val = os.environ.get("MXNET_CHAOS_" + name.upper())
        if val:
            _conf.setdefault(name, _parse_host_step(
                "MXNET_CHAOS_" + name.upper(), val))


def configure(**faults):
    """Arm faults programmatically: configure(nan_step=7, sigterm_at=12)
    or, for serving faults, configure(serve_kill=(replica, iteration)).
    A value of None disarms. Returns the active config."""
    _load_env()
    for name, step in faults.items():
        if name not in _FAULTS and name not in _SERVE_FAULTS \
                and name not in _HOST_FAULTS \
                and name not in _HOST_STEP_FAULTS:
            raise ValueError("unknown chaos fault %r (know %s)"
                             % (name, ", ".join(_FAULTS + _SERVE_FAULTS
                                                + _HOST_FAULTS
                                                + _HOST_STEP_FAULTS)))
        if step is None:
            _conf.pop(name, None)
            _fired.discard(name)
        elif name in _SERVE_FAULTS:
            _conf[name] = _parse_serve(name, step)
        elif name in _HOST_FAULTS:
            _conf[name] = _parse_host(name, step)
        elif name in _HOST_STEP_FAULTS:
            _conf[name] = _parse_host_step(name, step)
        else:
            _conf[name] = int(step)
    return dict(_conf)


def reset():
    """Disarm everything (test teardown)."""
    global _env_loaded
    _conf.clear()
    _fired.clear()
    _env_loaded = False


def active():
    _load_env()
    return dict(_conf)


def _should(name, step):
    _load_env()
    if name in _fired or _conf.get(name) != int(step):
        return False
    _fired.add(name)
    # the injected fault is itself a flight-recorder event: a post-mortem
    # timeline that cannot show the fault that caused it is useless
    from .. import telemetry
    telemetry.flight().record("fault", "chaos." + name, step=int(step))
    return True


# -- hooks (called from production code; no-ops when disarmed) --------------

def maybe_kill_during_save(step):
    """recovery.CheckpointManager._write calls this between writing the
    temp file and the atomic os.replace publish."""
    if _should("kill_save", step):
        # best-effort black box before dying: the fault event just
        # recorded (and the spans before it) reach the flight dir when
        # one is configured, so a crash-LOOPING worker still leaves a
        # postmortem trail. No-op without MXNET_FLIGHT_RECORDER_DIR.
        from .. import telemetry
        try:
            telemetry.flight().dump("chaos_kill")
        except Exception:
            pass
        os._exit(43)  # hard exit: no atexit, no flush — a real preemption


def maybe_corrupt_checkpoint(step, path):
    """recovery.CheckpointManager._write calls this after publishing
    ckpt for `step`; truncates the published file to half its size."""
    if _should("corrupt_ckpt", step):
        size = os.path.getsize(path)
        with open(path, "r+b") as f:
            f.truncate(max(1, size // 2))


def grad_poison(step):
    """TrainStep threads this scalar into the jitted step as `g + poison`
    on every gradient: 0.0 normally, NaN on the armed `nan_step`, a
    large FINITE value on the armed `spike_step` (the anomaly detector's
    quarry: the guard's finiteness check stays green while the grad norm
    explodes). Passing it as a runtime argument keeps the injection
    retrace-free."""
    if _should("nan_step", step):
        return float("nan")
    if _should("spike_step", step):
        return SPIKE_POISON
    return 0.0


def maybe_slow_host(step):
    """ResilientLoop calls this at each step boundary: an armed
    `slow_host` fault sleeps on the process whose MXNET_HOST_ID matches
    — one straggling host of an emulated pod. UNLATCHED (slow is a
    standing condition, not an event); the first firing records one
    flight event so the postmortem timeline names the injection."""
    _load_env()
    cfg = _conf.get("slow_host")
    if cfg is None or os.environ.get("MXNET_HOST_ID", "0") != cfg[0]:
        return False
    if int(step) < (cfg[2] if len(cfg) > 2 else 1):
        return False
    if "slow_host" not in _fired:
        _fired.add("slow_host")
        from .. import telemetry
        telemetry.flight().record("fault", "chaos.slow_host",
                                  host=cfg[0], secs=cfg[1],
                                  step=int(step))
    time.sleep(cfg[1])
    return True


def sdc_poison(step):
    """SDCProbe (parallel/supervisor.py) calls this with each probe's
    step: an armed `sdc_at` fault whose host matches this process's
    MXNET_HOST_ID returns True at the first probe with step >= the
    armed one (then latches) — the probe perturbs its computed values
    before digesting, emulating a chip that silently computes a
    finite-but-wrong answer. The digest flip is only attributable by
    the cross-host quorum; nothing else in the process misbehaves."""
    _load_env()
    cfg = _conf.get("sdc_at")
    if cfg is None or "sdc_at" in _fired:
        return False
    if os.environ.get("MXNET_HOST_ID", "0") != cfg[0] \
            or int(step) < cfg[1]:
        return False
    _fired.add("sdc_at")
    from .. import telemetry
    telemetry.flight().record("fault", "chaos.sdc_at", host=cfg[0],
                              step=int(step))
    return True


def maybe_sigterm(step):
    """ResilientLoop calls this at each step boundary; delivers SIGTERM
    to this very process on the armed step — the preemption watcher must
    catch it, checkpoint, and exit with the relaunch code."""
    if _should("sigterm_at", step):
        os.kill(os.getpid(), signal.SIGTERM)
        return True
    return False


def _should_serve(name, replica, iteration, latch=True):
    """Match one serving fault against (replica, loop iteration); fires
    at the FIRST opportunity with iteration >= the armed one (some hook
    sites only run under load — e.g. decode poison — so an exact-match
    iteration could slip past unconsumed). Latched like `_should` unless
    `latch=False` — the crash-loop fault re-fires for every respawned
    instance. Every firing lands in the flight recorder: the chaos
    drill's postmortem gate asserts each injected fault is on the
    merged timeline."""
    _load_env()
    cfg = _conf.get(name)
    if cfg is None or (latch and name in _fired):
        return None
    if int(replica) != cfg[0] or int(iteration) < cfg[1]:
        return None
    if latch:
        _fired.add(name)
    from .. import telemetry
    telemetry.flight().record("fault", "chaos." + name,
                              replica=int(replica), step=int(iteration))
    return cfg


def fired():
    """Fault names that have triggered in this process (drill/test
    observability; crash-loop firings are unlatched and not listed)."""
    return set(_fired)


def maybe_kill_serving_loop(replica, iteration):
    """LMServer's loop calls this every iteration, OUTSIDE the engine
    fault isolation: an armed serve_kill (one-shot) or serve_crash_loop
    (every instance of the replica, since a respawned LMServer restarts
    its iteration counter) raises — the loop dies like a real bug."""
    if _should_serve("serve_kill", replica, iteration):
        raise ChaosReplicaKilled(
            "chaos: serving loop of replica %r killed at iteration %d"
            % (replica, iteration))
    if _should_serve("serve_crash_loop", replica, iteration, latch=False):
        raise ChaosReplicaKilled(
            "chaos: replica %r crash-looping (dies every iteration %d)"
            % (replica, iteration))


def maybe_wedge_serving_loop(replica, iteration):
    """Armed serve_wedge: sleep inside the loop so the beat goes stale
    with the thread alive — the transient-stall shape the router must
    drain around and then RESTORE."""
    cfg = _should_serve("serve_wedge", replica, iteration)
    if cfg:
        time.sleep(cfg[2] if len(cfg) > 2 else 2.0)
        return True
    return False


def decode_poison(replica, iteration):
    """Armed serve_poison: the loop raises inside its decode try block,
    exercising the batch-fault path (requests resumed, loop alive)."""
    return _should_serve("serve_poison", replica, iteration) is not None


def spec_poison(replica, iteration):
    """Armed serve_spec_poison: the loop arms the engine's
    `chaos_spec_poison` flag for ONE iteration — the draft's logits
    come out NaN and the engine must degrade that batch to the
    non-speculative path (token-identical, `spec_fallbacks` counted),
    never emit from garbage."""
    return _should_serve("serve_spec_poison", replica,
                         iteration) is not None


def pool_exhaustion(replica, iteration):
    """Armed serve_exhaust: returns how many loop iterations the loop
    should hold the replica's entire free list hostage (0 = disarmed) —
    transient pool exhaustion, which must queue requests, not fail
    them."""
    cfg = _should_serve("serve_exhaust", replica, iteration)
    if cfg is None:
        return 0
    return int(cfg[2]) if len(cfg) > 2 else 20


def maybe_rollout_corrupt(step, files):
    """RolloutController's watcher calls this with each candidate
    step's published payload files BEFORE verifying them: an armed
    serve_rollout_corrupt=<step>:<file_index> bit-flips one byte in the
    middle of files[file_index % len(files)] — bitrot landing AFTER the
    manifest published, which the candidate verification (or the parity
    gate's digest probe) must catch and quarantine before any user
    request reaches the weights. Exact-step match, latched."""
    _load_env()
    cfg = _conf.get("serve_rollout_corrupt")
    if cfg is None or "serve_rollout_corrupt" in _fired:
        return False
    if int(step) != cfg[0] or not files:
        return False
    _fired.add("serve_rollout_corrupt")
    path = files[int(cfg[1]) % len(files)]
    size = os.path.getsize(path)
    with open(path, "r+b") as f:
        f.seek(size // 2)
        b = f.read(1)
        f.seek(size // 2)
        f.write(bytes([(b[0] ^ 0xFF) if b else 0xFF]))
    from .. import telemetry
    telemetry.flight().record("fault", "chaos.serve_rollout_corrupt",
                              step=int(step),
                              path=os.path.basename(path))
    return True


def rollout_slow_canary(replica, iteration):
    """LMServer's loop calls this every iteration: an armed
    serve_rollout_slow_canary=<r>:<i>[:<secs>] sleeps (default 0.05s)
    on replica r at EVERY iteration >= i — a canary whose weights are
    fine but whose latency is not, which the rollout judge must catch
    through its per-replica SLO burn and roll back instead of
    promoting. UNLATCHED like slow_host (slow is a standing condition);
    the first firing records one flight event."""
    _load_env()
    cfg = _conf.get("serve_rollout_slow_canary")
    if cfg is None:
        return False
    if int(replica) != cfg[0] or int(iteration) < cfg[1]:
        return False
    if "serve_rollout_slow_canary" not in _fired:
        _fired.add("serve_rollout_slow_canary")
        from .. import telemetry
        telemetry.flight().record(
            "fault", "chaos.serve_rollout_slow_canary",
            replica=int(replica), step=int(iteration))
    time.sleep(cfg[2] if len(cfg) > 2 else 0.05)
    return True


def maybe_sigkill(step):
    """ResilientLoop calls this at each step boundary; delivers SIGKILL
    on the armed step — uncatchable, so the process dies with NO drain
    checkpoint and NO cleanup. This is the dead-host fault of the
    multi-host chaos drill: the surviving hosts' next complete
    checkpoint step must exclude everything the dead host never
    published."""
    if _should("sigkill_at", step):
        os.kill(os.getpid(), signal.SIGKILL)
        return True
    return False
