"""Reference-format binary checkpoint import/export.

Parity: the reference's legacy NDArray container
(`src/ndarray/ndarray.cc:1583-1810` — list framing kMXAPINDArrayListMagic
0x112 + per-array V2/V1/V0 records) and its legacy symbol JSON
(`src/nnvm/legacy_json_util.cc` upgrade pass). This lets reference-trained
`.params` / `-symbol.json` artifacts load into the npz-native world, and
exports back for reference consumers.

Layout (all little-endian, dmlc::Stream conventions):
  file  := u64 magic=0x112, u64 reserved, vec<ndarray>, vec<string names>
  vec<T>:= u64 count, T*count; string := u64 len, bytes
  ndarray (V2, magic 0xF993FAC9 as u32):
    u32 magic, i32 stype, [storage_shape if sparse], shape, i32 dev_type,
    i32 dev_id, i32 type_flag, [i32 aux_type + aux_shape]*nad,
    raw data, raw aux data*nad
  shape := u32 ndim, i64*ndim        (V1 same; V0: magic IS ndim, u32 dims)
"""
from __future__ import annotations

import struct

import numpy as np

LIST_MAGIC = 0x112
V2_MAGIC = 0xF993FAC9
V1_MAGIC = 0xF993FAC8

# mshadow type flags (mshadow/base.h kFloat32..kInt64)
_DTYPES = {0: np.float32, 1: np.float64, 2: np.float16, 3: np.uint8,
           4: np.int32, 5: np.int8, 6: np.int64}
_FLAGS = {np.dtype(v): k for k, v in _DTYPES.items()}

# NDArrayStorageType (include/mxnet/ndarray.h:61-66); aux counts: row_sparse
# carries its row-index vector, csr carries indptr + indices
_STYPE_DEFAULT, _STYPE_ROW_SPARSE, _STYPE_CSR = 0, 1, 2
_NUM_AUX = {_STYPE_DEFAULT: 0, _STYPE_ROW_SPARSE: 1, _STYPE_CSR: 2}


class _Reader:
    def __init__(self, buf):
        self.buf = buf
        self.pos = 0

    def unpack(self, fmt):
        vals = struct.unpack_from("<" + fmt, self.buf, self.pos)
        self.pos += struct.calcsize("<" + fmt)
        return vals if len(vals) > 1 else vals[0]

    def unpack_many(self, fmt):
        """Always returns a tuple (unpack() collapses single values)."""
        vals = struct.unpack_from("<" + fmt, self.buf, self.pos)
        self.pos += struct.calcsize("<" + fmt)
        return vals

    def raw(self, n):
        out = self.buf[self.pos:self.pos + n]
        if len(out) != n:
            raise IOError("truncated legacy NDArray file")
        self.pos += n
        return out


def _read_shape(r):
    ndim = r.unpack("I")
    return list(r.unpack_many("%dq" % ndim)) if ndim else []


def _read_array_data(r, shape, type_flag):
    dt = np.dtype(_DTYPES[type_flag])
    n = int(np.prod(shape)) if shape else 1
    return np.frombuffer(r.raw(dt.itemsize * n), dtype=dt).reshape(shape)


def _read_one(r):
    """One NDArray record -> numpy array (sparse records densified)."""
    magic = r.unpack("I")
    if magic == V2_MAGIC:
        stype = r.unpack("i")
        nad = _NUM_AUX.get(stype)
        if nad is None:
            raise IOError("unknown storage type %d" % stype)
        sshape = _read_shape(r) if nad else None
        shape = _read_shape(r)
        if not shape:
            return None
        r.unpack("ii")  # context (dev_type, dev_id) — placement is ignored
        type_flag = r.unpack("i")
        aux = []
        for _ in range(nad):
            at = r.unpack("i")
            ash = _read_shape(r)
            aux.append((at, ash))
        data = _read_array_data(r, sshape if nad else shape, type_flag)
        aux_data = [_read_array_data(r, ash, at) for at, ash in aux]
        if stype == _STYPE_ROW_SPARSE:
            dense = np.zeros(shape, data.dtype)
            dense[aux_data[0].astype(np.int64)] = data
            return dense
        if stype == _STYPE_CSR:
            dense = np.zeros(shape, data.dtype)
            indptr = aux_data[0].astype(np.int64)
            indices = aux_data[1].astype(np.int64)
            for row in range(shape[0]):
                lo, hi = indptr[row], indptr[row + 1]
                dense[row, indices[lo:hi]] = data[lo:hi]
            return dense
        return data
    if magic == V1_MAGIC:
        shape = _read_shape(r)
    else:
        # V0: the magic word IS ndim; dims are u32. A plausible ndim bounds
        # the interpretation — anything larger is an unknown future format,
        # not a 4-billion-dimensional array
        ndim = magic
        if ndim > 32:
            raise IOError("unsupported NDArray record magic %#x" % magic)
        shape = list(r.unpack_many("%dI" % ndim)) if ndim else []
    if not shape:
        return None
    r.unpack("ii")  # context
    type_flag = r.unpack("i")
    return _read_array_data(r, shape, type_flag)


def is_legacy_ndarray_file(src):
    """True when `src` (a path or a byte buffer) starts with the reference
    list magic."""
    if isinstance(src, (bytes, bytearray)):
        head = bytes(src[:8])
    else:
        try:
            with open(src, "rb") as f:
                head = f.read(8)
        except OSError:
            return False
    return len(head) == 8 and struct.unpack("<Q", head)[0] == LIST_MAGIC


def load_legacy_ndarrays(src):
    """Read a reference .params file (path or byte buffer) ->
    dict[str, NDArray] (or list when the file carries no names)."""
    from ..ndarray import NDArray
    if isinstance(src, (bytes, bytearray)):
        r = _Reader(bytes(src))
    else:
        with open(src, "rb") as f:
            r = _Reader(f.read())
    header, _reserved = r.unpack("QQ")
    if header != LIST_MAGIC:
        raise IOError("not a legacy NDArray file (magic %#x)" % header)
    n = r.unpack("Q")
    arrays = [_read_one(r) for _ in range(n)]
    n_names = r.unpack("Q")
    names = [r.raw(r.unpack("Q")).decode() for _ in range(n_names)]
    if names and len(names) != len(arrays):
        raise IOError("invalid legacy NDArray file: %d names for %d arrays"
                      % (len(names), len(arrays)))
    wrapped = [None if a is None else NDArray(a) for a in arrays]
    if not names:
        return wrapped
    return dict(zip(names, wrapped))


def _write_shape(out, shape):
    out.append(struct.pack("<I", len(shape)))
    if shape:
        out.append(struct.pack("<%dq" % len(shape), *shape))


def save_legacy_ndarrays(fname, data):
    """Write dict/list of NDArrays in the reference V2 container so the
    artifacts load in the reference framework."""
    from ..ndarray import NDArray
    if isinstance(data, NDArray):
        data = [data]
    if isinstance(data, dict):
        names = list(data.keys())
        arrays = [data[k] for k in names]
    else:
        names, arrays = [], list(data)
    out = [struct.pack("<QQ", LIST_MAGIC, 0), struct.pack("<Q", len(arrays))]
    for a in arrays:
        npd = np.asarray(a.asnumpy() if hasattr(a, "asnumpy") else a)
        if npd.dtype not in _FLAGS:
            npd = npd.astype(np.float32)  # bf16 etc. have no legacy flag
        out.append(struct.pack("<Ii", V2_MAGIC, _STYPE_DEFAULT))
        _write_shape(out, npd.shape)
        out.append(struct.pack("<iii", 1, 0, _FLAGS[npd.dtype]))  # cpu(0)
        out.append(np.ascontiguousarray(npd).tobytes())
    out.append(struct.pack("<Q", len(names)))
    for nm in names:
        b = nm.encode()
        out.append(struct.pack("<Q", len(b)) + b)
    with open(fname, "wb") as f:
        f.write(b"".join(out))


# ---------------------------------------------------------------------------
# legacy symbol JSON
# ---------------------------------------------------------------------------


def upgrade_json(data):
    """Normalize a reference symbol-JSON dict to the modern layout (parity:
    src/nnvm/legacy_json_util.cc): op parameters move to 'attrs', 2-element
    inputs/heads pad to 3 elements.

    Era handling: oldest files keep op params in 'param' with node
    attributes (ctx_group, lr_mult, ...) in a separate 'attr' dict; the
    'attr'-era mixes both in one dict; modern files use 'attrs'. 'param'
    wins when present so node attributes never masquerade as op kwargs —
    the symbol loader additionally drops kwargs the op doesn't accept.
    """
    nodes = []
    for spec in data["nodes"]:
        spec = dict(spec)
        attrs = spec.pop("param", None)
        node_attr = {}
        if attrs is not None:
            # oldest era: 'attr' holds node attributes (ctx_group, lr_mult)
            # alongside the 'param' op kwargs — keep them as node attrs
            node_attr = dict(spec.pop("attr", None) or {})
        if attrs is None:
            attrs = spec.pop("attrs", None)
        if attrs is None:
            attrs = spec.pop("attr", None) or {}
        spec.pop("attrs", None)
        spec.pop("attr", None)
        spec["attrs"] = dict(attrs)
        spec["attr"] = node_attr
        spec["inputs"] = [list(i) + [0] * (3 - len(i))
                          for i in spec.get("inputs", [])]
        nodes.append(spec)
    heads = [list(h) + [0] * (3 - len(h)) for h in data["heads"]]
    return {"nodes": nodes, "heads": heads,
            "arg_nodes": data.get("arg_nodes", [])}
