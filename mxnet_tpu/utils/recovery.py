"""Failure recovery: asynchronous checkpointing with automatic resume.

Parity-and-beyond: the reference's recovery story is manual restart from
epoch checkpoints (SURVEY §5.3 — ps-lite liveness exists but there is no
elastic recovery or async checkpointing in-tree; `tools/kill-mxnet.py`
kills a job, the operator restarts it). This module EXCEEDS that: an
orbax-style CheckpointManager with

  * async saves — the host serializes on a background thread while the
    accelerator keeps training (device→host copy happens on the caller
    thread, write+fsync+rename off it);
  * atomic publication — write to a temp file then os.replace, so a
    preemption mid-save never corrupts the latest checkpoint;
  * retention — keep the newest `keep` checkpoints, prune older;
  * `restore_latest()` — the auto-resume entry a relaunched worker calls.

TrainStep integration: `TrainStep.state_dict()/load_state_dict()` capture
parameters, optimizer state, and the step counter, so
`manager.save(step.t, step.state_dict())` + `step.load_state_dict(...)`
is a complete resume.
"""
from __future__ import annotations

import os
import re
import threading

import numpy as np
import jax

_CKPT_RE = re.compile(r"^ckpt-(\d+)\.npz$")


class CheckpointManager:
    """Directory of ckpt-<step>.npz files with async atomic writes.

    Usage:
        mgr = CheckpointManager(dir, keep=3)
        for step in range(start, n):
            ...
            if step % 100 == 0:
                mgr.save(step, train_step.state_dict())
        # after a crash/preemption, the relaunched process:
        state = mgr.restore_latest()
        if state is not None:
            step0, tree = state
            train_step.load_state_dict(tree)
    """

    def __init__(self, directory, keep=3, async_save=True):
        self.directory = directory
        self.keep = keep
        self.async_save = async_save
        self._worker = None
        self._lock = threading.Lock()
        self._error = None
        os.makedirs(directory, exist_ok=True)

    # -- save ---------------------------------------------------------------
    def save(self, step, tree, block=False):
        """Snapshot `tree` (a dict of name -> array-like) at `step`.

        The device→host transfer happens here (values are frozen against
        further training); file IO runs on a background thread unless
        async_save=False or block=True.
        """
        self._raise_pending()
        host = {k: np.asarray(v) for k, v in _flatten(tree).items()}
        self.wait()  # one save at a time: bounded memory, no write races
        if self.async_save and not block:
            self._worker = threading.Thread(
                target=self._write, args=(step, host), daemon=True)
            self._worker.start()
        else:
            self._write(step, host)

    def _write(self, step, host):
        try:
            import io
            import zipfile
            final = os.path.join(self.directory, "ckpt-%d.npz" % step)
            tmp = final + ".tmp-%d" % os.getpid()
            with open(tmp, "wb") as f:
                # npz written by hand: np.savez(**host) would collide with
                # its own 'file'/'allow_pickle' parameter names for user
                # keys, and we need the fd for fsync anyway
                with zipfile.ZipFile(f, "w", zipfile.ZIP_STORED) as z:
                    for k, v in host.items():
                        buf = io.BytesIO()
                        np.lib.format.write_array(buf, np.asarray(v),
                                                  allow_pickle=False)
                        z.writestr(k + ".npy", buf.getvalue())
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, final)  # atomic publication
            self._prune()
        except Exception as e:  # surfaced on the next save()/wait()
            with self._lock:
                self._error = e

    def _prune(self):
        steps = sorted(self.all_steps())
        for s in steps[:-self.keep] if self.keep else []:
            try:
                os.remove(os.path.join(self.directory, "ckpt-%d.npz" % s))
            except OSError:
                pass

    def wait(self):
        """Block until the in-flight async save (if any) has published."""
        if self._worker is not None:
            self._worker.join()
            self._worker = None
        self._raise_pending()

    def _raise_pending(self):
        with self._lock:
            if self._error is not None:
                e, self._error = self._error, None
                raise e

    # -- restore ------------------------------------------------------------
    def all_steps(self):
        out = []
        for name in os.listdir(self.directory):
            m = _CKPT_RE.match(name)
            if m:
                out.append(int(m.group(1)))
        return sorted(out)

    def latest_step(self):
        steps = self.all_steps()
        return steps[-1] if steps else None

    def restore(self, step):
        path = os.path.join(self.directory, "ckpt-%d.npz" % step)
        archive = np.load(path, allow_pickle=False)
        return _unflatten({k: archive[k] for k in archive.files})

    def restore_latest(self):
        """(step, tree) of the newest intact checkpoint, or None. A
        corrupt file falls back (with a warning) to the previous one —
        only corruption-shaped errors are treated as fallback-able, so a
        systematic restore bug cannot silently become a cold start."""
        import warnings
        import zipfile
        for step in reversed(self.all_steps()):
            try:
                return step, self.restore(step)
            except (OSError, ValueError, zipfile.BadZipFile, EOFError) as e:
                warnings.warn("skipping corrupt checkpoint ckpt-%d.npz: %s"
                              % (step, e))
                continue
        return None


_SEP = "/"


def _flatten(tree, prefix=""):
    out = {}
    if isinstance(tree, dict):
        if not tree:
            out[prefix + "__ed__"] = np.zeros(0)  # empty-dict marker
        for k, v in tree.items():
            out.update(_flatten(v, prefix + str(k) + _SEP))
    elif isinstance(tree, (list, tuple)):
        if not tree:
            out[prefix + ("__et__" if isinstance(tree, tuple)
                          else "__el__")] = np.zeros(0)
        tag = "__t__" if isinstance(tree, tuple) else "__l__"
        for i, v in enumerate(tree):
            out.update(_flatten(v, prefix + tag + str(i) + _SEP))
    else:
        out[prefix.rstrip(_SEP)] = tree
    return out


def _unflatten(flat):
    root = {}
    for key, v in flat.items():
        parts = key.split(_SEP)
        node = root
        for p in parts[:-1]:
            node = node.setdefault(p, {})
        node[parts[-1]] = v

    def rebuild(node):
        if not isinstance(node, dict):
            return node
        keys = list(node.keys())
        if keys == ["__et__"]:
            return ()
        if keys == ["__el__"]:
            return []
        if keys == ["__ed__"]:
            return {}
        if keys and all(k.startswith(("__t__", "__l__")) for k in keys):
            tup = keys[0].startswith("__t__")
            items = sorted(((int(k[5:]), rebuild(v))
                            for k, v in node.items()))
            seq = [v for _, v in items]
            return tuple(seq) if tup else seq
        return {k: rebuild(v) for k, v in node.items()}

    return rebuild(root)
