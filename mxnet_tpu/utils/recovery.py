"""Failure recovery: asynchronous checkpointing with automatic resume.

Parity-and-beyond: the reference's recovery story is manual restart from
epoch checkpoints (SURVEY §5.3 — ps-lite liveness exists but there is no
elastic recovery or async checkpointing in-tree; `tools/kill-mxnet.py`
kills a job, the operator restarts it). This module EXCEEDS that: an
orbax-style CheckpointManager with

  * async saves — the host serializes on a background thread while the
    accelerator keeps training (device→host copy happens on the caller
    thread, write+fsync+rename off it);
  * atomic publication — write to a temp file then os.replace, then
    fsync the *directory* so the rename itself is durable across power
    loss (POSIX: a rename is only on disk once its directory entry is);
  * integrity manifest — every checkpoint publishes a sidecar
    `ckpt-<step>.manifest.json` carrying the npz's size + sha256 and the
    array-entry names; `restore()` verifies it before deserializing, so
    a truncated or bit-rotted file is detected up front and
    `restore_latest()` falls back to the previous intact checkpoint
    (per-array CRC32s inside the zip guard each entry during the read
    itself);
  * retention — keep the newest `keep` checkpoints, prune older;
  * `restore_latest()` — the auto-resume entry a relaunched worker calls;
  * single-writer protocol — in a multi-process (jax.distributed) run
    every process computes identical replicated state, so only process 0
    performs checkpoint IO; `save()` on other processes returns without
    touching the directory. The BARRIER POINT is `wait()`: call it on
    every process at the same program point (e.g. before exiting after a
    preemption) — on the writer it blocks until the checkpoint has
    published, on non-writers it is a cheap no-op, and when
    `jax.distributed` is initialized it then synchronizes all processes
    so no worker can exit (and be relaunched) before the checkpoint
    exists.
  * per-host SHARDED checkpoints — the pod-scale mode (ROADMAP item 4):
    when the state tree holds mesh-sharded `jax.Array`s (ZeRO-style
    optimizer-state sharding, tensor-parallel params), funnelling the
    full state through process 0 is both the scalability ceiling and
    the single point of failure. In sharded mode EVERY process writes
    `ckpt-<step>.shard<i>of<n>.npz` holding only the logical shards it
    owns (each distinct shard of each array is written exactly once
    globally; fully-replicated arrays round-robin across hosts so the
    bytes balance at ~total/n per host), plus a self-certifying per-host
    manifest (size + sha256 + the global index of every entry). Process
    0 additionally publishes the global `ckpt-<step>.manifest.json`
    recording the format, process count, mesh axes, every array's
    global shape/dtype/sharding spec, and the shard-file roster — the
    per-file sha256s live in the per-host manifests it points at, so no
    cross-host communication happens on the write path. `restore()`
    reassembles global logical arrays from whichever shard files cover
    them, which is what makes ELASTIC resume work: a relaunch onto a
    different process count (or a different mesh shape entirely) loads
    the same global arrays and re-places them under its own shardings
    (`TrainStep.load_state_dict` device_puts against the live mesh).
    Mode is selected automatically per save — a tree containing
    non-fully-addressable arrays must shard; `sharded=True/False`
    forces it, and `process_index`/`process_count` may be overridden to
    EMULATE a multi-host run from single-process workers (the
    jax.distributed-free chaos-drill fallback: each emulated host owns
    a contiguous block of the mesh's devices).

TrainStep integration: `TrainStep.state_dict()/load_state_dict()` capture
parameters, optimizer state, and the step counter, so
`manager.save(step.t, step.state_dict())` + `step.load_state_dict(...)`
is a complete resume. `parallel.resilient.ResilientLoop` layers the full
fault lifecycle (preemption watcher, bad-step policies, data cursor) on
top of this manager.

Fault injection: `utils.chaos` hooks fire inside `_write` when armed
(kill mid-save before publication, corrupt a published file) — the
chaos-test harness proves the atomicity/fallback claims above.
"""
from __future__ import annotations

import hashlib
import json
import os
import re
import threading
import time as _time

import numpy as np
import jax

from . import chaos as _chaos
from .. import telemetry


def _ckpt_metrics():
    """The checkpoint IO instruments, on the process-global registry
    (idempotent creation — every manager shares them)."""
    reg = telemetry.default_registry()
    return {
        "save_s": reg.histogram(
            "checkpoint_save_seconds",
            help="checkpoint write + atomic publish, per save"),
        "restore_s": reg.histogram(
            "checkpoint_restore_seconds",
            help="checkpoint verify + deserialize, per restore"),
        "bytes": reg.gauge(
            "checkpoint_bytes_per_host",
            help="bytes THIS host wrote for the last checkpoint "
                 "(sharded: only the shards it owns)"),
        "saves": reg.counter("checkpoint_saves_total",
                             help="checkpoints published by this host"),
        "restores": reg.counter("checkpoint_restores_total",
                                help="checkpoints restored"),
        "retries": reg.counter(
            "checkpoint_io_retries_total", flight=True,
            help="transient publish-IO failures retried with backoff"),
        "manifest_failures": reg.counter(
            "checkpoint_manifest_failures_total", flight=True,
            help="checkpoints skipped because manifest/shard "
                 "verification failed (corrupt or incomplete)"),
    }

_CKPT_RE = re.compile(r"^ckpt-(\d+)\.npz$")
_SHARD_RE = re.compile(r"^ckpt-(\d+)\.shard(\d+)of(\d+)\.npz$")


def _norm_index(idx, shape):
    """Normalize a shard index (tuple of slices) to a hashable, JSON-able
    ((start, stop), ...) against the array's global shape."""
    out = []
    for s, dim in zip(idx, shape):
        start, stop, _ = s.indices(dim)
        out.append((int(start), int(stop)))
    return tuple(out)


def _fsync_dir(path):
    """fsync a directory so a just-published rename survives power loss.
    Best-effort on platforms without O_DIRECTORY semantics."""
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


class CheckpointManager:
    """Directory of ckpt-<step>.npz files with async atomic writes.

    Usage:
        mgr = CheckpointManager(dir, keep=3)
        for step in range(start, n):
            ...
            if step % 100 == 0:
                mgr.save(step, train_step.state_dict())
        # after a crash/preemption, the relaunched process:
        state = mgr.restore_latest()
        if state is not None:
            step0, tree = state
            train_step.load_state_dict(tree)
    """

    def __init__(self, directory, keep=3, async_save=True,
                 process_index=None, process_count=None, sharded=None):
        self.directory = directory
        self.keep = keep
        self.async_save = async_save
        self._process_index = process_index
        self._process_count = process_count
        #: True = always shard, False = always single-writer, None = auto
        #: (shard iff the saved tree holds non-fully-addressable arrays).
        #: Overriding process_index/process_count past jax's own values
        #: EMULATES a multi-host run from independent single-process
        #: workers (the jax.distributed-free chaos-drill fallback).
        self._sharded = sharded
        #: bounded-backoff attempts for each filesystem publish operation
        #: (a transient NFS/GCS-fuse hiccup must not kill an async save)
        self.io_retries = 3
        #: optional () -> seconds-or-None deadline for publish-IO retry
        #: backoff. ResilientLoop points this at its PreemptionWatcher's
        #: `remaining_grace()`, so a SIGTERM drain's retry sleeps can
        #: never outlast MXNET_PREEMPT_GRACE_SECS and lose the final
        #: checkpoint to the grace-timer force-exit. None (or a callable
        #: returning None) = unbounded backoff.
        self.deadline_fn = None
        #: optional observers for the remediation supervisor
        #: (parallel/supervisor.py): `on_error(exc)` fires when a
        #: publish ultimately failed (after retries — the stored error
        #: still surfaces on the next save()/wait()), `on_success()`
        #: after a clean publish. Both best-effort, never raised into
        #: the writer thread.
        self.on_error = None
        self.on_success = None
        self._worker = None
        self._lock = threading.Lock()
        self._error = None
        self._metrics = _ckpt_metrics()
        if self.is_writer or sharded:
            os.makedirs(directory, exist_ok=True)

    @property
    def process_index(self):
        if self._process_index is None:
            try:
                self._process_index = jax.process_index()
            except Exception:
                self._process_index = 0
        return self._process_index

    @property
    def process_count(self):
        if self._process_count is None:
            try:
                self._process_count = jax.process_count()
            except Exception:
                self._process_count = 1
        return self._process_count

    @property
    def is_writer(self):
        """Single-writer protocol: only process 0 performs checkpoint IO
        (data-parallel state is replicated — every process holds the same
        values, so N writers would just race on the directory). In
        sharded mode every process writes its own shard file; process 0
        additionally owns the global manifest."""
        return self.process_index == 0

    # -- save ---------------------------------------------------------------
    def _resolve_sharded(self, flat):
        if self._sharded is not None:
            return bool(self._sharded)
        return any(isinstance(v, jax.Array) and not v.is_fully_addressable
                   for v in flat.values())

    def save(self, step, tree, block=False):
        """Snapshot `tree` (a dict of name -> array-like) at `step`.

        The device→host transfer happens here (values are frozen against
        further training); file IO runs on a background thread unless
        async_save=False or block=True. In single-writer mode this is a
        no-op on non-writer processes; in sharded mode (forced, or auto
        when the tree holds non-fully-addressable arrays) EVERY process
        copies out and writes only the shards it owns.
        """
        self._raise_pending()
        flat = _flatten(tree)
        if self._resolve_sharded(flat):
            os.makedirs(self.directory, exist_ok=True)
            host, entries, gmeta = self._extract_shards(step, flat)
            self.wait(_barrier=False)
            if self.async_save and not block:
                self._worker = threading.Thread(
                    target=self._write_sharded,
                    args=(step, host, entries, gmeta), daemon=True)
                self._worker.start()
            else:
                self._write_sharded(step, host, entries, gmeta)
            return
        if not self.is_writer:
            return

        def own(v):
            # the async writer must OWN every buffer: np.asarray on a jax
            # CPU array can alias the device buffer, which the next
            # (donating) train step then overwrites under the writer.
            # Arrays that already own their memory (TrainStep.state_dict
            # output) pass through — no second full-state memcpy.
            if isinstance(v, np.ndarray) and v.base is None:
                return v
            return np.array(v)

        host = {k: own(v) for k, v in flat.items()}
        self.wait(_barrier=False)  # one save at a time: bounded memory,
        if self.async_save and not block:  # no write races
            self._worker = threading.Thread(
                target=self._write, args=(step, host), daemon=True)
            self._worker.start()
        else:
            self._write(step, host)

    def _manifest_path(self, step):
        return os.path.join(self.directory, "ckpt-%d.manifest.json" % step)

    def _shard_basename(self, step, index=None):
        return "ckpt-%d.shard%dof%d" % (
            step, self.process_index if index is None else index,
            self.process_count)

    # -- sharded save: ownership plan + host extraction ---------------------
    def _device_owner_fn(self, devices):
        """Map a mesh device to the process that writes its shards.
        Real multi-host: the device's own process. Emulated multi-host
        (process_count overriding jax's): contiguous blocks of the
        device list, so emulated host i stands in for the i-th slice of
        a real pod."""
        n = self.process_count
        try:
            real = jax.process_count()
        except Exception:
            real = 1
        if n == real:
            return lambda d: d.process_index
        order = {d: i for i, d in
                 enumerate(sorted(devices, key=lambda d: d.id))}
        ndev = len(order)
        return lambda d: (order[d] * n) // ndev

    def _extract_shards(self, step, flat):
        """Host-copy every entry THIS process owns (synchronously — the
        next train step donates the device buffers) and build the
        per-host + global manifest metadata. The ownership plan is a
        pure function of the tree's shardings, so every process computes
        the same global plan without communicating:

          * a mesh-sharded array's distinct logical shards each get
            exactly one writer (the process holding that shard; replica
            groups rotate deterministically for balance);
          * fully-replicated / host-local leaves round-robin whole
            arrays across processes, so checkpoint bytes land at
            ~total/n per host instead of all on process 0.
        """
        me, n = self.process_index, self.process_count
        host, entries, arrays = {}, {}, {}
        mesh_axes = None
        for seq, key in enumerate(sorted(flat)):
            v = flat[key]
            groups = imap = None
            if isinstance(v, jax.Array):
                sharding = getattr(v, "sharding", None)
                if sharding is not None:
                    mesh = getattr(sharding, "mesh", None)
                    if mesh_axes is None and mesh is not None and \
                            getattr(mesh, "shape", None):
                        mesh_axes = {str(a): int(s)
                                     for a, s in dict(mesh.shape).items()}
                    try:
                        imap = sharding.devices_indices_map(v.shape)
                    except Exception:
                        imap = None
                    if imap and len(imap) > 1:
                        groups = {}
                        for d, idx in imap.items():
                            groups.setdefault(_norm_index(idx, v.shape),
                                              []).append(d)
            if groups and len(groups) > 1:
                spec = getattr(v.sharding, "spec", None)
                idx_sorted = sorted(groups)
                arrays[key] = {"shape": [int(s) for s in v.shape],
                               "dtype": str(np.dtype(v.dtype)),
                               "spec": None if spec is None else str(spec),
                               "shards": len(idx_sorted)}
                owner_of = self._device_owner_fn(list(imap.keys()))
                local = None
                for j, idx in enumerate(idx_sorted):
                    devs = sorted(groups[idx], key=lambda d: d.id)
                    # replicas of one logical shard rotate by (array,
                    # shard) so replicated-over-an-axis state balances
                    owner = owner_of(devs[(seq + j) % len(devs)])
                    if owner != me:
                        continue
                    if local is None:
                        local = {_norm_index(sh.index, v.shape): sh
                                 for sh in v.addressable_shards}
                    entry = "%s@s%d" % (key, j)
                    host[entry] = np.array(local[idx].data)
                    entries[entry] = {"key": key,
                                      "index": [list(p) for p in idx]}
            else:
                dt = v.dtype if hasattr(v, "dtype") else np.asarray(v).dtype
                arrays[key] = {"shape": [int(s) for s in np.shape(v)],
                               "dtype": str(np.dtype(dt)),
                               "spec": None, "shards": 1}
                if seq % n == me:
                    host[key] = np.array(v)
                    entries[key] = {"key": key, "index": None}
        gmeta = {"step": int(step), "format": "sharded",
                 "process_count": n,
                 "mesh": {"axes": mesh_axes or {}},
                 "files": [self._shard_basename(step, i) + ".npz"
                           for i in range(n)],
                 "arrays": arrays,
                 "note": "per-file sha256: each shard's .manifest.json "
                         "sidecar certifies its own file"}
        return host, entries, gmeta

    # -- IO primitives (each publish operation retries transients) ----------
    def _io_retry(self, fn):
        from mxnet_tpu.utils import retry
        deadline = self.deadline_fn() if self.deadline_fn else None
        return retry(fn, attempts=self.io_retries, backoff=0.05,
                     jitter=0.5, retry_on=OSError, deadline_s=deadline,
                     on_retry=lambda e, i: self._metrics["retries"].inc(
                         error=str(e), attempt=i))

    def _write_npz(self, path, host):
        import io
        import zipfile

        def go():
            with open(path, "wb") as f:
                # npz written by hand: np.savez(**host) would collide
                # with its own 'file'/'allow_pickle' parameter names for
                # user keys, and we need the fd for fsync anyway
                with zipfile.ZipFile(f, "w", zipfile.ZIP_STORED) as z:
                    for k, v in host.items():
                        buf = io.BytesIO()
                        np.lib.format.write_array(buf, np.asarray(v),
                                                  allow_pickle=False)
                        z.writestr(k + ".npy", buf.getvalue())
                f.flush()
                os.fsync(f.fileno())
        self._io_retry(go)

    def _sha_size(self, path):
        digest = hashlib.sha256()
        with open(path, "rb") as f:
            for block in iter(lambda: f.read(1 << 20), b""):
                digest.update(block)
        return digest.hexdigest(), os.path.getsize(path)

    def _publish_json(self, obj, final_path):
        tmp = final_path + ".tmp-%d" % os.getpid()

        def go():
            with open(tmp, "w") as f:
                json.dump(obj, f)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, final_path)
        self._io_retry(go)

    def _write(self, step, host):
        try:
            with telemetry.span("ckpt.write", category="ckpt", step=step):
                final = os.path.join(self.directory, "ckpt-%d.npz" % step)
                tmp = final + ".tmp-%d" % os.getpid()
                t0 = _time.perf_counter()
                self._write_npz(tmp, host)
                sha, size = self._sha_size(tmp)
                manifest = {"step": int(step),
                            "file": os.path.basename(final),
                            "size": size,
                            "sha256": sha,
                            "arrays": sorted(host.keys())}
                _chaos.maybe_kill_during_save(step)
                self._io_retry(lambda: os.replace(tmp, final))  # atomic
                self._publish_json(manifest, self._manifest_path(step))
                # rename durability: the publication is only real once
                # the directory entry itself is on disk
                _fsync_dir(self.directory)
                self._metrics["save_s"].observe(_time.perf_counter() - t0)
                self._metrics["bytes"].set(size)
                self._metrics["saves"].inc()
                _chaos.maybe_corrupt_checkpoint(step, final)
                self._prune()
            self._notify(True)
        except Exception as e:  # surfaced on the next save()/wait()
            with self._lock:
                self._error = e
            self._notify(False, e)

    def _notify(self, ok, exc=None):
        """Best-effort publish-outcome observers (the remediation
        supervisor's consecutive-failure signal); a raising callback
        must never poison the writer thread."""
        cb = self.on_success if ok else self.on_error
        if cb is None:
            return
        try:
            if ok:
                cb()
            else:
                cb(exc)
        except Exception:
            pass

    def _write_sharded(self, step, host, entries, gmeta):
        try:
            with telemetry.span("ckpt.write_sharded", category="ckpt",
                                step=step,
                                process_index=self.process_index):
                base = self._shard_basename(step)
                final = os.path.join(self.directory, base + ".npz")
                tmp = final + ".tmp-%d" % os.getpid()
                t0 = _time.perf_counter()
                self._write_npz(tmp, host)
                sha, size = self._sha_size(tmp)
                _chaos.maybe_kill_during_save(step)
                self._io_retry(lambda: os.replace(tmp, final))
                manifest = {"step": int(step), "file": base + ".npz",
                            "size": size, "sha256": sha,
                            "process_index": self.process_index,
                            "process_count": self.process_count,
                            "entries": entries}
                self._publish_json(manifest,
                                   os.path.join(self.directory,
                                                base + ".manifest.json"))
                if self.is_writer:
                    self._publish_json(gmeta, self._manifest_path(step))
                _fsync_dir(self.directory)
                self._metrics["save_s"].observe(_time.perf_counter() - t0)
                self._metrics["bytes"].set(size)
                self._metrics["saves"].inc()
                _chaos.maybe_corrupt_checkpoint(step, final)
                self._prune()
            self._notify(True)
        except Exception as e:  # surfaced on the next save()/wait()
            with self._lock:
                self._error = e
            self._notify(False, e)

    def _prune(self):
        steps = sorted(self.all_steps())
        for s in steps[:-self.keep] if self.keep else []:
            names = [self._shard_basename(s) + ".npz",
                     self._shard_basename(s) + ".manifest.json"]
            if self.is_writer:
                names += ["ckpt-%d.npz" % s,
                          os.path.basename(self._manifest_path(s))]
                # the writer also sweeps shard files of OTHER process
                # counts (an elastic relaunch must not leak the old
                # world's files forever)
                prefix = "ckpt-%d.shard" % s
                try:
                    names += [nm for nm in os.listdir(self.directory)
                              if nm.startswith(prefix)]
                except OSError:
                    pass
            for nm in set(names):
                try:
                    os.remove(os.path.join(self.directory, nm))
                except OSError:
                    pass

    def wait(self, _barrier=True):
        """Block until the in-flight async save (if any) has published.

        This is the multi-process BARRIER POINT: every process calls it
        at the same program point; when jax.distributed is active the
        processes then synchronize, so none can proceed (or exit for
        relaunch) before process 0's checkpoint is durably on disk."""
        if self._worker is not None:
            self._worker.join()
            self._worker = None
        self._raise_pending()
        if _barrier:
            try:
                nproc = jax.process_count()
            except Exception:
                nproc = 1
            if nproc > 1:
                from jax.experimental import multihost_utils
                multihost_utils.sync_global_devices("mxtpu-ckpt-wait")

    def _raise_pending(self):
        with self._lock:
            if self._error is not None:
                e, self._error = self._error, None
                raise e

    # -- restore ------------------------------------------------------------
    def all_steps(self):
        out = set()
        try:
            names = os.listdir(self.directory)
        except OSError:
            return []
        for name in names:
            m = _CKPT_RE.match(name) or _SHARD_RE.match(name)
            if m:
                out.add(int(m.group(1)))
        return sorted(out)

    def latest_step(self):
        steps = self.all_steps()
        return steps[-1] if steps else None

    def _verify_manifest(self, step, path):
        """Integrity gate before deserialization. A missing manifest is
        tolerated (pre-manifest checkpoints stay restorable); a corrupt
        or mismatching one raises ValueError, which restore_latest()
        treats as corruption-shaped (falls back to an older step)."""
        mpath = self._manifest_path(step)
        if not os.path.exists(mpath):
            return
        with open(mpath) as f:
            manifest = json.load(f)  # corrupt JSON -> ValueError
        if not isinstance(manifest, dict) or "sha256" not in manifest:
            raise ValueError("manifest %s is missing the checksum" % mpath)
        sha, size = self._sha_size(path)
        if manifest.get("size") not in (None, size):
            raise ValueError(
                "checkpoint ckpt-%d.npz is %d bytes but its manifest "
                "recorded %d — truncated write" % (step, size,
                                                   manifest["size"]))
        if sha != manifest["sha256"]:
            raise ValueError("checkpoint ckpt-%d.npz fails its manifest "
                             "sha256 — corrupt" % step)

    def global_manifest(self, step):
        """The step's global manifest dict, or None when absent. A
        sharded step's manifest carries format/process_count/mesh/arrays
        (the metadata elastic resume validates against)."""
        mpath = self._manifest_path(step)
        if not os.path.exists(mpath):
            return None
        with open(mpath) as f:
            g = json.load(f)  # corrupt JSON -> ValueError
        if not isinstance(g, dict):
            raise ValueError("manifest %s is not an object" % mpath)
        return g

    def _verify_shard(self, path):
        """Integrity gate for one shard file: its sidecar manifest must
        exist and its size + sha256 must match. Returns the manifest."""
        mpath = path[:-len(".npz")] + ".manifest.json"
        if not os.path.exists(mpath):
            raise ValueError("shard %s has no sidecar manifest" % path)
        with open(mpath) as f:
            m = json.load(f)
        if not isinstance(m, dict) or "sha256" not in m:
            raise ValueError("shard manifest %s is missing the checksum"
                             % mpath)
        size = os.path.getsize(path)
        if m.get("size") not in (None, size):
            raise ValueError("shard %s is %d bytes but its manifest "
                             "recorded %d — truncated write"
                             % (path, size, m["size"]))
        sha, _ = self._sha_size(path)
        if sha != m["sha256"]:
            raise ValueError("shard %s fails its manifest sha256 — corrupt"
                             % path)
        return m

    def _verify_step(self, step):
        """Raise ValueError/OSError when `step` is not fully intact ON
        THIS HOST'S VIEW of the directory: for a sharded step EVERY
        shard file in the global manifest's roster must exist and verify
        (a host that died mid-save leaves the step incomplete — it must
        not be chosen), for a single-file step the existing manifest
        check applies."""
        g = self.global_manifest(step)
        if g is not None and g.get("format") == "sharded":
            for fname in g.get("files", []):
                path = os.path.join(self.directory, fname)
                if not os.path.exists(path):
                    raise ValueError(
                        "sharded checkpoint step %d is missing %s — "
                        "incomplete save (a host died before publishing)"
                        % (step, fname))
                self._verify_shard(path)
            return
        path = os.path.join(self.directory, "ckpt-%d.npz" % step)
        if not os.path.exists(path):
            raise ValueError("step %d has shard files but no global "
                             "manifest — the manifest writer never "
                             "published" % step)
        self._verify_manifest(step, path)

    def step_files(self, step):
        """Every on-disk file belonging to `step` (single-file npz +
        manifest, every shard npz + sidecar, the global manifest) that
        currently exists — the demotion/audit unit."""
        step = int(step)
        out = []
        try:
            names = os.listdir(self.directory)
        except OSError:
            return out
        single = "ckpt-%d.npz" % step
        shard_prefix = "ckpt-%d.shard" % step
        manifest = os.path.basename(self._manifest_path(step))
        for name in sorted(names):
            if name == single or name == manifest \
                    or (name.startswith(shard_prefix)
                        and (name.endswith(".npz")
                             or name.endswith(".manifest.json"))):
                out.append(os.path.join(self.directory, name))
        return out

    def demote(self, step, reason=""):
        """Take `step` out of the restorable set by renaming every one
        of its files with a `.corrupt` suffix (kept on disk as evidence,
        invisible to `all_steps()`/`restore_latest()`). The background
        checkpoint auditor (parallel/supervisor.py) calls this when a
        PUBLISHED checkpoint later fails its manifest re-verification —
        bit-rot or a torn write between save and the restore that would
        have needed it. Returns the renamed paths."""
        renamed = []
        for path in self.step_files(step):
            try:
                os.replace(path, path + ".corrupt")
                renamed.append(path)
            except OSError:
                continue
        if renamed:
            _fsync_dir(self.directory)
            self._metrics["manifest_failures"].inc(step=int(step),
                                                   error=reason
                                                   or "demoted")
            telemetry.flight().record(
                "event", "train.ckpt_demoted", step=int(step),
                reason=str(reason)[:200], files=len(renamed))
        return renamed

    def intact_steps(self):
        """Steps whose checkpoints fully verify on this host (sharded:
        every shard file present + checksummed). Corrupt/incomplete
        steps are skipped with a warning."""
        import warnings
        import zipfile
        out = []
        for step in self.all_steps():
            try:
                self._verify_step(step)
                out.append(step)
            except (OSError, ValueError, zipfile.BadZipFile, EOFError,
                    KeyError) as e:
                self._metrics["manifest_failures"].inc(step=step,
                                                       error=str(e))
                warnings.warn("skipping corrupt checkpoint step %d: %s"
                              % (step, e))
        return out

    def _common_steps(self, steps):
        """Multi-process agreement: the set of steps intact on EVERY
        host. Without this, each host independently falls back past its
        own corrupt files and different hosts can deserialize different
        'latest intact' steps — mixed-step replicas. No-op when jax runs
        single-process (the emulated-multi-host drill shares one
        directory, so per-host views already agree)."""
        try:
            nproc = jax.process_count()
        except Exception:
            nproc = 1
        if nproc <= 1:
            return list(steps)
        from jax.experimental import multihost_utils
        mine = np.asarray(sorted(steps), np.int64)
        width = int(np.asarray(multihost_utils.process_allgather(
            np.int64(mine.size))).max())
        pad = np.full(max(width, 1), -1, np.int64)
        pad[:mine.size] = mine
        rows = np.asarray(multihost_utils.process_allgather(pad))
        common = set(int(s) for s in rows[0] if s >= 0)
        for r in rows[1:]:
            common &= set(int(s) for s in r if s >= 0)
        return sorted(common)

    def _restore_sharded(self, step, g):
        """Reassemble global logical arrays from whichever shard files
        cover them. Mesh-shape agnostic: the shard index ranges recorded
        in the per-host manifests are global coordinates, so a 4-host
        checkpoint restores under 8 hosts (or 1) identically — the
        caller re-places the arrays under its own live shardings."""
        arrays = g.get("arrays", {})
        out, covered = {}, {}
        for fname in g.get("files", []):
            path = os.path.join(self.directory, fname)
            if not os.path.exists(path):
                raise ValueError(
                    "sharded checkpoint step %d is missing %s — "
                    "incomplete save (a host died before publishing)"
                    % (step, fname))
            m = self._verify_shard(path)
            archive = np.load(path, allow_pickle=False)
            for entry, info in m.get("entries", {}).items():
                key = info["key"]
                data = archive[entry]
                if info.get("index") is None:
                    out[key] = data
                    continue
                meta = arrays.get(key)
                if meta is None:
                    raise ValueError("shard entry %r is not in the "
                                     "global manifest" % entry)
                if key not in out:
                    out[key] = np.empty([int(s) for s in meta["shape"]],
                                        np.dtype(meta["dtype"]))
                    covered[key] = 0
                slices = tuple(slice(int(a), int(b))
                               for a, b in info["index"])
                out[key][slices] = data
                covered[key] += int(data.size)
        for key, meta in arrays.items():
            if key not in out:
                raise ValueError("sharded checkpoint step %d never wrote "
                                 "%r — shard files incomplete" % (step, key))
            if int(meta.get("shards", 1)) > 1:
                want = int(np.prod(meta["shape"])) if meta["shape"] else 1
                if covered.get(key, 0) != want:
                    raise ValueError(
                        "array %r covered %d of %d elements — shard "
                        "files incomplete" % (key, covered.get(key, 0),
                                              want))
        return _unflatten(out)

    def restore(self, step):
        g = self.global_manifest(step)
        if g is not None and g.get("format") == "sharded":
            return self._restore_sharded(step, g)
        path = os.path.join(self.directory, "ckpt-%d.npz" % step)
        self._verify_manifest(step, path)
        archive = np.load(path, allow_pickle=False)
        return _unflatten({k: archive[k] for k in archive.files})

    def restore_latest(self):
        """(step, tree) of the newest checkpoint intact on EVERY host,
        or None. A corrupt file or manifest falls back (with a warning)
        to the previous one — only corruption-shaped errors are treated
        as fallback-able, so a systematic restore bug cannot silently
        become a cold start.

        Multi-process, the per-host intact-step sets are allgathered
        and intersected BEFORE deserializing, so hosts can never fall
        back past *different* corrupt checkpoints onto different steps;
        the up-front verification of every retained step is the price
        of that agreement without a coordinator. Single-process (and
        the emulated-multi-host drill, whose hosts share one directory
        view), verification stays LAZY newest-first — `restore()`
        itself is the integrity gate, so the hot relaunch path reads
        each candidate once."""
        import warnings
        import zipfile
        try:
            nproc = jax.process_count()
        except Exception:
            nproc = 1
        if nproc > 1:
            candidates = self._common_steps(self.intact_steps())
        else:
            candidates = self.all_steps()
        for step in reversed(candidates):
            try:
                t0 = _time.perf_counter()
                with telemetry.span("ckpt.restore", category="ckpt",
                                    step=step):
                    tree = self.restore(step)
                self._metrics["restore_s"].observe(
                    _time.perf_counter() - t0)
                self._metrics["restores"].inc()
                return step, tree
            except (OSError, ValueError, zipfile.BadZipFile, EOFError,
                    KeyError) as e:
                self._metrics["manifest_failures"].inc(step=step,
                                                       error=str(e))
                warnings.warn("skipping corrupt checkpoint step %d: %s"
                              % (step, e))
                continue
        return None


_SEP = "/"


def _flatten(tree, prefix=""):
    out = {}
    if isinstance(tree, dict):
        if not tree:
            out[prefix + "__ed__"] = np.zeros(0)  # empty-dict marker
        for k, v in tree.items():
            out.update(_flatten(v, prefix + str(k) + _SEP))
    elif isinstance(tree, (list, tuple)):
        if not tree:
            out[prefix + ("__et__" if isinstance(tree, tuple)
                          else "__el__")] = np.zeros(0)
        tag = "__t__" if isinstance(tree, tuple) else "__l__"
        for i, v in enumerate(tree):
            out.update(_flatten(v, prefix + tag + str(i) + _SEP))
    else:
        out[prefix.rstrip(_SEP)] = tree
    return out


def _unflatten(flat):
    root = {}
    for key, v in flat.items():
        parts = key.split(_SEP)
        node = root
        for p in parts[:-1]:
            node = node.setdefault(p, {})
        node[parts[-1]] = v

    def rebuild(node):
        if not isinstance(node, dict):
            return node
        keys = list(node.keys())
        if keys == ["__et__"]:
            return ()
        if keys == ["__el__"]:
            return []
        if keys == ["__ed__"]:
            return {}
        if keys and all(k.startswith(("__t__", "__l__")) for k in keys):
            tup = keys[0].startswith("__t__")
            items = sorted(((int(k[5:]), rebuild(v))
                            for k, v in node.items()))
            seq = [v for _, v in items]
            return tuple(seq) if tup else seq
        return {k: rebuild(v) for k, v in node.items()}

    return rebuild(root)
