"""Failure recovery: asynchronous checkpointing with automatic resume.

Parity-and-beyond: the reference's recovery story is manual restart from
epoch checkpoints (SURVEY §5.3 — ps-lite liveness exists but there is no
elastic recovery or async checkpointing in-tree; `tools/kill-mxnet.py`
kills a job, the operator restarts it). This module EXCEEDS that: an
orbax-style CheckpointManager with

  * async saves — the host serializes on a background thread while the
    accelerator keeps training (device→host copy happens on the caller
    thread, write+fsync+rename off it);
  * atomic publication — write to a temp file then os.replace, then
    fsync the *directory* so the rename itself is durable across power
    loss (POSIX: a rename is only on disk once its directory entry is);
  * integrity manifest — every checkpoint publishes a sidecar
    `ckpt-<step>.manifest.json` carrying the npz's size + sha256 and the
    array-entry names; `restore()` verifies it before deserializing, so
    a truncated or bit-rotted file is detected up front and
    `restore_latest()` falls back to the previous intact checkpoint
    (per-array CRC32s inside the zip guard each entry during the read
    itself);
  * retention — keep the newest `keep` checkpoints, prune older;
  * `restore_latest()` — the auto-resume entry a relaunched worker calls;
  * single-writer protocol — in a multi-process (jax.distributed) run
    every process computes identical replicated state, so only process 0
    performs checkpoint IO; `save()` on other processes returns without
    touching the directory. The BARRIER POINT is `wait()`: call it on
    every process at the same program point (e.g. before exiting after a
    preemption) — on the writer it blocks until the checkpoint has
    published, on non-writers it is a cheap no-op, and when
    `jax.distributed` is initialized it then synchronizes all processes
    so no worker can exit (and be relaunched) before the checkpoint
    exists.

TrainStep integration: `TrainStep.state_dict()/load_state_dict()` capture
parameters, optimizer state, and the step counter, so
`manager.save(step.t, step.state_dict())` + `step.load_state_dict(...)`
is a complete resume. `parallel.resilient.ResilientLoop` layers the full
fault lifecycle (preemption watcher, bad-step policies, data cursor) on
top of this manager.

Fault injection: `utils.chaos` hooks fire inside `_write` when armed
(kill mid-save before publication, corrupt a published file) — the
chaos-test harness proves the atomicity/fallback claims above.
"""
from __future__ import annotations

import hashlib
import json
import os
import re
import threading

import numpy as np
import jax

from . import chaos as _chaos

_CKPT_RE = re.compile(r"^ckpt-(\d+)\.npz$")


def _fsync_dir(path):
    """fsync a directory so a just-published rename survives power loss.
    Best-effort on platforms without O_DIRECTORY semantics."""
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


class CheckpointManager:
    """Directory of ckpt-<step>.npz files with async atomic writes.

    Usage:
        mgr = CheckpointManager(dir, keep=3)
        for step in range(start, n):
            ...
            if step % 100 == 0:
                mgr.save(step, train_step.state_dict())
        # after a crash/preemption, the relaunched process:
        state = mgr.restore_latest()
        if state is not None:
            step0, tree = state
            train_step.load_state_dict(tree)
    """

    def __init__(self, directory, keep=3, async_save=True,
                 process_index=None):
        self.directory = directory
        self.keep = keep
        self.async_save = async_save
        self._process_index = process_index
        self._worker = None
        self._lock = threading.Lock()
        self._error = None
        if self.is_writer:
            os.makedirs(directory, exist_ok=True)

    @property
    def is_writer(self):
        """Single-writer protocol: only process 0 performs checkpoint IO
        (data-parallel state is replicated — every process holds the same
        values, so N writers would just race on the directory)."""
        if self._process_index is None:
            try:
                self._process_index = jax.process_index()
            except Exception:
                self._process_index = 0
        return self._process_index == 0

    # -- save ---------------------------------------------------------------
    def save(self, step, tree, block=False):
        """Snapshot `tree` (a dict of name -> array-like) at `step`.

        The device→host transfer happens here (values are frozen against
        further training); file IO runs on a background thread unless
        async_save=False or block=True. On non-writer processes this is
        a no-op (see the single-writer protocol in the module docstring).
        """
        if not self.is_writer:
            return
        self._raise_pending()

        def own(v):
            # the async writer must OWN every buffer: np.asarray on a jax
            # CPU array can alias the device buffer, which the next
            # (donating) train step then overwrites under the writer.
            # Arrays that already own their memory (TrainStep.state_dict
            # output) pass through — no second full-state memcpy.
            if isinstance(v, np.ndarray) and v.base is None:
                return v
            return np.array(v)

        host = {k: own(v) for k, v in _flatten(tree).items()}
        self.wait(_barrier=False)  # one save at a time: bounded memory,
        if self.async_save and not block:  # no write races
            self._worker = threading.Thread(
                target=self._write, args=(step, host), daemon=True)
            self._worker.start()
        else:
            self._write(step, host)

    def _manifest_path(self, step):
        return os.path.join(self.directory, "ckpt-%d.manifest.json" % step)

    def _write(self, step, host):
        try:
            import io
            import zipfile
            final = os.path.join(self.directory, "ckpt-%d.npz" % step)
            tmp = final + ".tmp-%d" % os.getpid()
            with open(tmp, "wb") as f:
                # npz written by hand: np.savez(**host) would collide with
                # its own 'file'/'allow_pickle' parameter names for user
                # keys, and we need the fd for fsync anyway
                with zipfile.ZipFile(f, "w", zipfile.ZIP_STORED) as z:
                    for k, v in host.items():
                        buf = io.BytesIO()
                        np.lib.format.write_array(buf, np.asarray(v),
                                                  allow_pickle=False)
                        z.writestr(k + ".npy", buf.getvalue())
                f.flush()
                os.fsync(f.fileno())
            digest = hashlib.sha256()
            with open(tmp, "rb") as f:
                for block in iter(lambda: f.read(1 << 20), b""):
                    digest.update(block)
            manifest = {"step": int(step),
                        "file": os.path.basename(final),
                        "size": os.path.getsize(tmp),
                        "sha256": digest.hexdigest(),
                        "arrays": sorted(host.keys())}
            _chaos.maybe_kill_during_save(step)
            os.replace(tmp, final)  # atomic publication
            mtmp = self._manifest_path(step) + ".tmp-%d" % os.getpid()
            with open(mtmp, "w") as f:
                json.dump(manifest, f)
                f.flush()
                os.fsync(f.fileno())
            os.replace(mtmp, self._manifest_path(step))
            # rename durability: the publication is only real once the
            # directory entry itself is on disk
            _fsync_dir(self.directory)
            _chaos.maybe_corrupt_checkpoint(step, final)
            self._prune()
        except Exception as e:  # surfaced on the next save()/wait()
            with self._lock:
                self._error = e

    def _prune(self):
        steps = sorted(self.all_steps())
        for s in steps[:-self.keep] if self.keep else []:
            for path in (os.path.join(self.directory, "ckpt-%d.npz" % s),
                         self._manifest_path(s)):
                try:
                    os.remove(path)
                except OSError:
                    pass

    def wait(self, _barrier=True):
        """Block until the in-flight async save (if any) has published.

        This is the multi-process BARRIER POINT: every process calls it
        at the same program point; when jax.distributed is active the
        processes then synchronize, so none can proceed (or exit for
        relaunch) before process 0's checkpoint is durably on disk."""
        if self._worker is not None:
            self._worker.join()
            self._worker = None
        self._raise_pending()
        if _barrier:
            try:
                nproc = jax.process_count()
            except Exception:
                nproc = 1
            if nproc > 1:
                from jax.experimental import multihost_utils
                multihost_utils.sync_global_devices("mxtpu-ckpt-wait")

    def _raise_pending(self):
        with self._lock:
            if self._error is not None:
                e, self._error = self._error, None
                raise e

    # -- restore ------------------------------------------------------------
    def all_steps(self):
        out = []
        try:
            names = os.listdir(self.directory)
        except OSError:
            return out
        for name in names:
            m = _CKPT_RE.match(name)
            if m:
                out.append(int(m.group(1)))
        return sorted(out)

    def latest_step(self):
        steps = self.all_steps()
        return steps[-1] if steps else None

    def _verify_manifest(self, step, path):
        """Integrity gate before deserialization. A missing manifest is
        tolerated (pre-manifest checkpoints stay restorable); a corrupt
        or mismatching one raises ValueError, which restore_latest()
        treats as corruption-shaped (falls back to an older step)."""
        mpath = self._manifest_path(step)
        if not os.path.exists(mpath):
            return
        with open(mpath) as f:
            manifest = json.load(f)  # corrupt JSON -> ValueError
        if not isinstance(manifest, dict) or "sha256" not in manifest:
            raise ValueError("manifest %s is missing the checksum" % mpath)
        size = os.path.getsize(path)
        if manifest.get("size") not in (None, size):
            raise ValueError(
                "checkpoint ckpt-%d.npz is %d bytes but its manifest "
                "recorded %d — truncated write" % (step, size,
                                                   manifest["size"]))
        digest = hashlib.sha256()
        with open(path, "rb") as f:
            for block in iter(lambda: f.read(1 << 20), b""):
                digest.update(block)
        if digest.hexdigest() != manifest["sha256"]:
            raise ValueError("checkpoint ckpt-%d.npz fails its manifest "
                             "sha256 — corrupt" % step)

    def restore(self, step):
        path = os.path.join(self.directory, "ckpt-%d.npz" % step)
        self._verify_manifest(step, path)
        archive = np.load(path, allow_pickle=False)
        return _unflatten({k: archive[k] for k in archive.files})

    def restore_latest(self):
        """(step, tree) of the newest intact checkpoint, or None. A
        corrupt file or manifest falls back (with a warning) to the
        previous one — only corruption-shaped errors are treated as
        fallback-able, so a systematic restore bug cannot silently
        become a cold start."""
        import warnings
        import zipfile
        for step in reversed(self.all_steps()):
            try:
                return step, self.restore(step)
            except (OSError, ValueError, zipfile.BadZipFile, EOFError) as e:
                warnings.warn("skipping corrupt checkpoint ckpt-%d.npz: %s"
                              % (step, e))
                continue
        return None


_SEP = "/"


def _flatten(tree, prefix=""):
    out = {}
    if isinstance(tree, dict):
        if not tree:
            out[prefix + "__ed__"] = np.zeros(0)  # empty-dict marker
        for k, v in tree.items():
            out.update(_flatten(v, prefix + str(k) + _SEP))
    elif isinstance(tree, (list, tuple)):
        if not tree:
            out[prefix + ("__et__" if isinstance(tree, tuple)
                          else "__el__")] = np.zeros(0)
        tag = "__t__" if isinstance(tree, tuple) else "__l__"
        for i, v in enumerate(tree):
            out.update(_flatten(v, prefix + tag + str(i) + _SEP))
    else:
        out[prefix.rstrip(_SEP)] = tree
    return out


def _unflatten(flat):
    root = {}
    for key, v in flat.items():
        parts = key.split(_SEP)
        node = root
        for p in parts[:-1]:
            node = node.setdefault(p, {})
        node[parts[-1]] = v

    def rebuild(node):
        if not isinstance(node, dict):
            return node
        keys = list(node.keys())
        if keys == ["__et__"]:
            return ()
        if keys == ["__el__"]:
            return []
        if keys == ["__ed__"]:
            return {}
        if keys and all(k.startswith(("__t__", "__l__")) for k in keys):
            tup = keys[0].startswith("__t__")
            items = sorted(((int(k[5:]), rebuild(v))
                            for k, v in node.items()))
            seq = [v for _, v in items]
            return tuple(seq) if tup else seq
        return {k: rebuild(v) for k, v in node.items()}

    return rebuild(root)
