"""NDArray (de)serialization.

Parity: reference legacy binary NDArray format (`src/ndarray/ndarray.cc`
Save/Load, C API MXNDArraySave/Load `src/c_api/c_api.cc:279,302`) used by
.params checkpoints.

TPU-native redesign: a named .npz container (numpy archive) — portable,
inspectable, and byte-stable across hosts. The dict/list duality of the
reference format is preserved: a saved list round-trips as a list, a dict as
a dict. bfloat16 is stored as uint16 raw bits with a dtype tag.
"""
from __future__ import annotations

import io
import zipfile

import numpy as np
import jax.numpy as jnp

_BF16_TAG = "__bf16__:"
_LIST_TAG = "__list__:"


def _to_np(arr):
    from ..ndarray import NDArray
    data = arr._data if isinstance(arr, NDArray) else arr
    npd = np.asarray(data)
    if npd.dtype == jnp.bfloat16.dtype:
        return npd.view(np.uint16), True
    return npd, False


def save_ndarrays(fname, data):
    from ..ndarray import NDArray
    if isinstance(data, NDArray):
        data = [data]
    arrays = {}
    if isinstance(data, dict):
        for k, v in data.items():
            npd, bf16 = _to_np(v)
            arrays[(_BF16_TAG if bf16 else "") + k] = npd
    elif isinstance(data, (list, tuple)):
        for i, v in enumerate(data):
            npd, bf16 = _to_np(v)
            arrays[(_BF16_TAG if bf16 else "") + _LIST_TAG + str(i)] = npd
    else:
        raise TypeError("save expects NDArray, list, or dict")
    with open(fname, "wb") as f:  # file handle: stops savez appending '.npz'
        np.savez(f, **arrays)


def load_ndarrays(fname):
    """Load a .params container from a path or raw byte buffer (the
    c_predict_api contract passes param bytes). Auto-detects the
    reference-framework binary format."""
    from ..ndarray import NDArray
    from . import legacy
    if legacy.is_legacy_ndarray_file(fname):
        # reference-framework binary .params (ndarray.cc Save/Load framing)
        return legacy.load_legacy_ndarrays(fname)
    src = io.BytesIO(bytes(fname)) if isinstance(fname, (bytes, bytearray)) \
        else fname
    try:
        archive = np.load(src, allow_pickle=False)
    except (zipfile.BadZipFile, ValueError):
        raise IOError("not an mxnet_tpu .params/.npz archive: %s"
                      % (fname if isinstance(fname, str) else "<bytes>"))
    items = {}
    is_list = False
    for key in archive.files:
        name = key
        arr = archive[key]
        if name.startswith(_BF16_TAG):
            name = name[len(_BF16_TAG):]
            arr = arr.view(jnp.bfloat16.dtype)
        if name.startswith(_LIST_TAG):
            is_list = True
            items[int(name[len(_LIST_TAG):])] = NDArray(jnp.asarray(arr))
        else:
            items[name] = NDArray(jnp.asarray(arr))
    if is_list:
        return [items[i] for i in sorted(items)]
    return items


def split_arg_aux(payload, unprefixed=None):
    """Split a checkpoint dict on the reference 'arg:'/'aux:' key prefixes
    (one implementation of the format contract — model.load_checkpoint and
    the predict path both call this).

    unprefixed: 'arg' treats bare keys as arg params (plain npz saves);
    None drops them (the reference load_checkpoint behavior).
    """
    arg_params, aux_params = {}, {}
    for k, v in payload.items():
        if k.startswith("arg:"):
            arg_params[k[4:]] = v
        elif k.startswith("aux:"):
            aux_params[k[4:]] = v
        elif unprefixed == "arg":
            arg_params[k] = v
    return arg_params, aux_params
