"""Image processing + ImageIter.

Parity: reference `python/mxnet/image/image.py` (imdecode/imresize/crops/
color_normalize, ImageIter:493 with 15 augmenters:830, CreateAugmenter) and
the C++ augmenter defaults (`src/io/image_aug_default.cc`).

TPU-native note: decode/augment run host-side (cv2, like the reference's
OpenCV path); batches transfer to HBM via XLA's async host DMA. The
double-buffered prefetch lives in io.PrefetchingIter / gluon DataLoader.
"""
from __future__ import annotations

import os
import random

import numpy as np

try:
    import cv2
except ImportError:  # pragma: no cover
    cv2 = None

from .ndarray import NDArray
from .base import MXNetError


def imdecode(buf, flag=1, to_rgb=True, out=None):
    """Decode a jpeg/png buffer to HWC NDArray (parity: image.imdecode)."""
    if cv2 is None:
        raise MXNetError("cv2 is required for imdecode")
    if isinstance(buf, NDArray):
        buf = buf.asnumpy().astype(np.uint8)
    arr = np.frombuffer(buf, dtype=np.uint8) if isinstance(buf, (bytes, bytearray)) \
        else np.asarray(buf, dtype=np.uint8)
    img = cv2.imdecode(arr, flag)
    if img is None:
        raise MXNetError("imdecode failed")
    if to_rgb and img.ndim == 3:
        img = cv2.cvtColor(img, cv2.COLOR_BGR2RGB)
    if img.ndim == 2:
        img = img[:, :, None]
    return NDArray(img)


def imencode(img, quality=95, img_fmt=".jpg"):
    if cv2 is None:
        raise MXNetError("cv2 is required for imencode")
    arr = img.asnumpy() if isinstance(img, NDArray) else np.asarray(img)
    if arr.ndim == 3 and arr.shape[2] == 3:
        arr = cv2.cvtColor(arr.astype(np.uint8), cv2.COLOR_RGB2BGR)
    params = [cv2.IMWRITE_JPEG_QUALITY, quality] if img_fmt in (".jpg", ".jpeg") \
        else []
    ok, buf = cv2.imencode(img_fmt, arr, params)
    if not ok:
        raise MXNetError("imencode failed")
    return buf.tobytes()


def imread(filename, flag=1, to_rgb=True):
    with open(filename, "rb") as f:
        return imdecode(f.read(), flag=flag, to_rgb=to_rgb)


def imresize(src, w, h, interp=1):
    arr = src.asnumpy() if isinstance(src, NDArray) else np.asarray(src)
    out = cv2.resize(arr, (w, h), interpolation=_interp(interp))
    if out.ndim == 2:
        out = out[:, :, None]
    return NDArray(out)


def _interp(interp):
    return {0: cv2.INTER_NEAREST, 1: cv2.INTER_LINEAR, 2: cv2.INTER_CUBIC,
            3: cv2.INTER_AREA, 4: cv2.INTER_LANCZOS4}.get(interp,
                                                          cv2.INTER_LINEAR)


def resize_short(src, size, interp=2):
    h, w = src.shape[:2]
    if h > w:
        new_h, new_w = size * h // w, size
    else:
        new_h, new_w = size, size * w // h
    return imresize(src, new_w, new_h, interp=interp)


def fixed_crop(src, x0, y0, w, h, size=None, interp=2):
    arr = src.asnumpy() if isinstance(src, NDArray) else np.asarray(src)
    out = arr[y0:y0 + h, x0:x0 + w]
    if size is not None and (w, h) != size:
        return imresize(NDArray(out), size[0], size[1], interp=interp)
    return NDArray(out)


def random_crop(src, size, interp=2):
    h, w = src.shape[:2]
    new_w, new_h = min(size[0], w), min(size[1], h)
    x0 = random.randint(0, w - new_w)
    y0 = random.randint(0, h - new_h)
    out = fixed_crop(src, x0, y0, new_w, new_h, size, interp)
    return out, (x0, y0, new_w, new_h)


def center_crop(src, size, interp=2):
    h, w = src.shape[:2]
    new_w, new_h = min(size[0], w), min(size[1], h)
    x0 = (w - new_w) // 2
    y0 = (h - new_h) // 2
    out = fixed_crop(src, x0, y0, new_w, new_h, size, interp)
    return out, (x0, y0, new_w, new_h)


def random_size_crop(src, size, min_area=0.08, ratio=(3.0 / 4.0, 4.0 / 3.0),
                     interp=2):
    h, w = src.shape[:2]
    area = h * w
    for _ in range(10):
        target_area = random.uniform(min_area, 1.0) * area
        log_ratio = (np.log(ratio[0]), np.log(ratio[1]))
        aspect = np.exp(random.uniform(*log_ratio))
        new_w = int(round(np.sqrt(target_area * aspect)))
        new_h = int(round(np.sqrt(target_area / aspect)))
        if new_w <= w and new_h <= h:
            x0 = random.randint(0, w - new_w)
            y0 = random.randint(0, h - new_h)
            return fixed_crop(src, x0, y0, new_w, new_h, size, interp), \
                (x0, y0, new_w, new_h)
    return center_crop(src, size, interp)


def color_normalize(src, mean, std=None):
    arr = src.asnumpy().astype(np.float32) if isinstance(src, NDArray) \
        else np.asarray(src, dtype=np.float32)
    mean = np.asarray(mean, dtype=np.float32)
    arr = arr - mean
    if std is not None:
        arr = arr / np.asarray(std, dtype=np.float32)
    return NDArray(arr)


# ---------------------------------------------------------------------------
# augmenters (parity: image.py Augmenter classes + CreateAugmenter)
# ---------------------------------------------------------------------------


class Augmenter:
    def __init__(self, **kwargs):
        self._kwargs = kwargs

    def dumps(self):
        import json
        return json.dumps([self.__class__.__name__.lower(), self._kwargs])

    def __call__(self, src):
        raise NotImplementedError


class SequentialAug(Augmenter):
    def __init__(self, ts):
        super().__init__()
        self.ts = ts

    def __call__(self, src):
        for aug in self.ts:
            src = aug(src)
        return src


class RandomOrderAug(Augmenter):
    """Apply the augmenter list in a fresh random order per image
    (parity: image.py RandomOrderAug)."""

    def __init__(self, ts):
        super().__init__()
        self.ts = list(ts)

    def __call__(self, src):
        order = list(range(len(self.ts)))
        random.shuffle(order)
        for i in order:
            src = self.ts[i](src)
        return src


def scale_down(src_size, size):
    """Shrink a crop size to fit inside the image, preserving the crop's
    aspect ratio (parity: image.py scale_down); sizes are (w, h)."""
    w, h = size
    sw, sh = src_size
    if sh < h:
        w, h = float(w * sh) / h, sh
    if sw < w:
        w, h = sw, float(h * sw) / w
    return int(w), int(h)


class ResizeAug(Augmenter):
    def __init__(self, size, interp=2):
        super().__init__(size=size, interp=interp)
        self.size = size
        self.interp = interp

    def __call__(self, src):
        return resize_short(src, self.size, self.interp)


class ForceResizeAug(Augmenter):
    def __init__(self, size, interp=2):
        super().__init__(size=size, interp=interp)
        self.size = size
        self.interp = interp

    def __call__(self, src):
        return imresize(src, self.size[0], self.size[1], self.interp)


class RandomCropAug(Augmenter):
    def __init__(self, size, interp=2):
        super().__init__(size=size, interp=interp)
        self.size = size
        self.interp = interp

    def __call__(self, src):
        return random_crop(src, self.size, self.interp)[0]


class CenterCropAug(Augmenter):
    def __init__(self, size, interp=2):
        super().__init__(size=size, interp=interp)
        self.size = size
        self.interp = interp

    def __call__(self, src):
        return center_crop(src, self.size, self.interp)[0]


class RandomSizedCropAug(Augmenter):
    def __init__(self, size, min_area=0.08, ratio=(3 / 4., 4 / 3.), interp=2):
        super().__init__(size=size, min_area=min_area, ratio=ratio,
                         interp=interp)
        self.size = size
        self.min_area = min_area
        self.ratio = ratio
        self.interp = interp

    def __call__(self, src):
        return random_size_crop(src, self.size, self.min_area, self.ratio,
                                self.interp)[0]


class HorizontalFlipAug(Augmenter):
    def __init__(self, p):
        super().__init__(p=p)
        self.p = p

    def __call__(self, src):
        if random.random() < self.p:
            return NDArray(src.asnumpy()[:, ::-1].copy())
        return src


class CastAug(Augmenter):
    def __init__(self, typ="float32"):
        super().__init__(type=typ)
        self.typ = typ

    def __call__(self, src):
        return NDArray(src.asnumpy().astype(self.typ))


class BrightnessJitterAug(Augmenter):
    def __init__(self, brightness):
        super().__init__(brightness=brightness)
        self.brightness = brightness

    def __call__(self, src):
        alpha = 1.0 + random.uniform(-self.brightness, self.brightness)
        return NDArray(src.asnumpy().astype(np.float32) * alpha)


class ContrastJitterAug(Augmenter):
    def __init__(self, contrast):
        super().__init__(contrast=contrast)
        self.contrast = contrast

    def __call__(self, src):
        alpha = 1.0 + random.uniform(-self.contrast, self.contrast)
        arr = src.asnumpy().astype(np.float32)
        gray = arr.mean()
        return NDArray(gray * (1 - alpha) + arr * alpha)


class SaturationJitterAug(Augmenter):
    def __init__(self, saturation):
        super().__init__(saturation=saturation)
        self.saturation = saturation

    def __call__(self, src):
        alpha = 1.0 + random.uniform(-self.saturation, self.saturation)
        arr = src.asnumpy().astype(np.float32)
        gray = arr.mean(axis=2, keepdims=True)
        return NDArray(gray * (1 - alpha) + arr * alpha)


class HueJitterAug(Augmenter):
    def __init__(self, hue):
        super().__init__(hue=hue)
        self.hue = hue

    def __call__(self, src):
        alpha = random.uniform(-self.hue, self.hue)
        arr = src.asnumpy().astype(np.float32)
        rotated = np.roll(arr, 1, axis=2)
        return NDArray((1 - abs(alpha)) * arr + abs(alpha) * rotated)


class ColorJitterAug(SequentialAug):
    def __init__(self, brightness, contrast, saturation):
        ts = []
        if brightness > 0:
            ts.append(BrightnessJitterAug(brightness))
        if contrast > 0:
            ts.append(ContrastJitterAug(contrast))
        if saturation > 0:
            ts.append(SaturationJitterAug(saturation))
        super().__init__(ts)


class LightingAug(Augmenter):
    def __init__(self, alphastd, eigval, eigvec):
        super().__init__(alphastd=alphastd)
        self.alphastd = alphastd
        self.eigval = np.asarray(eigval)
        self.eigvec = np.asarray(eigvec)

    def __call__(self, src):
        alpha = np.random.normal(0, self.alphastd, size=(3,))
        rgb = np.dot(self.eigvec * alpha, self.eigval)
        return NDArray(src.asnumpy().astype(np.float32) + rgb)


class ColorNormalizeAug(Augmenter):
    def __init__(self, mean, std):
        super().__init__(mean=mean, std=std)
        self.mean = mean
        self.std = std

    def __call__(self, src):
        return color_normalize(src, self.mean, self.std)


class RandomGrayAug(Augmenter):
    def __init__(self, p):
        super().__init__(p=p)
        self.p = p

    def __call__(self, src):
        if random.random() < self.p:
            arr = src.asnumpy().astype(np.float32)
            gray = (arr * np.array([0.299, 0.587, 0.114])).sum(
                axis=2, keepdims=True)
            return NDArray(np.broadcast_to(gray, arr.shape).copy())
        return src


def CreateAugmenter(data_shape, resize=0, rand_crop=False, rand_resize=False,
                    rand_mirror=False, mean=None, std=None, brightness=0,
                    contrast=0, saturation=0, hue=0, pca_noise=0,
                    rand_gray=0, inter_method=2):
    """Parity: image.py CreateAugmenter — the standard augmentation recipe."""
    auglist = []
    if resize > 0:
        auglist.append(ResizeAug(resize, inter_method))
    crop_size = (data_shape[2], data_shape[1])
    if rand_resize:
        assert rand_crop
        auglist.append(RandomSizedCropAug(crop_size, 0.08, (3.0 / 4.0,
                                                            4.0 / 3.0),
                                          inter_method))
    elif rand_crop:
        auglist.append(RandomCropAug(crop_size, inter_method))
    else:
        auglist.append(CenterCropAug(crop_size, inter_method))
    if rand_mirror:
        auglist.append(HorizontalFlipAug(0.5))
    auglist.append(CastAug())
    if brightness or contrast or saturation:
        auglist.append(ColorJitterAug(brightness, contrast, saturation))
    if hue:
        auglist.append(HueJitterAug(hue))
    if pca_noise > 0:
        eigval = np.array([55.46, 4.794, 1.148])
        eigvec = np.array([[-0.5675, 0.7192, 0.4009],
                           [-0.5808, -0.0045, -0.8140],
                           [-0.5836, -0.6948, 0.4203]])
        auglist.append(LightingAug(pca_noise, eigval, eigvec))
    if rand_gray > 0:
        auglist.append(RandomGrayAug(rand_gray))
    if mean is True:
        mean = np.array([123.68, 116.28, 103.53])
    if std is True:
        std = np.array([58.395, 57.12, 57.375])
    if mean is not None and len(np.atleast_1d(mean)) > 0:
        auglist.append(ColorNormalizeAug(mean, std))
    return auglist


class ImageIter:
    """Python image iterator over RecordIO packs or file lists
    (parity: image.py ImageIter:493)."""

    def __init__(self, batch_size, data_shape, label_width=1,
                 path_imgrec=None, path_imglist=None, path_root=None,
                 shuffle=False, part_index=0, num_parts=1, aug_list=None,
                 imglist=None, data_name="data", label_name="softmax_label",
                 **kwargs):
        from .io import DataBatch, DataDesc
        assert path_imgrec or path_imglist or isinstance(imglist, list)
        self.batch_size = batch_size
        self.check_data_shape(tuple(data_shape))
        self.data_shape = tuple(data_shape)
        self.label_width = label_width
        self._data_name = data_name
        self._label_name = label_name
        self.auglist = aug_list if aug_list is not None else \
            CreateAugmenter(data_shape, **{k: v for k, v in kwargs.items()
                                           if k in ("resize", "rand_crop",
                                                    "rand_resize",
                                                    "rand_mirror", "mean",
                                                    "std", "brightness",
                                                    "contrast", "saturation",
                                                    "hue", "pca_noise",
                                                    "rand_gray",
                                                    "inter_method")})
        self.shuffle = shuffle
        self.imgrec = None
        self.imglist = None
        self.path_root = path_root
        if path_imgrec:
            idx_path = os.path.splitext(path_imgrec)[0] + ".idx"
            from . import recordio
            self.imgrec = recordio.MXIndexedRecordIO(idx_path, path_imgrec, "r")
            self.seq = list(self.imgrec.keys)
            # distributed sharding (parity: part_index/num_parts)
            self.seq = self.seq[part_index::num_parts]
        elif path_imglist:
            self.imglist = {}
            with open(path_imglist) as fin:
                for line in fin:
                    parts = line.strip().split("\t")
                    label = np.asarray([float(x) for x in parts[1:-1]],
                                       dtype=np.float32)
                    self.imglist[int(parts[0])] = (label, parts[-1])
            self.seq = sorted(self.imglist.keys())[part_index::num_parts]
        else:
            self.imglist = {i: (np.asarray(item[0], dtype=np.float32), item[1])
                            for i, item in enumerate(imglist)}
            self.seq = sorted(self.imglist.keys())[part_index::num_parts]
        self.cur = 0
        self.reset()

    @property
    def provide_data(self):
        from .io import DataDesc
        return [DataDesc(self._data_name,
                         (self.batch_size,) + self.data_shape)]

    @property
    def provide_label(self):
        from .io import DataDesc
        shape = (self.batch_size,) if self.label_width == 1 else \
            (self.batch_size, self.label_width)
        return [DataDesc(self._label_name, shape)]

    def reset(self):
        self.cur = 0
        if self.shuffle:
            random.shuffle(self.seq)

    # -- overridable pipeline hooks (parity: image.py ImageIter — users
    # subclass and override these to customize decode/augment/layout) ----

    def check_data_shape(self, data_shape):
        """Validate the (C, H, W) shape argument (parity hook)."""
        if len(data_shape) != 3:
            raise ValueError("data_shape must be (channels, height, "
                             "width), got %s" % (data_shape,))
        if data_shape[0] not in (1, 3):
            raise ValueError("data_shape channel dim must be 1 or 3")

    def check_valid_image(self, data):
        """Reject undecodable samples (parity hook)."""
        if len(data[0].shape) == 0:
            raise RuntimeError("Data shape is wrong")

    def imdecode(self, s):
        """Decode raw image bytes (parity hook; module-level imdecode)."""
        return imdecode(s)

    def read_image(self, fname):
        """Raw bytes of an image under path_root (parity hook)."""
        with open(os.path.join(self.path_root or "", fname), "rb") as f:
            return f.read()

    def augmentation_transform(self, data):
        """Run the augmenter list (parity hook)."""
        for aug in self.auglist:
            data = aug(data)
        return data

    def postprocess_data(self, datum):
        """Final per-sample layout transform HWC -> CHW (parity hook)."""
        arr = datum.asnumpy() if isinstance(datum, NDArray) \
            else np.asarray(datum)
        if arr.shape[:2] != self.data_shape[1:]:
            arr = cv2.resize(arr, (self.data_shape[2], self.data_shape[1]))
        if arr.ndim == 2:
            arr = arr[:, :, None]
        return arr.transpose(2, 0, 1)

    def next_sample(self):
        """(label, raw image bytes) of the next sample (parity hook)."""
        if self.cur >= len(self.seq):
            raise StopIteration
        idx = self.seq[self.cur]
        self.cur += 1
        if self.imgrec is not None:
            from . import recordio
            s = self.imgrec.read_idx(idx)
            header, img = recordio.unpack(s)
            return header.label, img
        label, fname = self.imglist[idx]
        return label, self.read_image(fname)

    def next(self):
        from .io import DataBatch
        batch_data = np.zeros((self.batch_size,) + self.data_shape,
                              dtype=np.float32)
        batch_label = np.zeros((self.batch_size, self.label_width),
                               dtype=np.float32)
        i = 0
        try:
            while i < self.batch_size:
                label, raw = self.next_sample()
                img = self.imdecode(raw)
                self.check_valid_image([img])
                img = self.augmentation_transform(img)
                batch_data[i] = self.postprocess_data(img)
                batch_label[i] = np.atleast_1d(label)[:self.label_width]
                i += 1
        except StopIteration:
            if i == 0:
                raise
        lab = batch_label[:, 0] if self.label_width == 1 else batch_label
        return DataBatch(data=[NDArray(batch_data)], label=[NDArray(lab)],
                         pad=self.batch_size - i)

    def __next__(self):
        return self.next()

    def __iter__(self):
        return self


# detection pipeline (parity: reference python/mxnet/image/detection.py) —
# imported at the tail so image_detection can import ImageIter from here
from .image_detection import (DetAugmenter, DetBorrowAug,  # noqa: E402,F401
                              DetRandomSelectAug, DetHorizontalFlipAug,
                              DetRandomCropAug, DetRandomPadAug,
                              CreateMultiRandCropAugmenter,
                              CreateDetAugmenter, ImageDetIter)
