"""Profiler.

Parity: reference `src/profiler/` (chrome://tracing JSON events, aggregate
per-op summary table, modes, pause/resume) + `python/mxnet/profiler.py`
(set_config/set_state/dump/pause/resume, custom Domains/Tasks/Counters),
env autostart MXNET_PROFILER_AUTOSTART.

TPU-native redesign: device-side op timing comes from jax.profiler (XPlane
traces viewable in TensorBoard/Perfetto — richer than the reference's
chrome://tracing). This module adds the reference's UX on top: a Python-side
event recorder that also emits chrome://tracing JSON, an aggregate summary
table, and the scoped Task/Frame/Counter API.
"""
from __future__ import annotations

import json
import os
import time
import threading
from collections import defaultdict

import jax

_state = {"running": False, "config": {"filename": "profile.json",
                                       "aggregate_stats": True,
                                       # block on each op's outputs so the
                                       # recorded duration is true device
                                       # time, not async dispatch time (the
                                       # reference's engine-execute timing,
                                       # profiler.h:85-159, measures the
                                       # kernel, not the push)
                                       "profile_sync": True},
          "events": [], "lock": threading.Lock(), "jax_trace_dir": None,
          # dump bookkeeping: events move to "flushed" once written (so a
          # re-dump never re-emits them into a fresh file) and files we
          # wrote this process are merged into, not clobbered
          "flushed": [], "dumped_to": set()}


def profile_sync():
    return _state["running"] and _state["config"].get("profile_sync", True)


def set_config(**kwargs):
    """Parity: profiler.py set_config (filename, profile_all, ...)."""
    _state["config"].update(kwargs)


def set_state(state="stop", profile_process="worker"):
    if state == "run":
        _state["running"] = True
        trace_dir = _state["config"].get("xplane_dir")
        if trace_dir:
            jax.profiler.start_trace(trace_dir)
            _state["jax_trace_dir"] = trace_dir
    else:
        if _state["jax_trace_dir"]:
            jax.profiler.stop_trace()
            _state["jax_trace_dir"] = None
        _state["running"] = False


def pause(profile_process="worker"):
    _state["running"] = False


def resume(profile_process="worker"):
    _state["running"] = True


def is_running():
    return _state["running"]


def record_event(name, category, start_us, dur_us, args=None):
    if not _state["running"]:
        return
    with _state["lock"]:
        _state["events"].append({"name": name, "cat": category, "ph": "X",
                                 "ts": start_us, "dur": dur_us,
                                 "pid": os.getpid(),
                                 "tid": threading.get_ident(),
                                 "args": args or {}})


def dump(finished=True, profile_process="worker"):
    """Write chrome://tracing JSON (parity: MXDumpProfile).

    Append-safe across multiple dump calls in one process: each call
    DRAINS the pending events (they move to the aggregate-only
    `flushed` list, so `dumps()` keeps seeing them) and merges them
    into the target file's existing traceEvents when this process wrote
    that file before — a re-dump never re-emits already-flushed events
    into a fresh file, and repeated dumps to one filename accumulate
    instead of duplicating. Events are written sorted by `ts` (the
    recording order can interleave across threads)."""
    fname = _state["config"].get("filename", "profile.json")
    # the whole read-merge-write runs under the lock: concurrent dump()
    # calls serialize (neither can discard the other's pending batch),
    # and events are only marked flushed AFTER the write succeeded — a
    # failed write leaves them pending for the next dump
    with _state["lock"]:
        pending = _state["events"]
        existing = []
        if fname in _state["dumped_to"] and os.path.exists(fname):
            try:
                with open(fname) as f:
                    existing = json.load(f).get("traceEvents", [])
            except (OSError, ValueError):
                existing = []
        events = sorted(existing + pending, key=lambda e: e.get("ts", 0))
        with open(fname, "w") as f:
            json.dump({"traceEvents": events, "displayTimeUnit": "ms"}, f)
        _state["events"] = []
        _state["flushed"].extend(pending)
        _state["dumped_to"].add(fname)
    return fname


def dumps(reset=False):
    """Aggregate per-op summary table (parity: aggregate_stats.cc)."""
    agg = defaultdict(lambda: [0, 0.0, float("inf"), 0.0])
    with _state["lock"]:
        for e in _state["flushed"] + _state["events"]:
            s = agg[e["name"]]
            s[0] += 1
            s[1] += e["dur"] / 1000.0
            s[2] = min(s[2], e["dur"] / 1000.0)
            s[3] = max(s[3], e["dur"] / 1000.0)
        if reset:
            _state["events"] = []
            _state["flushed"] = []
    lines = ["%-40s %8s %12s %12s %12s %12s" % (
        "Name", "Calls", "Total(ms)", "Min(ms)", "Max(ms)", "Avg(ms)")]
    for name, (calls, total, mn, mx) in sorted(agg.items(),
                                               key=lambda kv: -kv[1][1]):
        lines.append("%-40s %8d %12.3f %12.3f %12.3f %12.3f" % (
            name, calls, total, mn if calls else 0.0, mx, total / max(1, calls)))
    return "\n".join(lines)


class scope:
    """Time a region (used by internal instrumentation and users)."""

    def __init__(self, name, category="user"):
        self._name = name
        self._cat = category

    def __enter__(self):
        self._t0 = time.perf_counter_ns() // 1000
        return self

    def __exit__(self, *exc):
        t1 = time.perf_counter_ns() // 1000
        record_event(self._name, self._cat, self._t0, t1 - self._t0)


class Domain:
    """Parity: profiler.py Domain — grouping namespace for tasks/counters."""

    def __init__(self, name):
        self.name = name

    def new_task(self, name):
        return Task(self, name)

    def new_counter(self, name, value=None):
        return Counter(self, name, value)

    def new_frame(self, name):
        return Frame(self, name)


class Task:
    def __init__(self, domain, name):
        self.domain = domain
        self.name = name
        self._t0 = None

    def start(self):
        self._t0 = time.perf_counter_ns() // 1000

    def stop(self):
        if self._t0 is not None:
            t1 = time.perf_counter_ns() // 1000
            record_event(self.name, self.domain.name, self._t0, t1 - self._t0)
            self._t0 = None


Frame = Task


class Counter:
    def __init__(self, domain, name, value=None):
        self.domain = domain
        self.name = name
        self.value = value or 0

    def set_value(self, value):
        self.value = value
        record_event(self.name, self.domain.name,
                     time.perf_counter_ns() // 1000, 0,
                     {"value": value})

    def increment(self, delta=1):
        self.set_value(self.value + delta)

    def decrement(self, delta=1):
        self.set_value(self.value - delta)


# env autostart (parity: MXNET_PROFILER_AUTOSTART / MXNET_PROFILER_MODE,
# docs/faq/env_var.md:105-109)
if os.environ.get("MXNET_PROFILER_MODE"):
    _state["config"]["mode"] = os.environ["MXNET_PROFILER_MODE"]
if os.environ.get("MXNET_PROFILER_AUTOSTART", "0") == "1":
    set_state("run")


class Event(scope):
    """User-timed duration event (parity: profiler.py Event): start/stop
    pairs (or `with`) record one entry under the 'event' category. Rides
    the shared `scope` timing so the clock/format lives in one place."""

    def __init__(self, name):
        super().__init__(name, category="event")
        self.name = name
        self._started = False

    def start(self):
        self.__enter__()
        self._started = True

    def stop(self):
        if self._started:
            self.__exit__()
            self._started = False


class Marker:
    """Instant marker (parity: profiler.py Marker.mark): a zero-duration
    point in the trace, scoped 'process'/'thread'/'global'."""

    def __init__(self, domain, name):
        self.domain = domain
        self.name = name

    def mark(self, scope="process"):
        record_event(self.name, self.domain.name,
                     time.perf_counter_ns() // 1000, 0, {"scope": scope})


def dump_profile():
    """Deprecated alias (parity: profiler.py dump_profile -> dump)."""
    dump(True)


def profiler_set_config(**kwargs):
    """Deprecated alias (parity: profiler_set_config -> set_config)."""
    set_config(**kwargs)


def profiler_set_state(state="stop"):
    """Deprecated alias (parity: profiler_set_state -> set_state)."""
    set_state(state)
