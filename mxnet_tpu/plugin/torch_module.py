"""Run a torch.nn.Module as a Gluon block.

Parity: reference `plugin/torch/torch_module.cc` + `torch_function.cc` —
the TorchModule op adapts Torch modules into MXNet graphs, mapping the
module's weights into framework-visible parameter arrays so the MXNet
optimizer trains them.

TPU-native redesign: the torch module runs host-side (CPU) inside the
eager path; forward copies the framework's parameter values into the torch
module, runs torch with grad tracking, and backward replays torch
autograd to produce gradients for BOTH the inputs and the parameters —
so `gluon.Trainer` updates torch-defined layers exactly like native ones.
Host-bound by design (like the reference plugin, which was CPU/GPU-kernel
bound): not traceable into jit graphs; use it in eager training or wrap
the surrounding (non-torch) subgraph with hybridize.
"""
from __future__ import annotations

import numpy as np

from ..gluon.block import Block
from ..gluon.parameter import Parameter
from ..ndarray import NDArray
from .. import autograd


def _require_torch():
    try:
        import torch
        return torch
    except ImportError as e:  # pragma: no cover - torch is in this env
        raise ImportError(
            "mxnet_tpu.plugin.TorchBlock needs pytorch installed") from e


class TorchBlock(Block):
    """Wrap a ``torch.nn.Module``; its parameters become Gluon Parameters.

    Example::

        tb = TorchBlock(torch.nn.Linear(4, 2))
        tb(x)                       # forward
        gluon.Trainer(tb.collect_params(), "sgd", ...)  # trains torch weights
    """

    def __init__(self, torch_module, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        torch = _require_torch()
        assert isinstance(torch_module, torch.nn.Module)
        self._torch = torch
        self._module = torch_module
        self._tparam_names = []
        for tname, tp in torch_module.named_parameters():
            pname = tname.replace(".", "_")
            p = self.params.get(pname, shape=tuple(tp.shape),
                                allow_deferred_init=False, init="zeros")
            p._data = NDArray(np.ascontiguousarray(
                tp.detach().cpu().numpy()))
            if p._grad_req != "null":
                p._init_grad()
            self._reg_params[pname] = p
            self._tparam_names.append((pname, tname))

    def _sync_into_torch(self, param_nds):
        """Copy framework param values into the torch module — but only when
        they changed (NDArray._version stamps). Skipping the no-op copy
        matters for correctness, not just speed: an in-place copy_ between
        two recorded forwards bumps torch's version counters and
        invalidates the autograd graph the first forward saved (shared
        torch encoder called twice per loss)."""
        torch = self._torch
        stamps = tuple(p._version for p in param_nds)
        if stamps == getattr(self, "_sync_stamps", None):
            return
        tparams = dict(self._module.named_parameters())
        for (pname, tname), p in zip(self._tparam_names, param_nds):
            with torch.no_grad():
                # copy: jax-backed buffers surface as read-only numpy views
                tparams[tname].copy_(
                    torch.from_numpy(np.array(p.asnumpy(), copy=True)))
        self._sync_stamps = stamps

    def forward(self, *inputs):
        torch = self._torch
        param_nds = [self._reg_params[p].data()
                     for p, _ in self._tparam_names]
        self._sync_into_torch(param_nds)

        def _tin(a):
            t = torch.from_numpy(np.array(a.asnumpy(), copy=True))
            # integer inputs (embedding indices etc.) cannot require grad
            return t.requires_grad_(True) if t.is_floating_point() else t
        tin = [_tin(a) for a in inputs]
        self._module.train(autograd.is_training())
        tout = self._module(*tin)
        multi = isinstance(tout, (tuple, list))
        touts = list(tout) if multi else [tout]
        outs = [NDArray(o.detach().cpu().numpy()) for o in touts]

        if autograd.is_recording():
            module = self._module

            def torch_backward(out_grads, input_vals, kwargs):
                gouts = [torch.from_numpy(np.asarray(g)) for g in out_grads]
                tps = [dict(module.named_parameters())[tn]
                       for _, tn in self._tparam_names]
                # integer inputs can't require grad — exclude them from the
                # grad call and give them zero cotangents
                diff = [t for t in tin if t.requires_grad] + tps
                grads = iter(torch.autograd.grad(
                    touts, diff, grad_outputs=gouts,
                    retain_graph=True, allow_unused=True))
                out = []
                for t, v in zip(tin, input_vals):
                    g = next(grads) if t.requires_grad else None
                    out.append(np.zeros(np.asarray(v).shape, np.float32)
                               if g is None else g.detach().cpu().numpy())
                for v in input_vals[len(tin):]:
                    g = next(grads)
                    out.append(np.zeros(np.asarray(v).shape, np.float32)
                               if g is None else g.detach().cpu().numpy())
                return out

            class _OpDef:
                fn = None
                differentiable = True

            ins = list(inputs) + param_nds
            autograd.record_op(_OpDef, ins,
                               [np.asarray(i.asnumpy()) for i in ins],
                               outs, {}, custom_backward=torch_backward)
        return outs[0] if len(outs) == 1 else outs
