"""Run a torch.nn.Module as a Gluon block.

Parity: reference `plugin/torch/torch_module.cc` + `torch_function.cc` —
the TorchModule op adapts Torch modules into MXNet graphs, mapping the
module's weights into framework-visible parameter arrays so the MXNet
optimizer trains them.

TPU-native redesign: the torch module runs host-side (CPU) inside the
eager path; forward copies the framework's parameter values into the torch
module, runs torch with grad tracking, and backward replays torch
autograd to produce gradients for BOTH the inputs and the parameters —
so `gluon.Trainer` updates torch-defined layers exactly like native ones.
Torch buffers (BatchNorm running stats etc.) are exposed as grad_req='null'
parameters and synced back after every forward, so checkpoints keep them.
Host-bound by design (like the reference plugin, which was CPU/GPU-kernel
bound): not traceable into jit graphs; use it in eager training or wrap
the surrounding (non-torch) subgraph with hybridize.
"""
from __future__ import annotations

import numpy as np
import jax.numpy as jnp

from ..gluon.block import Block
from ..initializer import Zero
from ..ndarray import NDArray
from .. import autograd


def _require_torch():
    try:
        import torch
        return torch
    except ImportError as e:  # pragma: no cover - torch is in this env
        raise ImportError(
            "mxnet_tpu.plugin.TorchBlock needs pytorch installed") from e


class TorchBlock(Block):
    """Wrap a ``torch.nn.Module``; its parameters become Gluon Parameters.

    Example::

        tb = TorchBlock(torch.nn.Linear(4, 2))
        tb(x)                       # forward
        gluon.Trainer(tb.collect_params(), "sgd", ...)  # trains torch weights
    """

    def __init__(self, torch_module, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        torch = _require_torch()
        assert isinstance(torch_module, torch.nn.Module)
        self._torch = torch
        self._module = torch_module
        self._tparam_names = []   # trainable (torch requires_grad) params
        self._tbuffer_names = []  # frozen params + buffers (grad_req null)

        def _register(tname, tensor, trainable):
            pname = tname.replace(".", "_")
            p = self.params.get(pname, shape=tuple(tensor.shape),
                                allow_deferred_init=False, init=Zero(),
                                grad_req="write" if trainable else "null")
            p._data = NDArray(np.ascontiguousarray(
                tensor.detach().cpu().numpy()))
            if p._grad_req != "null":
                p._init_grad()
            self._reg_params[pname] = p
            return pname

        for tname, tp in torch_module.named_parameters():
            if tp.requires_grad:
                self._tparam_names.append(
                    (_register(tname, tp, True), tname))
            else:
                self._tbuffer_names.append(
                    (_register(tname, tp, False), tname))
        for tname, tb in torch_module.named_buffers():
            # integer buffers (num_batches_tracked) checkpoint as float32
            # and cast back on sync-in, so BatchNorm(momentum=None)'s
            # cumulative averaging survives save/load
            self._tbuffer_names.append(
                (_register(tname, tb.float() if not tb.is_floating_point()
                           else tb, False), tname))

    def _torch_state(self):
        d = dict(self._module.named_parameters())
        d.update(self._module.named_buffers())
        return d

    def _sync_into_torch(self, param_nds, buffer_nds):
        """Copy framework values into the torch module — but only when they
        changed (NDArray._version stamps). Skipping the no-op copy matters
        for correctness, not just speed: an in-place copy_ between two
        recorded forwards bumps torch's version counters and invalidates
        the autograd graph the first forward saved (shared torch encoder
        called twice per loss)."""
        torch = self._torch
        stamps = tuple(p._version for p in param_nds + buffer_nds)
        if stamps == getattr(self, "_sync_stamps", None):
            return
        state = self._torch_state()
        pairs = list(zip(self._tparam_names, param_nds)) + \
            list(zip(self._tbuffer_names, buffer_nds))
        for (pname, tname), p in pairs:
            with torch.no_grad():
                # copy: jax-backed buffers surface as read-only numpy views;
                # torch casts to the destination dtype (int buffers restore
                # from their float32 checkpoint form); reshape covers 0-d
                # scalars the framework stores as shape-(1,)
                t = torch.from_numpy(np.array(p.asnumpy(), copy=True))
                state[tname].copy_(t.reshape(state[tname].shape))
        self._sync_stamps = stamps

    def _sync_buffers_back(self, buffer_nds):
        """After a training forward, pull mutated torch buffers (BatchNorm
        running stats) back into the framework parameters."""
        state = self._torch_state()
        for (pname, tname), buf in zip(self._tbuffer_names, buffer_nds):
            # buf is the parameter's NDArray: rebind its raw buffer
            buf._data = jnp.asarray(np.ascontiguousarray(
                state[tname].detach().cpu().numpy().astype(np.float32)))
            buf._version += 1
        if buffer_nds:
            # the write above changes versions; refresh the sync stamp so
            # the next forward doesn't re-copy identical values into torch
            params = [self._reg_params[n].data()
                      for n, _ in self._tparam_names]
            self._sync_stamps = tuple(
                x._version for x in params + buffer_nds)

    def forward(self, *inputs):
        torch = self._torch
        param_nds = [self._reg_params[p].data()
                     for p, _ in self._tparam_names]
        buffer_nds = [self._reg_params[p].data()
                      for p, _ in self._tbuffer_names]
        self._sync_into_torch(param_nds, buffer_nds)

        def _tin(a):
            t = torch.from_numpy(np.array(a.asnumpy(), copy=True))
            # integer inputs (embedding indices etc.) cannot require grad
            return t.requires_grad_(True) if t.is_floating_point() else t
        tin = [_tin(a) for a in inputs]
        train = autograd.is_training()
        self._module.train(train)
        tout = self._module(*tin)
        multi = isinstance(tout, (tuple, list))
        touts = list(tout) if multi else [tout]
        outs = [NDArray(o.detach().cpu().numpy()) for o in touts]
        if train:
            self._sync_buffers_back(buffer_nds)

        if autograd.is_recording():
            tstate = self._torch_state()
            tps = [tstate[tn] for _, tn in self._tparam_names]

            def torch_backward(out_grads, input_vals, kwargs):
                gouts = [torch.from_numpy(np.array(g, copy=True))
                         for g in out_grads]
                # frozen/int tensors can't join the grad call — they get
                # zero cotangents below
                diff = [t for t in tin if t.requires_grad] + tps
                if not diff:  # fully frozen module on integer inputs
                    return [np.zeros(np.shape(v), np.float32)
                            for v in input_vals]
                grads = iter(torch.autograd.grad(
                    touts, diff, grad_outputs=gouts,
                    retain_graph=True, allow_unused=True))
                out = []
                for t, v in zip(tin, input_vals):
                    g = next(grads) if t.requires_grad else None
                    out.append(np.zeros(np.shape(v), np.float32)
                               if g is None else g.detach().cpu().numpy())
                for v in input_vals[len(tin):]:
                    g = next(grads)
                    out.append(np.zeros(np.shape(v), np.float32)
                               if g is None else g.detach().cpu().numpy())
                return out

            class _OpDef:
                fn = None
                differentiable = True

            ins = list(inputs) + param_nds
            # tape carries the buffer references (no copies): the backward
            # only reads shapes from these values
            autograd.record_op(_OpDef, ins, [i._data for i in ins],
                               outs, {}, custom_backward=torch_backward)
        return outs[0] if len(outs) == 1 else outs
