"""Optional plugin bridges (parity: reference `plugin/` — caffe/torch op
bridges, `plugin/torch/torch_module.cc`). Only the torch bridge is provided
(PyTorch is the one plugin framework present in this environment); it is
import-gated so the core framework never requires torch.
"""
from . import torch_module  # noqa: F401
from .torch_module import TorchBlock  # noqa: F401
