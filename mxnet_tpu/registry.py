"""Generic class registry (parity: python/mxnet/registry.py — used by
optimizer/initializer/metric/lr_scheduler registration and JSON round-trip)."""
from __future__ import annotations

import json

_REGISTRIES = {}


def _get_registry(base_class, nickname):
    key = nickname
    if key not in _REGISTRIES:
        _REGISTRIES[key] = {}
    return _REGISTRIES[key]


def get_register_func(base_class, nickname):
    registry = _get_registry(base_class, nickname)

    def register(klass, name=None):
        assert issubclass(klass, base_class), \
            "can only register subclass of %s" % base_class.__name__
        nm = (name or klass.__name__).lower()
        registry[nm] = klass
        return klass

    return register


def get_alias_func(base_class, nickname):
    registry = _get_registry(base_class, nickname)

    def alias(*aliases):
        def reg(klass):
            for a in aliases:
                registry[a.lower()] = klass
            return klass
        return reg

    return alias


def get_create_func(base_class, nickname):
    registry = _get_registry(base_class, nickname)

    def create(*args, **kwargs):
        if len(args) and isinstance(args[0], base_class):
            return args[0]
        if len(args) and isinstance(args[0], str) and args[0].startswith("["):
            name, kw = json.loads(args[0])
            return registry[name.lower()](**kw)
        name = args[0] if args else kwargs.pop(nickname)
        args = args[1:]
        if name.lower() not in registry:
            raise ValueError("%s is not registered as a %s (known: %s)"
                             % (name, nickname, sorted(registry)))
        return registry[name.lower()](*args, **kwargs)

    return create
