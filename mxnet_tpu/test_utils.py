"""Test utilities.

Parity: reference `python/mxnet/test_utils.py` — assert_almost_equal:470,
check_numeric_gradient:792 (finite differences), check_symbolic_forward/
backward, check_consistency:1207 (cross-context), rand_ndarray:339,
default_context, simple data generators.
"""
from __future__ import annotations

import os

import numpy as np

from .context import Context, cpu, current_context
from .ndarray import NDArray
from . import ndarray as nd
from .ndarray.sparse import CSRNDArray, RowSparseNDArray


def default_context():
    return current_context()


def set_default_context(ctx):
    Context._default_ctx.value = ctx


def default_dtype():
    return np.float32


def rand_shape_2d(dim0=10, dim1=10):
    return (np.random.randint(1, dim0 + 1), np.random.randint(1, dim1 + 1))


def rand_shape_3d(dim0=10, dim1=10, dim2=10):
    return (np.random.randint(1, dim0 + 1), np.random.randint(1, dim1 + 1),
            np.random.randint(1, dim2 + 1))


def rand_shape_nd(num_dim, dim=10):
    return tuple(np.random.randint(1, dim + 1, size=num_dim))


def rand_ndarray(shape, stype="default", density=None, dtype=None,
                 distribution=None):
    """Random (optionally sparse) ndarray (parity: test_utils.py:339)."""
    density = density if density is not None else 0.5
    dtype = dtype or np.float32
    if stype == "default":
        return NDArray(np.random.uniform(-1, 1, shape).astype(dtype))
    dense = np.random.uniform(-1, 1, shape).astype(dtype)
    mask = np.random.rand(shape[0]) < density
    dense[~mask] = 0
    if stype == "row_sparse":
        return RowSparseNDArray.from_dense(NDArray(dense))
    if stype == "csr":
        flat_mask = np.random.rand(*shape) < density
        dense = np.where(flat_mask, dense, 0)
        return CSRNDArray.from_dense(NDArray(dense))
    raise ValueError(stype)


def rand_sparse_ndarray(shape, stype, density=None, dtype=None):
    arr = rand_ndarray(shape, stype, density, dtype)
    return arr, (arr._indices if hasattr(arr, "_indices") else None)


def np_reduce(dat, axis, keepdims, numpy_reduce_func):
    if isinstance(axis, int):
        axis = [axis]
    else:
        axis = list(axis) if axis is not None else range(len(dat.shape))
    ret = dat
    for i in reversed(sorted(axis)):
        ret = numpy_reduce_func(ret, axis=i)
    if keepdims:
        keepdims_shape = list(dat.shape)
        for i in axis:
            keepdims_shape[i] = 1
        ret = ret.reshape(tuple(keepdims_shape))
    return ret


def find_max_violation(a, b, rtol=None, atol=None):
    rtol = 1e-5 if rtol is None else rtol
    atol = 1e-20 if atol is None else atol
    diff = np.abs(a - b)
    tol = atol + rtol * np.abs(b)
    violation = diff / (tol + 1e-20)
    loc = np.argmax(violation)
    idx = np.unravel_index(loc, violation.shape)
    return idx, np.max(violation)


def same(a, b):
    return np.array_equal(a, b)


def assert_almost_equal(a, b, rtol=None, atol=None, names=("a", "b"),
                        equal_nan=False):
    """Parity: test_utils.py:470."""
    rtol = 1e-5 if rtol is None else rtol
    atol = 1e-20 if atol is None else atol
    a = a.asnumpy() if hasattr(a, "asnumpy") else np.asarray(a)
    b = b.asnumpy() if hasattr(b, "asnumpy") else np.asarray(b)
    a = np.asarray(a, dtype=np.float64) if a.dtype.kind not in "fc" else a
    b = np.asarray(b, dtype=np.float64) if b.dtype.kind not in "fc" else b
    if np.allclose(np.asarray(a, np.float64), np.asarray(b, np.float64),
                   rtol=rtol, atol=atol, equal_nan=equal_nan):
        return
    index, rel = find_max_violation(np.asarray(a, np.float64),
                                    np.asarray(b, np.float64), rtol, atol)
    raise AssertionError(
        "Error %f exceeds tolerance rtol=%f, atol=%f. Location of maximum "
        "error:%s, a=%f, b=%f" % (rel, rtol, atol, str(index),
                                  np.asarray(a, np.float64)[index],
                                  np.asarray(b, np.float64)[index]))


def almost_equal(a, b, rtol=None, atol=None, equal_nan=False):
    try:
        assert_almost_equal(a, b, rtol, atol, equal_nan=equal_nan)
        return True
    except AssertionError:
        return False


def simple_forward(sym, ctx=None, is_train=False, **inputs):
    inputs = {k: NDArray(np.asarray(v, dtype=np.float32))
              if not isinstance(v, NDArray) else v
              for k, v in inputs.items()}
    exe = sym.bind(ctx, args=inputs, grad_req="null")
    exe.forward(is_train=is_train)
    outputs = [x.asnumpy() for x in exe.outputs]
    if len(outputs) == 1:
        outputs = outputs[0]
    return outputs


def _parse_location(sym, location, ctx):
    if isinstance(location, dict):
        wrong = set(location.keys()) - set(sym.list_arguments())
        assert not wrong, "Location keys %s not in arguments %s" % (
            wrong, sym.list_arguments())
        location = {k: np.asarray(v) if not isinstance(v, NDArray)
                    else v.asnumpy() for k, v in location.items()}
    else:
        location = {k: np.asarray(v) if not isinstance(v, NDArray)
                    else v.asnumpy()
                    for k, v in zip(sym.list_arguments(), location)}
    return {k: NDArray(v.astype(np.float32) if v.dtype == np.float64 else v)
            for k, v in location.items()}


def check_numeric_gradient(sym, location, aux_states=None, numeric_eps=1e-3,
                           rtol=1e-2, atol=None, grad_nodes=None,
                           use_forward_train=True, ctx=None,
                           grad_stype_dict=None, dtype=np.float32):
    """Finite-difference gradient check (parity: test_utils.py:792)."""
    location = _parse_location(sym, location, ctx)
    if grad_nodes is None:
        grad_nodes = [k for k in location]
    aux = {k: NDArray(np.asarray(v)) for k, v in (aux_states or {}).items()} \
        if isinstance(aux_states, dict) else None

    def fwd(loc_np):
        args = {k: NDArray(v) for k, v in loc_np.items()}
        exe = sym.bind(ctx, args=args, grad_req="null",
                       aux_states=aux)
        exe.forward(is_train=use_forward_train)
        return sum(float(np.sum(o.asnumpy())) for o in exe.outputs)

    # analytic grads via backward with all-ones head
    args = {k: v.copy() for k, v in location.items()}
    req = {k: ("write" if k in grad_nodes else "null") for k in args}
    exe = sym.bind(ctx, args=args, grad_req=req, aux_states=aux)
    exe.forward(is_train=use_forward_train)
    exe.backward()
    analytic = {k: exe.grad_dict[k].asnumpy() for k in grad_nodes}

    loc_np = {k: v.asnumpy().astype(np.float64) for k, v in location.items()}
    for name in grad_nodes:
        arr = loc_np[name]
        num_grad = np.zeros_like(arr)
        it = np.nditer(arr, flags=["multi_index"])
        while not it.finished:
            idx = it.multi_index
            orig = arr[idx]
            arr[idx] = orig + numeric_eps / 2
            f_plus = fwd({k: v.astype(np.float32) for k, v in loc_np.items()})
            arr[idx] = orig - numeric_eps / 2
            f_minus = fwd({k: v.astype(np.float32) for k, v in loc_np.items()})
            arr[idx] = orig
            num_grad[idx] = (f_plus - f_minus) / numeric_eps
            it.iternext()
        assert_almost_equal(analytic[name], num_grad, rtol=rtol,
                            atol=atol if atol is not None else 1e-4,
                            names=("analytic_%s" % name, "numeric_%s" % name))


def check_symbolic_forward(sym, location, expected, rtol=1e-5, atol=None,
                           aux_states=None, ctx=None, equal_nan=False,
                           dtype=np.float32):
    location = _parse_location(sym, location, ctx)
    aux = {k: NDArray(np.asarray(v)) for k, v in (aux_states or {}).items()} \
        if isinstance(aux_states, dict) else None
    exe = sym.bind(ctx, args=location, grad_req="null", aux_states=aux)
    exe.forward(is_train=False)
    if isinstance(expected, dict):
        expected = [expected[k] for k in sym.list_outputs()]
    for out, exp in zip(exe.outputs, expected):
        assert_almost_equal(out.asnumpy(), exp, rtol=rtol,
                            atol=atol if atol is not None else 1e-20,
                            equal_nan=equal_nan)
    return exe.outputs


def check_symbolic_backward(sym, location, out_grads, expected, rtol=1e-5,
                            atol=None, aux_states=None, grad_req="write",
                            ctx=None, grad_stypes=None, equal_nan=False,
                            dtype=np.float32):
    location = _parse_location(sym, location, ctx)
    aux = {k: NDArray(np.asarray(v)) for k, v in (aux_states or {}).items()} \
        if isinstance(aux_states, dict) else None
    if isinstance(expected, (list, tuple)):
        expected = dict(zip(sym.list_arguments(), expected))
    req = {k: (grad_req if isinstance(grad_req, str)
               else grad_req.get(k, "write")) for k in location}
    for k in req:
        if k not in expected and req[k] == "write":
            req[k] = "null" if not isinstance(grad_req, dict) else req[k]
    exe = sym.bind(ctx, args=location, grad_req=req, aux_states=aux)
    exe.forward(is_train=True)
    ograds = [NDArray(np.asarray(g, dtype=np.float32)) for g in out_grads] \
        if out_grads is not None else None
    exe.backward(ograds)
    for name, exp in expected.items():
        assert_almost_equal(exe.grad_dict[name].asnumpy(), exp, rtol=rtol,
                            atol=atol if atol is not None else 1e-20,
                            equal_nan=equal_nan)
    return [exe.grad_dict.get(k) for k in sym.list_arguments()]


def check_consistency(sym, ctx_list, scale=1.0, grad_req="write",
                      arg_params=None, aux_params=None, tol=None,
                      raise_on_err=True, ground_truth=None, equal_nan=False):
    """Cross-context consistency (parity: test_utils.py:1207). On this stack
    the contexts are cpu vs tpu — the CPU↔TPU harness of SURVEY §4."""
    tol = tol or 1e-3
    if isinstance(sym, (list, tuple)):
        syms = list(sym)
    else:
        syms = [sym] * len(ctx_list)
    outputs = []
    grads = []
    for s, spec in zip(syms, ctx_list):
        ctx = spec.get("ctx", cpu())
        shapes = {k: v for k, v in spec.items()
                  if k not in ("ctx", "type_dict")}
        exe = s.simple_bind(ctx, grad_req=grad_req,
                            type_dict=spec.get("type_dict"), **shapes)
        if arg_params:
            for k, v in arg_params.items():
                if k in exe.arg_dict:
                    exe.arg_dict[k]._data = NDArray(np.asarray(v))._data
        else:
            np.random.seed(0)
            for k in sorted(exe.arg_dict):
                if k not in shapes:
                    exe.arg_dict[k]._data = NDArray(
                        np.random.normal(0, scale,
                                         exe.arg_dict[k].shape).astype(
                            np.float32))._data
        np.random.seed(1)
        for k in sorted(shapes):
            exe.arg_dict[k]._data = NDArray(
                np.random.normal(0, scale, shapes[k]).astype(np.float32))._data
        exe.forward(is_train=grad_req != "null")
        outputs.append([o.asnumpy() for o in exe.outputs])
        if grad_req != "null":
            exe.backward()
            grads.append({k: v.asnumpy() for k, v in exe.grad_dict.items()})
    ref = ground_truth or outputs[0]
    for out in outputs[1:]:
        for o, r in zip(out, ref):
            assert_almost_equal(o, r, rtol=tol, atol=tol,
                                equal_nan=equal_nan)
    return outputs


def get_mnist():
    """Synthetic-backed MNIST dict (parity: test_utils.get_mnist)."""
    from .gluon.data.vision.datasets import _synthetic
    tr_d, tr_l = _synthetic(6000, (28, 28, 1), 10, 42)
    te_d, te_l = _synthetic(1000, (28, 28, 1), 10, 43)
    return {"train_data": tr_d.transpose(0, 3, 1, 2).astype(np.float32) / 255,
            "train_label": tr_l,
            "test_data": te_d.transpose(0, 3, 1, 2).astype(np.float32) / 255,
            "test_label": te_l}


def get_mnist_iterator(batch_size, input_shape, num_parts=1, part_index=0):
    from .io import NDArrayIter
    mnist = get_mnist()
    flat = len(input_shape) == 1
    shape = (-1,) + tuple(input_shape)
    train = NDArrayIter(mnist["train_data"].reshape(shape)[part_index::num_parts],
                        mnist["train_label"][part_index::num_parts],
                        batch_size, shuffle=True)
    val = NDArrayIter(mnist["test_data"].reshape(shape), mnist["test_label"],
                      batch_size)
    return train, val


def list_gpus():
    from .context import num_gpus
    return list(range(num_gpus()))


def set_env_var(key, val, default_val=""):
    import os
    prev_val = os.environ.get(key, default_val)
    os.environ[key] = val
    return prev_val


def make_synthetic_det_dataset(path, num_images=40, size=48, num_classes=2,
                               seed=0):
    """Write a synthetic detection dataset (JPEG files + imglist entries).

    Each image is noise background with 1-2 solid rectangles; class c fills
    channel c. Returns an imglist of [flat_det_label, filename] rows using
    the im2rec detection label format [header_width=2, obj_width=5,
    (cls, xmin, ymin, xmax, ymax)*] with normalized corner coords
    (parity: the tools/im2rec.py detection packing convention).
    """
    import cv2
    rng = np.random.RandomState(seed)
    os.makedirs(path, exist_ok=True)
    imglist = []
    for i in range(num_images):
        img = rng.randint(0, 60, (size, size, 3)).astype(np.uint8)
        objs = []
        for _ in range(rng.randint(1, 3)):
            cls = rng.randint(num_classes)
            w = rng.randint(size // 4, size // 2)
            h = rng.randint(size // 4, size // 2)
            x0 = rng.randint(0, size - w)
            y0 = rng.randint(0, size - h)
            img[y0:y0 + h, x0:x0 + w, cls] = 230
            objs += [float(cls), x0 / size, y0 / size,
                     (x0 + w) / size, (y0 + h) / size]
        fname = "img%04d.jpg" % i
        cv2.imwrite(os.path.join(path, fname),
                    cv2.cvtColor(img, cv2.COLOR_RGB2BGR))
        imglist.append([[2.0, 5.0] + objs, fname])
    return imglist


# ---------------------------------------------------------------------------
# round-5 parity fills (reference test_utils.py helpers reference-era test
# code imports): tolerance helpers, statistical generator checks, sparse
# factories, small utilities, and the data fetchers (hermetic synthetic
# fallbacks in this zero-egress environment).
# ---------------------------------------------------------------------------

_RTOLS = {np.dtype(np.float16): 1e-2, np.dtype(np.float32): 1e-4,
          np.dtype(np.float64): 1e-5}
_ATOLS = {np.dtype(np.float16): 1e-1, np.dtype(np.float32): 1e-3,
          np.dtype(np.float64): 1e-20}


def get_rtol(rtol=None, dtype=np.float32):
    """Default relative tolerance per dtype (parity: test_utils.py)."""
    if rtol is not None:
        return rtol
    return _RTOLS.get(np.dtype(dtype), 1e-4)


def get_atol(atol=None, dtype=np.float32):
    if atol is not None:
        return atol
    return _ATOLS.get(np.dtype(dtype), 1e-3)


def _to_np(a):
    return a.asnumpy() if hasattr(a, "asnumpy") else np.asarray(a)


def almost_equal_ignore_nan(a, b, rtol=None, atol=None):
    """Elementwise closeness ignoring positions where EITHER side is NaN
    (parity: test_utils.py)."""
    a, b = _to_np(a).copy(), _to_np(b).copy()
    nan_mask = np.logical_or(np.isnan(a), np.isnan(b))
    a[nan_mask] = 0
    b[nan_mask] = 0
    return np.allclose(a, b, rtol=get_rtol(rtol, a.dtype),
                       atol=get_atol(atol, a.dtype))


def assert_almost_equal_ignore_nan(a, b, rtol=None, atol=None, names=()):
    if not almost_equal_ignore_nan(a, b, rtol, atol):
        raise AssertionError(
            "arrays differ beyond tolerance (NaNs ignored)%s"
            % (": %s" % (names,) if names else ""))


def assert_exception(f, exception_type, *args, **kwargs):
    """f(*args) must raise exception_type (parity: test_utils.py)."""
    try:
        f(*args, **kwargs)
    except exception_type:
        return
    raise AssertionError("did not raise %s" % exception_type.__name__)


def same_array(array1, array2):
    """True when two NDArrays share storage — probed behaviorally, as the
    reference does: bump one and see the other move (parity)."""
    array1[:] = array1 + 1
    if not np.array_equal(_to_np(array1), _to_np(array2)):
        array1[:] = array1 - 1
        return False
    array1[:] = array1 - 1
    return np.array_equal(_to_np(array1), _to_np(array2))


def assign_each(input_arr, function):
    """Apply a scalar function elementwise on host (parity)."""
    out = np.vectorize(function)(_to_np(input_arr))
    return nd.array(out)


def assign_each2(input1, input2, function):
    out = np.vectorize(function)(_to_np(input1), _to_np(input2))
    return nd.array(out)


def discard_stderr():
    """Context manager silencing C-level stderr (parity: the reference
    uses it around deliberately-noisy calls)."""
    import contextlib
    import sys

    @contextlib.contextmanager
    def _ctx():
        with open(os.devnull, "w") as devnull:
            old = os.dup(2)
            os.dup2(devnull.fileno(), 2)
            try:
                yield
            finally:
                sys.stderr.flush()
                os.dup2(old, 2)
                os.close(old)
    return _ctx()


def retry(n):
    """Decorator retrying a flaky test up to n times (parity)."""
    if n <= 0:
        raise ValueError("n must be positive")

    def decorate(f):
        import functools

        @functools.wraps(f)
        def wrapper(*args, **kwargs):
            for i in range(n):
                try:
                    return f(*args, **kwargs)
                except AssertionError:
                    if i == n - 1:
                        raise
        return wrapper
    return decorate


def random_arrays(*shapes):
    """Random float32 arrays; scalar shape () gives a python float
    (parity)."""
    arrays = [np.array(np.random.randn(), dtype=np.float32) if not s
              else np.random.randn(*s).astype(np.float32) for s in shapes]
    return arrays[0] if len(arrays) == 1 else arrays


def random_sample(population, k):
    """Sample WITHOUT replacement, order preserved (parity)."""
    import random as _random
    assert k <= len(population)
    picks = sorted(_random.sample(range(len(population)), k))
    return [population[i] for i in picks]


def shuffle_csr_column_indices(csr):
    """Shuffle each row's column indices in place-order (parity: makes
    unsorted-column csr fixtures)."""
    indices = np.asarray(csr._indices).copy()
    indptr = np.asarray(csr._indptr)
    for i in range(len(indptr) - 1):
        seg = indices[indptr[i]:indptr[i + 1]]
        np.random.shuffle(seg)
        indices[indptr[i]:indptr[i + 1]] = seg
    import jax.numpy as jnp
    return CSRNDArray(csr._values, jnp.asarray(indices), csr._indptr,
                      csr.shape)


def create_sparse_array(shape, stype, data_init=None, rsp_indices=None,
                        dtype=None, modifier_func=None, density=0.5,
                        shuffle_csr_indices=False):
    """Random sparse ndarray factory (parity: test_utils.py).
    rsp_indices pins WHICH rows of a row_sparse array are populated."""
    if rsp_indices is not None:
        if stype != "row_sparse":
            raise ValueError("rsp_indices only applies to row_sparse")
        import jax.numpy as jnp
        idx = np.sort(np.asarray(rsp_indices).astype(np.int32))
        vals = np.random.randn(len(idx), *shape[1:]).astype(
            np.dtype(dtype) if dtype else np.float32)
        arr = RowSparseNDArray(jnp.asarray(idx), jnp.asarray(vals), shape)
    else:
        arr = rand_ndarray(shape, stype=stype, density=density,
                           dtype=dtype)
    if data_init is not None:
        d = _to_np(arr)
        d[d != 0] = data_init
        arr = nd.array(d).tostype(stype)
    if modifier_func is not None:
        d = np.vectorize(modifier_func)(_to_np(arr))
        arr = nd.array(d).tostype(stype)
    if stype == "csr" and shuffle_csr_indices:
        arr = shuffle_csr_column_indices(arr)
    return arr


def create_sparse_array_zd(shape, stype, density, data_init=None,
                           rsp_indices=None, dtype=None,
                           modifier_func=None, shuffle_csr_indices=False):
    """Sparse factory permitting all-zero (zero-density) arrays
    (parity)."""
    if density == 0:
        from .ndarray import sparse as _sp
        return _sp.zeros(stype, shape, dtype=dtype)
    return create_sparse_array(shape, stype, data_init=data_init,
                               rsp_indices=rsp_indices, dtype=dtype,
                               modifier_func=modifier_func,
                               density=density,
                               shuffle_csr_indices=shuffle_csr_indices)


class DummyIter(object):
    """Infinitely repeat one real batch (parity: test_utils.py DummyIter
    — benchmarking iterator that removes IO from the measurement)."""

    def __init__(self, real_iter):
        self.real_iter = real_iter
        self.provide_data = real_iter.provide_data
        self.provide_label = real_iter.provide_label
        self.batch_size = real_iter.batch_size
        self.the_batch = next(iter(real_iter))

    def __iter__(self):
        return self

    def next(self):
        return self.the_batch

    __next__ = next

    def reset(self):
        """No-op: the loop's end-of-epoch reset must not crash (the
        reference inherits this from DataIter)."""


def check_speed(sym, location=None, ctx=None, N=20, grad_req=None,
                typ="whole"):
    """Wall-clock one executor forward(+backward) (parity). typ='whole'
    times forward+backward, 'forward' only the forward pass."""
    import time as _time
    ctx = ctx or cpu()
    if grad_req is None:
        grad_req = "write"
    if location is None:
        arg_shapes, _, _ = sym.infer_shape()
        location = {name: np.random.normal(size=shape, scale=1.0)
                    for name, shape in zip(sym.list_arguments(),
                                           arg_shapes)}
    exe = sym.simple_bind(ctx, grad_req=grad_req,
                          **{k: v.shape for k, v in location.items()})
    for name, value in location.items():
        if name in exe.arg_dict:
            exe.arg_dict[name][:] = nd.array(value)
    if typ == "whole":
        def run():
            exe.forward(is_train=True)
            exe.backward(out_grads=exe.outputs)
            for o in exe.outputs:
                o.wait_to_read()
    elif typ == "forward":
        def run():
            exe.forward(is_train=False)
            for o in exe.outputs:
                o.wait_to_read()
    else:
        raise ValueError("typ can only be whole or forward")
    run()  # warmup/compile
    tic = _time.time()
    for _ in range(N):
        run()
    nd.waitall()
    return (_time.time() - tic) / N


def gen_buckets_probs_with_ppf(ppf, nbuckets):
    """Equiprobable buckets from a quantile function (parity)."""
    probs = [1.0 / nbuckets] * nbuckets
    buckets = [(ppf(i / float(nbuckets)), ppf((i + 1) / float(nbuckets)))
               for i in range(nbuckets)]
    return buckets, probs


def mean_check(generator, mu, sigma, nsamples=1000000):
    """Sample mean within mu +- 3 sigma/sqrt(n) (parity)."""
    samples = np.array(generator(nsamples))
    sample_mean = samples.mean()
    return (mu - 3 * sigma / np.sqrt(nsamples) < sample_mean <
            mu + 3 * sigma / np.sqrt(nsamples))


def var_check(generator, sigma, nsamples=1000000):
    """Sample variance within the 3-sigma band of its own sampling
    distribution (parity)."""
    samples = np.array(generator(nsamples))
    sample_var = samples.var(ddof=1)
    band = 3 * np.sqrt(2 * sigma ** 4 / (nsamples - 1))
    return sigma ** 2 - band < sample_var < sigma ** 2 + band


def chi_square_check(generator, buckets, probs, nsamples=1000000):
    """Chi-square goodness-of-fit of generator samples against bucket
    probabilities; continuous buckets are (lo, hi) tuples, discrete
    buckets are the category values (parity). Returns (p, obs_freq,
    expected_freq)."""
    from scipy import stats as _stats
    if not buckets:
        raise ValueError("buckets must be nonempty")
    expected = np.array(probs) * nsamples
    samples = np.asarray(generator(nsamples))
    if isinstance(buckets[0], (list, tuple)):
        edges = [b[0] for b in buckets] + [buckets[-1][1]]
        obs, _ = np.histogram(samples, bins=np.array(edges))
    else:
        mapping = {v: i for i, v in enumerate(buckets)}
        obs = np.zeros(len(buckets))
        for v, c in zip(*np.unique(samples, return_counts=True)):
            if v in mapping:
                obs[mapping[v]] = c
    # samples outside the bucket edges drop out of obs; rescale the
    # expected counts to the observed total so scipy's sum check holds
    if obs.sum() == 0:
        raise AssertionError(
            "chi_square_check: no sample landed in any bucket — the "
            "generator's support does not overlap the bucket range "
            "(sample range [%g, %g])" % (samples.min(), samples.max()))
    expected = expected * (obs.sum() / expected.sum())
    _, p = _stats.chisquare(f_obs=obs, f_exp=expected)
    return p, obs, expected


def verify_generator(generator, buckets, probs, nsamples=1000000,
                     nrepeat=5, success_rate=0.15, alpha=0.05):
    """Repeat the chi-square test; the fraction of runs with p > alpha
    must reach success_rate (parity). Returns the p-value list."""
    cs_ret_l = []
    for _ in range(nrepeat):
        p, _, _ = chi_square_check(generator, buckets, probs, nsamples)
        cs_ret_l.append(p)
    success = np.mean(np.array(cs_ret_l) > alpha)
    if success < success_rate:
        raise AssertionError(
            "generator failed chi-square: success rate %.2f < %.2f "
            "(p-values %s)" % (success, success_rate, cs_ret_l))
    return cs_ret_l


def numeric_grad(executor, location, aux_states=None, eps=1e-4,
                 use_forward_train=True, dtype=np.float32):
    """Finite-difference gradients of an executor's scalar-summed output
    w.r.t. every argument (parity; the symbolic-level helper is
    check_numeric_gradient)."""
    for k, v in location.items():
        if k in executor.arg_dict:
            executor.arg_dict[k][:] = nd.array(v)
    approx_grads = {k: np.zeros(v.shape, dtype=dtype)
                    for k, v in location.items()}
    for k, v in location.items():
        if k not in executor.arg_dict:
            continue
        old_value = np.array(v, dtype=dtype).copy()
        flat = old_value.reshape(-1)
        grad_flat = approx_grads[k].reshape(-1)
        for i in range(flat.size):
            flat[i] += eps / 2.0
            executor.arg_dict[k][:] = nd.array(old_value)
            executor.forward(is_train=use_forward_train)
            f_eps = sum(float(o.asnumpy().sum()) for o in executor.outputs)
            flat[i] -= eps
            executor.arg_dict[k][:] = nd.array(old_value)
            executor.forward(is_train=use_forward_train)
            f_neps = sum(float(o.asnumpy().sum())
                         for o in executor.outputs)
            grad_flat[i] = (f_eps - f_neps) / eps
            flat[i] += eps / 2.0
        executor.arg_dict[k][:] = nd.array(old_value)
    return approx_grads


# ---- data fetchers (hermetic synthetic fallbacks: zero-egress env) ------


def download(url, fname=None, dirname=None, overwrite=False):
    """Fetch a URL to a file (parity: test_utils.py download). In this
    zero-egress environment real fetches fail; the function exists for
    API compatibility and for images/networks that do have egress."""
    import urllib.request
    fname = fname or url.split("/")[-1]
    if dirname is not None:
        os.makedirs(dirname, exist_ok=True)
        fname = os.path.join(dirname, fname)
    if os.path.exists(fname) and not overwrite:
        return fname
    urllib.request.urlretrieve(url, fname)
    return fname


def get_mnist_pkl(data_dir="data"):
    """mnist.pkl.gz in the reference layout, generated from the synthetic
    MNIST (hermetic parity: the reference downloads it)."""
    import gzip
    import pickle
    os.makedirs(data_dir, exist_ok=True)
    path = os.path.join(data_dir, "mnist.pkl.gz")
    if os.path.exists(path):
        return path
    m = get_mnist()
    flat = m["train_data"].reshape(len(m["train_data"]), -1)
    tflat = m["test_data"].reshape(len(m["test_data"]), -1)
    n_val = len(tflat)
    splits = ((flat, m["train_label"]), (tflat, m["test_label"]),
              (tflat[:n_val], m["test_label"][:n_val]))
    with gzip.open(path, "wb") as f:
        pickle.dump(splits, f)
    return path


def get_mnist_ubyte(data_dir="data"):
    """idx-ubyte MNIST files in the reference layout, generated from the
    synthetic MNIST (hermetic parity)."""
    import struct
    os.makedirs(data_dir, exist_ok=True)
    m = get_mnist()

    def write_images(path, arr):
        arr = (arr * 255).astype(np.uint8).reshape(len(arr), 28, 28)
        with open(path, "wb") as f:
            f.write(struct.pack(">IIII", 2051, len(arr), 28, 28))
            f.write(arr.tobytes())

    def write_labels(path, lab):
        with open(path, "wb") as f:
            f.write(struct.pack(">II", 2049, len(lab)))
            f.write(lab.astype(np.uint8).tobytes())

    names = {"train-images-idx3-ubyte": ("train_data", write_images),
             "train-labels-idx1-ubyte": ("train_label", write_labels),
             "t10k-images-idx3-ubyte": ("test_data", write_images),
             "t10k-labels-idx1-ubyte": ("test_label", write_labels)}
    for name, (key, writer) in names.items():
        path = os.path.join(data_dir, name)
        if not os.path.exists(path):
            writer(path, m[key])
    return data_dir


def get_cifar10(data_dir="data"):
    """cifar/train.rec + test.rec in the reference layout, packed from
    synthetic 32x32 images (hermetic parity)."""
    from . import recordio
    import io as _pyio
    from PIL import Image
    cifar = os.path.join(data_dir, "cifar")
    os.makedirs(cifar, exist_ok=True)
    rng = np.random.RandomState(10)
    for split, n in (("train.rec", 500), ("test.rec", 100)):
        path = os.path.join(cifar, split)
        if os.path.exists(path):
            continue
        w = recordio.MXRecordIO(path, "w")
        for i in range(n):
            img = rng.randint(0, 255, (32, 32, 3)).astype(np.uint8)
            buf = _pyio.BytesIO()
            Image.fromarray(img).save(buf, format="JPEG", quality=90)
            w.write(recordio.pack(
                recordio.IRHeader(0, float(i % 10), i, 0), buf.getvalue()))
        w.close()
    return cifar


def get_im2rec_path(home_env="MXNET_HOME"):
    """Path of the im2rec tool (parity: finds the in-tree script)."""
    here = os.path.dirname(os.path.abspath(__file__))
    path = os.path.join(os.path.dirname(here), "tools", "im2rec.py")
    if os.path.isfile(path):
        return path
    raise IOError("tools/im2rec.py not found from %s" % here)


def get_bz2_data(data_dir, data_name, url, data_origin_name):
    """download + bunzip2 (parity); hermetic envs should ship the file."""
    import bz2
    os.makedirs(data_dir, exist_ok=True)
    out = os.path.join(data_dir, data_name)
    if os.path.exists(out):
        return out
    archive = download(url, fname=os.path.join(data_dir, data_origin_name))
    with bz2.BZ2File(archive) as fi, open(out, "wb") as fo:
        fo.write(fi.read())
    os.remove(archive)
    return out


def get_zip_data(data_dir, url, data_origin_name):
    """download + unzip (parity); hermetic envs should ship the file."""
    import zipfile
    os.makedirs(data_dir, exist_ok=True)
    archive = os.path.join(data_dir, data_origin_name)
    if not os.path.exists(archive):
        download(url, fname=archive)
    with zipfile.ZipFile(archive) as z:
        z.extractall(data_dir)
    return data_dir
