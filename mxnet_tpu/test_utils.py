"""Test utilities.

Parity: reference `python/mxnet/test_utils.py` — assert_almost_equal:470,
check_numeric_gradient:792 (finite differences), check_symbolic_forward/
backward, check_consistency:1207 (cross-context), rand_ndarray:339,
default_context, simple data generators.
"""
from __future__ import annotations

import os

import numpy as np

from .context import Context, cpu, current_context
from .ndarray import NDArray
from . import ndarray as nd
from .ndarray.sparse import CSRNDArray, RowSparseNDArray


def default_context():
    return current_context()


def set_default_context(ctx):
    Context._default_ctx.value = ctx


def default_dtype():
    return np.float32


def rand_shape_2d(dim0=10, dim1=10):
    return (np.random.randint(1, dim0 + 1), np.random.randint(1, dim1 + 1))


def rand_shape_3d(dim0=10, dim1=10, dim2=10):
    return (np.random.randint(1, dim0 + 1), np.random.randint(1, dim1 + 1),
            np.random.randint(1, dim2 + 1))


def rand_shape_nd(num_dim, dim=10):
    return tuple(np.random.randint(1, dim + 1, size=num_dim))


def rand_ndarray(shape, stype="default", density=None, dtype=None,
                 distribution=None):
    """Random (optionally sparse) ndarray (parity: test_utils.py:339)."""
    density = density if density is not None else 0.5
    dtype = dtype or np.float32
    if stype == "default":
        return NDArray(np.random.uniform(-1, 1, shape).astype(dtype))
    dense = np.random.uniform(-1, 1, shape).astype(dtype)
    mask = np.random.rand(shape[0]) < density
    dense[~mask] = 0
    if stype == "row_sparse":
        return RowSparseNDArray.from_dense(NDArray(dense))
    if stype == "csr":
        flat_mask = np.random.rand(*shape) < density
        dense = np.where(flat_mask, dense, 0)
        return CSRNDArray.from_dense(NDArray(dense))
    raise ValueError(stype)


def rand_sparse_ndarray(shape, stype, density=None, dtype=None):
    arr = rand_ndarray(shape, stype, density, dtype)
    return arr, (arr._indices if hasattr(arr, "_indices") else None)


def np_reduce(dat, axis, keepdims, numpy_reduce_func):
    if isinstance(axis, int):
        axis = [axis]
    else:
        axis = list(axis) if axis is not None else range(len(dat.shape))
    ret = dat
    for i in reversed(sorted(axis)):
        ret = numpy_reduce_func(ret, axis=i)
    if keepdims:
        keepdims_shape = list(dat.shape)
        for i in axis:
            keepdims_shape[i] = 1
        ret = ret.reshape(tuple(keepdims_shape))
    return ret


def find_max_violation(a, b, rtol=None, atol=None):
    rtol = 1e-5 if rtol is None else rtol
    atol = 1e-20 if atol is None else atol
    diff = np.abs(a - b)
    tol = atol + rtol * np.abs(b)
    violation = diff / (tol + 1e-20)
    loc = np.argmax(violation)
    idx = np.unravel_index(loc, violation.shape)
    return idx, np.max(violation)


def same(a, b):
    return np.array_equal(a, b)


def assert_almost_equal(a, b, rtol=None, atol=None, names=("a", "b"),
                        equal_nan=False):
    """Parity: test_utils.py:470."""
    rtol = 1e-5 if rtol is None else rtol
    atol = 1e-20 if atol is None else atol
    a = a.asnumpy() if hasattr(a, "asnumpy") else np.asarray(a)
    b = b.asnumpy() if hasattr(b, "asnumpy") else np.asarray(b)
    a = np.asarray(a, dtype=np.float64) if a.dtype.kind not in "fc" else a
    b = np.asarray(b, dtype=np.float64) if b.dtype.kind not in "fc" else b
    if np.allclose(np.asarray(a, np.float64), np.asarray(b, np.float64),
                   rtol=rtol, atol=atol, equal_nan=equal_nan):
        return
    index, rel = find_max_violation(np.asarray(a, np.float64),
                                    np.asarray(b, np.float64), rtol, atol)
    raise AssertionError(
        "Error %f exceeds tolerance rtol=%f, atol=%f. Location of maximum "
        "error:%s, a=%f, b=%f" % (rel, rtol, atol, str(index),
                                  np.asarray(a, np.float64)[index],
                                  np.asarray(b, np.float64)[index]))


def almost_equal(a, b, rtol=None, atol=None, equal_nan=False):
    try:
        assert_almost_equal(a, b, rtol, atol, equal_nan=equal_nan)
        return True
    except AssertionError:
        return False


def simple_forward(sym, ctx=None, is_train=False, **inputs):
    inputs = {k: NDArray(np.asarray(v, dtype=np.float32))
              if not isinstance(v, NDArray) else v
              for k, v in inputs.items()}
    exe = sym.bind(ctx, args=inputs, grad_req="null")
    exe.forward(is_train=is_train)
    outputs = [x.asnumpy() for x in exe.outputs]
    if len(outputs) == 1:
        outputs = outputs[0]
    return outputs


def _parse_location(sym, location, ctx):
    if isinstance(location, dict):
        wrong = set(location.keys()) - set(sym.list_arguments())
        assert not wrong, "Location keys %s not in arguments %s" % (
            wrong, sym.list_arguments())
        location = {k: np.asarray(v) if not isinstance(v, NDArray)
                    else v.asnumpy() for k, v in location.items()}
    else:
        location = {k: np.asarray(v) if not isinstance(v, NDArray)
                    else v.asnumpy()
                    for k, v in zip(sym.list_arguments(), location)}
    return {k: NDArray(v.astype(np.float32) if v.dtype == np.float64 else v)
            for k, v in location.items()}


def check_numeric_gradient(sym, location, aux_states=None, numeric_eps=1e-3,
                           rtol=1e-2, atol=None, grad_nodes=None,
                           use_forward_train=True, ctx=None,
                           grad_stype_dict=None, dtype=np.float32):
    """Finite-difference gradient check (parity: test_utils.py:792)."""
    location = _parse_location(sym, location, ctx)
    if grad_nodes is None:
        grad_nodes = [k for k in location]
    aux = {k: NDArray(np.asarray(v)) for k, v in (aux_states or {}).items()} \
        if isinstance(aux_states, dict) else None

    def fwd(loc_np):
        args = {k: NDArray(v) for k, v in loc_np.items()}
        exe = sym.bind(ctx, args=args, grad_req="null",
                       aux_states=aux)
        exe.forward(is_train=use_forward_train)
        return sum(float(np.sum(o.asnumpy())) for o in exe.outputs)

    # analytic grads via backward with all-ones head
    args = {k: v.copy() for k, v in location.items()}
    req = {k: ("write" if k in grad_nodes else "null") for k in args}
    exe = sym.bind(ctx, args=args, grad_req=req, aux_states=aux)
    exe.forward(is_train=use_forward_train)
    exe.backward()
    analytic = {k: exe.grad_dict[k].asnumpy() for k in grad_nodes}

    loc_np = {k: v.asnumpy().astype(np.float64) for k, v in location.items()}
    for name in grad_nodes:
        arr = loc_np[name]
        num_grad = np.zeros_like(arr)
        it = np.nditer(arr, flags=["multi_index"])
        while not it.finished:
            idx = it.multi_index
            orig = arr[idx]
            arr[idx] = orig + numeric_eps / 2
            f_plus = fwd({k: v.astype(np.float32) for k, v in loc_np.items()})
            arr[idx] = orig - numeric_eps / 2
            f_minus = fwd({k: v.astype(np.float32) for k, v in loc_np.items()})
            arr[idx] = orig
            num_grad[idx] = (f_plus - f_minus) / numeric_eps
            it.iternext()
        assert_almost_equal(analytic[name], num_grad, rtol=rtol,
                            atol=atol if atol is not None else 1e-4,
                            names=("analytic_%s" % name, "numeric_%s" % name))


def check_symbolic_forward(sym, location, expected, rtol=1e-5, atol=None,
                           aux_states=None, ctx=None, equal_nan=False,
                           dtype=np.float32):
    location = _parse_location(sym, location, ctx)
    aux = {k: NDArray(np.asarray(v)) for k, v in (aux_states or {}).items()} \
        if isinstance(aux_states, dict) else None
    exe = sym.bind(ctx, args=location, grad_req="null", aux_states=aux)
    exe.forward(is_train=False)
    if isinstance(expected, dict):
        expected = [expected[k] for k in sym.list_outputs()]
    for out, exp in zip(exe.outputs, expected):
        assert_almost_equal(out.asnumpy(), exp, rtol=rtol,
                            atol=atol if atol is not None else 1e-20,
                            equal_nan=equal_nan)
    return exe.outputs


def check_symbolic_backward(sym, location, out_grads, expected, rtol=1e-5,
                            atol=None, aux_states=None, grad_req="write",
                            ctx=None, grad_stypes=None, equal_nan=False,
                            dtype=np.float32):
    location = _parse_location(sym, location, ctx)
    aux = {k: NDArray(np.asarray(v)) for k, v in (aux_states or {}).items()} \
        if isinstance(aux_states, dict) else None
    if isinstance(expected, (list, tuple)):
        expected = dict(zip(sym.list_arguments(), expected))
    req = {k: (grad_req if isinstance(grad_req, str)
               else grad_req.get(k, "write")) for k in location}
    for k in req:
        if k not in expected and req[k] == "write":
            req[k] = "null" if not isinstance(grad_req, dict) else req[k]
    exe = sym.bind(ctx, args=location, grad_req=req, aux_states=aux)
    exe.forward(is_train=True)
    ograds = [NDArray(np.asarray(g, dtype=np.float32)) for g in out_grads] \
        if out_grads is not None else None
    exe.backward(ograds)
    for name, exp in expected.items():
        assert_almost_equal(exe.grad_dict[name].asnumpy(), exp, rtol=rtol,
                            atol=atol if atol is not None else 1e-20,
                            equal_nan=equal_nan)
    return [exe.grad_dict.get(k) for k in sym.list_arguments()]


def check_consistency(sym, ctx_list, scale=1.0, grad_req="write",
                      arg_params=None, aux_params=None, tol=None,
                      raise_on_err=True, ground_truth=None, equal_nan=False):
    """Cross-context consistency (parity: test_utils.py:1207). On this stack
    the contexts are cpu vs tpu — the CPU↔TPU harness of SURVEY §4."""
    tol = tol or 1e-3
    if isinstance(sym, (list, tuple)):
        syms = list(sym)
    else:
        syms = [sym] * len(ctx_list)
    outputs = []
    grads = []
    for s, spec in zip(syms, ctx_list):
        ctx = spec.get("ctx", cpu())
        shapes = {k: v for k, v in spec.items()
                  if k not in ("ctx", "type_dict")}
        exe = s.simple_bind(ctx, grad_req=grad_req,
                            type_dict=spec.get("type_dict"), **shapes)
        if arg_params:
            for k, v in arg_params.items():
                if k in exe.arg_dict:
                    exe.arg_dict[k]._data = NDArray(np.asarray(v))._data
        else:
            np.random.seed(0)
            for k in sorted(exe.arg_dict):
                if k not in shapes:
                    exe.arg_dict[k]._data = NDArray(
                        np.random.normal(0, scale,
                                         exe.arg_dict[k].shape).astype(
                            np.float32))._data
        np.random.seed(1)
        for k in sorted(shapes):
            exe.arg_dict[k]._data = NDArray(
                np.random.normal(0, scale, shapes[k]).astype(np.float32))._data
        exe.forward(is_train=grad_req != "null")
        outputs.append([o.asnumpy() for o in exe.outputs])
        if grad_req != "null":
            exe.backward()
            grads.append({k: v.asnumpy() for k, v in exe.grad_dict.items()})
    ref = ground_truth or outputs[0]
    for out in outputs[1:]:
        for o, r in zip(out, ref):
            assert_almost_equal(o, r, rtol=tol, atol=tol,
                                equal_nan=equal_nan)
    return outputs


def get_mnist():
    """Synthetic-backed MNIST dict (parity: test_utils.get_mnist)."""
    from .gluon.data.vision.datasets import _synthetic
    tr_d, tr_l = _synthetic(6000, (28, 28, 1), 10, 42)
    te_d, te_l = _synthetic(1000, (28, 28, 1), 10, 43)
    return {"train_data": tr_d.transpose(0, 3, 1, 2).astype(np.float32) / 255,
            "train_label": tr_l,
            "test_data": te_d.transpose(0, 3, 1, 2).astype(np.float32) / 255,
            "test_label": te_l}


def get_mnist_iterator(batch_size, input_shape, num_parts=1, part_index=0):
    from .io import NDArrayIter
    mnist = get_mnist()
    flat = len(input_shape) == 1
    shape = (-1,) + tuple(input_shape)
    train = NDArrayIter(mnist["train_data"].reshape(shape)[part_index::num_parts],
                        mnist["train_label"][part_index::num_parts],
                        batch_size, shuffle=True)
    val = NDArrayIter(mnist["test_data"].reshape(shape), mnist["test_label"],
                      batch_size)
    return train, val


def list_gpus():
    from .context import num_gpus
    return list(range(num_gpus()))


def set_env_var(key, val, default_val=""):
    import os
    prev_val = os.environ.get(key, default_val)
    os.environ[key] = val
    return prev_val


def make_synthetic_det_dataset(path, num_images=40, size=48, num_classes=2,
                               seed=0):
    """Write a synthetic detection dataset (JPEG files + imglist entries).

    Each image is noise background with 1-2 solid rectangles; class c fills
    channel c. Returns an imglist of [flat_det_label, filename] rows using
    the im2rec detection label format [header_width=2, obj_width=5,
    (cls, xmin, ymin, xmax, ymax)*] with normalized corner coords
    (parity: the tools/im2rec.py detection packing convention).
    """
    import cv2
    rng = np.random.RandomState(seed)
    os.makedirs(path, exist_ok=True)
    imglist = []
    for i in range(num_images):
        img = rng.randint(0, 60, (size, size, 3)).astype(np.uint8)
        objs = []
        for _ in range(rng.randint(1, 3)):
            cls = rng.randint(num_classes)
            w = rng.randint(size // 4, size // 2)
            h = rng.randint(size // 4, size // 2)
            x0 = rng.randint(0, size - w)
            y0 = rng.randint(0, size - h)
            img[y0:y0 + h, x0:x0 + w, cls] = 230
            objs += [float(cls), x0 / size, y0 / size,
                     (x0 + w) / size, (y0 + h) / size]
        fname = "img%04d.jpg" % i
        cv2.imwrite(os.path.join(path, fname),
                    cv2.cvtColor(img, cv2.COLOR_RGB2BGR))
        imglist.append([[2.0, 5.0] + objs, fname])
    return imglist
