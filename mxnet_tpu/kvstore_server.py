"""KVStore server entry point (parity: python/mxnet/kvstore_server.py —
the REPL that server-role processes run, applying the controller-sent
optimizer to stored weights, kvstore_server.py:28-75).

TPU-native redesign: there is no parameter-server tier — distributed
KVStore traffic rides symmetric jax.distributed collectives, and the
"server-side optimizer" capability lives in the stores themselves
(kvstore.py set_optimizer + update_on_kvstore). This module keeps the
reference's launch contract: a process started with DMLC_ROLE=server (an
old-style launcher script) parks in `_init_kvstore_server_module` instead
of crashing, logging that servers are not needed.
"""
from __future__ import annotations

import logging
import os


class KVStoreServer:
    """Accepted for API compatibility; commands are applied locally by the
    stores (kvstore.py), so the server loop has nothing to run."""

    def __init__(self, kvstore):
        self.kvstore = kvstore

    def run(self):
        logging.info("mxnet_tpu has no parameter-server tier; server role "
                     "is a no-op (collectives carry the traffic)")


def _init_kvstore_server_module():
    is_worker = int(os.environ.get("DMLC_ROLE", "worker") == "worker")
    if not is_worker:
        KVStoreServer(None).run()
