"""Collective primitives over the mesh.

Parity: reference `src/kvstore/comm.h` (device reduce/broadcast) and the NCCL
calls in kvstore_nccl.h — here they are XLA collectives usable inside
shard_map/pjit: psum rides ICI, ppermute builds rings, reduce_scatter +
all_gather decompose the allreduce the way tuned NCCL rings do (but the
compiler schedules them).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax


def allreduce(x, axis_name):
    """Sum-allreduce over a mesh axis (inside shard_map/pjit)."""
    return lax.psum(x, axis_name)


def allreduce_mean(x, axis_name):
    return lax.pmean(x, axis_name)


def reduce_scatter(x, axis_name, scatter_dim=0):
    return lax.psum_scatter(x, axis_name, scatter_dimension=scatter_dim,
                            tiled=True)


def all_gather(x, axis_name, gather_dim=0):
    return lax.all_gather(x, axis_name, axis=gather_dim, tiled=True)


def ring_permute(x, axis_name, shift=1):
    """Send each shard to the next device on the ring (ppermute)."""
    n = lax.axis_size(axis_name)
    perm = [(i, (i + shift) % n) for i in range(n)]
    return lax.ppermute(x, axis_name, perm)


def all_to_all(x, axis_name, split_axis, concat_axis):
    """The Ulysses-style sequence<->head reshard primitive."""
    return lax.all_to_all(x, axis_name, split_axis, concat_axis, tiled=True)


def compressed_allreduce_2bit(x, axis_name, threshold=0.5, residual=None):
    """2-bit-compressed allreduce with error feedback — the reference's
    gradient_compression.h algorithm lifted into the collective layer for
    bandwidth-bound (DCN) axes. Returns (reduced, new_residual)."""
    g = x if residual is None else x + residual
    q = jnp.where(g >= threshold, threshold,
                  jnp.where(g <= -threshold, -threshold, 0.0)).astype(x.dtype)
    new_residual = g - q
    return lax.psum(q, axis_name), new_residual
