"""Collective primitives over the mesh.

Parity: reference `src/kvstore/comm.h` (device reduce/broadcast) and the NCCL
calls in kvstore_nccl.h — here they are XLA collectives usable inside
shard_map/pjit: psum rides ICI, ppermute builds rings, reduce_scatter +
all_gather decompose the allreduce the way tuned NCCL rings do (but the
compiler schedules them).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax


def shard_map(f, mesh, in_specs, out_specs, check_vma=None):
    """Version-portable shard_map: jax >= 0.5 exports `jax.shard_map`
    (replication check kwarg `check_vma`); 0.4.x has
    `jax.experimental.shard_map` (same check named `check_rep`). One
    seam so library code and tests never pin a jax version."""
    try:
        from jax import shard_map as _sm
        kw = {} if check_vma is None else {"check_vma": check_vma}
    except ImportError:
        from jax.experimental.shard_map import shard_map as _sm
        kw = {} if check_vma is None else {"check_rep": check_vma}
    return _sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, **kw)


def axis_size(axis_name):
    """Version-portable mesh-axis size inside shard_map: `lax.axis_size`
    only exists in newer jax; `psum(1, axis)` is the classic idiom (a
    static int — XLA folds it)."""
    if hasattr(lax, "axis_size"):
        return lax.axis_size(axis_name)
    return lax.psum(1, axis_name)


def allreduce(x, axis_name):
    """Sum-allreduce over a mesh axis (inside shard_map/pjit)."""
    return lax.psum(x, axis_name)


def allreduce_mean(x, axis_name):
    return lax.pmean(x, axis_name)


def reduce_scatter(x, axis_name, scatter_dim=0):
    return lax.psum_scatter(x, axis_name, scatter_dimension=scatter_dim,
                            tiled=True)


def all_gather(x, axis_name, gather_dim=0):
    return lax.all_gather(x, axis_name, axis=gather_dim, tiled=True)


def ring_permute(x, axis_name, shift=1):
    """Send each shard to the next device on the ring (ppermute)."""
    n = axis_size(axis_name)
    perm = [(i, (i + shift) % n) for i in range(n)]
    return lax.ppermute(x, axis_name, perm)


def all_to_all(x, axis_name, split_axis, concat_axis):
    """The Ulysses-style sequence<->head reshard primitive."""
    return lax.all_to_all(x, axis_name, split_axis, concat_axis, tiled=True)


def compressed_allreduce_2bit(x, axis_name, threshold=0.5, residual=None):
    """2-bit-compressed allreduce with error feedback — the reference's
    gradient_compression.h algorithm lifted into the collective layer for
    bandwidth-bound (DCN) axes. Returns (reduced, new_residual)."""
    g = x if residual is None else x + residual
    q = jnp.where(g >= threshold, threshold,
                  jnp.where(g <= -threshold, -threshold, 0.0)).astype(x.dtype)
    new_residual = g - q
    return lax.psum(q, axis_name), new_residual
