"""Fault-tolerant training runtime: the full fault lifecycle in one driver.

The reference framework's recovery story is "operator restarts the job
from the last epoch checkpoint" (SURVEY §5.3). Real TPU fleets are
dominated by preemptions and occasional numeric faults, so this module
owns the whole lifecycle around `TrainStep` + `CheckpointManager`:

  * **Step-exact resume** — a checkpoint captures params, optimizer
    state, the step counter, the RNG key chain (dropout masks / SGLD
    noise), LR-schedule state, and the data-iterator cursor
    (epoch, batch index, sampler seed — `gluon.data.DataLoader
    .state_dict()`). Train-N-continuously and train-k / kill /
    `restore()` / train-(N−k) produce bit-identical params and metrics
    (tests/test_resilience.py pins this for LeNet and the word LM with
    Dropout active).

  * **Preemption watcher** — SIGTERM/SIGINT request a checkpoint at the
    next step boundary; the loop publishes it synchronously
    (`manager.wait()`, the multi-process barrier point) and exits with
    the distinct relaunch code `EXIT_PREEMPTED` (83) so a supervisor can
    tell "relaunch me" from a crash. `MXNET_PREEMPT_GRACE_SECS` bounds
    the drain: a hard deadline timer force-exits if the boundary never
    arrives (a wedged step must not eat the whole grace window).

  * **Bad-step guard** — `TrainStep(guard=True)` computes NaN/Inf
    detection on the loss and the global grad-norm *inside* the jitted
    step and drops the update in-graph when the step is bad (params,
    optimizer state, and BN stats all keep their old values). Policies
    (`MXNET_BAD_STEP_POLICY` or the `policy=` argument):
      - ``skip``      log and keep going (the in-graph select already
                      protected the state);
      - ``rollback``  after `rollback_after` consecutive bad steps,
                      restore the last checkpoint and multiply the LR by
                      `lr_shrink`;
      - ``raise``     raise `BadStepError` (fail fast);
      - ``off``       no guard compiled, zero overhead.

  * **Chaos integration** — every step boundary consults
    `utils.chaos` (SIGTERM delivery, NaN grad poison), so the whole
    lifecycle is drillable in-process and in subprocess tests without
    touching production code paths.

Usage (the resilient-training quickstart):

    step = TrainStep(net, loss_fn, "adam", {"learning_rate": 1e-3})
    mgr = CheckpointManager(ckpt_dir, keep=3)
    loop = ResilientLoop(step, mgr, loader=train_loader, save_every=100,
                         policy="skip")
    start = loop.restore()          # 0 on cold start, step N after relaunch
    for x, y in loop.batches():     # resumes mid-epoch, cursor-exact
        loss = loop.step(x, y)

A worker relaunched after `EXIT_PREEMPTED` runs the identical script: the
`restore()` + cursor fast-forward makes the resumed trajectory
bit-identical to an uninterrupted one.
"""
from __future__ import annotations

import json
import os
import signal
import threading
import time
import warnings

import numpy as np

from ..base import MXNetError
from .. import telemetry

#: distinct exit code meaning "preemption drained cleanly — relaunch me".
#: Chosen outside the usual 0/1/2 and shell-builtin ranges.
EXIT_PREEMPTED = 83

_POLICIES = ("off", "skip", "rollback", "raise")


class BadStepError(MXNetError):
    """Raised under policy='raise' when a step produces NaN/Inf loss or
    gradients."""


class Preempted(SystemExit):
    """Raised by ResilientLoop after a preemption checkpoint published.
    Subclasses SystemExit(EXIT_PREEMPTED): unhandled, the process exits
    with the relaunch code; in-process callers may catch it."""

    def __init__(self, step):
        super().__init__(EXIT_PREEMPTED)
        self.step = step


class PreemptionWatcher:
    """SIGTERM/SIGINT handler that converts a kill notice into a
    checkpoint request at the next step boundary.

    The first signal arms `triggered` and starts the grace-deadline
    timer (`MXNET_PREEMPT_GRACE_SECS`, default 30): if the loop cannot
    reach a boundary and publish within the grace window — e.g. a wedged
    collective — the timer force-exits with EXIT_PREEMPTED so the
    cluster's SIGKILL never finds us mid-write. A second signal exits
    immediately. Handlers install only on the main thread (signal module
    constraint); elsewhere the watcher degrades to never-triggered."""

    def __init__(self, signals=(signal.SIGTERM, signal.SIGINT),
                 grace_secs=None):
        if grace_secs is None:
            grace_secs = float(os.environ.get("MXNET_PREEMPT_GRACE_SECS",
                                              "30"))
        self.grace_secs = grace_secs
        self._signals = tuple(signals)
        self._saved = {}
        self._timer = None
        self._event = threading.Event()
        self.signal_time = None
        self.installed = False

    def install(self):
        try:
            for sig in self._signals:
                self._saved[sig] = signal.signal(sig, self._on_signal)
            self.installed = True
        except ValueError:  # not the main thread
            warnings.warn("PreemptionWatcher: not on the main thread — "
                          "signal handlers not installed, preemption "
                          "checkpointing disabled")
        return self

    def uninstall(self):
        for sig, old in self._saved.items():
            try:
                signal.signal(sig, old)
            except ValueError:
                pass
        self._saved.clear()
        self.installed = False
        self.cancel_deadline()

    def _on_signal(self, signum, frame):
        if self._event.is_set():
            # second notice: the supervisor is impatient — go now
            os._exit(EXIT_PREEMPTED)
        self.signal_time = time.monotonic()
        self._event.set()
        # arm the grace deadline BEFORE anything else in the handler: if
        # the flight dump itself wedges (filesystem stall), the timer
        # still force-exits inside the grace window
        if self.grace_secs and self.grace_secs > 0:
            self._timer = threading.Timer(self.grace_secs, os._exit,
                                          args=(EXIT_PREEMPTED,))
            self._timer.daemon = True
            self._timer.start()
        # black box next, before the drain even starts: a drain that
        # wedges (and gets force-exited by the grace timer) still leaves
        # a record of the last N spans before the signal
        telemetry.flight().record("fault", "train.preemption_signal",
                                  signum=signum)
        telemetry.flight().dump("sigterm")

    @property
    def triggered(self):
        return self._event.is_set()

    def remaining_grace(self):
        if self.signal_time is None:
            return None
        return max(0.0, self.grace_secs -
                   (time.monotonic() - self.signal_time))

    def cancel_deadline(self):
        if self._timer is not None:
            self._timer.cancel()
            self._timer = None

    # test seam: simulate a delivered signal without the OS
    def trigger(self):
        self._on_signal(None, None)


class ResilientLoop:
    """Drive a `TrainStep` through the full fault lifecycle.

    Parameters
    ----------
    step : TrainStep
        The compiled training step. If a bad-step policy is active and
        the step has not been built yet, its in-graph guard is enabled
        automatically; an already-compiled unguarded step raises.
    manager : utils.recovery.CheckpointManager
    loader : gluon.data.DataLoader, optional
        When given, its resumable cursor joins the checkpoint and
        `batches()` iterates resume-aware epochs.
    save_every : int
        Checkpoint cadence in steps (async publication).
    policy : str, optional
        'off' | 'skip' | 'rollback' | 'raise'; default from
        MXNET_BAD_STEP_POLICY, else 'off'.
    rollback_after : int
        Consecutive bad steps tolerated before a rollback.
    lr_shrink : float
        LR multiplier applied on each rollback (1.0 = keep LR).
    epochs : int
        Epoch budget `batches()` iterates (resume continues the count).
    watch_preemption : bool
        Install the SIGTERM/SIGINT watcher.
    grace_secs : float, optional
        Overrides MXNET_PREEMPT_GRACE_SECS.
    elastic_dp : str, optional
        'raise' (default) or 'rescale' — what `restore()` does when the
        checkpoint was written under a DIFFERENT data-parallel size and
        a DataLoader cursor is attached. The cursor counts GLOBAL
        batches, so a dp resize is only loss-curve-preserving when the
        driver holds the global batch size constant (per-chip batch =
        global/dp): 'rescale' proceeds under that documented contract
        (with a warning), 'raise' refuses the silently-lossy resume.
        Default from MXNET_ELASTIC_DP_POLICY.
    """

    def __init__(self, step, manager, loader=None, save_every=100,
                 policy=None, rollback_after=3, lr_shrink=1.0,
                 epochs=1, watch_preemption=True, grace_secs=None,
                 elastic_dp=None, verbose=True):
        if policy is None:
            policy = os.environ.get("MXNET_BAD_STEP_POLICY", "off") or "off"
        policy = policy.lower()
        if policy not in _POLICIES:
            raise ValueError("bad-step policy must be one of %s, got %r"
                             % ("/".join(_POLICIES), policy))
        if elastic_dp is None:
            elastic_dp = os.environ.get("MXNET_ELASTIC_DP_POLICY",
                                        "raise") or "raise"
        elastic_dp = elastic_dp.lower()
        if elastic_dp not in ("raise", "rescale"):
            raise ValueError("elastic_dp policy must be raise or rescale, "
                             "got %r" % (elastic_dp,))
        self.elastic_dp = elastic_dp
        self._step = step
        self._manager = manager
        self._loader = loader
        self.save_every = int(save_every)
        self.policy = policy
        self.rollback_after = int(rollback_after)
        self.lr_shrink = float(lr_shrink)
        self.epochs = int(epochs)
        self.verbose = verbose
        if policy != "off":
            if step._step_fn is None:
                step._guard = True
            elif not step._guard:
                raise MXNetError(
                    "bad-step policy %r needs TrainStep(guard=True), but "
                    "the step already compiled without the guard — "
                    "construct the TrainStep with guard=True or build the "
                    "ResilientLoop before the first step" % policy)
        # telemetry: the training loop's standing instruments (process-
        # global registry — one training loop per process)
        reg = telemetry.default_registry()
        self._m_step = reg.histogram(
            "train_step_seconds",
            help="host-observed train step time (dispatch + boundary)")
        self._m_data_wait = reg.histogram(
            "train_data_wait_seconds",
            help="time the loop waited on the data pipeline per batch")
        self._m_samples = reg.gauge(
            "train_samples_per_sec",
            help="batch items per second, last step")
        self._m_tokens = reg.gauge(
            "train_tokens_per_sec",
            help="tokens per second, last step (rank-2 inputs only)")
        self._m_gnorm = reg.gauge(
            "train_grad_norm",
            help="global gradient norm, last guarded step")
        self._m_bad = reg.counter(
            "train_bad_steps_total", flight=True,
            help="steps dropped by the NaN/Inf guard")
        self._m_rollbacks = reg.counter(
            "train_rollbacks_total", flight=True,
            help="checkpoint rollbacks taken by the bad-step policy")
        self._m_preempt = reg.counter(
            "train_preemptions_total", flight=True,
            help="preemption notices drained to a checkpoint")
        # fault-lifecycle counters (part of the checkpoint so a relaunch
        # keeps the history — e.g. rollback LR shrink must persist)
        self.consecutive_bad = 0
        self.bad_steps = 0
        self.rollbacks = 0
        self.preempted = False
        self._lr_scale = 1.0
        self._epoch = 0   # epochs batches() has fully consumed
        self._iter_invalid = False  # set by rollback: re-enter the loader
        self._base_lr_fn = None
        self.watcher = None
        if watch_preemption:
            self.watcher = PreemptionWatcher(grace_secs=grace_secs)
            self.watcher.install()

    # -- lr scale (rollback shrink) -----------------------------------------
    def _install_lr_scale(self):
        if self._base_lr_fn is not None:
            return
        step = self._step
        base = step._lr_schedule or step._opt.lr_scheduler
        if base is None:
            base_lr = step._opt.lr
            self._base_lr_fn = lambda t: base_lr
        else:
            self._base_lr_fn = base
        # keep the underlying scheduler reachable for state_dict(): the
        # wrapper lambda is stateless, the base scheduler is not
        step._lr_schedule_base = self._base_lr_fn
        step.set_lr_schedule(
            lambda t: self._base_lr_fn(t) * self._lr_scale)

    # -- state --------------------------------------------------------------
    def _dp_size(self):
        """The step's data-parallel world size (1 off-mesh): part of the
        checkpoint so an elastic relaunch can tell whether the data-
        cursor math still holds (the cursor counts GLOBAL batches)."""
        step = self._step
        mesh = getattr(step, "_mesh", None)
        axis = getattr(step, "_data_axis", None)
        if mesh is None or not axis:
            return 1
        return int(mesh.shape.get(axis, 1)) or 1

    def state_dict(self, device=False):
        """Composite checkpoint tree: TrainStep state + the loop's own
        lifecycle state (data cursor, bad-step counters, LR scale).
        device=True keeps the TrainStep leaves as live device arrays
        (shardings intact — the sharded-checkpoint path; see
        TrainStep.state_dict)."""
        loop = {"consecutive_bad": self.consecutive_bad,
                "bad_steps": self.bad_steps,
                "rollbacks": self.rollbacks,
                "lr_scale": self._lr_scale,
                "epoch": self._epoch,
                "dp_size": self._dp_size()}
        if self._loader is not None and hasattr(self._loader, "state_dict"):
            loop["loader"] = self._loader.state_dict()
        blob = np.frombuffer(json.dumps(loop).encode(), np.uint8).copy()
        return {"train": self._step.state_dict(device=device), "loop": blob}

    def load_state_dict(self, tree):
        if "train" not in tree:      # a bare TrainStep checkpoint
            self._step.load_state_dict(tree)
            return
        loop = json.loads(bytes(bytearray(
            np.asarray(tree["loop"]).astype(np.uint8))).decode())
        saved_dp = int(loop.get("dp_size", 0) or 0)
        cur_dp = self._dp_size()
        if saved_dp and saved_dp != cur_dp and "loader" in loop \
                and self._loader is not None:
            # elastic resume rail: the loader cursor counts GLOBAL
            # batches, so it only stays meaningful across a dp resize if
            # the driver keeps the global batch size constant
            if self.elastic_dp == "raise":
                raise MXNetError(
                    "checkpoint was written at dp=%d but this run is "
                    "dp=%d with a DataLoader cursor attached — a resize "
                    "silently breaks the data-cursor math unless the "
                    "GLOBAL batch size is held constant. Pass "
                    "ResilientLoop(elastic_dp='rescale') (or "
                    "MXNET_ELASTIC_DP_POLICY=rescale) to accept that "
                    "contract, or restart the data cursor."
                    % (saved_dp, cur_dp))
            warnings.warn(
                "elastic resume across dp=%d -> dp=%d: keeping the "
                "global-batch data cursor (rescale policy) — the driver "
                "must hold the global batch size constant"
                % (saved_dp, cur_dp))
        self._step.load_state_dict(tree["train"])
        self.consecutive_bad = int(loop.get("consecutive_bad", 0))
        self.bad_steps = int(loop.get("bad_steps", 0))
        self.rollbacks = int(loop.get("rollbacks", 0))
        self._lr_scale = float(loop.get("lr_scale", 1.0))
        self._epoch = int(loop.get("epoch", 0))
        if self._lr_scale != 1.0:
            self._install_lr_scale()
        if "loader" in loop and self._loader is not None:
            self._loader.load_state_dict(loop["loader"])

    def restore(self):
        """Auto-resume entry: load the newest intact checkpoint. Returns
        the restored step number, or 0 on a cold start.

        Multi-process: every process reads the (shared-filesystem)
        checkpoint directory; the processes must agree on the restored
        step or the data-parallel replicas would mix parameters from
        different steps. `restore_latest()` already allgathers and
        intersects the per-host intact-step sets (so hosts cannot fall
        back past DIFFERENT corrupt checkpoints), and this rail then
        cross-checks the chosen step itself: a residual disagreement
        (e.g. per-host local directories where only process 0 ever
        wrote) raises instead of silently cold-starting the
        non-writers."""
        state = self._manager.restore_latest()
        step0 = 0
        if state is not None:
            step0, tree = state
        try:
            import jax
            nproc = jax.process_count()
        except Exception:
            nproc = 1
        if nproc > 1:
            from jax.experimental import multihost_utils
            import numpy as _np
            steps = _np.asarray(multihost_utils.process_allgather(
                _np.int64(step0)))
            if int(steps.min()) != int(steps.max()):
                raise MXNetError(
                    "processes disagree on the restored step (%s) — the "
                    "checkpoint directory must live on a filesystem "
                    "shared by every process (single-writer protocol: "
                    "only process 0 writes)" % steps.tolist())
        if state is None:
            return 0
        self.load_state_dict(tree)
        if self.verbose:
            print("[resilient] resumed from step %d" % step0, flush=True)
        return step0

    def save(self, block=False):
        # device=True keeps shardings on the TrainStep leaves so the
        # manager can select sharded mode and copy out only the shards
        # this host owns; the manager's host copies happen synchronously
        # inside save(), before the next (donating) step can run. In
        # single-writer mode non-writers return before copying anything.
        # (The span times host capture + hand-off; the write itself is
        # timed inside the manager, async or not.)
        with telemetry.span("train.checkpoint_publish", category="train",
                            step=self._step.t, block=block):
            self._manager.save(self._step.t, self.state_dict(device=True),
                               block=block)

    # -- the lifecycle ------------------------------------------------------
    @property
    def t(self):
        return self._step.t

    def step(self, x, y):
        """One guarded train step + the full boundary protocol:
        bad-step policy, checkpoint cadence, chaos hooks, preemption
        drain. Returns the step's loss (device array).

        The preemption check runs ONLY at the post-step boundary: a
        batch the data pipeline already delivered gets trained before
        the drain checkpoint, so the saved data cursor always equals
        the trained-step count (an entry-side check would checkpoint a
        cursor one batch ahead and silently drop that batch on
        resume)."""
        from ..utils import chaos as _chaos
        t_wall = time.perf_counter()
        with telemetry.span("train.step", category="train",
                            step=self._step.t + 1):
            with telemetry.span("train.device_step", category="train",
                                step=self._step.t + 1):
                loss = self._step(x, y)
            t = self._step.t
            ok = True
            if self.policy != "off":
                ok = bool(np.asarray(self._step.last_step_ok))
                if ok:
                    self.consecutive_bad = 0
                else:
                    self._on_bad_step(t)
            dt = time.perf_counter() - t_wall
            self._m_step.observe(dt)
            shape = getattr(x, "shape", None)
            if shape and dt > 0:
                self._m_samples.set(shape[0] / dt)
                if len(shape) == 2:
                    # token-id matrices (N, T) / time-major (T, N): the
                    # element count is the token count either way
                    self._m_tokens.set(shape[0] * shape[1] / dt)
            if self.policy != "off":
                self._m_gnorm.set(
                    float(np.asarray(self._step.last_grad_norm)))
            # cadence save only on GOOD steps: after a bad step (or a
            # rollback) the state no longer corresponds to `t`, and a
            # checkpoint labeled with the wrong step poisons every later
            # restore
            if ok and self.save_every and t % self.save_every == 0:
                self.save()
        _chaos.maybe_sigterm(t)
        self._check_preempt()
        # after the preemption drain: a SIGKILL'd host gets no drain at
        # all (the multi-host chaos drill's dead-host fault)
        _chaos.maybe_sigkill(t)
        return loss

    def _on_bad_step(self, t):
        self.bad_steps += 1
        self.consecutive_bad += 1
        self._m_bad.inc(step=t)
        gnorm = float(np.asarray(self._step.last_grad_norm))
        if self.verbose:
            print("[resilient] bad step %d (non-finite loss/grads, "
                  "|g|=%r) — policy=%s, consecutive=%d"
                  % (t, gnorm, self.policy, self.consecutive_bad),
                  flush=True)
        if self.policy == "raise":
            raise BadStepError(
                "step %d produced non-finite loss/gradients (|g|=%r)"
                % (t, gnorm))
        if self.policy == "rollback" and \
                self.consecutive_bad >= self.rollback_after:
            self._rollback()

    def _rollback(self):
        self._manager.wait(_barrier=False)  # don't race the async save
        state = self._manager.restore_latest()
        self.rollbacks += 1
        self._m_rollbacks.inc(step=self._step.t)
        self.consecutive_bad = 0
        if state is None:
            warnings.warn("rollback requested but no checkpoint exists — "
                          "continuing from current (guard-protected) state")
            return
        step0, tree = state
        # the restore rewinds model/data state, but the PROCESS's fault
        # history (bad_steps, rollbacks, lr scale) must survive it — a
        # rollback that forgot it happened would retry forever at the
        # same LR
        new_scale = self._lr_scale * self.lr_shrink
        keep = (self.bad_steps, self.rollbacks)
        self.load_state_dict(tree)
        self.bad_steps, self.rollbacks = keep
        self.consecutive_bad = 0
        self._lr_scale = new_scale
        if self.lr_shrink != 1.0:
            self._install_lr_scale()
        # the data cursor rewound with the checkpoint: any in-flight
        # batches() iterator must re-enter the loader so the replayed
        # steps see the SAME batches they saw the first time
        self._iter_invalid = True
        if self.verbose:
            print("[resilient] rolled back to step %d (lr scale %.4g)"
                  % (step0, self._lr_scale), flush=True)

    def _check_preempt(self):
        w = self.watcher
        if w is None or not w.triggered or self.preempted:
            return
        self.preempted = True
        t = self._step.t
        self._m_preempt.inc(step=t)
        if self.verbose:
            print("[resilient] preemption notice — checkpointing step %d "
                  "(%.1fs grace left)" % (t, w.remaining_grace() or 0),
                  flush=True)
        # synchronous publication + the multi-process barrier: every
        # worker reaches this point (replicated state ⇒ same boundary),
        # process 0 writes, all wait, then all exit for relaunch
        self.save(block=True)
        self._manager.wait()
        w.cancel_deadline()
        if self.verbose:
            print("[resilient] checkpoint published; exiting with "
                  "relaunch code %d" % EXIT_PREEMPTED, flush=True)
        raise Preempted(t)

    # -- epoch driver -------------------------------------------------------
    def batches(self):
        """Resume-aware batch stream: iterates `epochs` passes over the
        loader, continuing mid-epoch after a restore (the loader's
        cursor fast-forwards index generation only). Rollback-aware: when
        a rollback rewinds the data cursor, the in-flight pass is
        abandoned and the loader re-entered, so replayed steps consume
        the same batches they saw the first time.

        Drivers not using a DataLoader must derive each batch from the
        CURRENT step counter (``while loop.t < N: loop.step(*batch(loop.t))``)
        for the same reason — a `for i in range(...)` index marches on
        through a rollback and desynchronizes data from parameters."""
        if self._loader is None:
            raise MXNetError("ResilientLoop(loader=...) is required for "
                             "batches()")
        while self._epoch < self.epochs:
            self._iter_invalid = False
            it = iter(self._loader)
            exhausted = False
            while True:
                # data wait: how long the loop sat blocked on the
                # pipeline before the next batch arrived
                t0_us = time.perf_counter_ns() // 1000
                t0 = time.perf_counter()
                try:
                    batch = next(it)
                except StopIteration:
                    exhausted = True
                    break
                dt = time.perf_counter() - t0
                self._m_data_wait.observe(dt)
                telemetry.record_span("train.data_wait", t0_us,
                                      time.perf_counter_ns() // 1000
                                      - t0_us, category="train")
                yield batch
                if self._iter_invalid:
                    break
            if exhausted:
                self._epoch += 1

    def finish(self):
        """End-of-training: publish a final checkpoint and block until
        durable (and, multi-process, until every worker arrived)."""
        self.save(block=True)
        self._manager.wait()
        if self.watcher is not None:
            self.watcher.uninstall()
