"""Fault-tolerant training runtime: the full fault lifecycle in one driver.

The reference framework's recovery story is "operator restarts the job
from the last epoch checkpoint" (SURVEY §5.3). Real TPU fleets are
dominated by preemptions and occasional numeric faults, so this module
owns the whole lifecycle around `TrainStep` + `CheckpointManager`:

  * **Step-exact resume** — a checkpoint captures params, optimizer
    state, the step counter, the RNG key chain (dropout masks / SGLD
    noise), LR-schedule state, and the data-iterator cursor
    (epoch, batch index, sampler seed — `gluon.data.DataLoader
    .state_dict()`). Train-N-continuously and train-k / kill /
    `restore()` / train-(N−k) produce bit-identical params and metrics
    (tests/test_resilience.py pins this for LeNet and the word LM with
    Dropout active).

  * **Preemption watcher** — SIGTERM/SIGINT request a checkpoint at the
    next step boundary; the loop publishes it synchronously
    (`manager.wait()`, the multi-process barrier point) and exits with
    the distinct relaunch code `EXIT_PREEMPTED` (83) so a supervisor can
    tell "relaunch me" from a crash. `MXNET_PREEMPT_GRACE_SECS` bounds
    the drain: a hard deadline timer force-exits if the boundary never
    arrives (a wedged step must not eat the whole grace window).

  * **Bad-step guard** — `TrainStep(guard=True)` computes NaN/Inf
    detection on the loss and the global grad-norm *inside* the jitted
    step and drops the update in-graph when the step is bad (params,
    optimizer state, and BN stats all keep their old values). Policies
    (`MXNET_BAD_STEP_POLICY` or the `policy=` argument):
      - ``skip``      log and keep going (the in-graph select already
                      protected the state);
      - ``rollback``  after `rollback_after` consecutive bad steps,
                      restore the last checkpoint and multiply the LR by
                      `lr_shrink`;
      - ``raise``     raise `BadStepError` (fail fast);
      - ``off``       no guard compiled, zero overhead.

  * **Chaos integration** — every step boundary consults
    `utils.chaos` (SIGTERM delivery, NaN grad poison, slow-host sleep),
    so the whole lifecycle is drillable in-process and in subprocess
    tests without touching production code paths.

  * **Straggler detection** (ISSUE 14) — `MXNET_STRAGGLER_WINDOW=k`
    closes a skew window every k steps: each host's mean step time is
    allgathered (the `process_allgather` seam under real multi-process
    jax; a shared-directory exchange under the emulated pod,
    `MXNET_STRAGGLER_DIR`), max/median skew lands on gauges, and a host
    exceeding `MXNET_STRAGGLER_FACTOR`x the pod median for
    `MXNET_STRAGGLER_PATIENCE` consecutive windows is flight-flagged by
    name (`train.straggler`) — off the hot path: one gather per window,
    never per step.

  * **Anomaly detection** (ISSUE 14) — `MXNET_ANOMALY_DETECT=1` scores
    each step's loss and grad norm with EWMA z-scores
    (telemetry/anomaly.py): the finite-but-wrong complement to the
    bad-step guard's NaN/Inf check, sharing its step seam.

  * **Live train console** (ISSUE 14) — `MXNET_TRAIN_METRICS_PORT`
    starts a stdlib HTTP endpoint (`/metrics` Prometheus+JSON,
    `/statusz` step-time percentiles / tok/s / data-wait fraction /
    checkpoint age / skew table / anomaly count, `/healthz` liveness)
    on a daemon thread; `tools/train_top.py` renders it live.

Usage (the resilient-training quickstart):

    step = TrainStep(net, loss_fn, "adam", {"learning_rate": 1e-3})
    mgr = CheckpointManager(ckpt_dir, keep=3)
    loop = ResilientLoop(step, mgr, loader=train_loader, save_every=100,
                         policy="skip")
    start = loop.restore()          # 0 on cold start, step N after relaunch
    for x, y in loop.batches():     # resumes mid-epoch, cursor-exact
        loss = loop.step(x, y)

A worker relaunched after `EXIT_PREEMPTED` runs the identical script: the
`restore()` + cursor fast-forward makes the resumed trajectory
bit-identical to an uninterrupted one.
"""
from __future__ import annotations

import json
import os
import signal
import statistics
import threading
import time
import warnings

import numpy as np

from ..base import MXNetError
from .. import telemetry

#: distinct exit code meaning "preemption drained cleanly — relaunch me".
#: Chosen outside the usual 0/1/2 and shell-builtin ranges.
EXIT_PREEMPTED = 83

#: distinct exit code meaning "remediation drained cleanly — re-read the
#: cordon roster and relaunch me at the new (usually smaller) world"
#: (ISSUE 15; parallel/supervisor.py). Distinct from EXIT_PREEMPTED so a
#: relauncher can tell "same shape" from "shape changed".
EXIT_RECONFIGURE = 84

_POLICIES = ("off", "skip", "rollback", "raise")


class BadStepError(MXNetError):
    """Raised under policy='raise' when a step produces NaN/Inf loss or
    gradients."""


class Preempted(SystemExit):
    """Raised by ResilientLoop after a preemption checkpoint published.
    Subclasses SystemExit(EXIT_PREEMPTED): unhandled, the process exits
    with the relaunch code; in-process callers may catch it."""

    def __init__(self, step):
        super().__init__(EXIT_PREEMPTED)
        self.step = step


class Reconfigured(SystemExit):
    """Raised by ResilientLoop after the remediation supervisor's
    reconfigure checkpoint published. Subclasses
    SystemExit(EXIT_RECONFIGURE): unhandled, the process exits with the
    reconfigure code and the relauncher rebuilds the world from the
    cordon roster; in-process callers may catch it."""

    def __init__(self, step, reason=None):
        super().__init__(EXIT_RECONFIGURE)
        self.step = step
        self.reason = reason


class PreemptionWatcher:
    """SIGTERM/SIGINT handler that converts a kill notice into a
    checkpoint request at the next step boundary.

    The first signal arms `triggered` and starts the grace-deadline
    timer (`MXNET_PREEMPT_GRACE_SECS`, default 30): if the loop cannot
    reach a boundary and publish within the grace window — e.g. a wedged
    collective — the timer force-exits with EXIT_PREEMPTED so the
    cluster's SIGKILL never finds us mid-write. A second signal exits
    immediately. Handlers install only on the main thread (signal module
    constraint); elsewhere the watcher degrades to never-triggered."""

    def __init__(self, signals=(signal.SIGTERM, signal.SIGINT),
                 grace_secs=None):
        if grace_secs is None:
            grace_secs = float(os.environ.get("MXNET_PREEMPT_GRACE_SECS",
                                              "30"))
        self.grace_secs = grace_secs
        self._signals = tuple(signals)
        self._saved = {}
        self._timer = None
        self._event = threading.Event()
        self.signal_time = None
        self.installed = False

    def install(self):
        try:
            for sig in self._signals:
                self._saved[sig] = signal.signal(sig, self._on_signal)
            self.installed = True
        except ValueError:  # not the main thread
            warnings.warn("PreemptionWatcher: not on the main thread — "
                          "signal handlers not installed, preemption "
                          "checkpointing disabled")
        return self

    def uninstall(self):
        for sig, old in self._saved.items():
            try:
                signal.signal(sig, old)
            except ValueError:
                pass
        self._saved.clear()
        self.installed = False
        self.cancel_deadline()

    def _on_signal(self, signum, frame):
        if self._event.is_set():
            # second notice: the supervisor is impatient — go now
            os._exit(EXIT_PREEMPTED)
        self.signal_time = time.monotonic()
        self._event.set()
        # arm the grace deadline BEFORE anything else in the handler: if
        # the flight dump itself wedges (filesystem stall), the timer
        # still force-exits inside the grace window
        if self.grace_secs and self.grace_secs > 0:
            self._timer = threading.Timer(self.grace_secs, os._exit,
                                          args=(EXIT_PREEMPTED,))
            self._timer.daemon = True
            self._timer.start()
        # black box next, before the drain even starts: a drain that
        # wedges (and gets force-exited by the grace timer) still leaves
        # a record of the last N spans before the signal
        telemetry.flight().record("fault", "train.preemption_signal",
                                  signum=signum)
        telemetry.flight().dump("sigterm")

    @property
    def triggered(self):
        return self._event.is_set()

    def remaining_grace(self):
        if self.signal_time is None:
            return None
        return max(0.0, self.grace_secs -
                   (time.monotonic() - self.signal_time))

    def cancel_deadline(self):
        if self._timer is not None:
            self._timer.cancel()
            self._timer = None

    # test seam: simulate a delivered signal without the OS
    def trigger(self):
        self._on_signal(None, None)


def straggler_window_env():
    """MXNET_STRAGGLER_WINDOW — steps per skew window (0/unset = off)."""
    raw = os.environ.get("MXNET_STRAGGLER_WINDOW", "0") or "0"
    try:
        return int(raw)
    except ValueError:
        raise ValueError("MXNET_STRAGGLER_WINDOW must be an integer "
                         "step count, got %r" % (raw,))


def straggler_factor():
    """MXNET_STRAGGLER_FACTOR — flag threshold as a multiple of the pod
    median step time (default 2.0; must be > 1)."""
    raw = os.environ.get("MXNET_STRAGGLER_FACTOR", "2.0") or "2.0"
    try:
        v = float(raw)
    except ValueError:
        raise ValueError("MXNET_STRAGGLER_FACTOR must be a number > 1, "
                         "got %r" % (raw,))
    if v <= 1.0:
        raise ValueError("MXNET_STRAGGLER_FACTOR must be > 1 (a host "
                         "at the median is not a straggler), got %r"
                         % (raw,))
    return v


def straggler_patience():
    """MXNET_STRAGGLER_PATIENCE — consecutive over-factor windows before
    a host is flagged (default 2)."""
    raw = os.environ.get("MXNET_STRAGGLER_PATIENCE", "2") or "2"
    try:
        return max(1, int(raw))
    except ValueError:
        raise ValueError("MXNET_STRAGGLER_PATIENCE must be an integer "
                         "window count, got %r" % (raw,))


class _FileTimeExchange:
    """Shared-directory step-time exchange for EMULATED pods
    (MXNET_STRAGGLER_DIR): each host publishes its window mean with an
    atomic rename and reads whatever its peers last published. Real
    multi-process jax uses `process_allgather` instead; the emulated
    drill's hosts are separate single-process runtimes that only share
    a filesystem — the same medium their sharded checkpoints use."""

    def __init__(self, dirpath, host, max_age_s=300.0):
        self.dir = dirpath
        self.host = str(host)
        self.max_age_s = float(max_age_s)
        safe = "".join(c if c.isalnum() or c in "-_" else "_"
                       for c in self.host)
        self._path = os.path.join(dirpath, "steptime-host%s.json" % safe)

    def __call__(self, mean_s):
        now = time.time()
        try:
            os.makedirs(self.dir, exist_ok=True)
            tmp = self._path + ".tmp%d" % os.getpid()
            with open(tmp, "w") as f:
                json.dump({"host": self.host, "mean_s": float(mean_s),
                           "t": now}, f)
            os.replace(tmp, self._path)
        except OSError:
            pass                      # a missed publish skews one window
        out = {self.host: float(mean_s)}
        try:
            names = os.listdir(self.dir)
        except OSError:
            return out
        for name in names:
            if not (name.startswith("steptime-host")
                    and name.endswith(".json")):
                continue
            try:
                with open(os.path.join(self.dir, name)) as f:
                    doc = json.load(f)
                # expire stale publishes: a dead host's frozen mean (or
                # a previous run's leftovers in a reused directory)
                # must not skew every future window's median
                if now - float(doc.get("t", 0.0)) > self.max_age_s:
                    continue
                out[str(doc["host"])] = float(doc["mean_s"])
            except (OSError, ValueError, KeyError, TypeError):
                continue              # torn peer write: skip this window
        return out


def _default_time_gather():
    """The per-window step-time gather: `process_allgather` under real
    multi-process jax (the restore() rail's seam), the shared-directory
    exchange when MXNET_STRAGGLER_DIR names one (the emulated pod), and
    a local-only view otherwise (skew degenerates to 1.0)."""
    sdir = os.environ.get("MXNET_STRAGGLER_DIR")
    host = telemetry.metrics._host_label()
    if sdir:
        return _FileTimeExchange(sdir, host)
    try:
        import jax
        nproc = jax.process_count()
    except Exception:
        nproc = 1
    if nproc > 1:
        def gather(mean_s):
            from jax.experimental import multihost_utils
            # carry each host's MXNET_HOST_ID label (fixed-width bytes)
            # alongside its time, so the skew table / flight events key
            # hosts the same way every other instrument does — not by
            # bare process index
            lab = np.zeros(32, np.uint8)
            raw = str(host).encode()[:32]
            lab[:len(raw)] = np.frombuffer(raw, np.uint8)
            times, labels = multihost_utils.process_allgather(
                (np.float64(mean_s), lab))
            times = np.ravel(np.asarray(times))
            labels = np.asarray(labels).reshape(len(times), -1)
            out = {}
            for i, t in enumerate(times):
                name = bytes(labels[i]).rstrip(b"\x00") \
                    .decode("utf-8", "replace")
                out[name or str(i)] = float(t)
            return out
        return gather
    return lambda mean_s: {host: float(mean_s)}


class StragglerMonitor:
    """Windowed per-host step-time skew detection (ISSUE 14).

    `observe(step, seconds)` accumulates this host's step wall times;
    every `window` steps the window MEAN is exchanged with the pod
    (`gather`: host -> mean seconds), max/median skew lands on gauges
    (`train_step_skew`, `train_step_window_median_s`,
    `train_step_window_max_s`), and a host whose mean exceeds
    `factor` x the pod median for `patience` CONSECUTIVE windows is
    flagged once per episode: `train_stragglers_total` counter (flight-
    mirrored) plus an explicit `train.straggler` flight event naming
    the host — what the multi-host chaos drill asserts survives in the
    black boxes. The gather runs once per window, never per step."""

    def __init__(self, window, factor=None, patience=None, gather=None,
                 registry=None):
        self.window = int(window)
        self.factor = straggler_factor() if factor is None \
            else float(factor)
        self.patience = straggler_patience() if patience is None \
            else int(patience)
        self._gather = gather or _default_time_gather()
        self._registry = registry
        self._times = []
        self.windows = 0              # closed windows
        self._consec = {}             # host -> consecutive slow windows
        self._episode = set()         # hosts flagged in the open episode
        self.flagged = {}             # host -> times flagged (lifetime)
        self.last_window = None       # host -> mean seconds
        self.last_skew = None

    def _reg(self):
        return self._registry or telemetry.default_registry()

    def observe(self, step, seconds):
        """One step's wall time; closes the window on cadence. Returns
        the list of hosts newly flagged at this boundary (usually [])."""
        self._times.append(float(seconds))
        if len(self._times) < self.window:
            return []
        mean = sum(self._times) / len(self._times)
        del self._times[:]
        return self._close_window(step, mean)

    def _close_window(self, step, mean_s):
        times = self._gather(mean_s)
        self.windows += 1
        self.last_window = dict(times)
        if not times:
            return []
        median = statistics.median(times.values())
        mx = max(times.values())
        skew = (mx / median) if median > 0 else 1.0
        self.last_skew = skew
        reg = self._reg()
        reg.gauge("train_step_skew",
                  help="max/median of per-host mean step time, last "
                       "skew window").set(skew)
        reg.gauge("train_step_window_median_s",
                  help="pod-median mean step seconds, last skew window"
                  ).set(median)
        reg.gauge("train_step_window_max_s",
                  help="slowest host's mean step seconds, last skew "
                       "window").set(mx)
        newly = []
        # a host absent from this window's gather (expired publish,
        # dead peer) breaks its "consecutive" chain and closes its
        # episode — otherwise two non-adjacent slow windows could
        # satisfy the patience contract, and a returning host could
        # never record a fresh episode onset
        for host in [h for h in self._consec if h not in times]:
            del self._consec[host]
            self._episode.discard(host)
        for host, t in sorted(times.items()):
            slow = median > 0 and t > self.factor * median
            if not slow:
                self._consec[host] = 0
                self._episode.discard(host)
                continue
            self._consec[host] = self._consec.get(host, 0) + 1
            if self._consec[host] < self.patience \
                    or host in self._episode:
                continue
            # flag once per slow episode: the host stays listed in
            # statusz while slow, but the flight record marks the onset
            self._episode.add(host)
            # copy-on-write: `flagged` is read by the console's HTTP
            # thread (statusz) — swap the dict atomically
            self.flagged = dict(self.flagged,
                                **{host: self.flagged.get(host, 0) + 1})
            newly.append(host)
            ratio = t / median if median > 0 else float("inf")
            reg.counter(
                "train_stragglers_total", flight=True,
                help="hosts flagged over MXNET_STRAGGLER_FACTOR x the "
                     "pod-median step time for MXNET_STRAGGLER_PATIENCE "
                     "consecutive windows"
            ).inc(host=host, ratio=round(ratio, 3))
            telemetry.flight().record(
                "event", "train.straggler", host=host,
                mean_s=round(t, 6), median_s=round(median, 6),
                ratio=round(ratio, 3), window=self.windows, step=step)
        return newly

    def status(self):
        """The /statusz skew table."""
        return {"window_steps": self.window, "factor": self.factor,
                "patience": self.patience, "windows": self.windows,
                "skew": self.last_skew,
                "hosts": self.last_window,
                "flagged": dict(self.flagged)}


class ResilientLoop:
    """Drive a `TrainStep` through the full fault lifecycle.

    Parameters
    ----------
    step : TrainStep
        The compiled training step. If a bad-step policy is active and
        the step has not been built yet, its in-graph guard is enabled
        automatically; an already-compiled unguarded step raises.
    manager : utils.recovery.CheckpointManager
    loader : gluon.data.DataLoader, optional
        When given, its resumable cursor joins the checkpoint and
        `batches()` iterates resume-aware epochs.
    save_every : int
        Checkpoint cadence in steps (async publication).
    policy : str, optional
        'off' | 'skip' | 'rollback' | 'raise'; default from
        MXNET_BAD_STEP_POLICY, else 'off'.
    rollback_after : int
        Consecutive bad steps tolerated before a rollback.
    lr_shrink : float
        LR multiplier applied on each rollback (1.0 = keep LR).
    epochs : int
        Epoch budget `batches()` iterates (resume continues the count).
    watch_preemption : bool
        Install the SIGTERM/SIGINT watcher.
    grace_secs : float, optional
        Overrides MXNET_PREEMPT_GRACE_SECS.
    elastic_dp : str, optional
        'raise' (default) or 'rescale' — what `restore()` does when the
        checkpoint was written under a DIFFERENT data-parallel size and
        a DataLoader cursor is attached. The cursor counts GLOBAL
        batches, so a dp resize is only loss-curve-preserving when the
        driver holds the global batch size constant (per-chip batch =
        global/dp): 'rescale' proceeds under that documented contract
        (with a warning), 'raise' refuses the silently-lossy resume.
        Default from MXNET_ELASTIC_DP_POLICY.
    straggler_window : int, optional
        Steps per straggler-skew window (default from
        MXNET_STRAGGLER_WINDOW; 0 = off). See `StragglerMonitor`.
    anomaly : bool, optional
        EWMA z-score anomaly detection on loss/grad-norm (default from
        MXNET_ANOMALY_DETECT; off — it syncs the loss to the host every
        step). See `telemetry/anomaly.py`.
    metrics_port : int or False, optional
        Start the live train console (stdlib HTTP `/metrics` +
        `/statusz` + `/healthz`) on this port; 0 binds an ephemeral
        port (`console_addr` holds the result). Default from
        MXNET_TRAIN_METRICS_PORT; unset = no console. Pass ``False``
        to suppress the console REGARDLESS of the env var — the opt-out
        for secondary loops in one process (a fixed env port can only
        be bound once).
    """

    def __init__(self, step, manager, loader=None, save_every=100,
                 policy=None, rollback_after=3, lr_shrink=1.0,
                 epochs=1, watch_preemption=True, grace_secs=None,
                 elastic_dp=None, verbose=True, straggler_window=None,
                 anomaly=None, metrics_port=None):
        if policy is None:
            policy = os.environ.get("MXNET_BAD_STEP_POLICY", "off") or "off"
        policy = policy.lower()
        if policy not in _POLICIES:
            raise ValueError("bad-step policy must be one of %s, got %r"
                             % ("/".join(_POLICIES), policy))
        if elastic_dp is None:
            elastic_dp = os.environ.get("MXNET_ELASTIC_DP_POLICY",
                                        "raise") or "raise"
        elastic_dp = elastic_dp.lower()
        if elastic_dp not in ("raise", "rescale"):
            raise ValueError("elastic_dp policy must be raise or rescale, "
                             "got %r" % (elastic_dp,))
        self.elastic_dp = elastic_dp
        self._step = step
        self._manager = manager
        self._loader = loader
        self.save_every = int(save_every)
        self.policy = policy
        self.rollback_after = int(rollback_after)
        self.lr_shrink = float(lr_shrink)
        self.epochs = int(epochs)
        self.verbose = verbose
        if policy != "off":
            if step._step_fn is None:
                step._guard = True
            elif not step._guard:
                raise MXNetError(
                    "bad-step policy %r needs TrainStep(guard=True), but "
                    "the step already compiled without the guard — "
                    "construct the TrainStep with guard=True or build the "
                    "ResilientLoop before the first step" % policy)
        # telemetry: the training loop's standing instruments (process-
        # global registry — one training loop per process)
        reg = telemetry.default_registry()
        self._m_step = reg.histogram(
            "train_step_seconds",
            help="host-observed train step time (dispatch + boundary)")
        self._m_data_wait = reg.histogram(
            "train_data_wait_seconds",
            help="time the loop waited on the data pipeline per batch")
        self._m_samples = reg.gauge(
            "train_samples_per_sec",
            help="batch items per second, last step")
        self._m_tokens = reg.gauge(
            "train_tokens_per_sec",
            help="tokens per second, last step (rank-2 inputs only)")
        self._m_gnorm = reg.gauge(
            "train_grad_norm",
            help="global gradient norm, last guarded step")
        self._m_bad = reg.counter(
            "train_bad_steps_total", flight=True,
            help="steps dropped by the NaN/Inf guard")
        self._m_rollbacks = reg.counter(
            "train_rollbacks_total", flight=True,
            help="checkpoint rollbacks taken by the bad-step policy")
        self._m_preempt = reg.counter(
            "train_preemptions_total", flight=True,
            help="preemption notices drained to a checkpoint")
        # fault-lifecycle counters (part of the checkpoint so a relaunch
        # keeps the history — e.g. rollback LR shrink must persist)
        self.consecutive_bad = 0
        self.bad_steps = 0
        self.rollbacks = 0
        self.preempted = False
        self._lr_scale = 1.0
        self._epoch = 0   # epochs batches() has fully consumed
        self._iter_invalid = False  # set by rollback: re-enter the loader
        self._base_lr_fn = None
        self._last_save = None        # (step, wall time) of last save()
        # -- ISSUE 14 observability layer (all opt-in) ---------------------
        if straggler_window is None:
            straggler_window = straggler_window_env()
        self._straggler = StragglerMonitor(straggler_window) \
            if straggler_window and straggler_window > 0 else None
        from ..telemetry import anomaly as _anomaly_mod
        if anomaly is None:
            anomaly = _anomaly_mod.detect_enabled()
        self._anomaly = _anomaly_mod.AnomalyDetector() if anomaly \
            else None
        self.console_addr = None
        self._console = None
        if metrics_port is None:
            raw = os.environ.get("MXNET_TRAIN_METRICS_PORT")
            if raw not in (None, ""):
                try:
                    metrics_port = int(raw)
                except ValueError:
                    raise ValueError("MXNET_TRAIN_METRICS_PORT must be "
                                     "an integer port, got %r" % (raw,))
        # identity check: False means "no console even if the env names
        # a port" (False == 0 would otherwise read as "ephemeral")
        if metrics_port is not None and metrics_port is not False:
            self.serve_metrics(port=int(metrics_port))
        self.watcher = None
        if watch_preemption:
            self.watcher = PreemptionWatcher(grace_secs=grace_secs)
            self.watcher.install()
            # thread the drain deadline through checkpoint publish IO:
            # retry backoff during a SIGTERM drain can no longer sleep
            # past the grace window and lose the final checkpoint to
            # the force-exit timer (remaining_grace() is None until a
            # signal actually arrives — no cap on ordinary saves)
            if hasattr(manager, "deadline_fn"):
                manager.deadline_fn = self.watcher.remaining_grace
        # -- ISSUE 15 remediation layer (opt-in) -------------------------
        self.supervisor = None
        from . import supervisor as _supervisor_mod
        if _supervisor_mod.remediation_enabled():
            _supervisor_mod.TrainSupervisor(self)

    # -- lr scale (rollback shrink) -----------------------------------------
    def _install_lr_scale(self):
        if self._base_lr_fn is not None:
            return
        step = self._step
        base = step._lr_schedule or step._opt.lr_scheduler
        if base is None:
            base_lr = step._opt.lr
            self._base_lr_fn = lambda t: base_lr
        else:
            self._base_lr_fn = base
        # keep the underlying scheduler reachable for state_dict(): the
        # wrapper lambda is stateless, the base scheduler is not
        step._lr_schedule_base = self._base_lr_fn
        step.set_lr_schedule(
            lambda t: self._base_lr_fn(t) * self._lr_scale)

    # -- state --------------------------------------------------------------
    def _dp_size(self):
        """The step's data-parallel world size (1 off-mesh): part of the
        checkpoint so an elastic relaunch can tell whether the data-
        cursor math still holds (the cursor counts GLOBAL batches)."""
        step = self._step
        mesh = getattr(step, "_mesh", None)
        axis = getattr(step, "_data_axis", None)
        if mesh is None or not axis:
            return 1
        return int(mesh.shape.get(axis, 1)) or 1

    def state_dict(self, device=False):
        """Composite checkpoint tree: TrainStep state + the loop's own
        lifecycle state (data cursor, bad-step counters, LR scale).
        device=True keeps the TrainStep leaves as live device arrays
        (shardings intact — the sharded-checkpoint path; see
        TrainStep.state_dict)."""
        loop = {"consecutive_bad": self.consecutive_bad,
                "bad_steps": self.bad_steps,
                "rollbacks": self.rollbacks,
                "lr_scale": self._lr_scale,
                "epoch": self._epoch,
                "dp_size": self._dp_size()}
        if self._loader is not None and hasattr(self._loader, "state_dict"):
            loop["loader"] = self._loader.state_dict()
        blob = np.frombuffer(json.dumps(loop).encode(), np.uint8).copy()
        return {"train": self._step.state_dict(device=device), "loop": blob}

    def load_state_dict(self, tree):
        if "train" not in tree:      # a bare TrainStep checkpoint
            self._step.load_state_dict(tree)
            return
        loop = json.loads(bytes(bytearray(
            np.asarray(tree["loop"]).astype(np.uint8))).decode())
        saved_dp = int(loop.get("dp_size", 0) or 0)
        cur_dp = self._dp_size()
        if saved_dp and saved_dp != cur_dp and "loader" in loop \
                and self._loader is not None:
            # elastic resume rail: the loader cursor counts GLOBAL
            # batches, so it only stays meaningful across a dp resize if
            # the driver keeps the global batch size constant
            if self.elastic_dp == "raise":
                raise MXNetError(
                    "checkpoint was written at dp=%d but this run is "
                    "dp=%d with a DataLoader cursor attached — a resize "
                    "silently breaks the data-cursor math unless the "
                    "GLOBAL batch size is held constant. Pass "
                    "ResilientLoop(elastic_dp='rescale') (or "
                    "MXNET_ELASTIC_DP_POLICY=rescale) to accept that "
                    "contract, or restart the data cursor."
                    % (saved_dp, cur_dp))
            warnings.warn(
                "elastic resume across dp=%d -> dp=%d: keeping the "
                "global-batch data cursor (rescale policy) — the driver "
                "must hold the global batch size constant"
                % (saved_dp, cur_dp))
        self._step.load_state_dict(tree["train"])
        self.consecutive_bad = int(loop.get("consecutive_bad", 0))
        self.bad_steps = int(loop.get("bad_steps", 0))
        self.rollbacks = int(loop.get("rollbacks", 0))
        self._lr_scale = float(loop.get("lr_scale", 1.0))
        self._epoch = int(loop.get("epoch", 0))
        if self._lr_scale != 1.0:
            self._install_lr_scale()
        if "loader" in loop and self._loader is not None:
            self._loader.load_state_dict(loop["loader"])

    def restore(self):
        """Auto-resume entry: load the newest intact checkpoint. Returns
        the restored step number, or 0 on a cold start.

        Multi-process: every process reads the (shared-filesystem)
        checkpoint directory; the processes must agree on the restored
        step or the data-parallel replicas would mix parameters from
        different steps. `restore_latest()` already allgathers and
        intersects the per-host intact-step sets (so hosts cannot fall
        back past DIFFERENT corrupt checkpoints), and this rail then
        cross-checks the chosen step itself: a residual disagreement
        (e.g. per-host local directories where only process 0 ever
        wrote) raises instead of silently cold-starting the
        non-writers."""
        state = self._manager.restore_latest()
        step0 = 0
        if state is not None:
            step0, tree = state
        try:
            import jax
            nproc = jax.process_count()
        except Exception:
            nproc = 1
        if nproc > 1:
            from jax.experimental import multihost_utils
            import numpy as _np
            steps = _np.asarray(multihost_utils.process_allgather(
                _np.int64(step0)))
            if int(steps.min()) != int(steps.max()):
                raise MXNetError(
                    "processes disagree on the restored step (%s) — the "
                    "checkpoint directory must live on a filesystem "
                    "shared by every process (single-writer protocol: "
                    "only process 0 writes)" % steps.tolist())
        if state is None:
            return 0
        self.load_state_dict(tree)
        if self.verbose:
            print("[resilient] resumed from step %d" % step0, flush=True)
        return step0

    def save(self, block=False):
        # device=True keeps shardings on the TrainStep leaves so the
        # manager can select sharded mode and copy out only the shards
        # this host owns; the manager's host copies happen synchronously
        # inside save(), before the next (donating) step can run. In
        # single-writer mode non-writers return before copying anything.
        # (The span times host capture + hand-off; the write itself is
        # timed inside the manager, async or not.)
        with telemetry.span("train.checkpoint_publish", category="train",
                            step=self._step.t, block=block):
            self._manager.save(self._step.t, self.state_dict(device=True),
                               block=block)
        self._last_save = (self._step.t, time.time())

    # -- the lifecycle ------------------------------------------------------
    @property
    def t(self):
        return self._step.t

    def step(self, x, y):
        """One guarded train step + the full boundary protocol:
        bad-step policy, checkpoint cadence, chaos hooks, preemption
        drain. Returns the step's loss (device array).

        The preemption check runs ONLY at the post-step boundary: a
        batch the data pipeline already delivered gets trained before
        the drain checkpoint, so the saved data cursor always equals
        the trained-step count (an entry-side check would checkpoint a
        cursor one batch ahead and silently drop that batch on
        resume)."""
        from ..utils import chaos as _chaos
        t_wall = time.perf_counter()
        with telemetry.span("train.step", category="train",
                            step=self._step.t + 1):
            with telemetry.span("train.device_step", category="train",
                                step=self._step.t + 1):
                loss = self._step(x, y)
            t = self._step.t
            # the slow-host chaos sleep lands INSIDE the timed step so
            # the straggler monitor (and train_step_seconds) see it —
            # that is what makes the injected straggler detectable
            _chaos.maybe_slow_host(t)
            ok = True
            if self.policy != "off":
                ok = bool(np.asarray(self._step.last_step_ok))
                if ok:
                    self.consecutive_bad = 0
                else:
                    self._on_bad_step(t)
            dt = time.perf_counter() - t_wall
            self._m_step.observe(dt)
            shape = getattr(x, "shape", None)
            if shape and dt > 0:
                self._m_samples.set(shape[0] / dt)
                if len(shape) == 2:
                    # token-id matrices (N, T) / time-major (T, N): the
                    # element count is the token count either way
                    self._m_tokens.set(shape[0] * shape[1] / dt)
            gnorm_val = None
            if self.policy != "off":
                gnorm_val = float(np.asarray(self._step.last_grad_norm))
                self._m_gnorm.set(gnorm_val)
            # ISSUE 14 detectors, gated like every recording site: under
            # MXNET_TELEMETRY=0 neither the per-window gather nor the
            # loss sync runs (the seams are no-ops)
            new_stragglers, anomalies = [], []
            if telemetry.enabled():
                if self._straggler is not None:
                    new_stragglers = self._straggler.observe(t, dt)
                if self._anomaly is not None:
                    anomalies = self._anomaly.observe(
                        t, loss=float(np.asarray(loss)),
                        grad_norm=gnorm_val)
            # ISSUE 15 remediation: the supervisor consumes this
            # boundary's detector signals and may run an SDC parity
            # probe; any resulting cordon arms the reconfigure drain
            # checked below, after the preemption protocol
            sup = self.supervisor
            if sup is not None:
                sup.note_batch(x, y)
                sup.on_step(t, stragglers=new_stragglers,
                            anomalies=anomalies)
            # cadence save only on GOOD steps: after a bad step (or a
            # rollback) the state no longer corresponds to `t`, and a
            # checkpoint labeled with the wrong step poisons every later
            # restore. An armed SDC quarantine suppresses publishing
            # entirely — suspect-window state must never become the
            # checkpoint a relaunch restores.
            if ok and self.save_every and t % self.save_every == 0 \
                    and not (sup is not None and sup.suppress_saves):
                self.save()
        _chaos.maybe_sigterm(t)
        self._check_preempt()
        if sup is not None and sup.reconfigure_requested \
                and not self.preempted:
            self._check_reconfigure()
        # after the preemption drain: a SIGKILL'd host gets no drain at
        # all (the multi-host chaos drill's dead-host fault)
        _chaos.maybe_sigkill(t)
        return loss

    def _on_bad_step(self, t):
        self.bad_steps += 1
        self.consecutive_bad += 1
        self._m_bad.inc(step=t)
        gnorm = float(np.asarray(self._step.last_grad_norm))
        if self.verbose:
            print("[resilient] bad step %d (non-finite loss/grads, "
                  "|g|=%r) — policy=%s, consecutive=%d"
                  % (t, gnorm, self.policy, self.consecutive_bad),
                  flush=True)
        if self.policy == "raise":
            raise BadStepError(
                "step %d produced non-finite loss/gradients (|g|=%r)"
                % (t, gnorm))
        if self.policy == "rollback" and \
                self.consecutive_bad >= self.rollback_after:
            self._rollback()

    def _rollback(self):
        self._manager.wait(_barrier=False)  # don't race the async save
        state = self._manager.restore_latest()
        self.rollbacks += 1
        self._m_rollbacks.inc(step=self._step.t)
        self.consecutive_bad = 0
        if state is None:
            warnings.warn("rollback requested but no checkpoint exists — "
                          "continuing from current (guard-protected) state")
            return
        step0, tree = state
        # the restore rewinds model/data state, but the PROCESS's fault
        # history (bad_steps, rollbacks, lr scale) must survive it — a
        # rollback that forgot it happened would retry forever at the
        # same LR
        new_scale = self._lr_scale * self.lr_shrink
        keep = (self.bad_steps, self.rollbacks)
        self.load_state_dict(tree)
        self.bad_steps, self.rollbacks = keep
        self.consecutive_bad = 0
        self._lr_scale = new_scale
        if self.lr_shrink != 1.0:
            self._install_lr_scale()
        # the data cursor rewound with the checkpoint: any in-flight
        # batches() iterator must re-enter the loader so the replayed
        # steps see the SAME batches they saw the first time
        self._iter_invalid = True
        if self.verbose:
            print("[resilient] rolled back to step %d (lr scale %.4g)"
                  % (step0, self._lr_scale), flush=True)

    def _check_preempt(self):
        w = self.watcher
        if w is None or not w.triggered or self.preempted:
            return
        self.preempted = True
        t = self._step.t
        self._m_preempt.inc(step=t)
        if self.verbose:
            print("[resilient] preemption notice — checkpointing step %d "
                  "(%.1fs grace left)" % (t, w.remaining_grace() or 0),
                  flush=True)
        # synchronous publication + the multi-process barrier: every
        # worker reaches this point (replicated state ⇒ same boundary),
        # process 0 writes, all wait, then all exit for relaunch
        self.save(block=True)
        self._manager.wait()
        w.cancel_deadline()
        if self.verbose:
            print("[resilient] checkpoint published; exiting with "
                  "relaunch code %d" % EXIT_PREEMPTED, flush=True)
        raise Preempted(t)

    def _check_reconfigure(self):
        """The remediation drain (ISSUE 15): the supervisor cordoned a
        host (or otherwise demanded a new world), so checkpoint at this
        boundary, dump the black box, and exit with EXIT_RECONFIGURE —
        the relauncher re-reads the cordon roster and rebuilds the pod
        at N−1 via the elastic sharded restore."""
        sup = self.supervisor
        t = self._step.t
        reason = sup.reconfigure_reason
        if sup.suppress_saves:
            # SDC quarantine: publish NOTHING — the relaunch must
            # restore the newest quorum-certified step, not this
            # suspect-window boundary
            if self.verbose:
                print("[resilient] reconfigure requested (%s) — SDC "
                      "quarantine active, exiting WITHOUT a drain "
                      "checkpoint (code %d)" % (reason,
                                                EXIT_RECONFIGURE),
                      flush=True)
        else:
            if self.verbose:
                print("[resilient] reconfigure requested (%s) — "
                      "checkpointing step %d and exiting with code %d"
                      % (reason, t, EXIT_RECONFIGURE), flush=True)
            # synchronous publication + the multi-process barrier,
            # exactly the preemption drain's protocol: the relaunched
            # (smaller) world must find this boundary complete on every
            # surviving host
            self.save(block=True)
            self._manager.wait()
        telemetry.flight().record("event", "train.reconfigure_exit",
                                  reason=reason, step=t)
        telemetry.flight().dump("reconfigure")
        if sup.auditor is not None:
            sup.auditor.stop()
        if self.verbose and not sup.suppress_saves:
            print("[resilient] checkpoint published; exiting with "
                  "reconfigure code %d" % EXIT_RECONFIGURE, flush=True)
        raise Reconfigured(t, reason)

    # -- epoch driver -------------------------------------------------------
    def batches(self):
        """Resume-aware batch stream: iterates `epochs` passes over the
        loader, continuing mid-epoch after a restore (the loader's
        cursor fast-forwards index generation only). Rollback-aware: when
        a rollback rewinds the data cursor, the in-flight pass is
        abandoned and the loader re-entered, so replayed steps consume
        the same batches they saw the first time.

        Drivers not using a DataLoader must derive each batch from the
        CURRENT step counter (``while loop.t < N: loop.step(*batch(loop.t))``)
        for the same reason — a `for i in range(...)` index marches on
        through a rollback and desynchronizes data from parameters."""
        if self._loader is None:
            raise MXNetError("ResilientLoop(loader=...) is required for "
                             "batches()")
        while self._epoch < self.epochs:
            self._iter_invalid = False
            it = iter(self._loader)
            exhausted = False
            while True:
                # data wait: how long the loop sat blocked on the
                # pipeline before the next batch arrived
                t0_us = time.perf_counter_ns() // 1000
                t0 = time.perf_counter()
                try:
                    batch = next(it)
                except StopIteration:
                    exhausted = True
                    break
                dt = time.perf_counter() - t0
                self._m_data_wait.observe(dt)
                telemetry.record_span("train.data_wait", t0_us,
                                      time.perf_counter_ns() // 1000
                                      - t0_us, category="train")
                yield batch
                if self._iter_invalid:
                    break
            if exhausted:
                self._epoch += 1

    def finish(self):
        """End-of-training: publish a final checkpoint and block until
        durable (and, multi-process, until every worker arrived)."""
        self.save(block=True)
        self._manager.wait()
        if self.watcher is not None:
            self.watcher.uninstall()
        if self.supervisor is not None:
            self.supervisor.close()
        self.close_console()

    # -- live train console (ISSUE 14) --------------------------------------
    def statusz(self):
        """The `/statusz` body: the one-look training health view —
        step-time percentiles, throughput, data-wait fraction,
        checkpoint age/bytes, the straggler skew table, anomaly counts,
        and the train.step comms ledger. Everything derives from the
        default registry and in-process state; no device work."""
        from ..telemetry import introspect as _introspect
        reg = telemetry.default_registry()
        snap = reg.snapshot()["metrics"]

        def hist(name):
            h = snap.get(name) or {}
            if not h.get("count"):
                return None
            return {"count": h["count"], "mean": h.get("mean"),
                    "p50": h.get("p50"), "p95": h.get("p95"),
                    "p99": h.get("p99")}

        def gauge(name):
            m = snap.get(name)
            return m.get("value") if m else None

        step_h = snap.get("train_step_seconds") or {}
        wait_h = snap.get("train_data_wait_seconds") or {}
        busy = float(step_h.get("sum") or 0.0)
        waited = float(wait_h.get("sum") or 0.0)
        wait_fraction = waited / (waited + busy) \
            if (waited + busy) > 0 else None
        step_p95 = (step_h.get("p95") if step_h.get("count") else None)
        ckpt = {"last_step": None, "age_s": None,
                "bytes_per_host": gauge("checkpoint_bytes_per_host")}
        if self._last_save is not None:
            ckpt["last_step"] = self._last_save[0]
            ckpt["age_s"] = round(time.time() - self._last_save[1], 3)
        comms = _introspect.site_comms("train.step")
        return {
            "host": reg.labels().get("host"),
            "step": self.t,
            "epoch": self._epoch,
            "preempted": self.preempted,
            "step_seconds": hist("train_step_seconds"),
            "step_p95_ms": (round(step_p95 * 1e3, 3)
                            if step_p95 is not None else None),
            "samples_per_sec": gauge("train_samples_per_sec"),
            "tokens_per_sec": gauge("train_tokens_per_sec"),
            "data_wait_fraction": wait_fraction,
            "grad_norm": gauge("train_grad_norm"),
            "bad_steps": self.bad_steps,
            "rollbacks": self.rollbacks,
            "checkpoint": ckpt,
            "straggler": (self._straggler.status()
                          if self._straggler is not None else None),
            "anomalies": ({"count": self._anomaly.anomalies,
                           "last": {k: {"value": v[0], "z": v[1]}
                                    for k, v in
                                    self._anomaly.last.items()}}
                          if self._anomaly is not None else None),
            "comms": comms,
            "remediation": (self.supervisor.status()
                            if self.supervisor is not None else None),
        }

    def serve_metrics(self, port=0, host=None):
        """Start the opt-in train console: a stdlib HTTP daemon thread
        serving `/metrics` (Prometheus under `Accept: text/plain`, JSON
        snapshot otherwise), `/statusz`, and `/healthz` — the same
        `_HTTPFrontend` the serving stack's doors share, read-only.
        Binds MXNET_TRAIN_METRICS_HOST (default 127.0.0.1 — exposing
        the console beyond the host is an explicit choice; a pod polled
        cross-host by `train_top --hosts` needs 0.0.0.0 or the fabric
        address). Returns the bound (host, port), also kept on
        `console_addr`."""
        if host is None:
            host = os.environ.get("MXNET_TRAIN_METRICS_HOST",
                                  "127.0.0.1") or "127.0.0.1"
        if self._console is not None:
            return self.console_addr
        from ..serving.server import _HTTPFrontend
        loop = self

        class _TrainConsole(_HTTPFrontend):
            def submit(self, *a, **k):
                raise MXNetError("the train console is read-only "
                                 "(GET /metrics, /statusz, /healthz)")

            def snapshot(self):
                return telemetry.default_registry().snapshot()

            def prometheus_text(self):
                return telemetry.default_registry().prometheus_text()

            def health(self):
                # reachable = the process is alive; the console runs on
                # a daemon thread, so it dies with the training process
                return {"ok": True, "step": loop.t,
                        "host": telemetry.default_registry()
                        .labels().get("host"),
                        "preempted": loop.preempted}

            def statusz(self):
                return loop.statusz()

            def close(self):
                if self._httpd is not None:
                    self._httpd.shutdown()
                    self._httpd.server_close()
                    self._httpd = None

        self._console = _TrainConsole()
        self.console_addr = self._console.serve_http(host=host,
                                                     port=port,
                                                     block=False)
        if self.verbose:
            print("[resilient] train console on http://%s:%d "
                  "(/metrics /statusz /healthz)" % self.console_addr,
                  flush=True)
        return self.console_addr

    def close_console(self):
        if self._console is not None:
            self._console.close()
            self._console = None
