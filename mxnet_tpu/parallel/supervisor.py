"""Training remediation supervisor (ISSUE 15): detect -> decide -> act.

PR 13 gave the pod-scale training stack eyes — straggler windows, EWMA
anomaly z-scores, the comms ledger, the flight recorder — but no hands:
a flagged slow host kept dragging the pod, a dead host needed a human
relaunch, and silent data corruption (finite-but-wrong math from a bad
chip) was only caught if it happened to trip the loss detector. This
module closes the loop the serving fleet already closed (PR 11:
respawn, backoff, circuit breaker), with bounded, policied actions:

  * **Host cordoning + elastic restart** — a host flagged as a
    persistent straggler or SDC suspect is written to a shared
    `CordonRoster` (a directory of atomic per-host JSON files beside
    the checkpoint dir — multiple hosts can cordon concurrently without
    a coordinator, first writer wins). Every pod member's supervisor
    then requests a RECONFIGURE: the `ResilientLoop` checkpoints at the
    next step boundary, dumps its flight recorder, and exits with the
    distinct code `EXIT_RECONFIGURE` (84) so the relauncher
    (`tools/train_supervise.py` single-pod; `tools/chaos_train.py
    --multihost --supervised` pod-scale) can tell "relaunch me smaller"
    from both a crash and a preemption. The relaunch excludes cordoned
    hosts and resumes at N−1 via PR 6's elastic sharded restore — under
    a restart budget with exponential backoff and a circuit breaker
    (`MXNET_TRAIN_RESTART_MAX`, mirroring the serving router's
    `respawn_backoff`), so a crash-looping pod degrades loudly instead
    of thrashing. Cordoning never shrinks the pod below
    `MXNET_CORDON_MIN_HOSTS` (bounded action: better a slow pod than no
    pod).

  * **SDC parity probes** — every `MXNET_SDC_PROBE_EVERY` steps, a
    deterministic probe (`TrainStep.probe`: fixed batch, fixed RNG,
    donation-free — params, optimizer state, RNG chain and step counter
    untouched) computes this host's loss + global grad norm; each host
    digests the pair and the digests are cross-checked (process
    allgather under real multi-process jax; an atomic-rename file
    exchange under `MXNET_SDC_PROBE_DIR` for the emulated pod). Hosts
    holding replicated parameters must produce bit-identical floats, so
    a digest diverging from the strict-majority quorum names exactly
    the silently-corrupting chip: `train_sdc_suspect_total` (flight) +
    a `train.sdc` event — and the suspect becomes cordon fodder. A
    split with no majority (e.g. a 2-host pod disagreeing 1–1) is
    recorded as an unattributable divergence, never a guess.

  * **Background checkpoint auditor** — `CheckpointAuditor`, a
    low-priority daemon thread, re-reads published checkpoint files and
    re-verifies size + sha256 against their manifests *after* publish
    (bit-rot / torn-write detection in the window between save and the
    restore that would have needed it). A published file that no longer
    matches demotes its whole step (`CheckpointManager.demote`: every
    file renamed `*.corrupt` — evidence kept, step invisible to
    `all_steps()`), so `restore_latest()` never wastes its fallback
    walk — or a relaunch — on a checkpoint that cannot verify. Missing
    files are NOT corruption (a peer may still be publishing; restore
    refuses incomplete steps on its own).

  * **Signal intake** — `ResilientLoop.step` feeds the supervisor at
    each boundary: straggler episodes (`StragglerMonitor`'s
    newly-flagged hosts), anomaly flags, host absence from the
    time-exchange (a peer that stops publishing windows —
    `train_host_absent_total`; relaunching the dead host is the
    RELAUNCHER's job, so absence alone records rather than cordons),
    and checkpoint publish failures (`CheckpointManager.on_error`;
    `publish_failure_max` consecutive failures cordon THIS host — its
    storage path is the broken part — and reconfigure).

The policy ladder (docs/FAULT_TOLERANCE.md "Automated remediation"):
observe (metrics/flight, always) -> flag (detector episodes) -> cordon
+ elastic restart (persistent straggler, SDC suspect, publish-failing
host) -> circuit breaker (restart budget exhausted: exit loudly,
postmortem rendered). Every action lands in the flight recorder, so
`tools/postmortem.py` renders the whole detect->decide->act chain on
one timeline and `tools/train_top.py` shows the roster live.
"""
from __future__ import annotations

import hashlib
import json
import os
import threading
import time

import numpy as np

from ..base import MXNetError
from .. import telemetry

#: metric-name templates (docs/OBSERVABILITY.md; doc-drift-checked)
SDC_PROBE_TOTAL = "train_sdc_probe_total"
SDC_SUSPECT_TOTAL = "train_sdc_suspect_total"
REMEDIATION_TOTAL = "train_remediation_actions_total"
CORDONS_TOTAL = "train_cordons_total"
CORDONED_GAUGE = "train_cordoned_hosts"
HOST_ABSENT_TOTAL = "train_host_absent_total"
AUDIT_TOTAL = "train_ckpt_audit_total"
AUDIT_FAILURES_TOTAL = "train_ckpt_audit_failures_total"


class CordonedHostError(MXNetError):
    """This host is on the cordon roster: the relauncher should never
    have launched it. Raised at supervisor construction so a cordoned
    host fails loudly at startup instead of rejoining the pod."""


def remediation_enabled():
    """MXNET_TRAIN_REMEDIATION=1 auto-attaches a TrainSupervisor to
    every ResilientLoop (default off: remediation acts, it does not
    just observe)."""
    return os.environ.get("MXNET_TRAIN_REMEDIATION", "0") == "1"


def _env_int(name, default, lo=0):
    raw = os.environ.get(name)
    if raw in (None, ""):
        return default
    try:
        v = int(raw)
    except ValueError:
        raise ValueError("%s must be an integer, got %r" % (name, raw))
    if v < lo:
        raise ValueError("%s must be >= %d, got %r" % (name, lo, raw))
    return v


def _env_float(name, default, lo=0.0):
    raw = os.environ.get(name)
    if raw in (None, ""):
        return default
    try:
        v = float(raw)
    except ValueError:
        raise ValueError("%s must be a number, got %r" % (name, raw))
    if v < lo:
        raise ValueError("%s must be >= %s, got %r" % (name, lo, raw))
    return v


def sdc_probe_every():
    """MXNET_SDC_PROBE_EVERY — steps between SDC parity probes
    (0/unset = off)."""
    return _env_int("MXNET_SDC_PROBE_EVERY", 0)


def sdc_probe_timeout():
    """MXNET_SDC_PROBE_TIMEOUT — seconds one probe waits for peer
    digests before judging with whoever answered (default 60; the
    emulated pod's hosts do not step in lockstep)."""
    return _env_float("MXNET_SDC_PROBE_TIMEOUT", 60.0)


def restart_max():
    """MXNET_TRAIN_RESTART_MAX — automatic relaunches the supervise
    relauncher grants before opening its circuit (default 3)."""
    return _env_int("MXNET_TRAIN_RESTART_MAX", 3)


def restart_backoff():
    """MXNET_TRAIN_RESTART_BACKOFF — base seconds of the relauncher's
    exponential backoff between restarts (default 0.5, mirroring the
    serving router's respawn_backoff)."""
    return _env_float("MXNET_TRAIN_RESTART_BACKOFF", 0.5)


def cordon_min_hosts():
    """MXNET_CORDON_MIN_HOSTS — the cordon floor: remediation never
    shrinks the pod below this many hosts (default 1)."""
    return _env_int("MXNET_CORDON_MIN_HOSTS", 1, lo=1)


def _safe_host(host):
    return "".join(c if c.isalnum() or c in "-_" else "_"
                   for c in str(host))


# ---------------------------------------------------------------------------
# cordon roster
# ---------------------------------------------------------------------------


class CordonRoster:
    """The shared cordon roster: a directory (by convention
    `<ckpt_dir>/cordon`) holding one `host-<label>.json` per cordoned
    host, each published with write-temp + atomic rename. One file per
    host makes concurrent cordons from different pod members race-free
    without a coordinator — the same medium the sharded checkpoints
    use. The relauncher reads the roster to size the next world; a
    launching worker checks it to refuse to rejoin (CordonedHostError).
    """

    def __init__(self, path):
        self.path = path

    @classmethod
    def beside(cls, ckpt_dir):
        """The conventional location: beside the checkpoints so the
        roster survives exactly as long as the run's durable state."""
        return cls(os.path.join(ckpt_dir, "cordon"))

    def _file(self, host):
        return os.path.join(self.path, "host-%s.json" % _safe_host(host))

    def cordon(self, host, reason="", step=None, detail=None):
        """Add `host` to the roster (idempotent). Returns True when this
        call created the entry (first writer), False when it already
        existed."""
        path = self._file(host)
        if os.path.exists(path):
            return False
        os.makedirs(self.path, exist_ok=True)
        tmp = path + ".tmp-%d" % os.getpid()
        entry = {"host": str(host), "reason": str(reason),
                 "step": None if step is None else int(step),
                 "detail": detail, "t": time.time()}
        with open(tmp, "w") as f:
            json.dump(entry, f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
        return True

    def uncordon(self, host):
        """Operator override: remove `host` from the roster."""
        try:
            os.remove(self._file(host))
            return True
        except OSError:
            return False

    def is_cordoned(self, host):
        return os.path.exists(self._file(host))

    def hosts(self):
        """host -> roster entry, sorted by host label. Torn peer writes
        are skipped (the atomic rename makes them transient)."""
        out = {}
        try:
            names = os.listdir(self.path)
        except OSError:
            return out
        for name in sorted(names):
            if not (name.startswith("host-") and name.endswith(".json")):
                continue
            try:
                with open(os.path.join(self.path, name)) as f:
                    entry = json.load(f)
                out[str(entry["host"])] = entry
            except (OSError, ValueError, KeyError, TypeError):
                continue
        return out

    def __len__(self):
        return len(self.hosts())


def effective_hosts(labels, roster):
    """The world the relauncher should build: `labels` minus the
    roster's cordoned hosts, order preserved — the "roster honored"
    contract the elastic-restart drill and the grown-world test pin."""
    cordoned = set(roster.hosts())
    return [l for l in labels if str(l) not in cordoned]


# ---------------------------------------------------------------------------
# SDC parity probes
# ---------------------------------------------------------------------------


class _FileDigestExchange:
    """Shared-directory digest exchange for EMULATED pods
    (MXNET_SDC_PROBE_DIR): each host publishes
    `sdc-<step>-host<label>.json` with an atomic rename, then POLLS
    until `expect` hosts have published for this probe step or
    `timeout_s` passes — the emulated hosts do not step in lockstep, so
    a quorum needs a wait, not a snapshot. Real multi-process jax uses
    `process_allgather` instead (a collective IS the barrier)."""

    def __init__(self, dirpath, host, expect=2, timeout_s=None,
                 poll_s=0.05):
        self.dir = dirpath
        self.host = str(host)
        self.expect = max(1, int(expect))
        self.timeout_s = sdc_probe_timeout() if timeout_s is None \
            else float(timeout_s)
        self.poll_s = float(poll_s)

    def _path(self, step, host):
        return os.path.join(self.dir, "sdc-%d-host%s.json"
                            % (int(step), _safe_host(host)))

    def __call__(self, step, digest):
        try:
            os.makedirs(self.dir, exist_ok=True)
            tmp = self._path(step, self.host) + ".tmp%d" % os.getpid()
            with open(tmp, "w") as f:
                json.dump({"host": self.host, "digest": str(digest),
                           "step": int(step), "t": time.time()}, f)
            os.replace(tmp, self._path(step, self.host))
            # prune this host's older probe files (bounded litter)
            for name in os.listdir(self.dir):
                if name.endswith("host%s.json" % _safe_host(self.host)) \
                        and name.startswith("sdc-") \
                        and name != os.path.basename(
                            self._path(step, self.host)):
                    try:
                        os.remove(os.path.join(self.dir, name))
                    except OSError:
                        pass
        except OSError:
            pass                      # a missed publish skews one probe
        prefix = "sdc-%d-host" % int(step)
        deadline = time.monotonic() + self.timeout_s
        out = {self.host: str(digest)}
        while True:
            try:
                names = os.listdir(self.dir)
            except OSError:
                names = []
            for name in names:
                if not (name.startswith(prefix)
                        and name.endswith(".json")):
                    continue
                try:
                    with open(os.path.join(self.dir, name)) as f:
                        doc = json.load(f)
                    out[str(doc["host"])] = str(doc["digest"])
                except (OSError, ValueError, KeyError, TypeError):
                    continue          # torn peer write: retry next poll
            if len(out) >= self.expect or time.monotonic() >= deadline:
                return out
            time.sleep(self.poll_s)


def _default_digest_exchange(host, expect, timeout_s=None):
    """The probe-digest exchange seam, mirroring the straggler
    monitor's: `MXNET_SDC_PROBE_DIR` names the emulated pod's shared
    directory; real multi-process jax allgathers (digest, host-label)
    byte rows; otherwise the exchange is local-only (a 1-host pod has
    no quorum and the probe degenerates to a determinism self-check)."""
    sdir = os.environ.get("MXNET_SDC_PROBE_DIR")
    if sdir:
        return _FileDigestExchange(sdir, host, expect=expect,
                                   timeout_s=timeout_s)
    try:
        import jax
        nproc = jax.process_count()
    except Exception:
        nproc = 1
    if nproc > 1:
        def gather(step, digest):
            from jax.experimental import multihost_utils
            row = np.zeros(96, np.uint8)
            raw = (str(host)[:32] + ":" + str(digest)[:63]).encode()
            row[:len(raw)] = np.frombuffer(raw[:96], np.uint8)
            rows = np.asarray(
                multihost_utils.process_allgather(row))
            rows = rows.reshape(-1, row.size)
            out = {}
            for i in range(rows.shape[0]):
                text = bytes(rows[i]).rstrip(b"\x00") \
                    .decode("utf-8", "replace")
                h, _, d = text.partition(":")
                out[h or str(i)] = d
            return out
        return gather
    return lambda step, digest: {str(host): str(digest)}


class SDCProbe:
    """Cross-host silent-data-corruption parity probe (the tentpole's
    part 2). `run(step)` executes the deterministic probe function,
    digests its floats, exchanges digests with the pod, and returns the
    hosts whose digest diverges from the strict-majority quorum. The
    chaos seam `utils.chaos.sdc_poison` perturbs THIS host's values
    before digesting when `MXNET_CHAOS_SDC_AT` names it — the injected
    bad chip of the supervised drill."""

    def __init__(self, probe_fn, every, host=None, expect=2,
                 exchange=None, timeout_s=None, registry=None):
        self.every = int(every)
        self._fn = probe_fn
        self.host = str(host if host is not None
                        else telemetry.metrics._host_label())
        self._exchange = exchange or _default_digest_exchange(
            self.host, expect, timeout_s)
        self._registry = registry
        self.probes = 0
        self.suspects = {}            # host -> times flagged (lifetime)
        self.last = None              # the last probe's full verdict
        #: newest probe step at which the assembled digests (>= 2) all
        #: agreed — the restore horizon the SDC quarantine trusts
        self.last_clean_step = 0

    def _reg(self):
        return self._registry or telemetry.default_registry()

    @staticmethod
    def digest(values):
        """Canonical digest of the probe's named floats: full-precision
        %.17g rendering so two bit-identical computations digest
        identically and ANY ulp of silent corruption flips it."""
        text = ",".join("%s=%.17g" % (k, float(values[k]))
                        for k in sorted(values))
        return hashlib.sha256(text.encode()).hexdigest()

    def run(self, step):
        """One probe: compute, digest, exchange, judge. Returns the
        suspect host labels (never this probe's quorum members)."""
        from ..utils import chaos as _chaos
        with telemetry.span("train.sdc_probe", category="train",
                            step=step):
            values = dict(self._fn())
            if _chaos.sdc_poison(step):
                # finite, tiny, and fatal: one ulp would do — the digest
                # is exact — but a relative nudge keeps the flip robust
                # to any downstream rounding of the rendered floats
                values = {k: float(v) + (1e-3 * abs(float(v)) + 1e-6)
                          for k, v in values.items()}
            mine = self.digest(values)
            peers = self._exchange(step, mine)
        self.probes += 1
        if telemetry.enabled():
            self._reg().counter(
                SDC_PROBE_TOTAL,
                help="deterministic SDC parity probes run by this host"
            ).inc()
        suspects = self._judge(step, peers)
        # copy-on-write for the console's HTTP thread
        self.last = {"step": int(step), "digest": mine,
                     "hosts": dict(peers), "suspects": list(suspects)}
        return suspects

    def _judge(self, step, peers):
        if len(peers) < 2 or len(set(peers.values())) == 1:
            if len(peers) >= 2:
                self.last_clean_step = int(step)
            return []
        counts = {}
        for d in peers.values():
            counts[d] = counts.get(d, 0) + 1
        best = max(counts.values())
        majority = [d for d, c in counts.items() if c == best]
        if len(majority) != 1 or best * 2 <= len(peers):
            # divergence with no strict majority (a 2-host pod split
            # 1-1): record it — an operator page, never a guess
            telemetry.flight().record(
                "event", "train.sdc", host=None, quorum=False,
                step=int(step), hosts=len(peers),
                digests=len(counts))
            return []
        quorum = majority[0]
        suspects = sorted(h for h, d in peers.items() if d != quorum)
        reg = self._reg()
        for h in suspects:
            self.suspects = dict(self.suspects,
                                 **{h: self.suspects.get(h, 0) + 1})
            if telemetry.enabled():
                reg.counter(
                    SDC_SUSPECT_TOTAL, flight=True,
                    help="hosts whose SDC parity-probe digest diverged "
                         "from the pod quorum"
                ).inc(host=h, step=int(step))
            telemetry.flight().record(
                "event", "train.sdc", host=h, quorum=True,
                step=int(step), hosts=len(peers))
        return suspects

    def status(self):
        return {"every": self.every, "probes": self.probes,
                "suspects": dict(self.suspects),
                "last_clean_step": self.last_clean_step,
                "last": self.last}


# ---------------------------------------------------------------------------
# background checkpoint auditor
# ---------------------------------------------------------------------------


class CheckpointAuditor:
    """Low-priority re-verification of PUBLISHED checkpoints (the
    tentpole's part 3): a daemon thread re-reads each retained step's
    existing files every `interval_s` and re-checks size + sha256
    against the manifests — the bit-rot / torn-write window between a
    clean publish and the restore that needs it. A file that no longer
    verifies demotes its whole step (`CheckpointManager.demote`) so
    `restore_latest()` never sees it. Missing files are NOT corruption:
    a peer host may still be publishing its shard, and restore already
    refuses incomplete steps."""

    def __init__(self, manager, interval_s=5.0, reaudit_every_s=300.0,
                 registry=None):
        self._mgr = manager
        self.interval_s = float(interval_s)
        #: how long one file's clean verification is trusted before it
        #: is re-hashed. The short wake interval keeps FRESH publishes
        #: verified promptly; this cadence bounds steady-state IO —
        #: re-hashing unchanged multi-GB shards every wake would
        #: compete with the data pipeline for the whole run. A file
        #: whose size or mtime changed re-verifies immediately.
        self.reaudit_every_s = float(reaudit_every_s)
        self._registry = registry
        self._verified = {}           # path -> (size, mtime_ns, t)
        self._stop = threading.Event()
        self._thread = None
        self.audits = 0               # steps verified (lifetime)
        self.demoted = []             # steps demoted (lifetime)

    def _reg(self):
        return self._registry or telemetry.default_registry()

    def start(self):
        if self._thread is not None:
            return self
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="ckpt-auditor")
        self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None

    def _loop(self):
        while not self._stop.wait(self.interval_s):
            try:
                self.audit_once()
            except Exception:
                # the auditor must never take training down with it
                pass

    def _needs_verify(self, path):
        """True when `path` warrants a (re)hash: never verified, changed
        on disk since (size/mtime), or its clean verification aged past
        `reaudit_every_s`."""
        try:
            st = os.stat(path)
        except OSError:
            return False
        rec = self._verified.get(path)
        return not (rec is not None and rec[0] == st.st_size
                    and rec[1] == st.st_mtime_ns
                    and time.monotonic() - rec[2] < self.reaudit_every_s)

    def _mark_verified(self, path):
        try:
            st = os.stat(path)
        except OSError:
            return
        self._verified[path] = (st.st_size, st.st_mtime_ns,
                                time.monotonic())

    def _audit_step(self, step):
        """Verify every EXISTING file of `step`. Raises ValueError on a
        file that is present but fails its manifest — the only shape
        that demotes."""
        mgr = self._mgr
        g = mgr.global_manifest(step)     # corrupt JSON -> ValueError
        if g is not None and g.get("format") == "sharded":
            for fname in g.get("files", []):
                path = os.path.join(mgr.directory, fname)
                side = path[:-len(".npz")] + ".manifest.json"
                # sidecar missing = mid-publish (the npz replaces before
                # its sidecar) or an absent peer — not corruption
                if os.path.exists(path) and os.path.exists(side) \
                        and self._needs_verify(path):
                    mgr._verify_shard(path)
                    self._mark_verified(path)
            return
        path = os.path.join(mgr.directory, "ckpt-%d.npz" % step)
        if os.path.exists(path) and self._needs_verify(path):
            mgr._verify_manifest(step, path)
            self._mark_verified(path)

    def audit_once(self):
        """One audit pass over every retained step; returns the steps
        demoted by this pass."""
        demoted = []
        for step in self._mgr.all_steps():
            try:
                self._audit_step(step)
                self.audits += 1
                if telemetry.enabled():
                    self._reg().counter(
                        AUDIT_TOTAL,
                        help="published checkpoint steps re-verified by "
                             "the background auditor").inc()
            except ValueError as e:
                if not self._mgr.step_files(step):
                    continue          # pruned mid-audit, not corruption
                self._mgr.demote(step, reason=str(e))
                demoted.append(step)
                self.demoted.append(step)
                if telemetry.enabled():
                    self._reg().counter(
                        AUDIT_FAILURES_TOTAL, flight=True,
                        help="published checkpoints the auditor caught "
                             "failing re-verification (demoted before "
                             "any restore saw them)"
                    ).inc(step=int(step))
            except OSError:
                continue              # transient IO: next pass retries
        # drop cache entries for pruned/demoted files (bounded memory)
        self._verified = {p: v for p, v in self._verified.items()
                          if os.path.exists(p)}
        return demoted

    def status(self):
        return {"interval_s": self.interval_s, "audits": self.audits,
                "demoted": list(self.demoted)}


# ---------------------------------------------------------------------------
# the supervisor
# ---------------------------------------------------------------------------


class TrainSupervisor:
    """The remediation supervisor: consumes the PR 13 detector signals
    at `ResilientLoop`'s step boundary and executes the bounded actions
    above. Construct it around a live loop (it attaches itself as
    `loop.supervisor`), or set MXNET_TRAIN_REMEDIATION=1 and the loop
    attaches one automatically.

    Parameters
    ----------
    loop : ResilientLoop
    roster : CordonRoster, optional
        Defaults to `CordonRoster.beside(manager.directory)`.
    probe_every : int, optional
        SDC probe cadence in steps (default MXNET_SDC_PROBE_EVERY;
        0 = no probes).
    probe_batch : (x, y), optional
        The FIXED probe batch. Must be byte-identical on every host —
        the cross-host digest contract. When omitted, the supervisor
        captures the first batch the loop trains on, which is only
        correct when the data pipeline is host-replicated (the emulated
        pod); real pods sharding a global batch per host must pass the
        common probe batch explicitly.
    probe_fn : callable, optional
        Overrides the probe entirely: () -> {name: float}. Wins over
        probe_batch.
    straggler_cordon_after : int
        Straggler EPISODES tolerated before the host is cordoned
        (default 1: the monitor's patience already debounced windows).
    publish_failure_max : int
        Consecutive checkpoint publish failures before THIS host
        cordons itself (its storage path is the broken part; default 3).
    min_hosts : int, optional
        Cordon floor (default MXNET_CORDON_MIN_HOSTS).
    audit : bool
        Start the background CheckpointAuditor (default True).
    expect_hosts : int, optional
        Pod size the SDC quorum expects (default the manager's
        process_count).
    """

    def __init__(self, loop, roster=None, probe_every=None,
                 probe_batch=None, probe_fn=None, exchange=None,
                 straggler_cordon_after=1, publish_failure_max=3,
                 min_hosts=None, audit=True, audit_interval_s=5.0,
                 expect_hosts=None, host=None, registry=None):
        self._loop = loop
        self._manager = loop._manager
        self._registry = registry
        self.host = str(host if host is not None
                        else telemetry.metrics._host_label())
        self.roster = roster if roster is not None \
            else CordonRoster.beside(self._manager.directory)
        if self.roster.is_cordoned(self.host):
            raise CordonedHostError(
                "host %r is on the cordon roster at %s (reason: %s) — "
                "the relauncher must exclude it; uncordon() to "
                "reinstate" % (self.host, self.roster.path,
                               (self.roster.hosts().get(self.host) or {})
                               .get("reason")))
        # entries already on the roster at startup belong to PREVIOUS
        # incarnations: the relauncher excluded those hosts from this
        # world, so they are (a) already outside expect_hosts — never
        # re-subtracted by the cordon floor — and (b) stale for drain
        # purposes — a fresh entry is a member of THIS world leaving
        self._initial_cordoned = set(self.roster.hosts())
        self.min_hosts = cordon_min_hosts() if min_hosts is None \
            else max(1, int(min_hosts))
        self.straggler_cordon_after = max(1, int(straggler_cordon_after))
        self.publish_failure_max = max(1, int(publish_failure_max))
        self._expect = int(expect_hosts
                           if expect_hosts is not None
                           else self._manager.process_count)
        self._probe_every = sdc_probe_every() if probe_every is None \
            else int(probe_every)
        self._probe_batch = probe_batch
        self._probe_fn = probe_fn
        self._exchange = exchange
        self.probe = None             # built lazily (needs a batch)
        self.auditor = None
        if audit:
            self.auditor = CheckpointAuditor(
                self._manager, interval_s=audit_interval_s,
                registry=registry).start()
        self.reconfigure_requested = False
        self.reconfigure_reason = None
        #: armed by the SDC quarantine: the loop must publish NOTHING
        #: further this incarnation (cadence saves and the reconfigure
        #: drain save) — the suspect window's state must not become the
        #: checkpoint the relaunch restores
        self.suppress_saves = False
        self.actions = []             # [(step, action, target, reason)]
        self.publish_failures = 0     # consecutive
        self._straggler_episodes = {}
        self._hosts_seen = set()
        self._absent = {}
        self._absent_flagged = set()
        self._last_windows = 0
        # wire the publish-outcome signals (best-effort: managers
        # without the hooks just skip them)
        if hasattr(self._manager, "on_error"):
            self._manager.on_error = self._on_publish_error
        if hasattr(self._manager, "on_success"):
            self._manager.on_success = self.on_publish_ok
        loop.supervisor = self

    def _reg(self):
        return self._registry or telemetry.default_registry()

    def _record_action(self, step, action, target, reason):
        self.actions.append({"step": int(step), "action": action,
                             "target": target, "reason": reason,
                             "t": time.time()})
        if telemetry.enabled():
            self._reg().counter(
                REMEDIATION_TOTAL, flight=True,
                help="remediation actions executed by the training "
                     "supervisor (cordon, reconfigure, self-cordon)"
            ).inc(action=action, target=target, reason=reason,
                  step=int(step))

    # -- signal intake (ResilientLoop.step calls these) ---------------------
    def note_batch(self, x, y):
        """First-batch capture for the default SDC probe (see
        `probe_batch` above for the host-replication contract)."""
        if self._probe_batch is None and self._probe_fn is None \
                and self._probe_every > 0:
            self._probe_batch = (np.array(np.asarray(x)),
                                 np.array(np.asarray(y)))

    def on_step(self, step, stragglers=(), anomalies=()):
        """One step boundary's worth of detector signals."""
        for h in stragglers:
            self.on_straggler(h, step)
        for sig in anomalies:
            # the bad-step guard + rollback policy own the numeric
            # response; the supervisor keeps the ledger so the anomaly
            # shows up beside the actions it may precede
            self.actions.append({"step": int(step), "action": "observe",
                                 "target": str(sig), "reason": "anomaly",
                                 "t": time.time()})
        self._watch_absence(step)
        if self._probe_every > 0 and step > 0 \
                and step % self._probe_every == 0:
            for h in self.run_probe(step):
                self.consider_cordon(h, "sdc", step)

    def on_straggler(self, host, step):
        """A StragglerMonitor episode onset for `host`."""
        n = self._straggler_episodes.get(str(host), 0) + 1
        self._straggler_episodes[str(host)] = n
        if n >= self.straggler_cordon_after:
            self.consider_cordon(host, "straggler", step)

    def _watch_absence(self, step):
        mon = getattr(self._loop, "_straggler", None)
        if mon is None or mon.last_window is None:
            return
        if mon.windows == self._last_windows:
            return                    # judge once per closed window
        self._last_windows = mon.windows
        present = {str(h) for h in mon.last_window}
        self._hosts_seen |= present
        for h in sorted(self._hosts_seen - present):
            self._absent[h] = self._absent.get(h, 0) + 1
            if self._absent[h] == 2 and h not in self._absent_flagged:
                # two consecutive silent windows: the peer stopped
                # publishing — dead host or severed exchange. Recorded,
                # not cordoned: relaunching the dead host is the
                # RELAUNCHER's job (it sees the exit), and cordoning a
                # host that may be mid-relaunch would evict it twice.
                self._absent_flagged.add(h)
                if telemetry.enabled():
                    self._reg().counter(
                        HOST_ABSENT_TOTAL, flight=True,
                        help="hosts that vanished from the step-time "
                             "exchange for 2+ consecutive windows"
                    ).inc(host=h, step=int(step))
                telemetry.flight().record(
                    "event", "train.host_absent", host=h,
                    step=int(step), windows=self._absent[h])
        for h in present:
            self._absent.pop(h, None)
            self._absent_flagged.discard(h)

    def _on_publish_error(self, exc):
        """CheckpointManager calls this when a (possibly async) publish
        ultimately failed. Consecutive failures past the budget cordon
        THIS host: its storage path is the broken part, and a pod
        member that cannot checkpoint is a liability to every restore."""
        self.publish_failures += 1
        telemetry.flight().record(
            "event", "train.publish_failure", host=self.host,
            consecutive=self.publish_failures, error=str(exc)[:200])
        if self.publish_failures >= self.publish_failure_max:
            self.consider_cordon(self.host, "ckpt_publish",
                                 self._loop.t,
                                 detail=str(exc)[:200])

    def on_publish_ok(self):
        self.publish_failures = 0

    # -- SDC probes ---------------------------------------------------------
    def run_probe(self, step):
        if self.probe is None:
            self.probe = self._build_probe()
        if self.probe is None:
            return []
        return self.probe.run(step)

    def _build_probe(self):
        fn = self._probe_fn
        if fn is None:
            batch = self._probe_batch
            if batch is None:
                return None           # nothing deterministic to probe
            step_obj = self._loop._step

            def fn():
                loss, gnorm = step_obj.probe(*batch)
                return {"loss": loss, "grad_norm": gnorm}
        return SDCProbe(fn, self._probe_every, host=self.host,
                        expect=self._expect, exchange=self._exchange,
                        registry=self._registry)

    # -- actions ------------------------------------------------------------
    def consider_cordon(self, host, reason, step, detail=None):
        """The cordon decision: bounded by the min-hosts floor, and
        followed by a reconfigure request when the roster actually
        gained a member — a pod with a FRESHLY cordoned host must
        shrink at the next boundary. A host already on the roster is a
        no-op: the world that excludes it is the relauncher's job, and
        a stale detector signal about it (e.g. its last straggler
        publishes surviving into the relaunched incarnation) must not
        re-drain the shrunk pod forever."""
        host = str(host)
        roster_now = self.roster.hosts()
        if host in roster_now:
            if host in self._initial_cordoned and host != self.host:
                # a PREVIOUS incarnation's entry: the relauncher already
                # excluded this host from my world, and stale detector
                # signals about it (its last straggler publishes
                # surviving the relaunch) must not re-drain the shrunk
                # pod forever
                return False
            # a FRESH entry — a peer beat me to the roster write for a
            # member of THIS world (possibly me). Every member must
            # still drain: a pod can only shrink together, and a
            # cordoned host training on is wasted (SDC-suspect) work
            # whose black box never dumps. No livelock: a fresh entry's
            # host never relaunches into the next world.
            if reason == "sdc":
                self._sdc_quarantine(step)
            self.request_reconfigure(
                "%s:%s" % (roster_now[host].get("reason", reason),
                           host), step=step)
            return True
        # cordon floor: entries from previous incarnations are already
        # outside self._expect (the relauncher shrank the world), so
        # only entries FRESH in this incarnation reduce the survivors
        fresh = [h for h in roster_now if h not in self._initial_cordoned]
        survivors = self._expect - len(fresh) - 1
        if survivors < self.min_hosts:
            telemetry.flight().record(
                "event", "train.cordon_refused", host=host,
                reason=reason, step=int(step),
                min_hosts=self.min_hosts)
            self._record_action(step, "cordon_refused", host, reason)
            return False
        created = self.roster.cordon(host, reason=reason, step=step,
                                     detail=detail)
        if created and telemetry.enabled():
            self._reg().counter(
                CORDONS_TOTAL, flight=True,
                help="hosts written to the cordon roster by this "
                     "supervisor").inc(host=host, reason=reason,
                                       step=int(step))
        telemetry.flight().record(
            "event", "train.cordon", host=host, reason=reason,
            step=int(step), first_writer=bool(created))
        self._record_action(step, "cordon", host, reason)
        if telemetry.enabled():
            self._reg().gauge(
                CORDONED_GAUGE,
                help="hosts currently on the cordon roster"
            ).set(len(self.roster.hosts()))
        if reason == "sdc":
            self._sdc_quarantine(step)
        self.request_reconfigure("%s:%s" % (reason, host), step=step)
        return True

    def _sdc_quarantine(self, step):
        """An SDC suspect means every checkpoint newer than the last
        CLEAN probe may hold finite-but-wrong shards (under sharded
        checkpoints the suspect's slice has no other copy). Response:
        stop publishing (this incarnation's saves — cadence AND the
        reconfigure drain — are suppressed) and demote the steps the
        corruption window covers, so the relaunch restores the newest
        step the quorum certified. Steps lost are bounded by the probe
        cadence — the documented price of the probes' guarantee."""
        if self.suppress_saves:
            return
        self.suppress_saves = True
        safe = self.probe.last_clean_step if self.probe is not None \
            else 0
        demoted = []
        for s in self._manager.all_steps():
            if s > safe and self._manager.demote(
                    s, reason="sdc quarantine (newer than last clean "
                              "probe %d)" % safe):
                demoted.append(s)
        telemetry.flight().record(
            "event", "train.sdc_quarantine", safe_step=int(safe),
            demoted=demoted, step=int(step))
        self._record_action(step, "sdc_quarantine",
                            "steps>%d" % safe, "sdc")

    def request_reconfigure(self, reason, step=None):
        """Arm the loop's reconfigure drain: checkpoint at the next
        step boundary, flight-dump, exit EXIT_RECONFIGURE (84)."""
        if self.reconfigure_requested:
            return
        self.reconfigure_requested = True
        self.reconfigure_reason = str(reason)
        step = self._loop.t if step is None else step
        telemetry.flight().record(
            "event", "train.reconfigure", reason=self.reconfigure_reason,
            step=int(step), cordoned=sorted(self.roster.hosts()))
        self._record_action(step, "reconfigure", self.host, reason)

    # -- console / teardown -------------------------------------------------
    def status(self):
        """The /statusz remediation block (train_top renders it)."""
        return {
            "host": self.host,
            "cordoned": {h: {"reason": e.get("reason"),
                             "step": e.get("step")}
                         for h, e in self.roster.hosts().items()},
            "min_hosts": self.min_hosts,
            "reconfigure": {"requested": self.reconfigure_requested,
                            "reason": self.reconfigure_reason},
            "publish_failures": self.publish_failures,
            "sdc": self.probe.status() if self.probe is not None
            else {"every": self._probe_every, "probes": 0,
                  "suspects": {}, "last": None},
            "audit": self.auditor.status() if self.auditor is not None
            else None,
            "actions": list(self.actions[-20:]),
        }

    def close(self):
        if self.auditor is not None:
            self.auditor.stop()
        if hasattr(self._manager, "on_error") \
                and self._manager.on_error == self._on_publish_error:
            self._manager.on_error = None
