"""TrainStep: the fully-fused XLA training step.

This is the TPU performance path that the eager Trainer (gluon/trainer.py)
API-matches: forward + loss + backward + optimizer update compile into ONE
XLA program with buffer donation, so parameters update in-place in HBM and
nothing round-trips to the host. Under a mesh, the batch shards over 'dp'
(GSPMD inserts the gradient psum — the KVStore('tpu') allreduce), while
parameters stay replicated (or sharded for tensor parallelism via
param_shardings).

Parity note: the reference overlapped backward with kvstore pushes via
engine priorities (src/kvstore/comm.h:171); XLA's latency-hiding scheduler
performs the same overlap inside this single program.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from ..ndarray import NDArray
from .. import autograd
from .. import random as _random


# -- pure optimizer rules (lr and t arrive as tracers, so no retrace/step) --

def _sgd_init(w, momentum):
    return (jnp.zeros_like(w),) if momentum else ()


def _sgd_apply(w, g, state, lr, t, momentum, wd, hyper):
    g = g + wd * w
    if state:
        m = momentum * state[0] - lr * g
        return w + m, (m,)
    return w - lr * g, state


def _nag_init(w, momentum):
    return (jnp.zeros_like(w),)


def _nag_apply(w, g, state, lr, t, momentum, wd, hyper):
    g = g + wd * w
    m = momentum * state[0] + g
    return w - lr * (g + momentum * m), (m,)


def _adam_init(w, momentum):
    return (jnp.zeros_like(w), jnp.zeros_like(w))


def _adam_apply(w, g, state, lr, t, momentum, wd, hyper):
    beta1 = hyper.get("beta1", 0.9)
    beta2 = hyper.get("beta2", 0.999)
    eps = hyper.get("epsilon", 1e-8)
    g = g + wd * w
    m, v = state
    m = beta1 * m + (1 - beta1) * g
    v = beta2 * v + (1 - beta2) * jnp.square(g)
    lr_t = lr * jnp.sqrt(1 - beta2 ** t) / (1 - beta1 ** t)
    return w - lr_t * m / (jnp.sqrt(v) + eps), (m, v)


_RULES = {"sgd": (_sgd_init, _sgd_apply),
          "nag": (_nag_init, _nag_apply),
          "adam": (_adam_init, _adam_apply)}


class TrainStep:
    """Compile net+loss+optimizer into one donated XLA program.

    Usage:
        step = TrainStep(net, loss_fn, 'sgd',
                         {'learning_rate': 0.1, 'momentum': 0.9}, mesh=mesh)
        loss = step(x_batch, y_batch)   # params update in device memory
        step.sync_params()              # write back before eval/save
    """

    def __init__(self, net, loss_fn, optimizer="sgd", optimizer_params=None,
                 mesh=None, data_axis="dp", param_shardings=None):
        self._net = net
        self._loss = loss_fn
        optimizer_params = dict(optimizer_params or {})
        self._lr = float(optimizer_params.pop("learning_rate", 0.01))
        self._momentum = float(optimizer_params.pop("momentum", 0.0))
        self._wd = float(optimizer_params.pop("wd", 0.0))
        self._hyper = optimizer_params
        self._opt_name = optimizer if isinstance(optimizer, str) else \
            type(optimizer).__name__.lower()
        if self._opt_name not in _RULES:
            raise ValueError(
                "TrainStep fuses %s; use gluon.Trainer for other optimizers"
                % sorted(_RULES))
        self._mesh = mesh
        self._data_axis = data_axis
        self._param_shardings = param_shardings or {}
        self._lr_schedule = None
        self._t = 0
        self._step_fn = None

    def set_lr_schedule(self, fn):
        self._lr_schedule = fn

    def _build(self):
        params = self._net.collect_params()
        names, plist = [], []
        for n, p in params.items():
            if p._data is None:
                raise RuntimeError("initialize parameters before TrainStep "
                                   "(missing %s)" % n)
            names.append(n)
            plist.append(p)
        grad_mask = [p.grad_req != "null" for p in plist]
        net, loss_fn = self._net, self._loss
        init_rule, apply_rule = _RULES[self._opt_name]
        momentum, wd, hyper = self._momentum, self._wd, self._hyper

        def forward_loss(grad_vals, nograd_vals, x, y, key):
            """Trace the eager net with tracer-backed parameter buffers.
            Returns (mean_loss, {plist_index: mutated_value}) where the aux
            dict carries BatchNorm running-stat writes."""
            merged = []
            gi = ni = 0
            for has_grad in grad_mask:
                if has_grad:
                    merged.append(grad_vals[gi])
                    gi += 1
                else:
                    merged.append(nograd_vals[ni])
                    ni += 1
            from .functional import swap_param_buffers
            with swap_param_buffers(plist, merged) as injected:
                with autograd._RecordingStateScope(False, True), \
                        _random.trace_key_scope(key):
                    out = net.forward(NDArray(x))
                    loss = loss_fn(out, NDArray(y))
                loss_val = jnp.mean(loss._data)
                aux_upd = {i: p._data._data for i, p in enumerate(plist)
                           if p._data._data is not injected[i]}
            return loss_val, aux_upd

        def step(grad_vals, nograd_vals, opt_state, x, y, key, lr, t):
            (loss_val, aux_upd), grads = jax.value_and_grad(
                forward_loss, has_aux=True)(grad_vals, nograd_vals, x, y, key)
            new_grad_vals, new_state = [], []
            for w, g, s in zip(grad_vals, grads, opt_state):
                w2, s2 = apply_rule(w, g, s, lr, t, momentum, wd, hyper)
                new_grad_vals.append(w2)
                new_state.append(s2)
            new_nograd_vals = list(nograd_vals)
            ni = 0
            for i, has_grad in enumerate(grad_mask):
                if not has_grad:
                    if i in aux_upd:
                        new_nograd_vals[ni] = aux_upd[i]
                    ni += 1
            return (loss_val, tuple(new_grad_vals), tuple(new_nograd_vals),
                    tuple(new_state))

        self._step_fn = jax.jit(step, donate_argnums=(0, 1, 2))
        self._names = names
        self._plist = plist
        self._grad_mask = grad_mask
        grad_vals = tuple(p._data._data
                          for p, m in zip(plist, grad_mask) if m)
        nograd_vals = tuple(p._data._data
                            for p, m in zip(plist, grad_mask) if not m)
        opt_state = tuple(init_rule(w, self._momentum) for w in grad_vals)
        if self._mesh is not None:
            def place(name, v):
                spec = self._param_shardings.get(name, P())
                return jax.device_put(v, NamedSharding(self._mesh, spec))
            gnames = [n for n, m in zip(self._names, grad_mask) if m]
            nnames = [n for n, m in zip(self._names, grad_mask) if not m]
            grad_vals = tuple(place(n, v) for n, v in zip(gnames, grad_vals))
            nograd_vals = tuple(place(n, v)
                                for n, v in zip(nnames, nograd_vals))
            opt_state = tuple(
                tuple(place(n, s) for s in st)
                for n, st in zip(gnames, opt_state))
        self._grad_vals = grad_vals
        self._nograd_vals = nograd_vals
        self._opt_state = opt_state

    def __call__(self, x, y):
        xv = x._data if isinstance(x, NDArray) else jnp.asarray(x)
        yv = y._data if isinstance(y, NDArray) else jnp.asarray(y)
        if self._step_fn is None:
            self._build()
        if self._mesh is not None:
            from .mesh import shard_batch
            xv = shard_batch(self._mesh, xv, self._data_axis)
            yv = shard_batch(self._mesh, yv, self._data_axis)
        self._t += 1
        lr = self._lr if self._lr_schedule is None else \
            self._lr_schedule(self._t)
        key = _random.next_key()
        loss, self._grad_vals, self._nograd_vals, self._opt_state = \
            self._step_fn(self._grad_vals, self._nograd_vals,
                          self._opt_state, xv, yv, key,
                          jnp.float32(lr), jnp.int32(self._t))
        return loss

    def sync_params(self):
        """Write device buffers back into the Parameters (for eval/save)."""
        gi = ni = 0
        for p, m in zip(self._plist, self._grad_mask):
            if m:
                p._data._data = self._grad_vals[gi]
                gi += 1
            else:
                p._data._data = self._nograd_vals[ni]
                ni += 1
            p._data._version += 1
