"""TrainStep: the fully-fused XLA training step.

This is the TPU performance path that the eager Trainer (gluon/trainer.py)
API-matches: forward + loss + backward + optimizer update compile into ONE
XLA program with buffer donation, so parameters update in-place in HBM and
nothing round-trips to the host. Under a mesh, the batch shards over 'dp'
(GSPMD inserts the gradient psum — the KVStore('tpu') allreduce), while
parameters stay replicated (or sharded for tensor parallelism via
param_shardings).

Every registered optimizer fuses: the update math lives once, as pure rules
in mxnet_tpu.optimizer_rules, shared with the eager classes — the analog of
the reference's fused optimizer kernels (src/operator/optimizer_op-inl.h)
covering the full optimizer list instead of a subset.

Mixed precision (dtype="bfloat16"): forward/backward compute in bf16 on the
MXU with float32 master weights and optimizer state; logits are promoted to
f32 before the loss for a stable softmax. This is the reference's
multi_precision fp16 capability (optimizer.py:483) in its TPU-native form.

Rematerialisation (remat=True/"full"): wraps each compute block's forward
in jax.checkpoint so the backward pass recomputes activations instead of
storing them — the MXNET_BACKWARD_DO_MIRROR capability
(docs/faq/env_var.md:93). remat="io" (or MXNET_REMAT_POLICY=io) keeps the
MXU outputs (conv/matmul, tagged checkpoint_name in ops/nn.py) and BN batch
stats, recomputing only the cheap elementwise chains — trading a few FLOPs
for HBM bytes on a bandwidth-bound step.

Parity note: the reference overlapped backward with kvstore pushes via
engine priorities (src/kvstore/comm.h:171); XLA's latency-hiding scheduler
performs the same overlap inside this single program.
"""
from __future__ import annotations

import contextlib

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from ..ndarray import NDArray
from .. import autograd
from .. import random as _random
from .. import optimizer_rules as _rules


#: remat modes -> jax.checkpoint policies. "full" is the reference's
#: MXNET_BACKWARD_DO_MIRROR trade (save only segment boundaries, recompute
#: everything). "io" is the HBM-traffic policy: SAVE what the MXU produced
#: (conv/matmul outputs, tagged in ops/nn.py via checkpoint_name) plus the
#: tiny BN batch statistics, and RECOMPUTE the cheap elementwise chains
#: (BN normalize, relu, residual adds) in backward instead of writing them
#: out in forward and re-reading them — the bandwidth-roofline lever for a
#: step measured at 95% of the HBM floor (BENCH_NOTES roofline analysis).
#: Composes with MXNET_FUSED_BN_EPILOGUE=1 (ops/pallas_fused.py): the
#: fused op's custom-VJP residuals are exactly this save set (conv_out +
#: bn_stats), so under "io" its relu outputs are never stored — backward
#: replays the Pallas epilogue kernel from the saved conv output.
_REMAT_POLICIES = {
    "full": lambda: None,  # jax.checkpoint default: nothing saveable
    "io": lambda: jax.checkpoint_policies.save_only_these_names(
        "conv_out", "bn_stats", "fc_out"),
}


def _remat_mode(remat):
    """Normalize the TrainStep remat argument / env vars to a mode string
    in {"none", "full", "io"}."""
    import os
    if remat is None:
        mode = os.environ.get("MXNET_REMAT_POLICY", "").lower()
        if mode:
            if mode != "none" and mode not in _REMAT_POLICIES:
                # a typo must not silently measure a different config
                raise ValueError(
                    "MXNET_REMAT_POLICY must be none, full or io, got %r"
                    % (mode,))
            return mode
        # parity: MXNET_BACKWARD_DO_MIRROR (docs/faq/env_var.md:93) —
        # trade recompute for activation memory by default when set
        if os.environ.get("MXNET_BACKWARD_DO_MIRROR", "0") == "1":
            return "full"
        return "none"
    if remat is True:
        return "full"
    if not remat or remat == "none":
        return "none"
    if remat in _REMAT_POLICIES:
        return remat
    raise ValueError(
        "remat must be bool, 'none', 'full' or 'io', got %r" % (remat,))


def _remat_segments(net):
    """Checkpoint segments: walk the block tree, recursing through
    Sequential-style containers so boundaries land at real compute blocks
    (a ResNet's 16 bottlenecks, an MLP's Dense layers) rather than one
    whole-feature-stack segment. Blocks that mutate auxiliary state
    (BatchNorm running stats) are fine: _segment_remat threads the aux
    buffers through the checkpoint as explicit inputs/outputs."""
    from ..gluon.nn.basic_layers import Sequential, HybridSequential
    segs = []

    def walk(block):
        for child in getattr(block, "_children", {}).values():
            if isinstance(child, (Sequential, HybridSequential)):
                walk(child)
            else:
                segs.append(child)

    walk(net)
    return segs


@contextlib.contextmanager
def _segment_remat(blocks, policy=None, net=None):
    """Wrap each block's forward in jax.checkpoint for the duration of the
    step trace. Whole-function checkpoint saves nothing at peak (the
    backward's recompute carries the same live set); per-segment checkpoint
    keeps only segment boundaries + policy-saveable values alive — the real
    MXNET_BACKWARD_DO_MIRROR/memonger trade.

    Aux-state blocks (BatchNorm running stats, grad_req 'null' params) are
    checkpointable: their buffers enter the checkpointed function as
    explicit arguments and the mutated values return as explicit outputs,
    written back in place — no inner tracer ever leaks through
    Parameter._data, and NDArray references taken before the step stay
    valid (same object identity as the non-remat path).

    `net` (when given) has its WHOLE tree's CachedOps deactivated for the
    trace: a hybridized container above the segments would otherwise route
    through its warmed jit cache and bypass every wrapped forward,
    silently skipping remat.
    """
    saved = []
    active = []

    def _collect_active(b):
        if getattr(b, "_active", False):
            active.append(b)
            b._active = False

    if net is not None and hasattr(net, "apply"):
        # deactivate hybridized blocks ANYWHERE in the tree (containers
        # included), not just the wrapped segments — inside the step
        # everything is jitted anyway, the CachedOp adds nothing
        net.apply(_collect_active)
    for block in blocks:
        _collect_active(block)
        orig = block.forward
        aux_params = [p for p in block.collect_params().values()
                      if p.grad_req == "null"]

        def wrapped(*args, _orig=orig, _aux=aux_params):
            if len(args) == 1 and isinstance(args[0], NDArray):
                # single trace through checkpoint — no retry path, so the
                # stateful trace-key counter advances exactly once and
                # remat numerics match the non-remat step bit for bit
                def pure(xv, aux_in):
                    for p, v in zip(_aux, aux_in):
                        p._data = NDArray(v)
                    out = _orig(NDArray(xv))
                    outs = out._data if isinstance(out, NDArray) \
                        else tuple(o._data for o in out)
                    return outs, tuple(p._data._data for p in _aux)
                aux_in = tuple(p._data._data for p in _aux)
                orig_nd = [p._data for p in _aux]
                res, aux_out = jax.checkpoint(pure, policy=policy)(
                    args[0]._data, aux_in)
                # write back IN PLACE on the pre-call NDArray objects:
                # rebinding p._data to a fresh NDArray would orphan any
                # reference taken before the step with a dead inner tracer
                for p, nd_, v in zip(_aux, orig_nd, aux_out):
                    nd_._data = v
                    p._data = nd_
                if isinstance(res, tuple):
                    return tuple(NDArray(r) for r in res)
                return NDArray(res)
            return _orig(*args)

        saved.append((block, orig))
        block.forward = wrapped
    try:
        yield
    finally:
        for block, orig in saved:
            block.forward = orig
        for block in active:
            block._active = True


class TrainStep:
    """Compile net+loss+optimizer into one donated XLA program.

    Usage:
        step = TrainStep(net, loss_fn, 'sgd',
                         {'learning_rate': 0.1, 'momentum': 0.9}, mesh=mesh)
        loss = step(x_batch, y_batch)   # params update in device memory
        step.sync_params()              # write back before eval/save
    """

    def __init__(self, net, loss_fn, optimizer="sgd", optimizer_params=None,
                 mesh=None, data_axis="dp", param_shardings=None,
                 dtype="float32", remat=None, shard_optimizer_states=False,
                 sharded_update=None, guard=False,
                 quantized_collectives=None):
        import os as _os
        from .. import optimizer as _opt_mod
        remat = _remat_mode(remat)
        self._net = net
        self._loss = loss_fn
        if isinstance(optimizer, str):
            optimizer = _opt_mod.create(optimizer,
                                        **dict(optimizer_params or {}))
        elif optimizer_params:
            raise ValueError("pass optimizer_params only with a string name")
        if optimizer.rule_name is None:
            raise ValueError("optimizer %s has no pure update rule"
                             % type(optimizer).__name__)
        self._opt = optimizer
        self._mesh = mesh
        self._data_axis = data_axis
        self._param_shardings = param_shardings or {}
        self._compute_dtype = jnp.dtype(dtype)
        self._remat = remat
        # ZeRO-style weight-update sharding (arXiv:2004.13336): optimizer
        # state shards over the data axis, GSPMD turning the grad all-reduce
        # into reduce-scatter + the post-update all-gather automatically
        if sharded_update is None:
            sharded_update = _os.environ.get("MXNET_SHARDED_UPDATE",
                                             "0") == "1"
        # sharded_update goes further than state *placement*: the step
        # itself pins the ZeRO-1 dataflow with sharding constraints —
        # grads reduce-scatter over dp, the optimizer applies to the
        # local 1/N shard of (weight, grad, state), updated params
        # all-gather back to replicated. Semantically identical to the
        # unsharded step (the constraints only re-place the same global
        # values), which tests pin bit-for-bit against the unsharded
        # oracle; per-chip it trades the full optimizer-state footprint
        # for 1/N + an all-gather. Implies sharded state placement, and
        # makes per-host sharded checkpoints (utils/recovery.py) the
        # natural way to save the now per-host optimizer state.
        self._sharded_update = bool(sharded_update)
        self._shard_opt = bool(shard_optimizer_states) or \
            self._sharded_update
        # bad-step guard (parallel/resilient.py): when on, the jitted step
        # also computes the global grad norm + a finiteness flag and
        # SELECTS the old (params, opt state, aux) when the step is bad —
        # the state protection itself needs no host round-trip.
        # Numerically transparent while every step is finite: the select
        # picks the identical new values. Note the POLICY layer
        # (ResilientLoop) reads last_step_ok on the host each step to
        # react, which serializes dispatch; policy="off" keeps full
        # async overlap, and BENCH_CONFIGS=resilience tracks the cost.
        self._guard = bool(guard)
        # int8 grad all-reduce (ISSUE 20 training leg): the dp gradient
        # collective carries int8 payload with per-tensor global scales
        # and kvstore-style error-feedback residuals
        # (_TwoBitCompressor's algorithm at the XLA collective seam).
        # Flag switches the COLLECTIVE's precision, never the training
        # contract: ineligible configs record the reason on
        # `collective_quant_fallback` and run the f32 psum verbatim.
        if quantized_collectives is None:
            quantized_collectives = _os.environ.get(
                "MXNET_QUANTIZED_COLLECTIVES", "").strip() or None
        self._qcoll_req = quantized_collectives
        self.collective_quant = None
        self.collective_quant_fallback = None
        self._quant_residuals = None
        self.last_step_ok = None     # device bool of the latest step
        self.last_grad_norm = None   # device f32 of the latest step
        self._lr_schedule = None
        self._t = 0
        self._step_fn = None
        self._probe_fn = None
        self._compiled = False

    def set_lr_schedule(self, fn):
        self._lr_schedule = fn

    @property
    def warm_loads(self):
        """Fused-step executables warm-loaded from the persistent AOT
        cache (mxnet_tpu/aot) instead of compiled — a supervised
        relaunch (tools/train_supervise.py --prewarm-cmd) lands here."""
        fn = self._step_fn
        return getattr(fn, "warm_loads", 0) if fn is not None else 0

    @property
    def t(self):
        """Completed optimizer steps (the checkpoint step number)."""
        return self._t

    def _build(self):
        params = self._net.collect_params()
        names, plist = [], []
        for n, p in params.items():
            if p._data is None:
                raise RuntimeError("initialize parameters before TrainStep "
                                   "(missing %s)" % n)
            names.append(n)
            plist.append(p)
        grad_mask = [p.grad_req != "null" for p in plist]
        net, loss_fn = self._net, self._loss
        opt = self._opt
        init_rule, apply_rule = _rules.get(opt.rule_name)
        hyper = opt.rule_hyper()
        stochastic_rule = opt.rule_name in _rules.STOCHASTIC
        rescale, clip = opt.rescale_grad, opt.clip_gradient
        # per-param lr/wd multipliers resolve to static floats at build time;
        # Parameter-level attrs take priority over name dicts, matching the
        # eager Optimizer._get_lr/_get_wd param_dict branch
        gparams = [(n, p) for n, p, m in zip(names, plist, grad_mask) if m]
        gnames_all = [n for n, _ in gparams]

        def _mult(p, n, dct, attr):
            v = getattr(p, attr, 1.0)
            if v != 1.0:
                return v
            return dct.get(n, 1.0)

        lr_mults = [_mult(p, n, opt.lr_mult, "lr_mult") for n, p in gparams]
        wd_mults = [_mult(p, n, opt.wd_mult, "wd_mult") for n, p in gparams]
        base_wd = opt.wd
        cdtype = self._compute_dtype
        mixed = cdtype != jnp.float32
        remat_on = self._remat != "none"
        remat_policy = _REMAT_POLICIES[self._remat]() if remat_on else None
        remat_blocks = _remat_segments(net) if remat_on else []
        # ZeRO-1 (arXiv:2004.13336) shard specs, one per grad param: the
        # first dp-divisible axis of each REPLICATED weight (tensor-
        # parallel params already shard their own way; scalars and
        # indivisible shapes stay replicated). Used both to place the
        # optimizer state and to pin the in-step dataflow below.
        mesh_obj = self._mesh
        dp_ax = self._data_axis
        dp_size = mesh_obj.shape.get(dp_ax, 0) \
            if (mesh_obj is not None and dp_ax) else 0
        zero_specs = []
        for n, p in gparams:
            pspec = self._param_shardings.get(n, P())
            replicated = all(ax is None for ax in pspec)
            w0 = p._data._data
            z = None
            if dp_size > 1 and replicated and np.ndim(w0) > 0:
                for axis in range(np.ndim(w0)):
                    if w0.shape[axis] % dp_size == 0:
                        z = P(*([None] * axis + [dp_ax]))
                        break
            zero_specs.append(z)
        szd = self._sharded_update and dp_size > 1 and \
            any(z is not None for z in zero_specs)
        # int8-collective eligibility: the compression targets the
        # replicated-parameter dp all-reduce, so ZeRO's reduce-scatter
        # dataflow and tensor-sharded params keep their f32 collectives
        self.collective_quant = None
        self.collective_quant_fallback = None
        if self._qcoll_req:
            if str(self._qcoll_req) != "int8":
                # a typo must not silently measure a different config
                raise ValueError(
                    "MXNET_QUANTIZED_COLLECTIVES must be int8 or unset, "
                    "got %r" % (self._qcoll_req,))
            if dp_size <= 1:
                self.collective_quant_fallback = (
                    "needs a data-parallel mesh (dp > 1); a single-chip "
                    "step has no gradient collective to compress")
            elif self._sharded_update:
                self.collective_quant_fallback = (
                    "sharded_update reshapes the grad all-reduce into "
                    "reduce-scatter + all-gather (ZeRO-1); int8 "
                    "compression targets the replicated all-reduce")
            elif any(any(ax is not None
                         for ax in self._param_shardings.get(n, P()))
                     for n in gnames_all):
                self.collective_quant_fallback = (
                    "tensor-sharded parameters reduce over their own "
                    "mesh axes; int8 compression targets "
                    "replicated-parameter dp gradients")
            else:
                self.collective_quant = "int8"
        qcoll = self.collective_quant is not None
        if qcoll:
            from .collectives import shard_map as _shard_map
            # each chip quantizes into [-cap, cap] so the int8 psum of
            # dp_size addends stays within int8 by construction
            _cap = float(max(1, 127 // dp_size))
            _dpn = dp_ax

            def _qcoll_grads(grad_vals, nograd_vals, x, y, key,
                             residuals):
                """Per-chip grads + error-feedback int8 all-reduce.
                Runs under shard_map: x/y are the chip's batch shard,
                `residuals` the chip's (1, *shape) quantization-error
                carry (the kvstore _TwoBitCompressor algorithm — the
                error a round drops is added back the next round, so
                the compression bias averages out instead of
                accumulating). The per-tensor scale is GLOBAL (pmax of
                the local amax): every chip quantizes onto the same
                grid, making the int8 psum a faithful sum."""
                (loss_local, aux_upd), grads = jax.value_and_grad(
                    forward_loss, has_aux=True)(grad_vals, nograd_vals,
                                                x, y, key)
                loss_val = jax.lax.pmean(loss_local, _dpn)
                aux_upd = {i: jax.lax.pmean(v, _dpn)
                           for i, v in aux_upd.items()}
                out_g, out_r = [], []
                for g, r in zip(grads, residuals):
                    gf = g.astype(jnp.float32) + r[0]
                    amax = jax.lax.pmax(jnp.max(jnp.abs(gf)), _dpn)
                    s = jnp.maximum(amax, 1e-30) / _cap
                    q = jnp.clip(jnp.rint(gf / s), -_cap,
                                 _cap).astype(jnp.int8)
                    out_r.append((gf - q.astype(jnp.float32) * s)[None])
                    total = jax.lax.psum(q, _dpn)  # the s8 all-reduce
                    out_g.append((total.astype(jnp.float32) * s
                                  / dp_size).astype(g.dtype))
                return loss_val, aux_upd, tuple(out_g), tuple(out_r)

            _qcoll_sm = _shard_map(
                _qcoll_grads, mesh_obj,
                in_specs=(P(), P(), P(dp_ax), P(dp_ax), P(), P(dp_ax)),
                out_specs=(P(), P(), P(), P(dp_ax)), check_vma=False)

        def forward_loss(grad_vals, nograd_vals, x, y, key):
            """Trace the eager net with tracer-backed parameter buffers.
            Returns (mean_loss, {plist_index: mutated_value}) where the aux
            dict carries BatchNorm running-stat writes."""
            merged = []
            gi = ni = 0
            for has_grad in grad_mask:
                if has_grad:
                    merged.append(grad_vals[gi])
                    gi += 1
                else:
                    merged.append(nograd_vals[ni])
                    ni += 1
            if mixed:
                # bf16 compute, f32 master weights: cast the traced buffers,
                # so grads flow back through the cast in f32
                merged = [v.astype(cdtype)
                          if jnp.issubdtype(v.dtype, jnp.floating) else v
                          for v in merged]
                x = x.astype(cdtype) if jnp.issubdtype(
                    jnp.asarray(x).dtype, jnp.floating) else x
            from .functional import swap_param_buffers
            remat_ctx = _segment_remat(remat_blocks, remat_policy, net) \
                if remat_blocks else contextlib.nullcontext()
            with swap_param_buffers(plist, merged) as injected:
                with autograd._RecordingStateScope(False, True), \
                        _random.trace_key_scope(key), remat_ctx:
                    out = net.forward(NDArray(x))
                    if mixed:
                        # f32 softmax/loss for numerical stability
                        out = NDArray(out._data.astype(jnp.float32))
                    loss = loss_fn(out, NDArray(y))
                loss_val = jnp.mean(loss._data.astype(jnp.float32))
                aux_upd = {i: p._data._data for i, p in enumerate(plist)
                           if p._data._data is not injected[i]}
            return loss_val, aux_upd

        if remat_on and not remat_blocks:
            # no segmentable children: whole-forward checkpoint (weaker —
            # peak is unchanged, but recompute semantics are preserved)
            forward_loss = jax.checkpoint(forward_loss, policy=remat_policy)

        # kept for the donation-free SDC parity probe (probe()): the
        # same forward/loss trace the step differentiates, minus the
        # optimizer update and the buffer donation
        self._forward_loss = forward_loss

        guard = self._guard

        def step(grad_vals, nograd_vals, opt_state, x, y, key, lr, t,
                 poison, residuals=None):
            # independent streams: forward-trace keys (dropout masks etc.)
            # derive from fwd_key; optimizer noise (SGLD) from noise_key —
            # fold_in on the SAME base key would collide with the trace keys
            fwd_key, noise_key = jax.random.split(key)
            if qcoll:
                # grads arrive PRE-REDUCED through the int8 collective
                # (per-chip local grads quantized with error feedback,
                # s8 psum, global-scale dequant); loss and BN stats
                # pmean over dp. The optimizer below sees ordinary
                # replicated f32 grads either way.
                loss_val, aux_upd, grads, new_resid = _qcoll_sm(
                    grad_vals, nograd_vals, x, y, fwd_key, residuals)
            else:
                (loss_val, aux_upd), grads = jax.value_and_grad(
                    forward_loss, has_aux=True)(grad_vals, nograd_vals,
                                                x, y, fwd_key)
            # chaos seam: `poison` is 0.0 on every real step; the chaos
            # harness passes NaN to fault a chosen step's gradients
            # without retracing (utils/chaos.grad_poison)
            grads = [g + poison.astype(g.dtype) for g in grads]
            if guard:
                gnorm = jnp.sqrt(sum(
                    jnp.sum(jnp.square(g.astype(jnp.float32)))
                    for g in grads))
                ok = jnp.isfinite(loss_val) & jnp.isfinite(gnorm)
            new_grad_vals, new_state = [], []
            for i, (w, g, s) in enumerate(zip(grad_vals, grads, opt_state)):
                g = g.astype(w.dtype) * rescale
                if clip is not None:
                    g = jnp.clip(g, -clip, clip)
                k = jax.random.fold_in(noise_key, i) if stochastic_rule \
                    else None
                # ZeRO-1 dataflow (sharded_update): the grad's allreduce
                # becomes reduce-scatter (constrain it dp-sharded — XLA
                # materializes only the 1/N shard per device), the
                # optimizer applies to the local shard of (w, g, state),
                # and only the UPDATED param all-gathers back. The
                # constraints re-place, never re-value: the unsharded
                # step is the bit-exact parity oracle (tests pin it).
                z = zero_specs[i] if szd else None
                w_in = w
                if z is not None:
                    zs = NamedSharding(mesh_obj, z)
                    g = jax.lax.with_sharding_constraint(g, zs)
                    w_in = jax.lax.with_sharding_constraint(w, zs)
                w2, s2 = apply_rule(w_in, g, s, lr * lr_mults[i],
                                    base_wd * wd_mults[i], t, hyper, k)
                if z is not None:
                    w2 = jax.lax.with_sharding_constraint(
                        w2, NamedSharding(mesh_obj, P()))
                    s2 = jax.tree.map(
                        lambda a: jax.lax.with_sharding_constraint(a, zs)
                        if jnp.shape(a) == jnp.shape(w) else a, s2)
                if guard:
                    # bad step -> drop the whole update: params AND
                    # optimizer state stay exactly as they were
                    w2 = jnp.where(ok, w2, w)
                    s2 = jax.tree.map(lambda a, b: jnp.where(ok, a, b),
                                      s2, s)
                new_grad_vals.append(w2)
                new_state.append(s2)
            new_nograd_vals = list(nograd_vals)
            ni = 0
            for i, has_grad in enumerate(grad_mask):
                if not has_grad:
                    if i in aux_upd:
                        upd = aux_upd[i].astype(nograd_vals[ni].dtype)
                        if guard:  # BN running stats also roll back
                            upd = jnp.where(ok, upd, nograd_vals[ni])
                        new_nograd_vals[ni] = upd
                    ni += 1
            out = (loss_val, tuple(new_grad_vals), tuple(new_nograd_vals),
                   tuple(new_state))
            if guard:
                out = out + (ok, gnorm)
            if qcoll:
                out = out + (new_resid,)
            return out

        # the compile watchdog (telemetry/introspect.py) owns the
        # executable cache: every (re)compilation of the fused step is an
        # attributed `compile` event with memory/cost accounting, and
        # MXNET_COMPILE_BUDGET / MXNET_HBM_BUDGET_GB apply. `.lower` and
        # `.__wrapped__` still reach the underlying jit (bench cost
        # probes, bytes reports, export_train_step).
        from ..telemetry import introspect as _introspect
        argnames = ("grad_vals", "nograd_vals", "opt_state", "x", "y",
                    "key", "lr", "t", "poison")
        donate = (0, 1, 2)
        if qcoll:
            # the error-feedback carry is step state: donated through,
            # like the params and optimizer state it rides with
            argnames = argnames + ("residuals",)
            donate = donate + (9,)
        self._step_fn = _introspect.instrument(
            jax.jit(step, donate_argnums=donate), site="train.step",
            phase="train", argnames=argnames, variant="train_step")
        self._names = names
        self._plist = plist
        self._grad_mask = grad_mask
        grad_vals = tuple(p._data._data
                          for p, m in zip(plist, grad_mask) if m)
        nograd_vals = tuple(p._data._data
                            for p, m in zip(plist, grad_mask) if not m)
        opt_state = tuple(init_rule(w, hyper) for w in grad_vals)
        if self._mesh is not None:
            def place(name, v):
                spec = self._param_shardings.get(name, P())
                if v.ndim == 0:  # scalar state (e.g. nadam m_schedule)
                    spec = P()
                return jax.device_put(v, NamedSharding(self._mesh, spec))

            dp = self._data_axis
            dp_size = self._mesh.shape.get(dp, 0) if dp else 0

            def place_state(name, s):
                """Optimizer state placement: with weight-update sharding
                on, a state whose weight is replicated shards its first
                divisible axis over the data axis (ZeRO-1)."""
                spec = self._param_shardings.get(name, P())
                replicated = all(ax is None for ax in spec)  # P() or P(None,)
                if self._shard_opt and dp_size > 1 and replicated \
                        and s.ndim > 0:
                    for axis in range(s.ndim):
                        if s.shape[axis] % dp_size == 0:
                            zspec = P(*([None] * axis + [dp]))
                            return jax.device_put(
                                s, NamedSharding(self._mesh, zspec))
                if s.ndim == 0:
                    spec = P()
                return jax.device_put(s, NamedSharding(self._mesh, spec))

            gnames = gnames_all
            nnames = [n for n, m in zip(self._names, grad_mask) if not m]
            grad_vals = tuple(place(n, v) for n, v in zip(gnames, grad_vals))
            nograd_vals = tuple(place(n, v)
                                for n, v in zip(nnames, nograd_vals))
            opt_state = tuple(
                tuple(place_state(n, s) for s in st)
                for n, st in zip(gnames, opt_state))
        self._grad_vals = grad_vals
        self._nograd_vals = nograd_vals
        self._opt_state = opt_state
        if qcoll:
            # per-chip error-feedback carries, zero at start: one
            # (dp, *shape) f32 array per grad param, dp-sharded so each
            # chip owns exactly its own residual (not checkpointed —
            # a resume restarts the feedback loop from zero, costing
            # one round of dropped error, never correctness)
            self._quant_residuals = tuple(
                jax.device_put(
                    jnp.zeros((dp_size,) + tuple(jnp.shape(w)),
                              jnp.float32),
                    NamedSharding(mesh_obj, P(dp_ax)))
                for w in grad_vals)

    def __call__(self, x, y):
        from .. import profiler as _profiler
        xv = x._data if isinstance(x, NDArray) else jnp.asarray(x)
        yv = y._data if isinstance(y, NDArray) else jnp.asarray(y)
        if self._step_fn is None:
            self._build()
        # first DISPATCH (not first build — load_state_dict also builds)
        # pays XLA compilation and captures the example specs
        first_call = not self._compiled
        if self._mesh is not None:
            from .mesh import shard_batch
            xv = shard_batch(self._mesh, xv, self._data_axis)
            yv = shard_batch(self._mesh, yv, self._data_axis)
        self._t += 1
        if self._lr_schedule is not None:
            lr = self._lr_schedule(self._t)
        elif self._opt.lr_scheduler is not None:
            lr = self._opt.lr_scheduler(self._t)
        else:
            lr = self._opt.lr
        key = _random.next_key()
        from ..utils import chaos as _chaos
        poison = jnp.float32(_chaos.grad_poison(self._t))
        call_args = (self._grad_vals, self._nograd_vals, self._opt_state,
                     xv, yv, key, jnp.float32(lr), jnp.int32(self._t),
                     poison)
        if self.collective_quant:
            call_args = call_args + (self._quant_residuals,)
        if first_call:
            self._example_args = jax.tree.map(
                lambda v: jax.ShapeDtypeStruct(jnp.shape(v),
                                               jnp.asarray(v).dtype),
                call_args)
        # compile vs run split in the profiler table: the first dispatch pays
        # XLA compilation, later ones are cached executions (parity with the
        # reference's symbolic bind-vs-run accounting)
        label = "TrainStep::compile" if first_call else "TrainStep::run"
        with _profiler.scope(label, "trainstep"):
            out = self._step_fn(*call_args)
            if self.collective_quant:
                out, self._quant_residuals = out[:-1], out[-1]
            if self._guard:
                (loss, self._grad_vals, self._nograd_vals, self._opt_state,
                 self.last_step_ok, self.last_grad_norm) = out
            else:
                loss, self._grad_vals, self._nograd_vals, self._opt_state \
                    = out
            if _profiler.profile_sync():
                jax.block_until_ready(loss)
        self._compiled = True
        # register the step's output buffers so mx.nd.waitall() blocks on
        # in-flight optimizer updates (the benchmark timing pattern)
        from .. import engine as _engine
        jax.tree.map(_engine.note, (loss, self._grad_vals,
                                    self._nograd_vals, self._opt_state))
        return loss

    def probe(self, x, y, seed=0):
        """Deterministic, donation-free parity probe (ISSUE 15): compute
        `(loss, global_grad_norm)` for the given batch under a FIXED RNG
        seed against the live parameters — without mutating params,
        optimizer state, the RNG key chain, or the step counter, and
        without donating any buffer. Two calls with the same batch and
        seed return bit-identical floats, and two HOSTS holding
        replicated parameters return bit-identical floats — which is
        what lets the SDC parity probe (parallel/supervisor.py)
        cross-check digests and attribute a divergence to one chip.
        Compiled once (its own non-donating executable, watchdog site
        `train.probe`); reuses the step's forward/loss trace verbatim.
        """
        from ..telemetry import introspect as _introspect
        if self._step_fn is None:
            self._build()
        if self._probe_fn is None:
            fwd = self._forward_loss

            def probe_fn(grad_vals, nograd_vals, x, y, key):
                (loss_val, _aux), grads = jax.value_and_grad(
                    fwd, has_aux=True)(grad_vals, nograd_vals, x, y, key)
                gnorm = jnp.sqrt(sum(
                    jnp.sum(jnp.square(g.astype(jnp.float32)))
                    for g in grads))
                return loss_val, gnorm

            self._probe_fn = _introspect.instrument(
                jax.jit(probe_fn), site="train.probe", phase="train",
                argnames=("grad_vals", "nograd_vals", "x", "y", "key"),
                variant="train_probe")
        xv = x._data if isinstance(x, NDArray) else jnp.asarray(x)
        yv = y._data if isinstance(y, NDArray) else jnp.asarray(y)
        if self._mesh is not None:
            from .mesh import shard_batch
            xv = shard_batch(self._mesh, xv, self._data_axis)
            yv = shard_batch(self._mesh, yv, self._data_axis)
        key = jax.random.PRNGKey(int(seed))
        loss, gnorm = self._probe_fn(self._grad_vals, self._nograd_vals,
                                     xv, yv, key)
        return float(np.asarray(loss)), float(np.asarray(gnorm))

    def memory_analysis(self):
        """XLA memory accounting of the compiled step (requires one prior
        call). `temp_size_in_bytes` is the live-activation footprint — the
        number the MXNET_BACKWARD_DO_MIRROR/remat trade shrinks on TPU
        (reference: memonger's measurement, docs/faq/env_var.md:93). Note
        XLA:CPU CSEs rematerialization away, so the difference shows on
        device backends; `lowered_stablehlo()` shows the program-level
        recompute on any backend."""
        if self._step_fn is None or not hasattr(self, "_example_args"):
            raise RuntimeError("run at least one step first")
        return self._step_fn.lower(*self._example_args).compile() \
            .memory_analysis()

    def lowered_stablehlo(self):
        """Pre-optimization StableHLO of the step (requires one prior
        call) — e.g. for auditing remat recompute + optimization barriers."""
        if self._step_fn is None or not hasattr(self, "_example_args"):
            raise RuntimeError("run at least one step first")
        return self._step_fn.lower(*self._example_args).as_text()

    def _lr_sched_obj(self):
        """The stateful schedule driving this step's lr, if any.
        `_lr_schedule_base` (set by ResilientLoop when it wraps the
        schedule with its rollback LR-scale) takes priority: the wrapper
        lambda has no state, the underlying scheduler does."""
        for cand in (getattr(self, "_lr_schedule_base", None),
                     self._lr_schedule, self._opt.lr_scheduler):
            if cand is not None and hasattr(cand, "state_dict"):
                return cand
        return None

    def state_dict(self, device=False):
        """Full resumable training state (params + optimizer state + step
        counter + RNG key chain + LR-schedule state) for
        utils.recovery.CheckpointManager. Materialized to host arrays —
        the live device buffers get donated by the next step, so handing
        out references would leave the caller with deleted arrays.

        device=True returns the LIVE device arrays instead (shardings
        intact — what sharded checkpointing needs to know which shards
        this host owns). The caller must copy out everything it keeps
        BEFORE the next step runs: CheckpointManager.save() does its
        host copies synchronously, so `mgr.save(t, step.state_dict(
        device=True))` is safe; holding the tree across a step is not.
        """
        if self._step_fn is None:
            self._build()
        # np.array (not np.asarray): on the CPU backend asarray can be a
        # ZERO-COPY view of the XLA buffer, and the next step DONATES
        # that buffer — an async checkpoint writer would then serialize
        # memory the t+1 update already overwrote (a checkpoint labeled
        # step t with step t+1's params breaks step-exact resume)
        live = (tuple(self._grad_vals), tuple(self._nograd_vals),
                tuple(self._opt_state))
        host = live if device else jax.tree.map(lambda v: np.array(v), live)
        out = {"t": np.int64(self._t), "grad_vals": host[0],
               "nograd_vals": host[1], "opt_state": host[2],
               # the global key stream feeds per-step dropout masks / SGLD
               # noise — without it a resume would replay early-step keys
               "rng_key": _random.get_state()}
        sched = self._lr_sched_obj()
        if sched is not None:
            # stateful schedulers (FactorScheduler's decayed base_lr etc.)
            # must not restart from scratch after a relaunch; JSON-encode
            # the tiny state into the array tree
            import json as _json
            out["lr_sched"] = np.frombuffer(
                _json.dumps(sched.state_dict()).encode(), np.uint8).copy()
        return out

    def load_state_dict(self, state):
        if self._step_fn is None:
            self._build()
        for name, tmpl in (("grad_vals", self._grad_vals),
                           ("nograd_vals", self._nograd_vals),
                           ("opt_state", self._opt_state)):
            if len(state[name]) != len(tmpl):
                raise ValueError(
                    "checkpoint %s has %d entries but the model expects %d "
                    "— wrong or since-modified model" %
                    (name, len(state[name]), len(tmpl)))
            # logical-shape gate for elastic resume: a checkpoint written
            # under ANY mesh shape holds the same GLOBAL arrays, so a
            # shape mismatch means a different model, never a different
            # mesh — refuse rather than let device_put fail cryptically
            # (or broadcast silently) mid-restore
            for t, v in zip(jax.tree.leaves(tuple(tmpl)),
                            jax.tree.leaves(tuple(state[name]))):
                if tuple(np.shape(v)) != tuple(jnp.shape(t)):
                    raise ValueError(
                        "checkpoint %s entry has shape %s but the model "
                        "expects %s — wrong model or a lossy resume"
                        % (name, tuple(np.shape(v)), tuple(jnp.shape(t))))
        self._t = int(state["t"])
        if "rng_key" in state:
            _random.set_state(state["rng_key"])
        if "lr_sched" in state:
            sched = self._lr_sched_obj()
            if sched is not None:
                import json as _json
                sched.load_state_dict(_json.loads(
                    bytes(bytearray(np.asarray(state["lr_sched"])
                                    .astype(np.uint8))).decode()))

        def place(tmpl, v):
            # jnp.array (copy), not asarray: a zero-copy alias of the
            # checkpoint's numpy buffer would be DONATED by the next
            # step — XLA would scribble outputs over external memory
            arr = jnp.array(np.asarray(v), dtype=jnp.asarray(tmpl).dtype)
            if self._mesh is not None:
                arr = jax.device_put(arr, tmpl.sharding)
            return arr

        self._grad_vals = tuple(
            place(t, v) for t, v in zip(self._grad_vals,
                                        state["grad_vals"]))
        self._nograd_vals = tuple(
            place(t, v) for t, v in zip(self._nograd_vals,
                                        state["nograd_vals"]))
        self._opt_state = jax.tree.map(place, tuple(self._opt_state),
                                       tuple(state["opt_state"]))

    def sync_params(self):
        """Write device buffers back into the Parameters (for eval/save)."""
        gi = ni = 0
        for p, m in zip(self._plist, self._grad_mask):
            if m:
                p._data._data = self._grad_vals[gi]
                gi += 1
            else:
                p._data._data = self._nograd_vals[ni]
                ni += 1
            p._data._version += 1
