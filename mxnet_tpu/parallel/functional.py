"""Functionalize a Gluon net into a pure ``fn(param_values, x)`` suitable
for jax.jit / pjit over a Mesh.

This is the seam between the imperative Gluon API (mutable Parameters, the
reference's `gluon/block.py` model) and XLA's functional compilation model:
parameter buffers are temporarily swapped for tracers while the eager net
is traced, exactly like TrainStep's fused step (parallel/trainer.py).
"""
from __future__ import annotations

import contextlib

from ..ndarray import NDArray
from .. import autograd
from .. import random as _random


@contextlib.contextmanager
def swap_param_buffers(plist, values):
    """Temporarily replace each Parameter's device buffer with ``values``
    (typically tracers during a jit trace); restore the originals on exit.

    Yields the injected list so callers can detect in-trace writes — a
    parameter whose ``_data._data`` no longer ``is`` its injected value was
    set_data()-ed during the trace (BatchNorm running stats) and must be
    threaded out as an extra output by the caller.
    """
    saved = [(p._data._data, p._data._entry) for p in plist]
    try:
        injected = list(values)
        for p, v in zip(plist, injected):
            p._data._data = v
            p._data._entry = None
        yield injected
    finally:
        for p, (d, e) in zip(plist, saved):
            p._data._data = d
            p._data._entry = e


def functionalize(net, train_mode=False):
    """Return ``(apply_fn, names, values)``.

    ``apply_fn(param_values, x, key=None)`` is pure and jittable: it runs
    ``net.forward`` with ``param_values`` (a tuple aligned with ``names``)
    injected in place of the stored parameter buffers and returns the raw
    ``jax.Array`` output. ``values`` is the current parameter tuple, ready
    to pass as the first argument (and to shard with jax.device_put).

    ``train_mode=True`` requires a ``key`` argument per call (stochastic
    layers like Dropout draw from it; without it a concrete key would be
    baked into the jitted program and every call would reuse one mask).
    Note: in train mode, BatchNorm running-stat writes are DISCARDED by
    apply_fn — use TrainStep (parallel/trainer.py), which threads them out
    as aux outputs, for actual training loops.

    The net must be fully initialized (run one dummy forward first if it
    uses deferred shape inference).
    """
    params = net.collect_params()
    names = list(params.keys())
    plist = [params[n] for n in names]
    for n, p in zip(names, plist):
        if p._data is None:
            raise RuntimeError(
                "functionalize: parameter %s is uninitialized; call "
                "net.initialize() and one dummy forward first" % n)
    values = tuple(p._data._data for p in plist)

    def apply_fn(param_values, x, key=None):
        if train_mode and key is None:
            raise ValueError(
                "functionalize(train_mode=True): pass a PRNG key per call, "
                "or stochastic layers would bake one mask into the program")
        key_scope = (_random.trace_key_scope(key) if key is not None
                     else contextlib.nullcontext())
        with swap_param_buffers(plist, param_values):
            with autograd._RecordingStateScope(False, train_mode), key_scope:
                out = net.forward(NDArray(x))
            if isinstance(out, (list, tuple)):
                return tuple(o._data for o in out)
            return out._data

    return apply_fn, names, values
