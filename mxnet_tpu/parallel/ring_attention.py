"""Ring attention: sequence/context parallelism over the mesh.

Capability upgrade over the reference (SURVEY §5.7: absent there — it only
had bucketing + recompute). Long-context training shards the sequence axis
across devices; each device holds a Q block and passes K/V blocks around the
ring (ppermute over ICI) while accumulating attention with a numerically
stable online softmax (flash-attention style running max/denominator).

Communication overlaps compute: block k's K/V transfer is issued while
block k-1's scores are on the MXU.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def _block_attn(q, k, v, m_prev, l_prev, o_prev, scale, mask=None):
    """One online-softmax accumulation step.

    q: [B, H, Tq, D]; k/v: [B, H, Tk, D]; running (m, l, o).
    """
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k,
                   preferred_element_type=jnp.float32) * scale
    if mask is not None:
        s = jnp.where(mask, s, -jnp.inf)
    m_cur = jnp.max(s, axis=-1)
    m_new = jnp.maximum(m_prev, m_cur)
    # guard fully-masked rows (m == -inf)
    m_safe = jnp.where(jnp.isfinite(m_new), m_new, 0.0)
    p = jnp.exp(s - m_safe[..., None])
    p = jnp.where(jnp.isfinite(s), p, 0.0)
    alpha = jnp.where(jnp.isfinite(m_prev), jnp.exp(m_prev - m_safe), 0.0)
    l_new = alpha * l_prev + jnp.sum(p, axis=-1)
    o_new = alpha[..., None] * o_prev + jnp.einsum(
        "bhqk,bhkd->bhqd", p, v.astype(p.dtype),
        preferred_element_type=jnp.float32)
    return m_new, l_new, o_new


def _ring_body(axis_name, causal, scale, q, k0, v0, q_index):
    """Scan over ring steps; each step attends to the current K/V block then
    rotates it to the neighbour."""
    from .collectives import axis_size
    n = axis_size(axis_name)
    B, H, T, D = q.shape
    m0 = jnp.full((B, H, T), -jnp.inf, dtype=jnp.float32)
    l0 = jnp.zeros((B, H, T), dtype=jnp.float32)
    o0 = jnp.zeros((B, H, T, D), dtype=jnp.float32)
    perm = [(i, (i + 1) % n) for i in range(n)]

    def step(carry, r):
        k, v, m, l, o = carry
        kv_index = (q_index - r) % n  # which shard this K/V block came from
        if causal:
            # block-level causality: attend fully if kv block strictly
            # earlier, diagonal gets a triangular mask, later blocks skipped
            tq = jnp.arange(T)[:, None] + q_index * T
            tk = jnp.arange(T)[None, :] + kv_index * T
            mask = (tk <= tq)[None, None]
        else:
            mask = None
        m2, l2, o2 = _block_attn(q, k, v, m, l, o, scale, mask)
        k2 = lax.ppermute(k, axis_name, perm)
        v2 = lax.ppermute(v, axis_name, perm)
        return (k2, v2, m2, l2, o2), None

    (kf, vf, m, l, o), _ = lax.scan(step, (k0, v0, m0, l0, o0),
                                    jnp.arange(n))
    out = o / jnp.maximum(l[..., None], 1e-20)
    return out.astype(q.dtype)


def ring_attention(q, k, v, axis_name="sp", causal=False, scale=None):
    """Per-shard ring attention; call inside shard_map over `axis_name`.

    q/k/v: [B, H, T_local, D] — the local sequence shard.
    """
    if scale is None:
        scale = 1.0 / (q.shape[-1] ** 0.5)
    q_index = lax.axis_index(axis_name)
    return _ring_body(axis_name, causal, scale, q, k, v, q_index)


def ring_attention_sharded(mesh, q, k, v, axis_name="sp", causal=False,
                           scale=None):
    """Convenience wrapper: shard the sequence axis over `axis_name` of
    `mesh` and run ring attention. q/k/v: [B, H, T, D] global arrays."""
    from .collectives import shard_map

    spec = P(None, None, axis_name, None)

    fn = shard_map(
        functools.partial(ring_attention, axis_name=axis_name, causal=causal,
                          scale=scale),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
        check_vma=False)
    return fn(q, k, v)


def attention_reference(q, k, v, causal=False, scale=None):
    """Dense reference implementation (for tests)."""
    if scale is None:
        scale = 1.0 / (q.shape[-1] ** 0.5)
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k).astype(jnp.float32) * scale
    if causal:
        T = q.shape[2]
        mask = jnp.tril(jnp.ones((T, T), dtype=bool))
        s = jnp.where(mask[None, None], s, -jnp.inf)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", p, v.astype(p.dtype)).astype(q.dtype)
