"""Pipeline parallelism: GPipe-style microbatch pipelining over a 'pp' mesh
axis.

Capability upgrade over the reference (SURVEY §2.3: absent there — it only
had manual inter-layer placement via group2ctx, graph_executor.cc:314). The
TPU-native formulation: stage parameters are sharded over 'pp' (each rank
holds one stage), microbatches circulate around the ring with ppermute, and
the whole schedule is a lax.scan — so forward AND backward pipeline through
XLA's AD of the scan, no hand-written schedule.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P


def _gpipe_local(stage_fn, params_local, x_mb, axis_name):
    """Runs on one pp rank inside shard_map.

    params_local: this rank's stage params, leading stage axis of size 1.
    x_mb: (M, mb, ...) microbatches (replicated across pp).
    Returns (M, mb, ...) outputs of the final stage (replicated).
    """
    params = jax.tree_util.tree_map(lambda a: a[0], params_local)
    from .collectives import axis_size
    n = axis_size(axis_name)
    idx = lax.axis_index(axis_name)
    M = x_mb.shape[0]
    T = M + n - 1  # pipeline ticks: fill + drain
    perm = [(i, (i + 1) % n) for i in range(n)]
    zero = jnp.zeros_like(x_mb[0])

    def tick(state, t):
        # rank 0 ingests microbatch t (while t < M), others take the
        # activation handed over from the left neighbour
        inp = jnp.where(t < M, x_mb[jnp.minimum(t, M - 1)], zero)
        cur = jnp.where(idx == 0, inp, state)
        out = stage_fn(params, cur)
        nxt = lax.ppermute(out, axis_name, perm)
        # the final stage emits valid output from tick n-1 onward
        emit = jnp.where((idx == n - 1) & (t >= n - 1), out,
                         jnp.zeros_like(out))
        return nxt, emit

    _, emits = lax.scan(tick, zero, jnp.arange(T))
    outs = lax.dynamic_slice_in_dim(emits, n - 1, M, axis=0)
    # broadcast final-stage outputs to every rank (zeros elsewhere -> psum)
    return lax.psum(outs, axis_name)


def gpipe_apply(stage_fn, stacked_params, x, n_microbatches, mesh,
                axis_name="pp", extra_specs=None):
    """Apply a pipeline of identical stages to x.

    stage_fn(params, x_mb) -> y_mb applies ONE stage (same shape in/out).
    stacked_params: pytree whose leaves have a leading stage axis of size
      mesh.shape[axis_name]; sharded over 'pp' inside.
    x: (B, ...) batch; split into n_microbatches along axis 0.
    Returns (B, ...) outputs of the last stage.
    """
    from .collectives import shard_map

    B = x.shape[0]
    assert B % n_microbatches == 0, "batch must divide into microbatches"
    x_mb = x.reshape((n_microbatches, B // n_microbatches) + x.shape[1:])

    param_specs = jax.tree_util.tree_map(
        lambda a: P(axis_name), stacked_params)
    fn = shard_map(
        functools.partial(_gpipe_local, stage_fn, axis_name=axis_name),
        mesh=mesh,
        in_specs=(param_specs, P()),
        out_specs=P(),
        check_vma=False)
    out_mb = fn(stacked_params, x_mb)
    return out_mb.reshape((B,) + out_mb.shape[2:])
