"""Device mesh construction + sharding helpers.

The mesh replaces the reference's context lists (`ctx=[mx.gpu(i) ...]`) and
hostfile topology (`tools/launch.py`): axes are named for their parallelism
role — 'dp' (data), 'tp' (tensor), 'pp' (pipeline), 'sp' (sequence/context),
'ep' (expert). Shardings ride ICI within a slice; DCN spans multi-slice axes
(leading axes by convention).
"""
from __future__ import annotations

import numpy as np
import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def build_mesh(axes, devices=None):
    """Build a named mesh, e.g. build_mesh({'dp': 4, 'tp': 2}).

    Axis sizes of -1 absorb the remaining devices (like reshape's -1).
    """
    devices = devices if devices is not None else jax.devices()
    names = list(axes.keys())
    sizes = list(axes.values())
    n = len(devices)
    if -1 in sizes:
        known = int(np.prod([s for s in sizes if s != -1]))
        sizes[sizes.index(-1)] = n // known
    total = int(np.prod(sizes))
    assert total <= n, "mesh %s needs %d devices, have %d" % (axes, total, n)
    dev_array = np.asarray(devices[:total]).reshape(sizes)
    return Mesh(dev_array, names)


def data_parallel_mesh(devices=None):
    return build_mesh({"dp": -1}, devices)


def replica_devices(replica, tp, devices=None):
    """Device window for serving replica `replica` at tensor-parallel
    degree `tp`: the contiguous slice [replica*tp, (replica+1)*tp) —
    contiguity keeps each replica's tp collectives on neighboring chips
    (ICI, not DCN). The window wraps modulo the device count, so with
    fewer than replicas*tp devices, replicas SHARE windows
    (oversubscription — fine for emulated/CPU hosts; real deployments
    should size replicas*tp <= devices). A mesh can never hold the same
    device twice, so when the host has fewer than tp devices the full
    (short) device list is returned and the Engine's placement fallback
    reports the honest reason instead of building a broken mesh."""
    devices = devices if devices is not None else jax.devices()
    n = len(devices)
    if n < tp:
        return list(devices)
    start = (replica * tp) % n
    return [devices[(start + i) % n] for i in range(tp)]


def mesh_sharding(mesh, *spec):
    """NamedSharding shorthand: mesh_sharding(mesh, 'dp', None)."""
    return NamedSharding(mesh, P(*spec))


def shard_batch(mesh, array, axis_name="dp", batch_dim=0):
    """Place a host batch sharded along the data axis of the mesh."""
    spec = [None] * array.ndim
    spec[batch_dim] = axis_name
    return jax.device_put(array, NamedSharding(mesh, P(*spec)))


def replicate(mesh, array):
    return jax.device_put(array, NamedSharding(mesh, P()))
