"""Parallelism: mesh, sharded training, collectives, sequence parallelism.

This package is the TPU-native replacement for the reference's distributed
machinery (SURVEY §2.3/§5.8):
  reference                         ->  here
  DataParallelExecutorGroup         ->  mesh data-axis sharding (GSPMD)
  KVStore device/nccl reduce        ->  lax.psum over ICI inside the step
  ps-lite dist_sync push/pull       ->  multi-host mesh collectives over DCN
  group2ctx model parallelism       ->  tensor-parallel shardings (upgrade)
  (absent) sequence parallelism     ->  ring attention (capability upgrade)
"""
from .mesh import build_mesh, data_parallel_mesh, mesh_sharding
from .trainer import TrainStep
from .resilient import (ResilientLoop, PreemptionWatcher, BadStepError,
                        Preempted, EXIT_PREEMPTED, StragglerMonitor,
                        Reconfigured, EXIT_RECONFIGURE)
from .supervisor import (TrainSupervisor, CordonRoster, SDCProbe,
                         CheckpointAuditor, CordonedHostError,
                         effective_hosts)
from .ring_attention import ring_attention, ring_attention_sharded
from . import collectives
from .pipeline import gpipe_apply
from .functional import functionalize, swap_param_buffers
from .embedding import row_sharded_spec, shard_embedding_params
