"""Sharded embedding flow over the device mesh.

Parity: the reference shards large (row-sparse) embeddings across parameter
servers and pulls only the needed rows per step
(`src/kvstore/kvstore_dist.h:437-476`, `python/mxnet/kvstore.py:307`,
`example/sparse/*`).

TPU-native redesign: the table is a mesh-sharded parameter — rows split
over an axis via `PartitionSpec(axis, None)` — and the lookup is a plain
gather inside the jitted step. GSPMD partitions the gather (each shard
serves its rows, a psum combines) and keeps the backward scatter-add
sharded, so only touched-row gradients move over ICI: the row_sparse_pull
capability without a parameter server. Use `row_sharded_spec()` in
`TrainStep(param_shardings=...)` or any pjit sharding map.
"""
from __future__ import annotations

from jax.sharding import PartitionSpec as P


def row_sharded_spec(axis="tp"):
    """PartitionSpec sharding an embedding table's vocabulary rows over a
    mesh axis (the PS key-sharding analog)."""
    return P(axis, None)


def shard_embedding_params(net, mesh_axis="tp", pattern="embedding"):
    """Build a TrainStep `param_shardings` dict that row-shards every
    embedding weight of `net` (matched by name) over `mesh_axis`, e.g.:

        shardings = shard_embedding_params(net, "tp")
        step = TrainStep(net, loss, mesh=mesh, param_shardings=shardings)
    """
    out = {}
    for name, p in net.collect_params().items():
        if pattern in name and name.endswith("weight") and \
                p.shape is not None and len(p.shape) == 2:
            out[name] = row_sharded_spec(mesh_axis)
    return out
