"""Optimizers.

Parity: reference `python/mxnet/optimizer.py` (17 classes, optimizer.py:35-1453)
+ the fused C++ update kernels (`src/operator/optimizer_op-inl.h`).

TPU-native redesign: every optimizer's math is a *pure* update function
(weight, grad, states) -> (new_weight, new_states) in jnp — so the same rule
runs eagerly (Updater path), inside the Gluon fused jit train step, and
sharded under pjit (the reference's "server-side optimizer" capability maps
to running these rules on sharded state inside the step function). Sparse
(row_sparse) grads apply lazily to touched rows via scatter, mirroring the
reference's lazy_update path.
"""
from __future__ import annotations

import math
import pickle
import logging

import numpy as np
import jax.numpy as jnp

from .ndarray import NDArray
from .ndarray.sparse import RowSparseNDArray
from .registry import get_register_func, get_create_func
from . import optimizer_rules as _rules


class Optimizer:
    opt_registry = {}

    def __init__(self, rescale_grad=1.0, param_idx2name=None, wd=0.0,
                 clip_gradient=None, learning_rate=0.01, lr_scheduler=None,
                 sym=None, begin_num_update=0, multi_precision=False,
                 param_dict=None):
        self.rescale_grad = rescale_grad
        self.lr = learning_rate
        self.lr_scheduler = lr_scheduler
        if lr_scheduler is not None:
            self.lr_scheduler.base_lr = learning_rate
        self.wd = wd
        self.lr_mult = {}
        self.wd_mult = {}
        self.begin_num_update = begin_num_update
        self.num_update = begin_num_update
        self._index_update_count = {}
        self.clip_gradient = clip_gradient
        self.multi_precision = multi_precision
        if param_idx2name is None:
            param_idx2name = {}
        self.idx2name = param_idx2name.copy()
        self.sym_info = (sym.attr_dict(), sym.list_arguments()) if sym is not None \
            else ({}, [])
        self.param_dict = param_dict if param_dict else {}
        self.set_lr_mult({})
        self.set_wd_mult({})

    # -- registry ----------------------------------------------------------
    @staticmethod
    def register(klass):
        name = klass.__name__.lower()
        Optimizer.opt_registry[name] = klass
        return klass

    @staticmethod
    def create_optimizer(name, **kwargs):
        if name.lower() in Optimizer.opt_registry:
            return Optimizer.opt_registry[name.lower()](**kwargs)
        raise ValueError("Cannot find optimizer %s" % name)

    # -- state -------------------------------------------------------------
    def create_state(self, index, weight):
        return None

    def create_state_multi_precision(self, index, weight):
        if self.multi_precision and weight.dtype == np.float16:
            master = NDArray(weight._data.astype(jnp.float32), ctx=weight.context)
            return (master, self.create_state(index, master))
        return self.create_state(index, weight)

    def update(self, index, weight, grad, state):
        raise NotImplementedError

    def update_multi_precision(self, index, weight, grad, state):
        if self.multi_precision and weight.dtype == np.float16:
            master, st = state
            g32 = NDArray(grad._data.astype(jnp.float32)) \
                if isinstance(grad, NDArray) else grad
            self.update(index, master, g32, st)
            weight._data = master._data.astype(jnp.float16)
            weight._version += 1
        else:
            self.update(index, weight, grad, state)

    # -- lr / wd bookkeeping (parity: optimizer.py Optimizer base) ---------
    @property
    def learning_rate(self):
        """Current lr, scheduler-aware (parity: Optimizer.learning_rate)."""
        if self.lr_scheduler is not None:
            return self.lr_scheduler(self.num_update)
        return self.lr

    def set_learning_rate(self, lr):
        if self.lr_scheduler is not None:
            raise UserWarning("LRScheduler of the optimizer has already been "
                              "defined.")
        self.lr = lr

    def set_lr_scale(self, args_lrscale):  # pylint: disable=unused-argument
        """Deprecated reference API (parity: Optimizer.set_lr_scale)."""
        raise DeprecationWarning("use set_lr_mult instead")

    def set_lr_mult(self, args_lr_mult):
        self.lr_mult = {}
        if self.sym_info[0]:
            attr, arg_names = self.sym_info
            for name in arg_names:
                if name in attr and "__lr_mult__" in attr[name]:
                    self.lr_mult[name] = float(attr[name]["__lr_mult__"])
        self.lr_mult.update(args_lr_mult)

    def set_wd_mult(self, args_wd_mult):
        self.wd_mult = {}
        for n in self.idx2name.values():
            if not (n.endswith("_weight") or n.endswith("_gamma")):
                self.wd_mult[n] = 0.0
        if self.sym_info[0]:
            attr, arg_names = self.sym_info
            for name in arg_names:
                if name in attr and "__wd_mult__" in attr[name]:
                    self.wd_mult[name] = float(attr[name]["__wd_mult__"])
        self.wd_mult.update(args_wd_mult)

    def _update_count(self, index):
        if index not in self._index_update_count:
            self._index_update_count[index] = self.begin_num_update
        self._index_update_count[index] += 1
        self.num_update = max(self._index_update_count[index], self.num_update)

    def _get_lr(self, index):
        if self.lr_scheduler is not None:
            lr = self.lr_scheduler(self.num_update)
        else:
            lr = self.lr
        if index in self.param_dict:
            lr *= self.param_dict[index].lr_mult
        elif index in self.lr_mult:
            lr *= self.lr_mult[index]
        elif index in self.idx2name:
            lr *= self.lr_mult.get(self.idx2name[index], 1.0)
        return lr

    def _get_wd(self, index):
        wd = self.wd
        if index in self.param_dict:
            wd *= self.param_dict[index].wd_mult
        elif index in self.wd_mult:
            wd *= self.wd_mult[index]
        elif index in self.idx2name:
            wd *= self.wd_mult.get(self.idx2name[index], 1.0)
        return wd

    # -- helpers ------------------------------------------------------------
    def _preprocess_grad(self, grad):
        g = grad._data * self.rescale_grad
        if self.clip_gradient is not None:
            g = jnp.clip(g, -self.clip_gradient, self.clip_gradient)
        return g

    def _sparse_rows(self, grad):
        """Return (rows, grad_rows) for row_sparse grads, else None."""
        if isinstance(grad, RowSparseNDArray):
            g = grad._values * self.rescale_grad
            if self.clip_gradient is not None:
                g = jnp.clip(g, -self.clip_gradient, self.clip_gradient)
            return grad._indices.astype(jnp.int32), g
        return None

    # rule delegation: the dense math for every optimizer lives ONCE, as a
    # pure function in optimizer_rules.py, shared with the fused TrainStep
    rule_name = None  # subclasses set this to their optimizer_rules key

    def rule_hyper(self):
        """Static hyper-parameter dict passed to the pure rule."""
        return {}

    def _dense_update(self, index, weight, grad, states, t=None, key=None):
        """Apply this optimizer's pure rule to a dense gradient.

        `states` is the tuple of NDArray state buffers in the rule's state
        order; they are updated in place (buffer rebinding)."""
        _, apply_rule = _rules.get(self.rule_name)
        lr, wd = self._get_lr(index), self._get_wd(index)
        if t is None:
            t = self._index_update_count[index]
        if isinstance(grad, RowSparseNDArray):
            g = grad.todense()._data * self.rescale_grad
            if self.clip_gradient is not None:
                g = jnp.clip(g, -self.clip_gradient, self.clip_gradient)
        else:
            g = self._preprocess_grad(grad)
        vals = tuple(s._data for s in states)
        new_w, new_vals = apply_rule(weight._data, g, vals, lr, wd, t,
                                     self.rule_hyper(), key)
        for s, v in zip(states, new_vals):
            s._data = v
        _assign(weight, new_w)


register = Optimizer.register
create = Optimizer.create_optimizer


def _assign(weight, new):
    weight._data = new.astype(weight._data.dtype)
    weight._version += 1


@register
class SGD(Optimizer):
    """SGD with momentum, multi-precision, and lazy sparse updates
    (parity: optimizer.py:483 + optimizer_op-inl.h sgd_update/sgd_mom_update)."""

    rule_name = "sgd"

    def __init__(self, momentum=0.0, lazy_update=True, **kwargs):
        super().__init__(**kwargs)
        self.momentum = momentum
        self.lazy_update = lazy_update

    def rule_hyper(self):
        return {"momentum": self.momentum}

    def create_state(self, index, weight):
        if self.momentum == 0.0:
            return None
        return NDArray(jnp.zeros(weight.shape, dtype=weight._data.dtype),
                       ctx=weight.context)

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr, wd = self._get_lr(index), self._get_wd(index)
        sparse = self._sparse_rows(grad)
        if sparse is not None and self.lazy_update:
            rows, g = sparse
            w_rows = weight._data[rows]
            g = g + wd * w_rows
            if state is not None:
                m_rows = state._data[rows]
                m_rows = self.momentum * m_rows - lr * g
                state._data = state._data.at[rows].set(m_rows)
                _assign(weight, weight._data.at[rows].add(m_rows))
            else:
                _assign(weight, weight._data.at[rows].add(-lr * g))
            return
        self._dense_update(index, weight, grad,
                           () if state is None else (state,))


@register
class Signum(Optimizer):
    """Parity: optimizer.py Signum (signSGD + momentum variant)."""

    rule_name = "signum"

    def __init__(self, learning_rate=0.01, momentum=0.9, wd_lh=0.0, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.momentum = momentum
        self.wd_lh = wd_lh

    def rule_hyper(self):
        return {"momentum": self.momentum, "wd_lh": self.wd_lh}

    def create_state(self, index, weight):
        if self.momentum == 0.0:
            return None
        return NDArray(jnp.zeros(weight.shape, dtype=weight._data.dtype))

    def update(self, index, weight, grad, state):
        self._update_count(index)
        self._dense_update(index, weight, grad,
                           () if state is None else (state,))


@register
class FTML(Optimizer):
    """Parity: optimizer.py FTML (Follow The Moving Leader)."""

    rule_name = "ftml"

    def __init__(self, beta1=0.6, beta2=0.999, epsilon=1e-8, **kwargs):
        super().__init__(**kwargs)
        self.beta1, self.beta2, self.epsilon = beta1, beta2, epsilon

    def rule_hyper(self):
        return {"beta1": self.beta1, "beta2": self.beta2,
                "epsilon": self.epsilon}

    def create_state(self, index, weight):
        z = jnp.zeros(weight.shape, dtype=weight._data.dtype)
        return (NDArray(z), NDArray(z), NDArray(z))  # d, v, z

    def update(self, index, weight, grad, state):
        self._update_count(index)
        self._dense_update(index, weight, grad, state)


@register
class LBSGD(Optimizer):
    """Large-batch SGD w/ LARS-style layerwise scaling (parity: optimizer.py
    LBSGD; warmup strategies simplified to 'linear')."""

    def __init__(self, momentum=0.0, multi_precision=False,
                 warmup_strategy="linear", warmup_epochs=5, batch_scale=1,
                 updates_per_epoch=32, begin_epoch=0, num_epochs=60, **kwargs):
        super().__init__(multi_precision=multi_precision, **kwargs)
        self.momentum = momentum
        self.warmup_epochs = warmup_epochs
        self.batch_scale = batch_scale
        self.updates_per_epoch = updates_per_epoch

    rule_name = "lbsgd"

    def rule_hyper(self):
        return {"momentum": self.momentum,
                "warmup_epochs": self.warmup_epochs,
                "updates_per_epoch": self.updates_per_epoch}

    def create_state(self, index, weight):
        return NDArray(jnp.zeros(weight.shape, dtype=weight._data.dtype))

    def update(self, index, weight, grad, state):
        self._update_count(index)
        # warmup is driven by the global update count (reference semantics)
        self._dense_update(index, weight, grad, (state,), t=self.num_update)


@register
class DCASGD(Optimizer):
    """Delay-compensated async SGD (parity: optimizer.py DCASGD)."""

    rule_name = "dcasgd"

    def __init__(self, momentum=0.0, lamda=0.04, **kwargs):
        super().__init__(**kwargs)
        self.momentum = momentum
        self.lamda = lamda

    def rule_hyper(self):
        return {"momentum": self.momentum, "lamda": self.lamda}

    def create_state(self, index, weight):
        mom = None if self.momentum == 0.0 else \
            NDArray(jnp.zeros(weight.shape, dtype=weight._data.dtype))
        prev = NDArray(weight._data + 0)
        return (mom, prev)

    def update(self, index, weight, grad, state):
        self._update_count(index)
        mom, prev = state
        states = (prev,) if mom is None else (mom, prev)
        self._dense_update(index, weight, grad, states)


@register
class NAG(Optimizer):
    """Nesterov accelerated SGD (parity: optimizer.py NAG)."""

    rule_name = "nag"

    def __init__(self, momentum=0.0, **kwargs):
        super().__init__(**kwargs)
        self.momentum = momentum

    def rule_hyper(self):
        return {"momentum": self.momentum}

    def create_state(self, index, weight):
        if self.momentum == 0.0:
            return None
        return NDArray(jnp.zeros(weight.shape, dtype=weight._data.dtype))

    def update(self, index, weight, grad, state):
        self._update_count(index)
        self._dense_update(index, weight, grad,
                           () if state is None else (state,))


@register
class SGLD(Optimizer):
    """Stochastic Gradient Langevin Dynamics (parity: optimizer.py SGLD)."""

    rule_name = "sgld"

    def update(self, index, weight, grad, state):
        self._update_count(index)
        from . import random as _rng
        self._dense_update(index, weight, grad, (), key=_rng.next_key())


@register
class ccSGD(SGD):
    """Parity: optimizer.py ccSGD — alias of SGD kept for back-compat."""


@register
class Adam(Optimizer):
    """Parity: optimizer.py Adam + adam_update kernels; lazy sparse update."""

    rule_name = "adam"

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, lazy_update=True, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1, self.beta2, self.epsilon = beta1, beta2, epsilon
        self.lazy_update = lazy_update

    def rule_hyper(self):
        return {"beta1": self.beta1, "beta2": self.beta2,
                "epsilon": self.epsilon}

    def create_state(self, index, weight):
        z = jnp.zeros(weight.shape, dtype=weight._data.dtype)
        return (NDArray(z), NDArray(z))  # mean, var

    def update(self, index, weight, grad, state):
        self._update_count(index)
        sparse = self._sparse_rows(grad)
        if sparse is not None and self.lazy_update:
            lr, wd = self._get_lr(index), self._get_wd(index)
            t = self._index_update_count[index]
            lr_t = lr * math.sqrt(1.0 - self.beta2 ** t) / \
                (1.0 - self.beta1 ** t)
            mean, var = state
            rows, g = sparse
            g = g + wd * weight._data[rows]
            m_r = self.beta1 * mean._data[rows] + (1 - self.beta1) * g
            v_r = self.beta2 * var._data[rows] + (1 - self.beta2) * jnp.square(g)
            mean._data = mean._data.at[rows].set(m_r)
            var._data = var._data.at[rows].set(v_r)
            upd = lr_t * m_r / (jnp.sqrt(v_r) + self.epsilon)
            _assign(weight, weight._data.at[rows].add(-upd))
            return
        self._dense_update(index, weight, grad, state)


@register
class AdaGrad(Optimizer):
    rule_name = "adagrad"

    def __init__(self, eps=1e-7, **kwargs):
        super().__init__(**kwargs)
        self.float_stable_eps = eps

    def rule_hyper(self):
        return {"eps": self.float_stable_eps}

    def create_state(self, index, weight):
        return NDArray(jnp.zeros(weight.shape, dtype=weight._data.dtype))

    def update(self, index, weight, grad, state):
        self._update_count(index)
        self._dense_update(index, weight, grad, (state,))


@register
class RMSProp(Optimizer):
    """Parity: optimizer.py RMSProp (centered=False Tieleman, True Graves)."""

    def __init__(self, learning_rate=0.001, gamma1=0.9, gamma2=0.9,
                 epsilon=1e-8, centered=False, clip_weights=None, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.gamma1, self.gamma2 = gamma1, gamma2
        self.centered = centered
        self.epsilon = epsilon
        self.clip_weights = clip_weights

    rule_name = "rmsprop"

    def rule_hyper(self):
        return {"gamma1": self.gamma1, "gamma2": self.gamma2,
                "epsilon": self.epsilon, "centered": self.centered,
                "clip_weights": self.clip_weights}

    def create_state(self, index, weight):
        z = jnp.zeros(weight.shape, dtype=weight._data.dtype)
        if self.centered:
            return (NDArray(z), NDArray(z), NDArray(z))  # n, g, delta
        return (NDArray(z),)

    def update(self, index, weight, grad, state):
        self._update_count(index)
        self._dense_update(index, weight, grad, state)


@register
class AdaDelta(Optimizer):
    rule_name = "adadelta"

    def __init__(self, rho=0.90, epsilon=1e-5, **kwargs):
        super().__init__(**kwargs)
        self.rho, self.epsilon = rho, epsilon

    def rule_hyper(self):
        return {"rho": self.rho, "epsilon": self.epsilon}

    def create_state(self, index, weight):
        z = jnp.zeros(weight.shape, dtype=weight._data.dtype)
        return (NDArray(z), NDArray(z))  # acc_g, acc_delta

    def update(self, index, weight, grad, state):
        self._update_count(index)
        self._dense_update(index, weight, grad, state)


@register
class Ftrl(Optimizer):
    rule_name = "ftrl"

    def __init__(self, lamda1=0.01, learning_rate=0.1, beta=1, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.lamda1 = lamda1
        self.beta = beta

    def rule_hyper(self):
        return {"lamda1": self.lamda1, "beta": self.beta}

    def create_state(self, index, weight):
        z = jnp.zeros(weight.shape, dtype=weight._data.dtype)
        return (NDArray(z), NDArray(z))  # z, n

    def update(self, index, weight, grad, state):
        self._update_count(index)
        self._dense_update(index, weight, grad, state)


@register
class Adamax(Optimizer):
    rule_name = "adamax"

    def __init__(self, learning_rate=0.002, beta1=0.9, beta2=0.999, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1, self.beta2 = beta1, beta2

    def rule_hyper(self):
        return {"beta1": self.beta1, "beta2": self.beta2}

    def create_state(self, index, weight):
        z = jnp.zeros(weight.shape, dtype=weight._data.dtype)
        return (NDArray(z), NDArray(z))

    def update(self, index, weight, grad, state):
        self._update_count(index)
        self._dense_update(index, weight, grad, state)


@register
class Nadam(Optimizer):
    """Nadam. Unlike the reference (which keeps one Python-float m_schedule
    shared across ALL parameters — a cross-parameter leak), m_schedule is
    per-parameter state, the mathematically intended form."""

    rule_name = "nadam"

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, schedule_decay=0.004, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1, self.beta2, self.epsilon = beta1, beta2, epsilon
        self.schedule_decay = schedule_decay

    def rule_hyper(self):
        return {"beta1": self.beta1, "beta2": self.beta2,
                "epsilon": self.epsilon,
                "schedule_decay": self.schedule_decay}

    def create_state(self, index, weight):
        z = jnp.zeros(weight.shape, dtype=weight._data.dtype)
        return (NDArray(z), NDArray(z),
                NDArray(jnp.ones((), dtype=weight._data.dtype)))

    def update(self, index, weight, grad, state):
        self._update_count(index)
        self._dense_update(index, weight, grad, state)


@register
class Test(Optimizer):
    """Parity: optimizer.py Test — trivial optimizer used by unit tests."""

    rule_name = "test"

    def create_state(self, index, weight):
        return NDArray(jnp.zeros(weight.shape, dtype=weight._data.dtype))

    def update(self, index, weight, grad, state):
        self._update_count(index)
        self._dense_update(index, weight, grad, (state,))


class Updater:
    """Applies per-key optimizer state (parity: optimizer.py:1453 get_updater;
    the KVStore server-side update path)."""

    def __init__(self, optimizer):
        self.optimizer = optimizer
        self.states = {}
        self.states_synced = {}

    def __call__(self, index, grad, weight):
        if index not in self.states:
            self.states[index] = \
                self.optimizer.create_state_multi_precision(index, weight)
            self.states_synced[index] = True
        self.optimizer.update_multi_precision(index, weight, grad,
                                              self.states[index])

    def set_states(self, states):
        states = pickle.loads(states) if isinstance(states, bytes) else states
        if isinstance(states, tuple) and len(states) == 2:
            self.states, self.optimizer = states
        else:
            self.states = states

        def to_device(v):
            if isinstance(v, np.ndarray):
                return NDArray(v)
            if isinstance(v, (tuple, list)):
                return type(v)(to_device(x) for x in v)
            return v
        self.states = {k: to_device(v) for k, v in self.states.items()}
        self.states_synced = dict.fromkeys(self.states.keys(), False)

    def get_states(self, dump_optimizer=False):
        def to_host(v):
            if isinstance(v, NDArray):
                return v.asnumpy()
            if isinstance(v, (tuple, list)):
                return type(v)(to_host(x) for x in v)
            return v
        host_states = {k: to_host(v) for k, v in self.states.items()}
        return pickle.dumps((host_states, self.optimizer) if dump_optimizer
                            else host_states)


def get_updater(optimizer):
    return Updater(optimizer)


# convenience aliases (parity: mx.optimizer.sgd etc. lowercased lookups)
def create_optimizer(name, **kwargs):
    return Optimizer.create_optimizer(name, **kwargs)
