"""Library/version info (parity: python/mxnet/libinfo.py — find_lib_path +
__version__). The "library" here is the native runtime shared object built
from native/mxtpu_native.cc."""
from __future__ import annotations

import os

# single source of truth for the version string; mxnet_tpu/__init__ imports
# it from here (the reference's layout: __init__ imports libinfo.__version__)
__version__ = "0.1.0"


def find_lib_path():
    """Return candidate paths of the native runtime library.

    Parity: libinfo.py find_lib_path (raises if the library is absent in a
    non-dev install; here the native lib is optional — pure-JAX paths work
    without it — so an empty list is allowed).
    """
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    candidates = [
        os.path.join(repo_root, "native", "libmxtpu_native.so"),
        os.path.join(os.path.dirname(os.path.abspath(__file__)),
                     "native", "libmxtpu_native.so"),
    ]
    return [p for p in candidates if os.path.exists(p)]
