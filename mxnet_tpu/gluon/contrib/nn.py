"""Contrib containers (parity: gluon/contrib/nn/basic_layers.py)."""
from ..block import HybridBlock
from ..nn.basic_layers import Sequential, HybridSequential


class Concurrent(Sequential):
    def __init__(self, axis=-1, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self.axis = axis

    def forward(self, x):
        from ... import ndarray as F
        out = [block(x) for block in self._children.values()]
        return F.Concat(*out, dim=self.axis)


class HybridConcurrent(HybridSequential):
    def __init__(self, axis=-1, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self.axis = axis

    def forward(self, x):
        from ... import ndarray as F
        out = [block(x) for block in self._children.values()]
        return F.Concat(*out, dim=self.axis)

    hybrid_forward = None  # forward handles both paths directly


class Identity(HybridBlock):
    def __init__(self, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)

    def hybrid_forward(self, F, x):
        return x
