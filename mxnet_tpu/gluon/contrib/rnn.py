"""Contrib RNN cells (parity: gluon/contrib/rnn/ — VariationalDropoutCell,
Conv*Cell are niche; VariationalDropoutCell provided)."""
from ..rnn.rnn_cell import ModifierCell, BidirectionalCell


class VariationalDropoutCell(ModifierCell):
    """Same dropout mask reused across time steps (Gal & Ghahramani)."""

    def __init__(self, base_cell, drop_inputs=0., drop_states=0.,
                 drop_outputs=0.):
        assert not drop_states or not isinstance(base_cell, BidirectionalCell)
        super().__init__(base_cell)
        self.drop_inputs = drop_inputs
        self.drop_states = drop_states
        self.drop_outputs = drop_outputs
        self._input_mask = None
        self._state_mask = None
        self._output_mask = None

    def _alias(self):
        return "vardrop"

    def reset(self):
        super().reset()
        self._input_mask = None
        self._state_mask = None
        self._output_mask = None

    def _mask(self, F, p, like):
        # standard (training-gated) Dropout of ones: random keep/scale mask
        # while training, identity at inference — reference
        # VariationalDropoutCell builds its masks the same way
        return F.Dropout(F.ones_like(like), p=p)

    def hybrid_forward(self, F, inputs, states):
        cell = self.base_cell
        if self.drop_states:
            if self._state_mask is None:
                self._state_mask = self._mask(F, self.drop_states, states[0])
            states = [states[0] * self._state_mask] + list(states[1:])
        if self.drop_inputs:
            if self._input_mask is None:
                self._input_mask = self._mask(F, self.drop_inputs, inputs)
            inputs = inputs * self._input_mask
        next_output, next_states = cell(inputs, states)
        if self.drop_outputs:
            if self._output_mask is None:
                self._output_mask = self._mask(F, self.drop_outputs,
                                               next_output)
            next_output = next_output * self._output_mask
        return next_output, next_states


# ---------------------------------------------------------------------------
# Convolutional recurrent cells (parity: gluon/contrib/rnn/conv_rnn_cell.py
# Conv{1,2,3}D{RNN,LSTM,GRU}Cell) — i2h/h2h are convolutions over the
# spatial dims, built on the layout-aware Convolution op.
# ---------------------------------------------------------------------------
from ..rnn.rnn_cell import HybridRecurrentCell, _b
from ..block import HybridBlock  # noqa: F401  (re-export surface parity)


def _tup(v, dims):
    return (v,) * dims if isinstance(v, int) else tuple(v)


def _spatial_out(size, k, p, d):
    return tuple(x + 2 * pi - di * (ki - 1) for x, ki, pi, di
                 in zip(size, k, p, d))


class _ConvCellBase(HybridRecurrentCell):
    """Shared geometry/params/conv plumbing for the conv cell family."""

    _gate_names = ("",)

    def __init__(self, input_shape, hidden_channels, i2h_kernel, h2h_kernel,
                 i2h_pad, i2h_dilate, h2h_dilate, i2h_weight_initializer,
                 h2h_weight_initializer, i2h_bias_initializer,
                 h2h_bias_initializer, dims, conv_layout, activation,
                 prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._dims = dims
        self._layout = conv_layout
        self._channels_last = conv_layout[-1] == "C"
        self._hidden_channels = hidden_channels
        self._activation = activation
        self._i2h_kernel = _tup(i2h_kernel, dims)
        self._h2h_kernel = _tup(h2h_kernel, dims)
        if any(k % 2 == 0 for k in self._h2h_kernel):
            raise ValueError("h2h_kernel must be odd so the recurrent conv "
                             "preserves the state's spatial size; got %s"
                             % (self._h2h_kernel,))
        self._i2h_pad = _tup(i2h_pad, dims)
        self._i2h_dilate = _tup(i2h_dilate, dims)
        self._h2h_dilate = _tup(h2h_dilate, dims)
        self._h2h_pad = tuple(d * (k - 1) // 2 for d, k
                              in zip(self._h2h_dilate, self._h2h_kernel))

        if self._channels_last:
            in_ch = input_shape[-1]
            spatial = input_shape[:-1]
        else:
            in_ch = input_shape[0]
            spatial = input_shape[1:]
        state_spatial = _spatial_out(spatial, self._i2h_kernel,
                                     self._i2h_pad, self._i2h_dilate)
        self._state_shape = (state_spatial + (hidden_channels,)
                             if self._channels_last
                             else (hidden_channels,) + state_spatial)
        gates = len(self._gate_names)
        out_ch = hidden_channels * gates
        if self._channels_last:
            i2h_shape = (out_ch,) + self._i2h_kernel + (in_ch,)
            h2h_shape = (out_ch,) + self._h2h_kernel + (hidden_channels,)
        else:
            i2h_shape = (out_ch, in_ch) + self._i2h_kernel
            h2h_shape = (out_ch, hidden_channels) + self._h2h_kernel
        self.i2h_weight = self.params.get(
            "i2h_weight", shape=i2h_shape, init=i2h_weight_initializer,
            allow_deferred_init=True)
        self.h2h_weight = self.params.get(
            "h2h_weight", shape=h2h_shape, init=h2h_weight_initializer,
            allow_deferred_init=True)
        self.i2h_bias = self.params.get(
            "i2h_bias", shape=(out_ch,),
            init=_b(i2h_bias_initializer or "zeros"),
            allow_deferred_init=True)
        self.h2h_bias = self.params.get(
            "h2h_bias", shape=(out_ch,),
            init=_b(h2h_bias_initializer or "zeros"),
            allow_deferred_init=True)

    def state_info(self, batch_size=0):
        return [{"shape": (batch_size,) + self._state_shape,
                 "__layout__": self._layout}
                for _ in range(len(self.state_info_names()))]

    def state_info_names(self):
        return ("h",)

    def _convs(self, F, inputs, state_h, i2h_weight, h2h_weight, i2h_bias,
               h2h_bias):
        gates = len(self._gate_names)
        i2h = F.Convolution(inputs, i2h_weight, i2h_bias,
                            kernel=self._i2h_kernel, pad=self._i2h_pad,
                            dilate=self._i2h_dilate, layout=self._layout,
                            num_filter=self._hidden_channels * gates)
        h2h = F.Convolution(state_h, h2h_weight, h2h_bias,
                            kernel=self._h2h_kernel, pad=self._h2h_pad,
                            dilate=self._h2h_dilate, layout=self._layout,
                            num_filter=self._hidden_channels * gates)
        return i2h, h2h

    def _split_gates(self, F, arr, n):
        axis = self._layout.find("C")
        return list(F.SliceChannel(arr, num_outputs=n, axis=axis))


class _ConvRNNCell(_ConvCellBase):
    _gate_names = ("",)

    def _alias(self):
        return "conv_rnn"

    def hybrid_forward(self, F, inputs, states, i2h_weight, h2h_weight,
                       i2h_bias, h2h_bias):
        i2h, h2h = self._convs(F, inputs, states[0], i2h_weight, h2h_weight,
                               i2h_bias, h2h_bias)
        out = self._get_activation(F, i2h + h2h, self._activation)
        return out, [out]


class _ConvLSTMCell(_ConvCellBase):
    _gate_names = ("_i", "_f", "_c", "_o")

    def _alias(self):
        return "conv_lstm"

    def state_info_names(self):
        return ("h", "c")

    def hybrid_forward(self, F, inputs, states, i2h_weight, h2h_weight,
                       i2h_bias, h2h_bias):
        i2h, h2h = self._convs(F, inputs, states[0], i2h_weight, h2h_weight,
                               i2h_bias, h2h_bias)
        gi, gf, gc, go = self._split_gates(F, i2h + h2h, 4)
        i = F.sigmoid(gi)
        f = F.sigmoid(gf)
        o = F.sigmoid(go)
        c = f * states[1] + i * self._get_activation(F, gc, self._activation)
        h = o * self._get_activation(F, c, self._activation)
        return h, [h, c]


class _ConvGRUCell(_ConvCellBase):
    _gate_names = ("_r", "_z", "_o")

    def _alias(self):
        return "conv_gru"

    def hybrid_forward(self, F, inputs, states, i2h_weight, h2h_weight,
                       i2h_bias, h2h_bias):
        i2h, h2h = self._convs(F, inputs, states[0], i2h_weight, h2h_weight,
                               i2h_bias, h2h_bias)
        ir, iz, inew = self._split_gates(F, i2h, 3)
        hr, hz, hnew = self._split_gates(F, h2h, 3)
        r = F.sigmoid(ir + hr)
        z = F.sigmoid(iz + hz)
        n = self._get_activation(F, inew + r * hnew, self._activation)
        out = (1.0 - z) * n + z * states[0]
        return out, [out]


def _make_conv_cell(base, dims, default_layout, alias):
    class Cell(base):
        def __init__(self, input_shape, hidden_channels, i2h_kernel,
                     h2h_kernel, i2h_pad=0, i2h_dilate=1, h2h_dilate=1,
                     i2h_weight_initializer=None,
                     h2h_weight_initializer=None,
                     i2h_bias_initializer="zeros",
                     h2h_bias_initializer="zeros",
                     conv_layout=default_layout, activation="tanh",
                     prefix=None, params=None):
            super().__init__(input_shape, hidden_channels, i2h_kernel,
                             h2h_kernel, i2h_pad, i2h_dilate, h2h_dilate,
                             i2h_weight_initializer, h2h_weight_initializer,
                             i2h_bias_initializer, h2h_bias_initializer,
                             dims, conv_layout, activation, prefix, params)
    Cell.__name__ = Cell.__qualname__ = alias
    return Cell


Conv1DRNNCell = _make_conv_cell(_ConvRNNCell, 1, "NCW", "Conv1DRNNCell")
Conv2DRNNCell = _make_conv_cell(_ConvRNNCell, 2, "NCHW", "Conv2DRNNCell")
Conv3DRNNCell = _make_conv_cell(_ConvRNNCell, 3, "NCDHW", "Conv3DRNNCell")
Conv1DLSTMCell = _make_conv_cell(_ConvLSTMCell, 1, "NCW", "Conv1DLSTMCell")
Conv2DLSTMCell = _make_conv_cell(_ConvLSTMCell, 2, "NCHW", "Conv2DLSTMCell")
Conv3DLSTMCell = _make_conv_cell(_ConvLSTMCell, 3, "NCDHW", "Conv3DLSTMCell")
Conv1DGRUCell = _make_conv_cell(_ConvGRUCell, 1, "NCW", "Conv1DGRUCell")
Conv2DGRUCell = _make_conv_cell(_ConvGRUCell, 2, "NCHW", "Conv2DGRUCell")
Conv3DGRUCell = _make_conv_cell(_ConvGRUCell, 3, "NCDHW", "Conv3DGRUCell")


class LSTMPCell(HybridRecurrentCell):
    """LSTM with a projected recurrent state (arXiv:1402.1128; parity:
    gluon/contrib/rnn/rnn_cell.py LSTMPCell). States: [r (b, projection),
    c (b, hidden)]."""

    def __init__(self, hidden_size, projection_size,
                 i2h_weight_initializer=None, h2h_weight_initializer=None,
                 h2r_weight_initializer=None, i2h_bias_initializer="zeros",
                 h2h_bias_initializer="zeros", input_size=0, prefix=None,
                 params=None):
        super().__init__(prefix=prefix, params=params)
        self._hidden_size = hidden_size
        self._projection_size = projection_size
        self._input_size = input_size
        self.i2h_weight = self.params.get(
            "i2h_weight", shape=(4 * hidden_size, input_size),
            init=i2h_weight_initializer, allow_deferred_init=True)
        self.h2h_weight = self.params.get(
            "h2h_weight", shape=(4 * hidden_size, projection_size),
            init=h2h_weight_initializer, allow_deferred_init=True)
        self.h2r_weight = self.params.get(
            "h2r_weight", shape=(projection_size, hidden_size),
            init=h2r_weight_initializer, allow_deferred_init=True)
        self.i2h_bias = self.params.get(
            "i2h_bias", shape=(4 * hidden_size,),
            init=_b(i2h_bias_initializer), allow_deferred_init=True)
        self.h2h_bias = self.params.get(
            "h2h_bias", shape=(4 * hidden_size,),
            init=_b(h2h_bias_initializer), allow_deferred_init=True)

    def state_info(self, batch_size=0):
        return [{"shape": (batch_size, self._projection_size),
                 "__layout__": "NC"},
                {"shape": (batch_size, self._hidden_size),
                 "__layout__": "NC"}]

    def _alias(self):
        return "lstmp"

    def _shape_probe(self, x, *args):
        self.i2h_weight.shape = (4 * self._hidden_size, x.shape[-1])
        for p in (self.i2h_weight, self.h2h_weight, self.h2r_weight,
                  self.i2h_bias, self.h2h_bias):
            if p._deferred_init:
                p._finish_deferred_init(p.shape)

    def hybrid_forward(self, F, inputs, states, i2h_weight, h2h_weight,
                       h2r_weight, i2h_bias, h2h_bias):
        gates = F.FullyConnected(inputs, i2h_weight, i2h_bias,
                                 num_hidden=4 * self._hidden_size) + \
            F.FullyConnected(states[0], h2h_weight, h2h_bias,
                             num_hidden=4 * self._hidden_size)
        gi, gf, gc, go = list(F.SliceChannel(gates, num_outputs=4, axis=1))
        i = F.sigmoid(gi)
        f = F.sigmoid(gf)
        o = F.sigmoid(go)
        c = f * states[1] + i * F.tanh(gc)
        r = F.FullyConnected(o * F.tanh(c), h2r_weight, no_bias=True,
                             num_hidden=self._projection_size)
        return r, [r, c]
