"""Contrib RNN cells (parity: gluon/contrib/rnn/ — VariationalDropoutCell,
Conv*Cell are niche; VariationalDropoutCell provided)."""
from ..rnn.rnn_cell import ModifierCell, BidirectionalCell


class VariationalDropoutCell(ModifierCell):
    """Same dropout mask reused across time steps (Gal & Ghahramani)."""

    def __init__(self, base_cell, drop_inputs=0., drop_states=0.,
                 drop_outputs=0.):
        assert not drop_states or not isinstance(base_cell, BidirectionalCell)
        super().__init__(base_cell)
        self.drop_inputs = drop_inputs
        self.drop_states = drop_states
        self.drop_outputs = drop_outputs
        self._input_mask = None
        self._state_mask = None
        self._output_mask = None

    def _alias(self):
        return "vardrop"

    def reset(self):
        super().reset()
        self._input_mask = None
        self._state_mask = None
        self._output_mask = None

    def _mask(self, F, p, like):
        # standard (training-gated) Dropout of ones: random keep/scale mask
        # while training, identity at inference — reference
        # VariationalDropoutCell builds its masks the same way
        return F.Dropout(F.ones_like(like), p=p)

    def hybrid_forward(self, F, inputs, states):
        cell = self.base_cell
        if self.drop_states:
            if self._state_mask is None:
                self._state_mask = self._mask(F, self.drop_states, states[0])
            states = [states[0] * self._state_mask] + list(states[1:])
        if self.drop_inputs:
            if self._input_mask is None:
                self._input_mask = self._mask(F, self.drop_inputs, inputs)
            inputs = inputs * self._input_mask
        next_output, next_states = cell(inputs, states)
        if self.drop_outputs:
            if self._output_mask is None:
                self._output_mask = self._mask(F, self.drop_outputs,
                                               next_output)
            next_output = next_output * self._output_mask
        return next_output, next_states
