"""Gluon contrib (parity: python/mxnet/gluon/contrib/ — concurrent
containers and experimental rnn cells)."""
from .nn import Concurrent, HybridConcurrent, Identity
from . import rnn
from . import data
