"""Contrib data helpers (parity: gluon/contrib/data/)."""
from .sampler import IntervalSampler
