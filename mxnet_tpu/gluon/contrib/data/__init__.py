"""Contrib data helpers (parity: gluon/contrib/data/)."""
from .sampler import IntervalSampler
from .text import WikiText2, WikiText103
