"""Language-model datasets (parity: reference gluon/contrib/data/text.py —
WikiText2 / WikiText103 yielding (data, label) next-token windows of
`seq_len`, with a Vocabulary built from the corpus).

Hermetic-environment behavior: when the real `wiki.<segment>.tokens`
files exist under `root` they are read verbatim; otherwise (zero-egress
CI) a deterministic synthetic corpus with Zipf-distributed word
frequencies and sentence structure stands in, so vocabulary building,
indexing, and the windowing contract are exercised identically.
"""
from __future__ import annotations

import os

import numpy as np

from ....contrib.text import utils as _text_utils
from ....contrib.text.vocab import Vocabulary
from ...data.dataset import Dataset
from ....ndarray import NDArray

_EOS = "<eos>"


def _synthetic_corpus(n_sentences, vocab_size, seed):
    """Zipf-ish word stream with sentence breaks (deterministic)."""
    rng = np.random.RandomState(seed)
    words = ["w%03d" % i for i in range(vocab_size)]
    p = 1.0 / np.arange(1, vocab_size + 1)
    p /= p.sum()
    lines = []
    for _ in range(n_sentences):
        length = rng.randint(5, 25)
        lines.append(" ".join(rng.choice(words, size=length, p=p)))
    return "\n".join(lines)


class _WikiText(Dataset):
    def __init__(self, root, segment, vocab, seq_len, synth_sentences,
                 synth_vocab, file_names):
        self._root = os.path.expanduser(root)
        self._segment = segment
        self._seq_len = int(seq_len)
        self._vocab = vocab
        self._counter = None
        if segment not in file_names:
            raise ValueError("segment must be one of %s"
                             % sorted(file_names))
        path = os.path.join(self._root, file_names[segment])
        if os.path.exists(path):
            with open(path, encoding="utf8") as f:
                content = f.read()
        else:
            import logging
            logging.warning(
                "%s: %s not found — substituting the deterministic "
                "synthetic corpus (perplexities will NOT be comparable to "
                "the real dataset)", type(self).__name__, path)
            content = _synthetic_corpus(
                synth_sentences,
                synth_vocab,
                seed={"train": 11, "validation": 12, "test": 13}[segment])
        self._load(content)

    @property
    def vocabulary(self):
        return self._vocab

    @property
    def frequencies(self):
        return self._counter

    def _load(self, content):
        self._counter = _text_utils.count_tokens_from_str(content)
        if self._vocab is None:
            self._vocab = Vocabulary(counter=self._counter,
                                     reserved_tokens=[_EOS])
        tokens = []
        for line in content.splitlines():
            parts = line.strip().split()
            if parts:
                tokens.extend(parts)
                tokens.append(_EOS)
        t2i = self._vocab.token_to_idx
        unk = t2i[self._vocab.unknown_token]
        idx = np.asarray([t2i.get(t, unk) for t in tokens], np.int32)
        n = (len(idx) - 1) // self._seq_len
        self._data = idx[:n * self._seq_len].reshape(n, self._seq_len)
        self._label = idx[1:n * self._seq_len + 1].reshape(n, self._seq_len)

    def __getitem__(self, i):
        return NDArray(self._data[i]), NDArray(self._label[i])

    def __len__(self):
        return len(self._label)


class WikiText2(_WikiText):
    """WikiText-2 word-level LM dataset (reference
    gluon/contrib/data/text.py:106); reads `wiki.<segment>.tokens` under
    `root` when present, else a deterministic synthetic stand-in."""

    def __init__(self, root=os.path.join("~", ".mxnet", "datasets",
                                         "wikitext-2"),
                 segment="train", vocab=None, seq_len=35):
        super().__init__(
            root, segment, vocab, seq_len,
            synth_sentences=2000, synth_vocab=600,
            file_names={"train": "wiki.train.tokens",
                        "validation": "wiki.valid.tokens",
                        "test": "wiki.test.tokens"})


class WikiText103(_WikiText):
    """WikiText-103 (reference gluon/contrib/data/text.py:144) — same
    contract, larger corpus."""

    def __init__(self, root=os.path.join("~", ".mxnet", "datasets",
                                         "wikitext-103"),
                 segment="train", vocab=None, seq_len=35):
        super().__init__(
            root, segment, vocab, seq_len,
            synth_sentences=8000, synth_vocab=2000,
            file_names={"train": "wiki.train.tokens",
                        "validation": "wiki.valid.tokens",
                        "test": "wiki.test.tokens"})
