"""Contrib samplers (parity: gluon/contrib/data/sampler.py)."""
from ...data.sampler import IntervalSampler  # noqa: F401
