"""Gluon Parameter / ParameterDict.

Parity: reference `python/mxnet/gluon/parameter.py:43,267,518` (Parameter with
deferred init + grad_req, ParameterDict with prefix scoping, save/load).

TPU-native redesign: one buffer per parameter (no per-context copies — the
reference kept one copy per GPU and reduced with KVStore; here multi-device
means *sharding* the single logical array over the mesh, handled by
mxnet_tpu.parallel). grad_req wires into the autograd tape via
mark_variables.
"""
from __future__ import annotations

import numpy as np
import jax.numpy as jnp

from ..base import MXNetError, dtype_np
from ..context import current_context, cpu
from ..ndarray import NDArray
from ..ndarray.sparse import RowSparseNDArray
from .. import autograd
from .. import initializer as init_mod
from ..symbol import Variable


class DeferredInitializationError(MXNetError):
    pass


class Parameter:
    def __init__(self, name, grad_req="write", shape=None, dtype=np.float32,
                 lr_mult=1.0, wd_mult=1.0, init=None, allow_deferred_init=False,
                 differentiable=True, stype="default", grad_stype="default"):
        self.name = name
        self._grad_req = None
        if isinstance(shape, int):
            shape = (shape,)
        self.shape = tuple(shape) if shape is not None else None
        self.dtype = dtype
        self.lr_mult = lr_mult
        self.wd_mult = wd_mult
        self.init = init
        self.allow_deferred_init = allow_deferred_init
        self._differentiable = differentiable
        self._stype = stype
        self._data = None
        self._grad = None
        self._deferred_init = ()
        self._var = None
        self.grad_req = grad_req if differentiable else "null"

    def __repr__(self):
        return "Parameter {name} (shape={shape}, dtype={dtype})".format(
            name=self.name, shape=self.shape, dtype=self.dtype)

    @property
    def grad_req(self):
        return self._grad_req

    @grad_req.setter
    def grad_req(self, req):
        assert req in ("write", "add", "null")
        if not self._differentiable:
            req = "null"
        if self._grad_req == req:
            return
        self._grad_req = req
        if req == "null":
            self._grad = None
            if self._data is not None:
                self._data._entry = None
        elif self._data is not None:
            self._init_grad()

    def _needs_shape(self):
        return self.shape is None or any(s == 0 for s in self.shape)

    def initialize(self, init=None, ctx=None, default_init=None,
                   force_reinit=False):
        default_init = default_init or init_mod.Uniform()
        if self._data is not None and not force_reinit:
            return
        if self._needs_shape():
            if self.allow_deferred_init:
                self._deferred_init = (init, ctx, default_init)
                return
            raise MXNetError(
                "Cannot initialize Parameter '%s' because it has invalid "
                "shape: %s." % (self.name, str(self.shape)))
        self._finish_init(init, ctx, default_init)

    def _finish_init(self, init, ctx, default_init):
        ctx = ctx if ctx is not None else current_context()
        if isinstance(ctx, (list, tuple)):
            ctx = ctx[0]  # one logical buffer; devices = sharding
        data = NDArray(jnp.zeros(self.shape, dtype=dtype_np(self.dtype)),
                       ctx=ctx)
        initializer = init if init is not None else (self.init or default_init)
        desc = init_mod.InitDesc(self.name)
        initializer(desc, data)
        self._data = data
        self._deferred_init = ()
        if self._grad_req != "null":
            self._init_grad()

    def _finish_deferred_init(self, shape):
        if not self._deferred_init:
            return
        if self._needs_shape():
            inferred = list(self.shape) if self.shape else list(shape)
            for i, s in enumerate(inferred):
                if s == 0:
                    inferred[i] = shape[i]
            self.shape = tuple(inferred) if self.shape else tuple(shape)
        init, ctx, default_init = self._deferred_init
        self._finish_init(init, ctx, default_init)

    def _init_grad(self):
        if self._stype == "row_sparse":
            pass  # grads materialize as row_sparse at update time
        self._grad = NDArray(jnp.zeros(self._data.shape,
                                       dtype=self._data._data.dtype),
                             ctx=self._data._ctx)
        autograd.mark_variables([self._data], [self._grad], self._grad_req)

    # -- accessors ----------------------------------------------------------
    def _check_initialized(self):
        if self._data is None:
            if self._deferred_init:
                raise DeferredInitializationError(
                    "Parameter '%s' has not been initialized yet because "
                    "initialization was deferred. Actual initialization "
                    "happens during the first forward pass." % self.name)
            raise MXNetError(
                "Parameter '%s' has not been initialized. You should first "
                "call block.collect_params().initialize() before using it."
                % self.name)

    def data(self, ctx=None):
        self._check_initialized()
        return self._data

    def list_data(self):
        self._check_initialized()
        return [self._data]

    def grad(self, ctx=None):
        self._check_initialized()
        if self._grad is None:
            raise MXNetError(
                "Cannot get gradient array for Parameter '%s' because "
                "grad_req='null'" % self.name)
        return self._grad

    def list_grad(self):
        return [self.grad()]

    def list_ctx(self):
        self._check_initialized()
        return [self._data._ctx]

    def set_data(self, data):
        if self._data is None:
            # allow set before init (load path) when shape known
            self.shape = tuple(data.shape)
            self._data = data if isinstance(data, NDArray) else NDArray(data)
            if self._grad_req != "null":
                self._init_grad()
            return
        self._data._data = (data._data if isinstance(data, NDArray)
                            else jnp.asarray(data)).astype(self._data._data.dtype).reshape(self._data.shape)
        self._data._version += 1

    def zero_grad(self):
        if self._grad is not None:
            self._grad._data = jnp.zeros_like(self._grad._data)
            self._grad._version += 1

    def row_sparse_data(self, row_id):
        self._check_initialized()
        rsp = RowSparseNDArray.from_dense(self._data)
        return rsp.retain(row_id)

    def cast(self, dtype):
        self.dtype = dtype
        if self._data is not None:
            self._data._data = self._data._data.astype(dtype_np(dtype))
            if self._grad is not None:
                self._grad._data = self._grad._data.astype(dtype_np(dtype))
                autograd.mark_variables([self._data], [self._grad],
                                        self._grad_req)

    def reset_ctx(self, ctx):
        pass  # placement is XLA/sharding-managed

    def var(self):
        if self._var is None:
            self._var = Variable(self.name, shape=self.shape,
                                 dtype=self.dtype, init=self.init,
                                 lr_mult=self.lr_mult, wd_mult=self.wd_mult)
        return self._var


class Constant(Parameter):
    """Parity: gluon.Constant — non-differentiable fixed value."""

    def __init__(self, name, value):
        if not isinstance(value, NDArray):
            value = NDArray(np.asarray(value))
        self.value = value

        class _CInit(init_mod.Initializer):
            def _init_weight(self, _, arr):
                arr._data = value._data
        super().__init__(name, grad_req="null", shape=value.shape,
                         dtype=value.dtype, init=_CInit(),
                         differentiable=False)


class ParameterDict:
    def __init__(self, prefix="", shared=None):
        self._prefix = prefix
        self._params = {}  # ordered by insertion (py3.7 dict)
        self._shared = shared

    @property
    def prefix(self):
        return self._prefix

    def __repr__(self):
        s = "{name}(\n{content}\n)"
        name = self._prefix + " " if self._prefix else ""
        return s.format(name=name, content="\n".join(
            "  " + repr(v) for v in self.values()))

    def items(self):
        return self._params.items()

    def keys(self):
        return self._params.keys()

    def values(self):
        return self._params.values()

    def __iter__(self):
        return iter(self._params)

    def __getitem__(self, key):
        return self._params[key]

    def __contains__(self, key):
        return key in self._params

    def _get_impl(self, name):
        if name in self._params:
            return self._params[name]
        if self._shared is not None and name in self._shared._params:
            self._params[name] = self._shared._params[name]
            return self._params[name]
        return None

    def get(self, name, **kwargs):
        name = self._prefix + name
        param = self._get_impl(name)
        if param is None:
            param = Parameter(name, **kwargs)
            self._params[name] = param
        else:
            for k, v in kwargs.items():
                if hasattr(param, k) and getattr(param, k) is not None:
                    existing = getattr(param, k)
                    if k == "shape" and v is not None and existing is not None:
                        v = tuple(v) if not isinstance(v, int) else (v,)
                        # merge partial shapes
                        if len(v) == len(existing):
                            merged = tuple(a if a else b
                                           for a, b in zip(existing, v))
                            param.shape = merged
                            continue
                else:
                    setattr(param, k, v)
        return param

    def get_constant(self, name, value=None):
        name = self._prefix + name
        param = self._get_impl(name)
        if param is None:
            if value is None:
                raise KeyError("No constant named '{}'.".format(name))
            param = Constant(name, value)
            self._params[name] = param
        return param

    def update(self, other):
        for k, v in other.items():
            if k in self._params:
                assert self._params[k] is v, \
                    "Cannot update self with other because they have different " \
                    "Parameters with the same name '%s'" % k
            else:
                self._params[k] = v

    def initialize(self, init=None, ctx=None, verbose=False,
                   force_reinit=False):
        default = init if init is not None else init_mod.Uniform()
        for _, v in self.items():
            v.initialize(None, ctx, default, force_reinit=force_reinit)

    def zero_grad(self):
        for v in self.values():
            v.zero_grad()

    def reset_ctx(self, ctx):
        for v in self.values():
            v.reset_ctx(ctx)

    def setattr(self, name, value):
        for v in self.values():
            setattr(v, name, value)

    def save(self, filename, strip_prefix=""):
        from ..utils import serialization
        arg_dict = {}
        for param in self.values():
            block = param.list_data()
            weight = block[0]
            if not param.name.startswith(strip_prefix):
                raise ValueError(
                    "Prefix '%s' is to be striped before saving, but "
                    "Parameter's name '%s' does not start with it"
                    % (strip_prefix, param.name))
            arg_dict[param.name[len(strip_prefix):]] = weight
        serialization.save_ndarrays(filename, arg_dict)

    def load(self, filename, ctx=None, allow_missing=False,
             ignore_extra=False, restore_prefix=""):
        from ..utils import serialization
        arg_dict = serialization.load_ndarrays(filename)
        # accept export/Module artifacts: 'arg:'/'aux:' key prefixes strip
        # (parity: reference load_parameters legacy handling)
        arg_dict = {(k.split(":", 1)[1] if k.startswith(("arg:", "aux:"))
                     else k): v for k, v in arg_dict.items()}
        arg_dict = {restore_prefix + k: v for k, v in arg_dict.items()}
        if not allow_missing:
            for name in self.keys():
                assert name in arg_dict, \
                    "Parameter '%s' is missing in file '%s'" % (name, filename)
        for name in arg_dict:
            if name not in self._params:
                assert ignore_extra, \
                    "Parameter '%s' loaded from file '%s' is not present in " \
                    "ParameterDict" % (name, filename)
                continue
            self[name].set_data(arg_dict[name])
