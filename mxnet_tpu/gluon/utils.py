"""Gluon utilities (parity: python/mxnet/gluon/utils.py — split_data,
split_and_load, clip_global_norm, check_sha1, download)."""
from __future__ import annotations

import os
import hashlib

import numpy as np
import jax.numpy as jnp

from ..ndarray import NDArray
from .. import ndarray as nd


def split_data(data, num_slice, batch_axis=0, even_split=True):
    size = data.shape[batch_axis]
    if size < num_slice:
        raise ValueError(
            "Too many slices for data with shape %s. Arguments are "
            "num_slice=%d and batch_axis=%d." % (str(data.shape), num_slice,
                                                 batch_axis))
    if even_split and size % num_slice != 0:
        raise ValueError(
            "data with shape %s cannot be evenly split into %d slices "
            "along axis %d. Use a batch size that's multiple of %d or set "
            "even_split=False to allow uneven partitioning of data."
            % (str(data.shape), num_slice, batch_axis, num_slice))
    step = size // num_slice
    slices = []
    for i in range(num_slice):
        begin = i * step
        end = (i + 1) * step if i < num_slice - 1 else size
        idx = [slice(None)] * data.ndim
        idx[batch_axis] = slice(begin, end)
        slices.append(data[tuple(idx)])
    return slices


def split_and_load(data, ctx_list, batch_axis=0, even_split=True):
    """Parity: utils.split_and_load. On TPU multi-device execution shards a
    single array over the mesh instead of making per-device copies, so with
    one logical context the batch is NOT split; with an explicit ctx list the
    reference-compatible per-slice list is returned."""
    if not isinstance(data, NDArray):
        data = NDArray(np.asarray(data))
    if not isinstance(ctx_list, (list, tuple)):
        ctx_list = [ctx_list]
    if len(ctx_list) == 1:
        return [data.as_in_context(ctx_list[0])]
    slices = split_data(data, len(ctx_list), batch_axis, even_split)
    return [s.as_in_context(ctx) for s, ctx in zip(slices, ctx_list)]


def clip_global_norm(arrays, max_norm):
    """Parity: utils.clip_global_norm (rescales in place)."""
    assert len(arrays) > 0
    total_norm = float(jnp.sqrt(sum(
        float(jnp.sum(jnp.square(a._data))) for a in arrays)))
    scale = max_norm / (total_norm + 1e-8)
    if scale < 1.0:
        for a in arrays:
            a._data = a._data * scale
            a._version += 1
    return total_norm


def check_sha1(filename, sha1_hash):
    sha1 = hashlib.sha1()
    with open(filename, "rb") as f:
        while True:
            data = f.read(1048576)
            if not data:
                break
            sha1.update(data)
    return sha1.hexdigest() == sha1_hash


def download(url, path=None, overwrite=False, sha1_hash=None, retries=3):
    """Fetch `url` to `path` (parity: reference gluon/utils.py download).

    Transient fetch failures retry with exponential backoff + jitter
    (`utils.retry`, `retries` attempts total); the file lands via a
    temp-write + atomic rename so a killed download never leaves a
    truncated file at the final path. `file://` URLs work without any
    network egress (the test path); in a zero-egress environment http(s)
    fetches exhaust their retries and raise with guidance."""
    fname = path if path and not os.path.isdir(path) else os.path.join(
        path or ".", url.split("/")[-1])
    if os.path.exists(fname) and not overwrite and (
            not sha1_hash or check_sha1(fname, sha1_hash)):
        return fname
    from ..utils import retry as _retry

    def fetch():
        import shutil
        import urllib.request
        d = os.path.dirname(os.path.abspath(fname))
        os.makedirs(d, exist_ok=True)
        tmp = fname + ".tmp-%d" % os.getpid()
        try:
            with urllib.request.urlopen(url, timeout=30) as src, \
                    open(tmp, "wb") as dst:
                shutil.copyfileobj(src, dst)
            if sha1_hash and not check_sha1(tmp, sha1_hash):
                raise IOError("downloaded %s fails its sha1 check" % url)
            os.replace(tmp, fname)
        finally:
            if os.path.exists(tmp):
                os.remove(tmp)
        return fname

    try:
        return _retry(fetch, attempts=retries, backoff=0.2,
                      retry_on=(OSError, IOError))
    except (OSError, IOError, ValueError) as e:
        raise IOError(
            "download(%s) failed after %d attempts (%s). If this "
            "environment has no network egress, place the file at %s "
            "manually." % (url, retries, e, fname))


def _indent(s_, numSpaces):
    s = s_.split("\n")
    if len(s) == 1:
        return s_
    first = s.pop(0)
    s = [first] + [(numSpaces * " ") + line for line in s]
    return "\n".join(s)
