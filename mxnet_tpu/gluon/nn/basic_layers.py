"""Basic Gluon layers.

Parity: reference `python/mxnet/gluon/nn/basic_layers.py` (Dense:142,
BatchNorm:273, Embedding:369, LayerNorm:525, Dropout, Activation, Flatten,
Lambda/HybridLambda, Sequential/HybridSequential, InstanceNorm, activations).
"""
from __future__ import annotations

import numpy as np

from ..block import Block, HybridBlock
from ..parameter import Parameter


class Sequential(Block):
    def __init__(self, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)

    def add(self, *blocks):
        for block in blocks:
            self.register_child(block)

    def forward(self, x):
        for block in self._children.values():
            x = block(x)
        return x

    def __len__(self):
        return len(self._children)

    def __getitem__(self, key):
        layers = list(self._children.values())[key]
        if isinstance(layers, list):
            net = type(self)(prefix=self._prefix)
            with net.name_scope():
                net.add(*layers)
            return net
        return layers

    def __iter__(self):
        return iter(self._children.values())

    def hybridize(self, active=True, **kwargs):
        super().hybridize(active, **kwargs)


class HybridSequential(HybridBlock):
    def __init__(self, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)

    def add(self, *blocks):
        for block in blocks:
            self.register_child(block)

    def forward(self, x):
        for block in self._children.values():
            x = block(x)
        return x

    def hybrid_forward(self, F, x):
        for block in self._children.values():
            x = block(x)
        return x

    def __len__(self):
        return len(self._children)

    def __getitem__(self, key):
        layers = list(self._children.values())[key]
        if isinstance(layers, list):
            net = type(self)(prefix=self._prefix)
            with net.name_scope():
                net.add(*layers)
            return net
        return layers

    def __iter__(self):
        return iter(self._children.values())


class Dense(HybridBlock):
    """Parity: basic_layers.py:142; weight layout (units, in_units) matches
    the reference so checkpoints transliterate. The matmul rides the MXU."""

    def __init__(self, units, activation=None, use_bias=True, flatten=True,
                 dtype="float32", weight_initializer=None,
                 bias_initializer="zeros", in_units=0, **kwargs):
        super().__init__(**kwargs)
        self._units = units
        self._flatten = flatten
        self._act_type = activation
        with self.name_scope():
            self.weight = self.params.get(
                "weight", shape=(units, in_units), dtype=dtype,
                init=_init(weight_initializer), allow_deferred_init=True)
            if use_bias:
                self.bias = self.params.get(
                    "bias", shape=(units,), dtype=dtype,
                    init=_init(bias_initializer), allow_deferred_init=True)
            else:
                self.bias = None

    def _shape_probe(self, x, *args):
        in_units = int(np.prod(x.shape[1:])) if self._flatten else x.shape[-1]
        self.weight.shape = (self._units, in_units)
        self.weight._finish_deferred_init(self.weight.shape)
        if self.bias is not None and self.bias._deferred_init:
            self.bias._finish_deferred_init(self.bias.shape)

    def hybrid_forward(self, F, x, weight, bias=None):
        out = F.FullyConnected(x, weight, bias, num_hidden=self._units,
                               flatten=self._flatten,
                               no_bias=(bias is None))
        if self._act_type:
            out = F.Activation(out, act_type=self._act_type)
        return out


class Activation(HybridBlock):
    def __init__(self, activation, **kwargs):
        self._act_type = activation  # before super(): _alias() needs it
        super().__init__(**kwargs)

    def _alias(self):
        return self._act_type

    def hybrid_forward(self, F, x):
        return F.Activation(x, act_type=self._act_type)


class Dropout(HybridBlock):
    def __init__(self, rate, axes=(), **kwargs):
        super().__init__(**kwargs)
        self._rate = rate
        self._axes = axes

    def hybrid_forward(self, F, x):
        return F.Dropout(x, p=self._rate, axes=self._axes)


class BatchNorm(HybridBlock):
    """Parity: basic_layers.py:273. Running stats are updated functionally —
    set_data during forward is captured by the hybridize trace and threaded
    as an extra output (see HybridBlock.pure_fn)."""

    def __init__(self, axis=1, momentum=0.9, epsilon=1e-5, center=True,
                 scale=True, use_global_stats=False, beta_initializer="zeros",
                 gamma_initializer="ones", running_mean_initializer="zeros",
                 running_variance_initializer="ones", in_channels=0, **kwargs):
        super().__init__(**kwargs)
        self._axis = axis
        self._momentum = momentum
        self._epsilon = epsilon
        self._center = center
        self._scale = scale
        self._use_global_stats = use_global_stats
        with self.name_scope():
            self.gamma = self.params.get(
                "gamma", grad_req="write" if scale else "null",
                shape=(in_channels,), init=_init(gamma_initializer),
                allow_deferred_init=True, differentiable=scale)
            self.beta = self.params.get(
                "beta", grad_req="write" if center else "null",
                shape=(in_channels,), init=_init(beta_initializer),
                allow_deferred_init=True, differentiable=center)
            self.running_mean = self.params.get(
                "running_mean", grad_req="null", shape=(in_channels,),
                init=_init(running_mean_initializer),
                allow_deferred_init=True, differentiable=False)
            self.running_var = self.params.get(
                "running_var", grad_req="null", shape=(in_channels,),
                init=_init(running_variance_initializer),
                allow_deferred_init=True, differentiable=False)
            # auxiliary STATES (layer-mutated), distinct from merely-frozen
            # params — export/symbol tracing classifies by this flag
            self.running_mean._is_aux = True
            self.running_var._is_aux = True

    def _shape_probe(self, x, *args):
        c = x.shape[self._axis]
        for p in (self.gamma, self.beta, self.running_mean, self.running_var):
            p.shape = (c,)
            if p._deferred_init:
                p._finish_deferred_init(p.shape)

    def hybrid_forward(self, F, x, gamma, beta, running_mean, running_var):
        res = F.BatchNorm(
            x, gamma, beta, running_mean, running_var,
            eps=self._epsilon, momentum=self._momentum,
            fix_gamma=not self._scale,
            use_global_stats=self._use_global_stats, axis=self._axis)
        if not isinstance(res, tuple):
            # symbolic trace: BN exposes the normalized output; moving-stat
            # threading is the executor's job (symbol._eval aux_updates)
            return res
        out, mean, var = res
        self._update_moving_stats(mean, var)
        return out

    def _update_moving_stats(self, mean, var):
        from ... import autograd
        if autograd.is_training() and not self._use_global_stats:
            m = self._momentum
            self.running_mean.set_data(
                m * self.running_mean.data() + (1 - m) * mean.detach())
            self.running_var.set_data(
                m * self.running_var.data() + (1 - m) * var.detach())

    def fused_call(self, x, act=None, residual=None):
        """BN with the ReLU/residual epilogue folded into one op
        (`_contrib_BatchNormAddRelu`; MXNET_FUSED_BN_EPILOGUE=1 routes it
        through the Pallas kernels, off-flag it composes the same math in
        XLA). Same deferred-init and moving-stat semantics as the plain
        forward — the residual-block fast path in model_zoo resnet uses
        this for the relu(BN(x) + residual) tails."""
        from ...gluon.parameter import DeferredInitializationError
        from ... import ndarray as F
        try:
            params = {n: p.data() for n, p in self._reg_params.items()}
        except DeferredInitializationError:
            self._finish_deferred_init(x)
            params = {n: p.data() for n, p in self._reg_params.items()}
        out, mean, var = F._contrib_BatchNormAddRelu(
            x, params["gamma"], params["beta"], params["running_mean"],
            params["running_var"], addend=residual, eps=self._epsilon,
            momentum=self._momentum, fix_gamma=not self._scale,
            use_global_stats=self._use_global_stats, axis=self._axis,
            act_type=act)
        self._update_moving_stats(mean, var)
        return out


class LayerNorm(HybridBlock):
    """Parity: basic_layers.py:525."""

    def __init__(self, axis=-1, epsilon=1e-5, center=True, scale=True,
                 beta_initializer="zeros", gamma_initializer="ones",
                 in_channels=0, **kwargs):
        super().__init__(**kwargs)
        self._axis = axis
        self._epsilon = epsilon
        with self.name_scope():
            self.gamma = self.params.get(
                "gamma", grad_req="write" if scale else "null",
                shape=(in_channels,), init=_init(gamma_initializer),
                allow_deferred_init=True)
            self.beta = self.params.get(
                "beta", grad_req="write" if center else "null",
                shape=(in_channels,), init=_init(beta_initializer),
                allow_deferred_init=True)

    def _shape_probe(self, x, *args):
        c = x.shape[self._axis]
        for p in (self.gamma, self.beta):
            p.shape = (c,)
            if p._deferred_init:
                p._finish_deferred_init(p.shape)

    def hybrid_forward(self, F, x, gamma, beta):
        return F.LayerNorm(x, gamma, beta, axis=self._axis, eps=self._epsilon)


class InstanceNorm(HybridBlock):
    def __init__(self, axis=1, epsilon=1e-5, center=True, scale=False,
                 beta_initializer="zeros", gamma_initializer="ones",
                 in_channels=0, **kwargs):
        super().__init__(**kwargs)
        self._epsilon = epsilon
        with self.name_scope():
            self.gamma = self.params.get(
                "gamma", grad_req="write" if scale else "null",
                shape=(in_channels,), init=_init(gamma_initializer),
                allow_deferred_init=True)
            self.beta = self.params.get(
                "beta", grad_req="write" if center else "null",
                shape=(in_channels,), init=_init(beta_initializer),
                allow_deferred_init=True)

    def _shape_probe(self, x, *args):
        c = x.shape[1]
        for p in (self.gamma, self.beta):
            p.shape = (c,)
            if p._deferred_init:
                p._finish_deferred_init(p.shape)

    def hybrid_forward(self, F, x, gamma, beta):
        return F.InstanceNorm(x, gamma, beta, eps=self._epsilon)


class Embedding(HybridBlock):
    """Parity: basic_layers.py:369; sparse_grad maps to row_sparse grads via
    the KVStore layer (grads here are dense XLA scatter-adds)."""

    def __init__(self, input_dim, output_dim, dtype="float32",
                 weight_initializer=None, sparse_grad=False, **kwargs):
        super().__init__(**kwargs)
        self._input_dim = input_dim
        self._output_dim = output_dim
        self._sparse_grad = sparse_grad
        with self.name_scope():
            self.weight = self.params.get(
                "weight", shape=(input_dim, output_dim), dtype=dtype,
                init=_init(weight_initializer),
                grad_stype="row_sparse" if sparse_grad else "default")

    def hybrid_forward(self, F, x, weight):
        return F.Embedding(x, weight, input_dim=self._input_dim,
                           output_dim=self._output_dim,
                           sparse_grad=self._sparse_grad)


class Flatten(HybridBlock):
    def __init__(self, **kwargs):
        super().__init__(**kwargs)

    def hybrid_forward(self, F, x):
        return F.Flatten(x)


class Lambda(Block):
    def __init__(self, function, prefix=None):
        super().__init__(prefix=prefix)
        if isinstance(function, str):
            from ... import ndarray as nd
            function = getattr(nd, function)
        self._func = function

    def forward(self, *args):
        return self._func(*args)


class HybridLambda(HybridBlock):
    def __init__(self, function, prefix=None):
        super().__init__(prefix=prefix)
        self._func_name = function if isinstance(function, str) else None
        self._func = function

    def hybrid_forward(self, F, *args):
        if self._func_name is not None:
            return getattr(F, self._func_name)(*args)
        return self._func(F, *args)


class LeakyReLU(HybridBlock):
    def __init__(self, alpha, **kwargs):
        super().__init__(**kwargs)
        self._alpha = alpha

    def hybrid_forward(self, F, x):
        return F.LeakyReLU(x, act_type="leaky", slope=self._alpha)


class PReLU(HybridBlock):
    def __init__(self, alpha_initializer=None, **kwargs):
        super().__init__(**kwargs)
        from ... import initializer
        with self.name_scope():
            self.alpha = self.params.get(
                "alpha", shape=(1,),
                init=alpha_initializer or initializer.Constant(0.25))

    def hybrid_forward(self, F, x, alpha):
        return F.LeakyReLU(x, gamma=alpha, act_type="prelu")


class ELU(HybridBlock):
    def __init__(self, alpha=1.0, **kwargs):
        super().__init__(**kwargs)
        self._alpha = alpha

    def hybrid_forward(self, F, x):
        return F.LeakyReLU(x, act_type="elu", slope=self._alpha)


class SELU(HybridBlock):
    def hybrid_forward(self, F, x):
        return F.LeakyReLU(x, act_type="selu")


class Swish(HybridBlock):
    def __init__(self, beta=1.0, **kwargs):
        super().__init__(**kwargs)
        self._beta = beta

    def hybrid_forward(self, F, x):
        return x * F.sigmoid(self._beta * x)


class GELU(HybridBlock):
    """Capability upgrade (transformer blocks); tanh approximation."""

    def hybrid_forward(self, F, x):
        return 0.5 * x * (1.0 + F.tanh(0.7978845608028654 *
                                       (x + 0.044715 * x * x * x)))


def _init(spec):
    if spec is None or not isinstance(spec, str):
        return spec
    from ... import initializer
    return initializer.create(spec)
