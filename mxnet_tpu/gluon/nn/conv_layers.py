"""Convolution / pooling Gluon layers.

Parity: reference `python/mxnet/gluon/nn/conv_layers.py` (Conv1D/2D/3D:
163-319, transposed:399-566, pooling:653-1000). Layout NCHW (channels-first)
like the reference default; XLA retiles for the MXU internally.
"""
from __future__ import annotations

import numpy as np

from ..block import HybridBlock
from .basic_layers import _init


def _pair(v, n):
    if isinstance(v, (tuple, list)):
        return tuple(v)
    return (v,) * n


class _Conv(HybridBlock):
    def __init__(self, channels, kernel_size, strides, padding, dilation,
                 groups, layout, in_channels=0, activation=None, use_bias=True,
                 weight_initializer=None, bias_initializer="zeros",
                 op_name="Convolution", adj=None, **kwargs):
        super().__init__(**kwargs)
        self._channels = channels
        self._in_channels = in_channels
        self._op_name = op_name
        ndim = len(kernel_size)
        self._kwargs = {
            "kernel": kernel_size, "stride": strides, "dilate": dilation,
            "pad": padding, "num_filter": channels, "num_group": groups,
            "no_bias": not use_bias, "layout": layout}
        if adj is not None:
            self._kwargs["adj"] = adj
        self._act_type = activation
        from ...ops.nn import _CHANNELS_LAST
        self._channels_last = layout in _CHANNELS_LAST
        with self.name_scope():
            cin = in_channels // groups if in_channels else 0
            if op_name == "Convolution":
                # channels-last stores the weight as (O, *k, I)
                wshape = (channels,) + kernel_size + (cin,) \
                    if self._channels_last else (channels, cin) + kernel_size
            else:  # Deconvolution: (in_channels, channels//groups, *k)
                wshape = (in_channels, channels // groups) + kernel_size \
                    if in_channels else (0, channels // groups) + kernel_size
            self.weight = self.params.get(
                "weight", shape=wshape, init=_init(weight_initializer),
                allow_deferred_init=True)
            if use_bias:
                self.bias = self.params.get(
                    "bias", shape=(channels,), init=_init(bias_initializer),
                    allow_deferred_init=True)
            else:
                self.bias = None

    def _shape_probe(self, x, *args):
        cin = x.shape[-1] if self._channels_last else x.shape[1]
        g = self._kwargs["num_group"]
        k = tuple(self._kwargs["kernel"])
        if self._op_name == "Convolution":
            self.weight.shape = (self._channels,) + k + (cin // g,) \
                if self._channels_last else (self._channels, cin // g) + k
        else:
            self.weight.shape = (cin, self._channels // g) + k
        self.weight._finish_deferred_init(self.weight.shape)
        if self.bias is not None and self.bias._deferred_init:
            self.bias._finish_deferred_init(self.bias.shape)

    def hybrid_forward(self, F, x, weight, bias=None):
        op = getattr(F, self._op_name)
        out = op(x, weight, bias, **self._kwargs)
        if self._act_type:
            out = F.Activation(out, act_type=self._act_type)
        return out


class Conv1D(_Conv):
    def __init__(self, channels, kernel_size, strides=1, padding=0,
                 dilation=1, groups=1, layout="NCW", activation=None,
                 use_bias=True, weight_initializer=None,
                 bias_initializer="zeros", in_channels=0, **kwargs):
        super().__init__(channels, _pair(kernel_size, 1), _pair(strides, 1),
                         _pair(padding, 1), _pair(dilation, 1), groups,
                         layout, in_channels, activation, use_bias,
                         weight_initializer, bias_initializer, **kwargs)


class Conv2D(_Conv):
    def __init__(self, channels, kernel_size, strides=(1, 1), padding=(0, 0),
                 dilation=(1, 1), groups=1, layout="NCHW", activation=None,
                 use_bias=True, weight_initializer=None,
                 bias_initializer="zeros", in_channels=0, **kwargs):
        super().__init__(channels, _pair(kernel_size, 2), _pair(strides, 2),
                         _pair(padding, 2), _pair(dilation, 2), groups,
                         layout, in_channels, activation, use_bias,
                         weight_initializer, bias_initializer, **kwargs)


class Conv3D(_Conv):
    def __init__(self, channels, kernel_size, strides=(1, 1, 1),
                 padding=(0, 0, 0), dilation=(1, 1, 1), groups=1,
                 layout="NCDHW", activation=None, use_bias=True,
                 weight_initializer=None, bias_initializer="zeros",
                 in_channels=0, **kwargs):
        super().__init__(channels, _pair(kernel_size, 3), _pair(strides, 3),
                         _pair(padding, 3), _pair(dilation, 3), groups,
                         layout, in_channels, activation, use_bias,
                         weight_initializer, bias_initializer, **kwargs)


class _ConvTranspose(_Conv):
    pass


class Conv1DTranspose(_Conv):
    def __init__(self, channels, kernel_size, strides=1, padding=0,
                 output_padding=0, dilation=1, groups=1, layout="NCW",
                 activation=None, use_bias=True, weight_initializer=None,
                 bias_initializer="zeros", in_channels=0, **kwargs):
        super().__init__(channels, _pair(kernel_size, 1), _pair(strides, 1),
                         _pair(padding, 1), _pair(dilation, 1), groups,
                         layout, in_channels, activation, use_bias,
                         weight_initializer, bias_initializer,
                         op_name="Deconvolution",
                         adj=_pair(output_padding, 1), **kwargs)


class Conv2DTranspose(_Conv):
    def __init__(self, channels, kernel_size, strides=(1, 1), padding=(0, 0),
                 output_padding=(0, 0), dilation=(1, 1), groups=1,
                 layout="NCHW", activation=None, use_bias=True,
                 weight_initializer=None, bias_initializer="zeros",
                 in_channels=0, **kwargs):
        super().__init__(channels, _pair(kernel_size, 2), _pair(strides, 2),
                         _pair(padding, 2), _pair(dilation, 2), groups,
                         layout, in_channels, activation, use_bias,
                         weight_initializer, bias_initializer,
                         op_name="Deconvolution",
                         adj=_pair(output_padding, 2), **kwargs)


class Conv3DTranspose(_Conv):
    def __init__(self, channels, kernel_size, strides=(1, 1, 1),
                 padding=(0, 0, 0), output_padding=(0, 0, 0),
                 dilation=(1, 1, 1), groups=1, layout="NCDHW",
                 activation=None, use_bias=True, weight_initializer=None,
                 bias_initializer="zeros", in_channels=0, **kwargs):
        super().__init__(channels, _pair(kernel_size, 3), _pair(strides, 3),
                         _pair(padding, 3), _pair(dilation, 3), groups,
                         layout, in_channels, activation, use_bias,
                         weight_initializer, bias_initializer,
                         op_name="Deconvolution",
                         adj=_pair(output_padding, 3), **kwargs)


class _Pooling(HybridBlock):
    def __init__(self, pool_size, strides, padding, ceil_mode, global_pool,
                 pool_type, count_include_pad=None, layout=None, **kwargs):
        super().__init__(**kwargs)
        if strides is None:
            strides = pool_size
        self._kwargs = {
            "kernel": pool_size, "stride": strides, "pad": padding,
            "global_pool": global_pool, "pool_type": pool_type,
            "layout": layout,
            "pooling_convention": "full" if ceil_mode else "valid"}
        if count_include_pad is not None:
            self._kwargs["count_include_pad"] = count_include_pad

    def _alias(self):
        return "pool"

    def hybrid_forward(self, F, x):
        return F.Pooling(x, **self._kwargs)


class MaxPool1D(_Pooling):
    def __init__(self, pool_size=2, strides=None, padding=0, layout="NCW",
                 ceil_mode=False, **kwargs):
        super().__init__(_pair(pool_size, 1),
                         _pair(strides, 1) if strides is not None else None,
                         _pair(padding, 1), ceil_mode, False, "max", layout=layout, **kwargs)


class MaxPool2D(_Pooling):
    def __init__(self, pool_size=(2, 2), strides=None, padding=0,
                 layout="NCHW", ceil_mode=False, **kwargs):
        super().__init__(_pair(pool_size, 2),
                         _pair(strides, 2) if strides is not None else None,
                         _pair(padding, 2), ceil_mode, False, "max", layout=layout, **kwargs)


class MaxPool3D(_Pooling):
    def __init__(self, pool_size=(2, 2, 2), strides=None, padding=0,
                 layout="NCDHW", ceil_mode=False, **kwargs):
        super().__init__(_pair(pool_size, 3),
                         _pair(strides, 3) if strides is not None else None,
                         _pair(padding, 3), ceil_mode, False, "max", layout=layout, **kwargs)


class AvgPool1D(_Pooling):
    def __init__(self, pool_size=2, strides=None, padding=0, layout="NCW",
                 ceil_mode=False, count_include_pad=True, **kwargs):
        super().__init__(_pair(pool_size, 1),
                         _pair(strides, 1) if strides is not None else None,
                         _pair(padding, 1), ceil_mode, False, "avg",
                         count_include_pad, layout=layout, **kwargs)


class AvgPool2D(_Pooling):
    def __init__(self, pool_size=(2, 2), strides=None, padding=0,
                 layout="NCHW", ceil_mode=False, count_include_pad=True,
                 **kwargs):
        super().__init__(_pair(pool_size, 2),
                         _pair(strides, 2) if strides is not None else None,
                         _pair(padding, 2), ceil_mode, False, "avg",
                         count_include_pad, layout=layout, **kwargs)


class AvgPool3D(_Pooling):
    def __init__(self, pool_size=(2, 2, 2), strides=None, padding=0,
                 layout="NCDHW", ceil_mode=False, count_include_pad=True,
                 **kwargs):
        super().__init__(_pair(pool_size, 3),
                         _pair(strides, 3) if strides is not None else None,
                         _pair(padding, 3), ceil_mode, False, "avg",
                         count_include_pad, layout=layout, **kwargs)


class GlobalMaxPool1D(_Pooling):
    def __init__(self, layout="NCW", **kwargs):
        super().__init__((1,), None, (0,), False, True, "max", layout=layout, **kwargs)


class GlobalMaxPool2D(_Pooling):
    def __init__(self, layout="NCHW", **kwargs):
        super().__init__((1, 1), None, (0, 0), False, True, "max", layout=layout, **kwargs)


class GlobalMaxPool3D(_Pooling):
    def __init__(self, layout="NCDHW", **kwargs):
        super().__init__((1, 1, 1), None, (0, 0, 0), False, True, "max", layout=layout,
                         **kwargs)


class GlobalAvgPool1D(_Pooling):
    def __init__(self, layout="NCW", **kwargs):
        super().__init__((1,), None, (0,), False, True, "avg", layout=layout, **kwargs)


class GlobalAvgPool2D(_Pooling):
    def __init__(self, layout="NCHW", **kwargs):
        super().__init__((1, 1), None, (0, 0), False, True, "avg", layout=layout, **kwargs)


class GlobalAvgPool3D(_Pooling):
    def __init__(self, layout="NCDHW", **kwargs):
        super().__init__((1, 1, 1), None, (0, 0, 0), False, True, "avg",
                         layout=layout, **kwargs)


class ReflectionPad2D(HybridBlock):
    def __init__(self, padding=0, **kwargs):
        super().__init__(**kwargs)
        if isinstance(padding, int):
            padding = (0, 0, 0, 0, padding, padding, padding, padding)
        self._padding = padding

    def hybrid_forward(self, F, x):
        return F.Pad(x, mode="reflect", pad_width=self._padding)
