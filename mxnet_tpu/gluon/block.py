"""Gluon Block / HybridBlock / SymbolBlock.

Parity: reference `python/mxnet/gluon/block.py:124,429,653` — Block (eager),
HybridBlock (hybridize -> _build_cache -> CachedOp, block.py:480-513),
SymbolBlock (wrap a Symbol as a Block).

TPU-native redesign: `hybridize()` IS `jax.jit`. The first hybridized call
traces the block's eager forward with tracer-backed NDArrays and compiles one
XLA program per (input shapes/dtypes, train-mode) key — the shape-keyed
re-specialization of CachedOp (`src/imperative/cached_op.cc:209,263`) is
jax.jit's native cache. Parameter mutations during forward (BatchNorm
running stats) are detected at trace time and threaded functionally as extra
outputs. The compiled call is recorded on the autograd tape as a single
node, so `loss.backward()` differentiates *through the compiled program*
(jax.vjp of the jitted fn) — the analog of CachedOp::Backward
(`cached_op.cc:480`).
"""
from __future__ import annotations

import re
import threading

import numpy as np
import jax
import jax.numpy as jnp

from ..base import MXNetError
from ..ndarray import NDArray
from .. import ndarray as nd_mod
from .. import autograd
from .. import random as _random
from .parameter import Parameter, ParameterDict, DeferredInitializationError


class _BlockScope:
    _current = threading.local()

    def __init__(self, block):
        self._block = block
        self._counter = {}
        self._old_scope = None
        self._name_scope = None

    @staticmethod
    def create(prefix, params, hint):
        current = getattr(_BlockScope._current, "value", None)
        if current is None:
            if prefix is None:
                from .. import name as name_mod
                prefix = name_mod.current().get(None, hint) + "_"
            if params is None:
                params = ParameterDict(prefix)
            else:
                params = ParameterDict(params.prefix, params)
            return prefix, params
        if prefix is None:
            count = current._counter.get(hint, 0)
            prefix = "%s%d_" % (hint, count)
            current._counter[hint] = count + 1
        if params is None:
            parent = current._block.params
            params = ParameterDict(parent.prefix + prefix, parent._shared)
        else:
            params = ParameterDict(params.prefix, params)
        return current._block.prefix + prefix, params

    def __enter__(self):
        if self._block._empty_prefix:
            return self
        self._old_scope = getattr(_BlockScope._current, "value", None)
        _BlockScope._current.value = self
        from .. import name as name_mod
        self._name_scope = name_mod.Prefix(self._block.prefix)
        self._name_scope.__enter__()
        return self

    def __exit__(self, ptype, value, trace):
        if self._block._empty_prefix:
            return
        self._name_scope.__exit__(ptype, value, trace)
        self._name_scope = None
        _BlockScope._current.value = self._old_scope


class Block:
    """Base class for all layers and models (parity: gluon/block.py:124)."""

    def __init__(self, prefix=None, params=None):
        self._empty_prefix = prefix == ""
        self._prefix, self._params = _BlockScope.create(
            prefix, params, self._alias())
        self._name = self._prefix[:-1] if self._prefix.endswith("_") \
            else self._prefix
        self._scope = _BlockScope(self)
        self._children = {}
        self._reg_params = {}
        self._forward_hooks = {}
        self._forward_pre_hooks = {}

    def _alias(self):
        return self.__class__.__name__.lower()

    def __repr__(self):
        s = "{name}(\n{modstr}\n)"
        modstr = "\n".join("  ({key}): {block}".format(
            key=key, block=_indent(repr(block), 2))
            for key, block in self._children.items())
        return s.format(name=self.__class__.__name__, modstr=modstr)

    def __setattr__(self, name, value):
        if hasattr(self, name):
            existing = getattr(self, name)
            if isinstance(existing, (Parameter, Block)) and \
                    not isinstance(value, type(existing)):
                raise TypeError("Changing attribute type for {name} from "
                                "{type1} to {type2} is not allowed.".format(
                                    name=name, type1=type(existing),
                                    type2=type(value)))
        if isinstance(value, Block):
            self.register_child(value, name)
        elif isinstance(value, Parameter):
            assert name not in self._reg_params or \
                self._reg_params[name] is value, \
                "Overriding Parameter attribute %s is not allowed." % name
            self._reg_params[name] = value
        super().__setattr__(name, value)

    @property
    def prefix(self):
        return self._prefix

    @property
    def name(self):
        return self._name

    def name_scope(self):
        return self._scope

    @property
    def params(self):
        return self._params

    def collect_params(self, select=None):
        ret = ParameterDict(self._params.prefix)
        if not select:
            ret.update(self.params)
        else:
            pattern = re.compile(select)
            ret.update({name: value for name, value in self.params.items()
                        if pattern.match(name)})
        for child in self._children.values():
            ret.update(child.collect_params(select=select))
        return ret

    def register_child(self, block, name=None):
        if name is None:
            name = str(len(self._children))
        self._children[name] = block

    def register_forward_hook(self, hook):
        handle = len(self._forward_hooks)
        self._forward_hooks[handle] = hook
        return handle

    def register_forward_pre_hook(self, hook):
        handle = len(self._forward_pre_hooks)
        self._forward_pre_hooks[handle] = hook
        return handle

    def apply(self, fn):
        for child in self._children.values():
            child.apply(fn)
        fn(self)
        return self

    def initialize(self, init=None, ctx=None, verbose=False,
                   force_reinit=False):
        self.collect_params().initialize(init, ctx, verbose, force_reinit)

    def hybridize(self, active=True, **kwargs):
        for child in self._children.values():
            child.hybridize(active, **kwargs)

    def cast(self, dtype):
        for child in self._children.values():
            child.cast(dtype)
        for _, param in self.params.items():
            param.cast(dtype)

    # -- persistence (parity: save_params/load_params block.py:308,318) ----
    def save_params(self, filename):
        self.collect_params().save(filename, strip_prefix=self.prefix)

    save_parameters = save_params

    def load_params(self, filename, ctx=None, allow_missing=False,
                    ignore_extra=False):
        self.collect_params().load(filename, ctx, allow_missing, ignore_extra,
                                   self.prefix)

    load_parameters = load_params

    def __call__(self, *args):
        for hook in self._forward_pre_hooks.values():
            hook(self, args)
        out = self.forward(*args)
        for hook in self._forward_hooks.values():
            hook(self, args, out)
        return out

    def forward(self, *args):
        raise NotImplementedError

    def summary(self, *inputs):
        from ..visualization import block_summary
        return block_summary(self, *inputs)


def _indent(s_, num_spaces):
    lines = s_.split("\n")
    first = lines.pop(0)
    return first + ("\n" + " " * num_spaces).join([""] + lines) \
        if lines else first


class HybridBlock(Block):
    """Block that can be traced+compiled (parity: gluon/block.py:429)."""

    def __init__(self, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._active = False
        self._cached_fn = None
        self._flags = {}

    def hybridize(self, active=True, **kwargs):
        self._active = active
        self._flags = kwargs
        self._cached_fn = None  # invalidate compile cache
        super().hybridize(active, **kwargs)

    def cast(self, dtype):
        self._cached_fn = None
        super().cast(dtype)

    def infer_shape(self, *args):
        """Finish deferred parameter init by probing with the given inputs."""
        self._deferred_infer(args)

    def infer_type(self, *args):
        """Infer parameter dtypes from the inputs (parity: block.py
        infer_type). Dtype follows the probe inputs: run the deferred
        probe, then cast parameters whose dtype disagrees with the
        input's floating dtype."""
        self._deferred_infer(args)
        in_dtypes = {a.dtype for a in args
                     if hasattr(a, "dtype") and
                     np.issubdtype(np.dtype(a.dtype), np.floating)}
        if len(in_dtypes) == 1:
            want = next(iter(in_dtypes))
            for p in self.collect_params().values():
                if p._data is not None and \
                        np.issubdtype(np.dtype(p.dtype), np.floating) and \
                        np.dtype(p.dtype) != np.dtype(want):
                    p.cast(want)

    def _deferred_infer(self, args):
        # run one abstract forward with eval_shape to trigger deferred inits
        try:
            self.forward(*args)
        except DeferredInitializationError:
            raise

    def forward(self, x, *args):
        """Dispatch to hybrid_forward with the nd namespace + param arrays.
        A Symbol input instead traces the block into a symbolic graph
        (parity: reference HybridBlock's F-dispatch — this is what makes
        `export` and symbol-level composition work)."""
        from ..symbol import Symbol as _Symbol
        if isinstance(x, _Symbol):
            from .. import symbol as S
            return self.hybrid_forward(S, x, *args,
                                       **self._trace_param_symbols())
        try:
            params = {name: p.data() for name, p in self._reg_params.items()}
        except DeferredInitializationError:
            self._finish_deferred_init(x, *args)
            params = {name: p.data() for name, p in self._reg_params.items()}
        return self.hybrid_forward(nd_mod, x, *args, **params)

    def _finish_deferred_init(self, *args):
        """Infer missing param shapes from input shapes via the layer's
        shape rule (each layer overrides _infer_param_shapes) or eval_shape."""
        self._shape_probe(*args)
        for p in self._reg_params.values():
            if p._deferred_init:
                raise DeferredInitializationError(
                    "Could not infer shape for %s" % p.name)

    def _shape_probe(self, x, *args):
        # default: layers override; composite blocks never hit this because
        # their children handle their own params
        raise DeferredInitializationError(
            "%s has uninitialized parameters and no shape rule" % self.name)

    def hybrid_forward(self, F, x, *args, **kwargs):
        raise NotImplementedError

    # -- the jit seam -------------------------------------------------------
    def __call__(self, *args):
        if self._active:
            try:
                return self._call_cached(*args)
            except DeferredInitializationError:
                # first call with deferred params: run eagerly once to infer
                return super().__call__(*args)
        return super().__call__(*args)

    def _collect_all_params(self):
        params = self.collect_params()
        names = list(params.keys())
        return names, [params[n] for n in names]

    def _build_cache(self):
        """Compile the forward (parity: _build_cache block.py:480)."""
        names, plist = self._collect_all_params()
        for p in plist:
            if p._data is None:
                raise DeferredInitializationError(
                    "hybridize: parameter %s not initialized" % p.name)
        block = self

        def pure_fn(param_vals, input_vals, key, train):
            # rebind parameter buffers to tracers, run the eager forward,
            # harvest outputs + mutated params (functional aux threading)
            saved = [(p._data._data, p._data._entry) for p in plist]
            injected = []
            try:
                for p, v in zip(plist, param_vals):
                    p._data._data = v
                    p._data._entry = None
                    injected.append(v)
                ins = [NDArray(v) for v in input_vals]
                with autograd._RecordingStateScope(False, train), \
                        _random.trace_key_scope(key):
                    out = block.forward(*ins)
                outs = out if isinstance(out, (list, tuple)) else [out]
                out_vals = tuple(o._data for o in outs)
                updates = {}
                for i, p in enumerate(plist):
                    if p._data._data is not injected[i]:
                        updates[i] = p._data._data
                return out_vals, updates
            finally:
                for p, (d, e) in zip(plist, saved):
                    p._data._data = d
                    p._data._entry = e

        grad_idx = [i for i, p in enumerate(plist) if p.grad_req != "null"]

        def bwd_impl(tensors, nograd_snapshot, key, out_cts, train):
            """vjp of the (unjitted) trace, itself jit-compiled — the
            CachedOp::Backward program. (vjp over an already-jitted fn can't
            linearize reduce_window et al., so we vjp the raw trace.)"""
            n_in = len(tensors) - len(grad_idx)

            def g(*ts):
                ins = ts[:n_in]
                gvals = ts[n_in:]
                full = list(nograd_snapshot)
                for j, i in enumerate(grad_idx):
                    full[i] = gvals[j]
                out_vals, _ = pure_fn(tuple(full), tuple(ins), key, train)
                return out_vals

            _, vjp_fn = jax.vjp(g, *tensors)
            return vjp_fn(tuple(out_cts))

        self._cached_fn = (names, plist,
                           jax.jit(pure_fn, static_argnames=("train",)),
                           jax.jit(bwd_impl, static_argnames=("train",)),
                           grad_idx)

    def _call_cached(self, *args):
        if self._cached_fn is None:
            self._build_cache()
        names, plist, fn, bwd, grad_idx = self._cached_fn
        in_vals = tuple(a._data if isinstance(a, NDArray) else jnp.asarray(a)
                        for a in args)
        param_vals = tuple(p._data._data for p in plist)
        key = _random.next_key()
        train = autograd.is_training()
        out_vals, updates = fn(param_vals, in_vals, key, train=train)
        for i, v in updates.items():
            plist[i]._data._data = v
            plist[i]._data._version += 1
        outs = [NDArray(v) for v in out_vals]
        needs_grad = bool(grad_idx) or any(
            getattr(a, "_entry", None) is not None for a in args)
        if autograd.is_recording() and needs_grad:
            snapshot = param_vals

            def custom_backward(out_grads, input_vals, kwargs):
                gins = bwd(tuple(input_vals), snapshot, key,
                           tuple(out_grads), train=train)
                return list(gins)

            class _OpDef:
                fn = None
                differentiable = True
                name = "CachedOp"

            # keep positions aligned with `vals`: non-NDArray args contribute
            # a None parent entry but still occupy a cotangent slot
            nd_inputs = list(args) + [plist[i]._data for i in grad_idx]
            vals = list(in_vals) + [param_vals[i] for i in grad_idx]
            autograd.record_op(_OpDef, nd_inputs, vals, outs, {},
                               custom_backward=custom_backward)
        if len(outs) == 1:
            return outs[0]
        return tuple(outs)

    def _trace_param_symbols(self):
        """Parameter Variables for a symbolic trace: known shapes travel as
        hints (only when fully concrete — deferred shapes contain 0 and
        must leave bind-time inference in charge), layer-mutated states
        carry the aux flag."""
        from .. import symbol as S
        params = {}
        for name, p in self._reg_params.items():
            shape = p.shape if p.shape and all(d > 0 for d in p.shape) \
                else None
            v = S.Variable(p.name, shape=shape)
            if getattr(p, "_is_aux", False):
                v._outputs[0][0].is_aux = True
            params[name] = v
        return params

    def export(self, path, epoch=0, inputs=("data",)):
        """Write `path-symbol.json` + `path-%04d.params` (parity:
        HybridBlock.export) — the train-in-Gluon, deploy-symbolically flow.
        The graph comes from tracing this block with Symbol inputs (pass
        `inputs` names for multi-input blocks); params save under
        'arg:'/'aux:' keys with their raw names, so
        `mx.model.load_checkpoint` + Module (or SymbolBlock.imports) load
        the artifact directly."""
        from .. import symbol as S
        from ..utils import serialization
        if isinstance(inputs, str):
            inputs = (inputs,)
        # Symbols cannot enter the jit cache: trace through plain forward,
        # temporarily deactivating hybridize() across the whole tree
        toggled = []

        def _deactivate(b):
            if getattr(b, "_active", False):
                b._active = False
                toggled.append(b)

        self.apply(_deactivate)
        try:
            out = self(*[S.Variable(n) for n in inputs])
        except TypeError as e:
            raise TypeError(
                "export could not trace %s with inputs %s — pass the "
                "block's input names via export(..., inputs=(...)): %s"
                % (self.name, list(inputs), e)) from None
        finally:
            for b in toggled:
                b._active = True
        if isinstance(out, (list, tuple)):
            out = S.Group(list(out))
        out.save("%s-symbol.json" % path)
        save_dict = {}
        for name, p in self.collect_params().items():
            kind = "aux" if getattr(p, "_is_aux", False) else "arg"
            save_dict["%s:%s" % (kind, name)] = p.data()
        serialization.save_ndarrays("%s-%04d.params" % (path, epoch),
                                    save_dict)


class SymbolBlock(HybridBlock):
    """Wrap a Symbol + params into a Block (parity: gluon/block.py:653)."""

    def __init__(self, outputs, inputs, params=None):
        super().__init__(prefix=None, params=params)
        from ..symbol import Symbol, Group
        if isinstance(outputs, (list, tuple)):
            outputs = Group(outputs)
        if isinstance(inputs, Symbol):
            inputs = [inputs]
        self._symbol = outputs
        self._input_names = [i.name for i in inputs]
        arg_names = outputs.list_arguments()
        aux_names = outputs.list_auxiliary_states()
        # params carry the block prefix; the symbol wants its raw arg names
        self._sym_name_of = {}
        for name in arg_names + aux_names:
            if name not in self._input_names:
                p = self.params.get(name, allow_deferred_init=True,
                                    grad_req="null" if name in aux_names
                                    else "write")
                self._sym_name_of[p.name] = name
        # static per-block metadata used on every forward (hot path)
        self._param_of_sym = {s: p for p, s in self._sym_name_of.items()}
        self._aux_names = aux_names
        self._n_out = len(outputs.list_outputs())

    def forward(self, *args):
        if len(args) != len(self._input_names):
            raise MXNetError(
                "SymbolBlock expects %d inputs (%s), got %d"
                % (len(self._input_names), self._input_names, len(args)))
        if any(p._data is None and p._deferred_init
               for p in self.params.values()):
            self._finish_symbol_deferred_init(args)
        names = list(self._input_names)
        tensors = [a if isinstance(a, NDArray) else NDArray(a)
                   for a in args]
        aux_params = []
        for name, p in self.params.items():
            if p._data is not None:
                names.append(self._sym_name_of.get(p.name, name))
                tensors.append(p.data())
        train = autograd.is_training()
        symbol = self._symbol
        aux_names = self._aux_names if train else []
        if aux_names:
            pd = self.params
            aux_params = [pd[self._param_of_sym[n]]
                          if self._param_of_sym.get(n) in pd else None
                          for n in aux_names]

        def eval_fn(*vals):
            d = dict(zip(names, vals))
            outs, aux_upd = symbol._eval(d, train=train)
            # thread updated aux states (BatchNorm moving stats) out as
            # extra outputs so the block can write them back — fixed arity:
            # unchanged aux pass through
            outs = tuple(outs) + tuple(aux_upd.get(n, d[n])
                                       for n in aux_names)
            return outs if len(outs) > 1 else outs[0]

        # route through the op machinery so the evaluation lands on the
        # autograd tape (gradients flow to params like any gluon block);
        # stochastic=True threads ONE rng key through forward AND its vjp
        # replay, keeping dropout masks consistent with the forward pass
        n_out = self._n_out
        res = nd_mod._apply_op(
            nd_mod._AdhocOp(eval_fn, "symbol_block", stochastic=True,
                            num_outputs=n_out + len(aux_names)),
            tuple(tensors), {})
        if not isinstance(res, tuple):
            return res
        outs, aux_new = res[:n_out], res[n_out:]
        for p, v in zip(aux_params, aux_new):
            if p is not None:
                p.set_data(v)
        return outs[0] if n_out == 1 else list(outs)

    def _finish_symbol_deferred_init(self, args):
        """Infer deferred param shapes from input shapes via the symbol's
        shape inference (parity: SymbolBlock's deferred init through
        infer_shape, gluon/block.py:653 area)."""
        in_shapes = {n: a.shape for n, a in zip(self._input_names, args)}
        arg_shapes, _, aux_shapes = self._symbol.infer_shape(**in_shapes)
        shape_of = dict(zip(self._symbol.list_arguments(), arg_shapes))
        shape_of.update(zip(self._symbol.list_auxiliary_states(),
                            aux_shapes))
        for name, p in self.params.items():
            sname = self._sym_name_of.get(p.name, name)
            if p._data is None and p._deferred_init and sname in shape_of:
                p._finish_deferred_init(shape_of[sname])

    @staticmethod
    def imports(symbol_file, input_names, param_file=None, ctx=None):
        from .. import symbol as sym_mod
        s = sym_mod.load(symbol_file)
        inputs = [sym_mod.Variable(n) for n in
                  ([input_names] if isinstance(input_names, str)
                   else input_names)]
        block = SymbolBlock(s, inputs)
        if param_file:
            from ..utils import serialization
            raw = serialization.load_ndarrays(param_file)
            # accept both Module-style 'arg:/aux:' keys and plain names;
            # map the file's raw symbol names onto the block's prefixed
            # params (see _sym_name_of)
            raw = {k.split(":", 1)[-1]: v for k, v in raw.items()}
            by_sym = block._param_of_sym
            params = block.collect_params()
            for sname, arr in raw.items():
                pname = by_sym.get(sname)
                if pname is not None and pname in params:
                    if ctx is not None:
                        arr = arr.as_in_context(
                            ctx[0] if isinstance(ctx, (list, tuple)) else ctx)
                    params[pname].set_data(arr)
        return block
