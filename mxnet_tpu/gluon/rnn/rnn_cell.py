"""RNN cells.

Parity: reference `python/mxnet/gluon/rnn/rnn_cell.py` — RecurrentCell base
(begin_state/unroll), RNNCell, LSTMCell, GRUCell, SequentialRNNCell,
DropoutCell, ZoneoutCell, ResidualCell, BidirectionalCell.

Gate order i,f,g,o for LSTM and r,z,n for GRU matches the reference cells so
parameters transliterate.
"""
from __future__ import annotations

from ..block import HybridBlock
from ..parameter import Parameter, DeferredInitializationError
from ... import ndarray as nd_mod


class RecurrentCell(HybridBlock):
    def __init__(self, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._modified = False
        self.reset()

    def reset(self):
        self._init_counter = -1
        self._counter = -1
        for cell in self._children.values():
            if isinstance(cell, RecurrentCell):
                cell.reset()

    def state_info(self, batch_size=0):
        raise NotImplementedError()

    def begin_state(self, batch_size=0, func=None, **kwargs):
        assert not self._modified, \
            "After applying modifier cells the base cell cannot be called " \
            "directly. Call the modifier cell instead."
        if func is None:
            func = nd_mod.zeros
        states = []
        for info in self.state_info(batch_size):
            self._init_counter += 1
            if info is not None:
                info.update(kwargs)
            else:
                info = kwargs
            state = func(**{k: v for k, v in info.items()
                            if k != "__layout__"})
            states.append(state)
        return states

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None, valid_length=None):
        self.reset()
        inputs, axis, batch_size = _format_sequence(length, inputs, layout)
        if begin_state is None:
            # keyword, not positional: ModifierCell.begin_state's first
            # parameter is `func` (reference signature), not batch_size
            begin_state = self.begin_state(batch_size=batch_size)
        states = begin_state
        outputs = []
        for i in range(length):
            output, states = self(inputs[i], states)
            outputs.append(output)
        if valid_length is not None:
            from ... import ndarray as F
            stacked = F.stack(*outputs, axis=0)
            masked = F.SequenceMask(stacked, sequence_length=valid_length,
                                    use_sequence_length=True, value=0)
            outputs = [masked[i] for i in range(length)]
        outputs = _merge_outputs(outputs, axis if merge_outputs in (True, None)
                                 else None, merge_outputs)
        return outputs, states

    def _get_activation(self, F, inputs, activation, **kwargs):
        if isinstance(activation, str):
            return F.Activation(inputs, act_type=activation, **kwargs)
        return activation(inputs, **kwargs)

    def __call__(self, inputs, states):
        self._counter += 1
        return super().__call__(inputs, states)

    def forward(self, inputs, states):
        try:
            params = {name: p.data() for name, p in self._reg_params.items()}
        except DeferredInitializationError:
            # first call with deferred input_size: probe shapes from the
            # input like HybridBlock.forward does (cells define _shape_probe)
            self._finish_deferred_init(inputs, states)
            params = {name: p.data() for name, p in self._reg_params.items()}
        return self.hybrid_forward(nd_mod, inputs, states, **params)


class HybridRecurrentCell(RecurrentCell):
    def hybrid_forward(self, F, x, *args, **kwargs):
        raise NotImplementedError


def _fn_kwargs(fn):
    import inspect
    try:
        return inspect.signature(fn).parameters
    except (TypeError, ValueError):
        return {}


def _format_sequence(length, inputs, layout, merge=None):
    from ...ndarray import NDArray
    from ... import ndarray as F
    axis = layout.find("T")
    if isinstance(inputs, NDArray):
        batch_size = inputs.shape[layout.find("N")]
        split = F.SliceChannel(inputs, num_outputs=length, axis=axis,
                               squeeze_axis=True)
        inputs = [split] if length == 1 else list(split)
    else:
        batch_size = inputs[0].shape[0]
    return inputs, axis, batch_size


def _merge_outputs(outputs, axis, merge):
    from ... import ndarray as F
    if merge is False:
        return outputs
    return F.stack(*outputs, axis=axis if axis is not None else 0)


class RNNCell(HybridRecurrentCell):
    def __init__(self, hidden_size, activation="tanh",
                 i2h_weight_initializer=None, h2h_weight_initializer=None,
                 i2h_bias_initializer="zeros", h2h_bias_initializer="zeros",
                 input_size=0, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._hidden_size = hidden_size
        self._activation = activation
        self._input_size = input_size
        self.i2h_weight = self.params.get(
            "i2h_weight", shape=(hidden_size, input_size),
            init=i2h_weight_initializer, allow_deferred_init=True)
        self.h2h_weight = self.params.get(
            "h2h_weight", shape=(hidden_size, hidden_size),
            init=h2h_weight_initializer, allow_deferred_init=True)
        self.i2h_bias = self.params.get(
            "i2h_bias", shape=(hidden_size,), init=_b(i2h_bias_initializer),
            allow_deferred_init=True)
        self.h2h_bias = self.params.get(
            "h2h_bias", shape=(hidden_size,), init=_b(h2h_bias_initializer),
            allow_deferred_init=True)

    def state_info(self, batch_size=0):
        return [{"shape": (batch_size, self._hidden_size), "__layout__": "NC"}]

    def _alias(self):
        return "rnn"

    def _shape_probe(self, x, *args):
        self.i2h_weight.shape = (self._hidden_size, x.shape[-1])
        for p in (self.i2h_weight, self.h2h_weight, self.i2h_bias,
                  self.h2h_bias):
            if p._deferred_init:
                p._finish_deferred_init(p.shape)

    def hybrid_forward(self, F, inputs, states, i2h_weight, h2h_weight,
                       i2h_bias, h2h_bias):
        i2h = F.FullyConnected(inputs, i2h_weight, i2h_bias,
                               num_hidden=self._hidden_size)
        h2h = F.FullyConnected(states[0], h2h_weight, h2h_bias,
                               num_hidden=self._hidden_size)
        output = self._get_activation(F, i2h + h2h, self._activation)
        return output, [output]


class LSTMCell(HybridRecurrentCell):
    def __init__(self, hidden_size, i2h_weight_initializer=None,
                 h2h_weight_initializer=None, i2h_bias_initializer="zeros",
                 h2h_bias_initializer="zeros", input_size=0, prefix=None,
                 params=None):
        super().__init__(prefix=prefix, params=params)
        self._hidden_size = hidden_size
        self._input_size = input_size
        self.i2h_weight = self.params.get(
            "i2h_weight", shape=(4 * hidden_size, input_size),
            init=i2h_weight_initializer, allow_deferred_init=True)
        self.h2h_weight = self.params.get(
            "h2h_weight", shape=(4 * hidden_size, hidden_size),
            init=h2h_weight_initializer, allow_deferred_init=True)
        self.i2h_bias = self.params.get(
            "i2h_bias", shape=(4 * hidden_size,),
            init=_b(i2h_bias_initializer), allow_deferred_init=True)
        self.h2h_bias = self.params.get(
            "h2h_bias", shape=(4 * hidden_size,),
            init=_b(h2h_bias_initializer), allow_deferred_init=True)

    def state_info(self, batch_size=0):
        return [{"shape": (batch_size, self._hidden_size), "__layout__": "NC"},
                {"shape": (batch_size, self._hidden_size), "__layout__": "NC"}]

    def _alias(self):
        return "lstm"

    def _shape_probe(self, x, *args):
        self.i2h_weight.shape = (4 * self._hidden_size, x.shape[-1])
        for p in (self.i2h_weight, self.h2h_weight, self.i2h_bias,
                  self.h2h_bias):
            if p._deferred_init:
                p._finish_deferred_init(p.shape)

    def hybrid_forward(self, F, inputs, states, i2h_weight, h2h_weight,
                       i2h_bias, h2h_bias):
        i2h = F.FullyConnected(inputs, i2h_weight, i2h_bias,
                               num_hidden=4 * self._hidden_size)
        h2h = F.FullyConnected(states[0], h2h_weight, h2h_bias,
                               num_hidden=4 * self._hidden_size)
        gates = i2h + h2h
        in_gate, forget_gate, in_trans, out_gate = F.SliceChannel(
            gates, num_outputs=4, axis=1)
        in_gate = F.sigmoid(in_gate)
        forget_gate = F.sigmoid(forget_gate)
        in_trans = F.tanh(in_trans)
        out_gate = F.sigmoid(out_gate)
        next_c = forget_gate * states[1] + in_gate * in_trans
        next_h = out_gate * F.tanh(next_c)
        return next_h, [next_h, next_c]


class GRUCell(HybridRecurrentCell):
    def __init__(self, hidden_size, i2h_weight_initializer=None,
                 h2h_weight_initializer=None, i2h_bias_initializer="zeros",
                 h2h_bias_initializer="zeros", input_size=0, prefix=None,
                 params=None):
        super().__init__(prefix=prefix, params=params)
        self._hidden_size = hidden_size
        self._input_size = input_size
        self.i2h_weight = self.params.get(
            "i2h_weight", shape=(3 * hidden_size, input_size),
            init=i2h_weight_initializer, allow_deferred_init=True)
        self.h2h_weight = self.params.get(
            "h2h_weight", shape=(3 * hidden_size, hidden_size),
            init=h2h_weight_initializer, allow_deferred_init=True)
        self.i2h_bias = self.params.get(
            "i2h_bias", shape=(3 * hidden_size,),
            init=_b(i2h_bias_initializer), allow_deferred_init=True)
        self.h2h_bias = self.params.get(
            "h2h_bias", shape=(3 * hidden_size,),
            init=_b(h2h_bias_initializer), allow_deferred_init=True)

    def state_info(self, batch_size=0):
        return [{"shape": (batch_size, self._hidden_size), "__layout__": "NC"}]

    def _alias(self):
        return "gru"

    def _shape_probe(self, x, *args):
        self.i2h_weight.shape = (3 * self._hidden_size, x.shape[-1])
        for p in (self.i2h_weight, self.h2h_weight, self.i2h_bias,
                  self.h2h_bias):
            if p._deferred_init:
                p._finish_deferred_init(p.shape)

    def hybrid_forward(self, F, inputs, states, i2h_weight, h2h_weight,
                       i2h_bias, h2h_bias):
        prev_state_h = states[0]
        i2h = F.FullyConnected(inputs, i2h_weight, i2h_bias,
                               num_hidden=3 * self._hidden_size)
        h2h = F.FullyConnected(prev_state_h, h2h_weight, h2h_bias,
                               num_hidden=3 * self._hidden_size)
        i2h_r, i2h_z, i2h_n = F.SliceChannel(i2h, num_outputs=3, axis=1)
        h2h_r, h2h_z, h2h_n = F.SliceChannel(h2h, num_outputs=3, axis=1)
        reset_gate = F.sigmoid(i2h_r + h2h_r)
        update_gate = F.sigmoid(i2h_z + h2h_z)
        next_h_tmp = F.tanh(i2h_n + reset_gate * h2h_n)
        next_h = (1. - update_gate) * next_h_tmp + update_gate * prev_state_h
        return next_h, [next_h]


class SequentialRNNCell(RecurrentCell):
    def __init__(self, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)

    def add(self, cell):
        self.register_child(cell)

    def state_info(self, batch_size=0):
        return _cells_state_info(self._children.values(), batch_size)

    def begin_state(self, batch_size=0, **kwargs):
        assert not self._modified
        return _cells_begin_state(self._children.values(), batch_size,
                                  **kwargs)

    def __call__(self, inputs, states):
        self._counter += 1
        next_states = []
        p = 0
        for cell in self._children.values():
            assert not isinstance(cell, BidirectionalCell)
            n = len(cell.state_info())
            state = states[p:p + n]
            p += n
            inputs, state = cell(inputs, state)
            next_states.append(state)
        return inputs, sum(next_states, [])

    def __len__(self):
        return len(self._children)

    def __getitem__(self, i):
        return list(self._children.values())[i]

    def forward(self, *args):
        raise NotImplementedError


def _cells_state_info(cells, batch_size):
    return sum([c.state_info(batch_size) for c in cells], [])


def _cells_begin_state(cells, batch_size, **kwargs):
    return sum([c.begin_state(batch_size=batch_size, **kwargs)
                for c in cells], [])


class DropoutCell(HybridRecurrentCell):
    def __init__(self, rate, axes=(), prefix=None, params=None):
        super().__init__(prefix, params)
        assert isinstance(rate, float)
        self._rate = rate
        self._axes = axes

    def state_info(self, batch_size=0):
        return []

    def _alias(self):
        return "dropout"

    def hybrid_forward(self, F, inputs, states):
        if self._rate > 0:
            inputs = F.Dropout(inputs, p=self._rate, axes=self._axes)
        return inputs, states


class ModifierCell(HybridRecurrentCell):
    def __init__(self, base_cell):
        super().__init__(prefix=None, params=None)
        base_cell._modified = True
        self.base_cell = base_cell

    @property
    def params(self):
        return self.base_cell.params

    def state_info(self, batch_size=0):
        return self.base_cell.state_info(batch_size)

    def begin_state(self, func=None, **kwargs):
        assert not self._modified
        self.base_cell._modified = False
        begin = self.base_cell.begin_state(func=func, **kwargs) \
            if func is not None else self.base_cell.begin_state(**kwargs)
        self.base_cell._modified = True
        return begin


class ZoneoutCell(ModifierCell):
    def __init__(self, base_cell, zoneout_outputs=0., zoneout_states=0.):
        assert not isinstance(base_cell, BidirectionalCell)
        super().__init__(base_cell)
        self.zoneout_outputs = zoneout_outputs
        self.zoneout_states = zoneout_states
        self._prev_output = None

    def _alias(self):
        return "zoneout"

    def reset(self):
        super().reset()
        self._prev_output = None

    def hybrid_forward(self, F, inputs, states):
        cell = self.base_cell
        next_output, next_states = cell(inputs, states)
        mask = lambda p, like: F.Dropout(F.ones_like(like), p=p)
        prev_output = self._prev_output if self._prev_output is not None \
            else F.zeros_like(next_output)
        output = F.where(mask(self.zoneout_outputs, next_output),
                         next_output, prev_output) \
            if self.zoneout_outputs > 0. else next_output
        new_states = [F.where(mask(self.zoneout_states, new_s), new_s, old_s)
                      for new_s, old_s in zip(next_states, states)] \
            if self.zoneout_states > 0. else next_states
        self._prev_output = output
        return output, new_states


class ResidualCell(ModifierCell):
    def __init__(self, base_cell):
        super().__init__(base_cell)

    def hybrid_forward(self, F, inputs, states):
        output, states = self.base_cell(inputs, states)
        output = output + inputs
        return output, states


class BidirectionalCell(HybridRecurrentCell):
    def __init__(self, l_cell, r_cell, output_prefix="bi_"):
        super().__init__(prefix="", params=None)
        self.register_child(l_cell, "l_cell")
        self.register_child(r_cell, "r_cell")
        self._output_prefix = output_prefix

    def __call__(self, inputs, states):
        raise NotImplementedError(
            "Bidirectional cannot be stepped. Please use unroll")

    def state_info(self, batch_size=0):
        return _cells_state_info(self._children.values(), batch_size)

    def begin_state(self, **kwargs):
        assert not self._modified
        return _cells_begin_state(self._children.values(), **kwargs)

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None, valid_length=None):
        from ... import ndarray as F
        self.reset()
        inputs, axis, batch_size = _format_sequence(length, inputs, layout)
        if begin_state is None:
            begin_state = self.begin_state(batch_size=batch_size)
        states = begin_state
        l_cell, r_cell = self._children.values()
        l_outputs, l_states = l_cell.unroll(
            length, inputs=inputs,
            begin_state=states[:len(l_cell.state_info(batch_size))],
            layout=layout, merge_outputs=False, valid_length=valid_length)
        rev_inputs = list(reversed(inputs))
        r_outputs, r_states = r_cell.unroll(
            length, inputs=rev_inputs,
            begin_state=states[len(l_cell.state_info(batch_size)):],
            layout=layout, merge_outputs=False, valid_length=valid_length)
        r_outputs = list(reversed(r_outputs))
        outputs = [F.Concat(l_o, r_o, dim=1)
                   for l_o, r_o in zip(l_outputs, r_outputs)]
        outputs = _merge_outputs(outputs, axis, merge_outputs)
        states = l_states + r_states
        return outputs, states


def _b(spec):
    if spec is None or not isinstance(spec, str):
        return spec
    from ... import initializer
    return initializer.create(spec)
