"""Fused recurrent layers (RNN/LSTM/GRU).

Parity: reference `python/mxnet/gluon/rnn/rnn_layer.py` — multi-layer
(bi)directional layers backed by the fused RNN op (`src/operator/rnn-inl.h`,
cuDNN path `cudnn_rnn-inl.h`).

TPU-native redesign: the fused op is a lax.scan (ops/nn.py RNN); under
hybridize the whole stack compiles to one XLA while-loop program. Parameters
are kept per-layer/direction/gate (i2h/h2h weight+bias) with reference-
compatible names, packed into the flat vector at call time.
"""
from __future__ import annotations

import numpy as np
import jax.numpy as jnp

from ..block import HybridBlock
from ...ndarray import NDArray
from ... import ndarray as F_nd


class _RNNLayer(HybridBlock):
    def __init__(self, hidden_size, num_layers, layout, dropout,
                 bidirectional, input_size, i2h_weight_initializer,
                 h2h_weight_initializer, i2h_bias_initializer,
                 h2h_bias_initializer, mode, fused=None, **kwargs):
        self._mode = mode  # before super(): _alias() needs it
        super().__init__(**kwargs)
        assert layout in ("TNC", "NTC"), \
            "Invalid layout %s; must be one of ['TNC' or 'NTC']" % layout
        self._hidden_size = hidden_size
        self._num_layers = num_layers
        self._layout = layout
        self._dropout = dropout
        # None = honor MXNET_FUSED_RNN at trace time; True/False pin the
        # persistent Pallas scan kernel (ops/pallas_rnn.py) per layer
        self._fused = fused
        self._dir = 2 if bidirectional else 1
        self._input_size = input_size
        self._gates = {"rnn_relu": 1, "rnn_tanh": 1, "lstm": 4, "gru": 3}[mode]
        ng, ni, nh = self._gates, input_size, hidden_size
        for i in range(num_layers):
            for j in ["l", "r"][:self._dir]:
                self._register_param("%s%d_i2h_weight" % (j, i),
                                     shape=(ng * nh, ni),
                                     init=i2h_weight_initializer)
                self._register_param("%s%d_h2h_weight" % (j, i),
                                     shape=(ng * nh, nh),
                                     init=h2h_weight_initializer)
                self._register_param("%s%d_i2h_bias" % (j, i),
                                     shape=(ng * nh,),
                                     init=i2h_bias_initializer)
                self._register_param("%s%d_h2h_bias" % (j, i),
                                     shape=(ng * nh,),
                                     init=h2h_bias_initializer)
            ni = nh * self._dir

    def _register_param(self, name, shape, init):
        from ..nn.basic_layers import _init
        p = self.params.get(name, shape=shape, init=_init(init),
                            allow_deferred_init=True)
        setattr(self, name, p)

    def _alias(self):
        return self._mode

    def state_info(self, batch_size=0):
        raise NotImplementedError

    def _shape_probe(self, x, *args):
        ni = x.shape[-1]
        ng, nh = self._gates, self._hidden_size
        for i in range(self._num_layers):
            for j in ["l", "r"][:self._dir]:
                getattr(self, "%s%d_i2h_weight" % (j, i)).shape = (ng * nh, ni)
            ni = nh * self._dir
        for p in self._reg_params.values():
            if p._deferred_init:
                p._finish_deferred_init(p.shape)

    def begin_state(self, batch_size=0, func=None, **kwargs):
        if func is None:
            func = F_nd.zeros
        states = []
        for info in self.state_info(batch_size):
            states.append(func(**{k: v for k, v in info.items()
                                  if k != "__layout__"}))
        return states

    def _pack_params(self, params, F=None):
        """Flatten per-gate params into the fused-op vector (layout documented
        in ops/nn.py _unpack_rnn_params). F picks the namespace: nd (default)
        or symbol for export tracing."""
        if F is None:
            from ... import ndarray as F
        chunks = []
        for i in range(self._num_layers):
            for j in ["l", "r"][:self._dir]:
                for part in ("i2h_weight", "h2h_weight", "i2h_bias",
                             "h2h_bias"):
                    chunks.append(F.Reshape(
                        params["%s%d_%s" % (j, i, part)], shape=(-1,)))
        return F.Concat(*chunks, dim=0)

    def _symbolic_forward(self, inputs, in_states=None):
        """Trace into a Symbol graph (export path)."""
        from ... import symbol as S
        params = self._trace_param_symbols()
        x = S.swapaxes(inputs, dim1=0, dim2=1) if self._layout == "NTC" \
            else inputs
        if in_states is None:
            # begin states as AUX variables: the executor allocates them as
            # zeros and init_params never touches them (a free arg variable
            # would get randomly initialized by Module.init_params, silently
            # perturbing the exported model's outputs)
            n_states = 2 if self._mode == "lstm" else 1
            states = []
            for nm in ("state", "state_cell")[:n_states]:
                v = S.Variable("%s%s" % (self.prefix, nm))
                v._outputs[0][0].is_aux = True
                states.append(v)
        else:
            states = list(in_states)
        rnn = S.RNN(x, self._pack_params(params, F=S), *states,
                    state_size=self._hidden_size,
                    num_layers=self._num_layers, mode=self._mode,
                    bidirectional=self._dir == 2, p=self._dropout,
                    state_outputs=in_states is not None,
                    **({} if self._fused is None
                       else {"fused": self._fused}))
        if in_states is not None:
            out = rnn[0]
            out_states = [rnn[i] for i in range(1, len(states) + 1)]
        else:
            out, out_states = rnn, None
        if self._layout == "NTC":
            out = S.swapaxes(out, dim1=0, dim2=1)
        # shape parity with the eager path: states passed -> both returned
        return out if out_states is None else (out, out_states)

    def forward(self, inputs, states=None):
        from ...symbol import Symbol as _Symbol
        if isinstance(inputs, _Symbol):
            return self._symbolic_forward(inputs, states)
        try:
            params = {name: p.data() for name, p in self._reg_params.items()}
        except Exception:
            self._finish_deferred_init(
                inputs if self._layout == "TNC" else inputs)
            params = {name: p.data() for name, p in self._reg_params.items()}
        batch_size = inputs.shape[self._layout.find("N")]
        skip_states = states is None
        if skip_states:
            states = self.begin_state(batch_size)
        if isinstance(states, NDArray):
            states = [states]
        out = self.hybrid_forward(F_nd, inputs, states, **params)
        return out[0] if skip_states else out

    def hybrid_forward(self, F, inputs, states, **params):
        if self._layout == "NTC":
            inputs = F.swapaxes(inputs, dim1=0, dim2=1)
        flat = self._pack_params(params)
        rnn_args = [inputs, flat, states[0]]
        if self._mode == "lstm":
            rnn_args.append(states[1])
        ret = F.RNN(*rnn_args, state_size=self._hidden_size,
                    num_layers=self._num_layers, mode=self._mode,
                    bidirectional=self._dir == 2, p=self._dropout,
                    state_outputs=True,
                    **({} if self._fused is None
                       else {"fused": self._fused}))
        if self._mode == "lstm":
            outputs, state_h, state_c = ret
            out_states = [state_h, state_c]
        else:
            outputs, state_h = ret
            out_states = [state_h]
        if self._layout == "NTC":
            outputs = F.swapaxes(outputs, dim1=0, dim2=1)
        return outputs, out_states


class RNN(_RNNLayer):
    """Parity: rnn_layer.py RNN (modes rnn_relu/rnn_tanh)."""

    def __init__(self, hidden_size, num_layers=1, activation="relu",
                 layout="TNC", dropout=0, bidirectional=False,
                 i2h_weight_initializer=None, h2h_weight_initializer=None,
                 i2h_bias_initializer="zeros", h2h_bias_initializer="zeros",
                 input_size=0, fused=None, **kwargs):
        super().__init__(hidden_size, num_layers, layout, dropout,
                         bidirectional, input_size, i2h_weight_initializer,
                         h2h_weight_initializer, i2h_bias_initializer,
                         h2h_bias_initializer, "rnn_" + activation,
                         fused=fused, **kwargs)

    def state_info(self, batch_size=0):
        return [{"shape": (self._num_layers * self._dir, batch_size,
                           self._hidden_size), "__layout__": "LNC"}]


class LSTM(_RNNLayer):
    def __init__(self, hidden_size, num_layers=1, layout="TNC", dropout=0,
                 bidirectional=False, input_size=0,
                 i2h_weight_initializer=None, h2h_weight_initializer=None,
                 i2h_bias_initializer="zeros", h2h_bias_initializer="zeros",
                 fused=None, **kwargs):
        super().__init__(hidden_size, num_layers, layout, dropout,
                         bidirectional, input_size, i2h_weight_initializer,
                         h2h_weight_initializer, i2h_bias_initializer,
                         h2h_bias_initializer, "lstm", fused=fused, **kwargs)

    def state_info(self, batch_size=0):
        return [{"shape": (self._num_layers * self._dir, batch_size,
                           self._hidden_size), "__layout__": "LNC"},
                {"shape": (self._num_layers * self._dir, batch_size,
                           self._hidden_size), "__layout__": "LNC"}]


class GRU(_RNNLayer):
    def __init__(self, hidden_size, num_layers=1, layout="TNC", dropout=0,
                 bidirectional=False, input_size=0,
                 i2h_weight_initializer=None, h2h_weight_initializer=None,
                 i2h_bias_initializer="zeros", h2h_bias_initializer="zeros",
                 fused=None, **kwargs):
        # gru currently always falls back to the scan path (the hidden
        # bias feeds the reset-gate product); the kwarg is accepted so the
        # gate decision stays in one place (ops/pallas_rnn.fused_eligible)
        super().__init__(hidden_size, num_layers, layout, dropout,
                         bidirectional, input_size, i2h_weight_initializer,
                         h2h_weight_initializer, i2h_bias_initializer,
                         h2h_bias_initializer, "gru", fused=fused, **kwargs)

    def state_info(self, batch_size=0):
        return [{"shape": (self._num_layers * self._dir, batch_size,
                           self._hidden_size), "__layout__": "LNC"}]
