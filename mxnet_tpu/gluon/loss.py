"""Gluon loss API.

Redesigned rather than ported: every "elementwise residual, mean over
non-batch axes" loss plugs a single `_residual` hook into one shared
scale-and-reduce pipeline in `_MatchedLoss`, instead of repeating the
reshape/weight/mean boilerplate per class the way the reference does.
The binary cross-entropy on logits uses the softplus identity
``softplus(z) - z*y`` (one stable call) rather than the three-term
``relu(z) - z*y + softplus(-|z|)`` expansion.

Parity (class and argument surface only): reference
`python/mxnet/gluon/loss.py:66-666` — Loss, L2Loss, L1Loss,
SigmoidBinaryCrossEntropyLoss, SoftmaxCrossEntropyLoss, KLDivLoss,
CTCLoss:398, HuberLoss, HingeLoss, SquaredHingeLoss, LogisticLoss,
TripletLoss:666, PoissonNLLLoss. Numerics are pinned independently by
torch oracles in tests/test_loss.py and tests/test_torch_oracle.py.
"""
from __future__ import annotations

from .block import HybridBlock


def _like(x, ref):
    """View `x` with `ref`'s geometry (labels arrive rank-deficient)."""
    return x.reshape(ref.shape)


def _scaled(F, loss, const_weight, sample_weight):
    """Fold the constructor weight and per-sample weights into `loss`."""
    if sample_weight is not None:
        loss = F.broadcast_mul(loss, sample_weight)
    return loss if const_weight is None else loss * const_weight


def _logit_bce(F, z, y):
    """Stable binary cross-entropy on logits: softplus(z) - z*y."""
    return F.softrelu(z) - z * y


class Loss(HybridBlock):
    """Holds (weight, batch_axis) and the shared scale/reduce plumbing."""

    def __init__(self, weight, batch_axis, **kwargs):
        super().__init__(**kwargs)
        self._weight = weight
        self._batch_axis = batch_axis

    def __repr__(self):
        return "%s(batch_axis=%s, w=%s)" % (
            type(self).__name__, self._batch_axis, self._weight)

    def _finish(self, F, loss, sample_weight):
        loss = _scaled(F, loss, self._weight, sample_weight)
        return F.mean(loss, axis=self._batch_axis, exclude=True)

    def hybrid_forward(self, F, x, *args, **kwargs):
        raise NotImplementedError


class _MatchedLoss(Loss):
    """mean_over_non_batch(residual(pred, label_reshaped_like_pred))."""

    def _residual(self, F, pred, label):
        raise NotImplementedError

    def hybrid_forward(self, F, pred, label, sample_weight=None):
        return self._finish(
            F, self._residual(F, pred, _like(label, pred)), sample_weight)


class L2Loss(_MatchedLoss):
    def __init__(self, weight=1., batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)

    def _residual(self, F, pred, label):
        return 0.5 * F.square(pred - label)


class L1Loss(_MatchedLoss):
    def __init__(self, weight=None, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)

    def _residual(self, F, pred, label):
        return F.abs(pred - label)


class SigmoidBinaryCrossEntropyLoss(_MatchedLoss):
    def __init__(self, from_sigmoid=False, weight=None, batch_axis=0,
                 **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._from_sigmoid = from_sigmoid

    def _residual(self, F, pred, label):
        if self._from_sigmoid:
            eps = 1e-12
            return -label * F.log(pred + eps) \
                - (1. - label) * F.log(1. - pred + eps)
        return _logit_bce(F, pred, label)


SigmoidBCELoss = SigmoidBinaryCrossEntropyLoss


class SoftmaxCrossEntropyLoss(Loss):
    def __init__(self, axis=-1, sparse_label=True, from_logits=False,
                 weight=None, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._axis = axis
        self._sparse_label = sparse_label
        self._from_logits = from_logits

    def hybrid_forward(self, F, pred, label, sample_weight=None):
        logp = pred if self._from_logits \
            else F.log_softmax(pred, axis=self._axis)
        if self._sparse_label:
            nll = -F.pick(logp, label, axis=self._axis, keepdims=True)
        else:
            nll = -F.sum(logp * _like(label, logp), axis=self._axis,
                         keepdims=True)
        return self._finish(F, nll, sample_weight)


SoftmaxCELoss = SoftmaxCrossEntropyLoss


class KLDivLoss(Loss):
    def __init__(self, from_logits=True, axis=-1, weight=None, batch_axis=0,
                 **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._from_logits = from_logits
        self._axis = axis

    def hybrid_forward(self, F, pred, label, sample_weight=None):
        # pred is expected in log space; label stays a distribution
        logp = pred if self._from_logits \
            else F.log_softmax(pred, axis=self._axis)
        div = label * (F.log(label + 1e-12) - logp)
        return self._finish(F, div, sample_weight)


class CTCLoss(Loss):
    """Connectionist temporal classification, blank = last class.

    Parity: loss.py:398 (layouts NTC/TNC over contrib ctc_loss).
    """

    def __init__(self, layout="NTC", label_layout="NT", weight=None,
                 **kwargs):
        if layout not in ("NTC", "TNC"):
            raise ValueError("layout must be NTC or TNC, got %r" % layout)
        if label_layout not in ("NT", "TN"):
            raise ValueError("label_layout must be NT or TN, got %r"
                             % label_layout)
        self._layout = layout
        self._label_layout = label_layout
        super().__init__(weight, label_layout.find("N"), **kwargs)

    def hybrid_forward(self, F, pred, label, pred_lengths=None,
                       label_lengths=None, sample_weight=None):
        # the kernel wants time-major activations and batch-major labels
        if self._layout == "NTC":
            pred = F.swapaxes(pred, dim1=0, dim2=1)
        if self._label_layout == "TN":
            label = F.swapaxes(label, dim1=0, dim2=1)
        per_seq = F.CTCLoss(pred, label, pred_lengths, label_lengths,
                            use_data_lengths=pred_lengths is not None,
                            use_label_lengths=label_lengths is not None,
                            blank_label="last")
        return _scaled(F, per_seq, self._weight, sample_weight)


class HuberLoss(_MatchedLoss):
    def __init__(self, rho=1, weight=None, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._rho = rho

    def _residual(self, F, pred, label):
        err = F.abs(pred - label)
        # quadratic inside the rho tube, linear outside (equal at err==rho)
        return F.where(err < self._rho,
                       (0.5 / self._rho) * F.square(err),
                       err - 0.5 * self._rho)


class HingeLoss(_MatchedLoss):
    def __init__(self, margin=1, weight=None, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._margin = margin

    def _residual(self, F, pred, label):
        return F.relu(self._margin - pred * label)


class SquaredHingeLoss(HingeLoss):
    def _residual(self, F, pred, label):
        return F.square(super()._residual(F, pred, label))


class LogisticLoss(_MatchedLoss):
    def __init__(self, weight=None, batch_axis=0, label_format="signed",
                 **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        if label_format not in ("signed", "binary"):
            raise ValueError("label_format must be signed or binary, got %r"
                             % label_format)
        self._label_format = label_format

    def _residual(self, F, pred, label):
        if self._label_format == "signed":
            label = 0.5 * (label + 1.)   # {-1,1} -> {0,1}
        return _logit_bce(F, pred, label)


class TripletLoss(Loss):
    """Parity: loss.py:666 (reduce the margin gap, then clamp)."""

    def __init__(self, margin=1, weight=None, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._margin = margin

    def hybrid_forward(self, F, pred, positive, negative, sample_weight=None):
        gap = F.square(pred - _like(positive, pred)) \
            - F.square(pred - _like(negative, pred))
        gap = F.sum(gap, axis=self._batch_axis, exclude=True)
        return _scaled(F, F.relu(gap + self._margin), self._weight,
                       sample_weight)


class PoissonNLLLoss(Loss):
    def __init__(self, weight=None, from_logits=True, batch_axis=0,
                 compute_full=False, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._from_logits = from_logits
        self._compute_full = compute_full

    def hybrid_forward(self, F, pred, target, sample_weight=None,
                       epsilon=1e-08):
        target = _like(target, pred)
        if self._from_logits:
            nll = F.exp(pred) - target * pred
        else:
            nll = pred - target * F.log(pred + epsilon)
        if self._compute_full:
            # Stirling correction log(k!) ~ k log k - k + log(2*pi*k)/2,
            # applied only where it is meaningful (target > 1). The clamp
            # keeps log() finite at target==0 in the unselected branch —
            # masking by multiply would turn its -inf into NaN.
            safe = F.maximum(target, 1.)
            stirling = target * F.log(safe) - target \
                + 0.5 * F.log(6.2831853 * safe)
            nll = nll + F.where(target > 1, stirling, stirling * 0.)
        # reference quirk kept: full mean, not a per-sample vector
        return F.mean(_scaled(F, nll, self._weight, sample_weight))
