"""Vision datasets.

Parity: reference `python/mxnet/gluon/data/vision/datasets.py` — MNIST,
FashionMNIST, CIFAR10/100, ImageRecordDataset, ImageFolderDataset.

No network egress here: datasets read the standard on-disk formats if
present under `root`; otherwise (train/test smoke use) they synthesize a
deterministic procedurally-generated stand-in with the right shapes/label
space so end-to-end pipelines and convergence tests run hermetically.
"""
from __future__ import annotations

import gzip
import os
import struct
import pickle as pkl

import numpy as np

from ...data.dataset import Dataset
from ....ndarray import NDArray


class _DownloadedDataset(Dataset):
    def __init__(self, root, transform):
        self._transform = transform
        self._data = None
        self._label = None
        self._root = os.path.expanduser(root)
        self._get_data()

    def __getitem__(self, idx):
        if self._transform is not None:
            return self._transform(NDArray(self._data[idx]),
                                   self._label[idx])
        return NDArray(self._data[idx]), self._label[idx]

    def __len__(self):
        return len(self._label)

    def _get_data(self):
        raise NotImplementedError


def _synthetic(n, shape, num_classes, seed):
    """Deterministic stand-in when the real files are absent (hermetic CI).

    Class prototypes come from a FIXED seed so train/test splits share the
    same classes (different `seed` only varies the samples/noise)."""
    proto_rng = np.random.RandomState(1234 + num_classes)
    base = proto_rng.rand(num_classes, *shape).astype(np.float32)
    rng = np.random.RandomState(seed)
    label = rng.randint(0, num_classes, n).astype(np.int32)
    noise = rng.rand(n, *shape).astype(np.float32) * 0.3
    data = (base[label] * 0.7 + noise)
    return (data * 255).astype(np.uint8), label


class MNIST(_DownloadedDataset):
    """Parity: datasets.py MNIST (idx-ubyte format reader)."""

    def __init__(self, root=os.path.join("~", ".mxnet", "datasets", "mnist"),
                 train=True, transform=None):
        self._train = train
        self._train_data = ("train-images-idx3-ubyte.gz",)
        self._train_label = ("train-labels-idx1-ubyte.gz",)
        self._test_data = ("t10k-images-idx3-ubyte.gz",)
        self._test_label = ("t10k-labels-idx1-ubyte.gz",)
        self._num_classes = 10
        self._shape = (28, 28, 1)
        super().__init__(root, transform)

    def _get_data(self):
        if self._train:
            data_file = os.path.join(self._root, self._train_data[0])
            label_file = os.path.join(self._root, self._train_label[0])
            n_syn = 6000
        else:
            data_file = os.path.join(self._root, self._test_data[0])
            label_file = os.path.join(self._root, self._test_label[0])
            n_syn = 1000
        if os.path.exists(data_file) and os.path.exists(label_file):
            with gzip.open(label_file, "rb") as fin:
                struct.unpack(">II", fin.read(8))
                label = np.frombuffer(fin.read(), dtype=np.uint8).astype(np.int32)
            with gzip.open(data_file, "rb") as fin:
                struct.unpack(">IIII", fin.read(16))
                data = np.frombuffer(fin.read(), dtype=np.uint8)
                data = data.reshape(len(label), 28, 28, 1)
            self._data = data
            self._label = label
        else:
            self._data, self._label = _synthetic(
                n_syn, self._shape, self._num_classes,
                seed=42 if self._train else 43)


class FashionMNIST(MNIST):
    def __init__(self, root=os.path.join("~", ".mxnet", "datasets",
                                         "fashion-mnist"),
                 train=True, transform=None):
        super().__init__(root=root, train=train, transform=transform)


class CIFAR10(_DownloadedDataset):
    """Parity: datasets.py CIFAR10 (binary batches format)."""

    def __init__(self, root=os.path.join("~", ".mxnet", "datasets", "cifar10"),
                 train=True, transform=None):
        self._train = train
        self._num_classes = 10
        super().__init__(root, transform)

    def _read_batch(self, filename):
        with open(filename, "rb") as fin:
            data = np.frombuffer(fin.read(), dtype=np.uint8).reshape(-1, 3073)
        return data[:, 1:].reshape(-1, 3, 32, 32).transpose(0, 2, 3, 1), \
            data[:, 0].astype(np.int32)

    def _get_data(self):
        files = ["data_batch_%d.bin" % i for i in range(1, 6)] \
            if self._train else ["test_batch.bin"]
        paths = [os.path.join(self._root, "cifar-10-batches-bin", f)
                 for f in files]
        if all(os.path.exists(p) for p in paths):
            data, label = zip(*[self._read_batch(p) for p in paths])
            self._data = np.concatenate(data)
            self._label = np.concatenate(label)
        else:
            self._data, self._label = _synthetic(
                5000 if self._train else 1000, (32, 32, 3),
                self._num_classes, seed=44 if self._train else 45)


class CIFAR100(CIFAR10):
    def __init__(self, root=os.path.join("~", ".mxnet", "datasets", "cifar100"),
                 fine_label=False, train=True, transform=None):
        self._fine_label = fine_label
        self._train = train
        self._num_classes = 100 if fine_label else 20
        _DownloadedDataset.__init__(self, root, transform)

    def _get_data(self):
        f = "train.bin" if self._train else "test.bin"
        path = os.path.join(self._root, "cifar-100-binary", f)
        if os.path.exists(path):
            with open(path, "rb") as fin:
                data = np.frombuffer(fin.read(), dtype=np.uint8).reshape(-1, 3074)
            self._data = data[:, 2:].reshape(-1, 3, 32, 32).transpose(0, 2, 3, 1)
            self._label = data[:, 1 if self._fine_label else 0].astype(np.int32)
        else:
            self._data, self._label = _synthetic(
                5000 if self._train else 1000, (32, 32, 3),
                self._num_classes, seed=46 if self._train else 47)


class ImageRecordDataset(Dataset):
    """Parity: datasets.py ImageRecordDataset over RecordIO packs."""

    def __init__(self, filename, flag=1, transform=None):
        from ...data.dataset import RecordFileDataset
        self._record = RecordFileDataset(filename)
        self._flag = flag
        self._transform = transform

    def __len__(self):
        return len(self._record)

    def __getitem__(self, idx):
        from .... import recordio, image
        record = self._record[idx]
        header, img = recordio.unpack(record)
        img = image.imdecode(img, flag=self._flag)
        label = header.label
        if self._transform is not None:
            return self._transform(img, label)
        return img, label


class ImageFolderDataset(Dataset):
    """Parity: datasets.py ImageFolderDataset (label = subfolder index)."""

    def __init__(self, root, flag=1, transform=None):
        self._root = os.path.expanduser(root)
        self._flag = flag
        self._transform = transform
        self._exts = [".jpg", ".jpeg", ".png"]
        self._list_images(self._root)

    def _list_images(self, root):
        self.synsets = []
        self.items = []
        for folder in sorted(os.listdir(root)):
            path = os.path.join(root, folder)
            if not os.path.isdir(path):
                continue
            label = len(self.synsets)
            self.synsets.append(folder)
            for filename in sorted(os.listdir(path)):
                filename = os.path.join(path, filename)
                ext = os.path.splitext(filename)[1]
                if ext.lower() not in self._exts:
                    continue
                self.items.append((filename, label))

    def __getitem__(self, idx):
        from .... import image
        with open(self.items[idx][0], "rb") as f:
            img = image.imdecode(f.read(), flag=self._flag)
        label = self.items[idx][1]
        if self._transform is not None:
            return self._transform(img, label)
        return img, label

    def __len__(self):
        return len(self.items)
