"""Vision transforms (parity: python/mxnet/gluon/data/vision/transforms.py —
ToTensor, Normalize, Resize, CenterCrop, RandomResizedCrop, RandomFlip*,
RandomColorJitter family, Compose, Cast)."""
from __future__ import annotations

import numpy as np

from ...block import Block, HybridBlock
from ....ndarray import NDArray
from .... import image as _image


class Compose(Block):
    def __init__(self, transforms):
        super().__init__()
        self._transforms = transforms

    def forward(self, x):
        for t in self._transforms:
            x = t(x)
        return x


class Cast(HybridBlock):
    def __init__(self, dtype="float32"):
        super().__init__()
        self._dtype = dtype

    def hybrid_forward(self, F, x):
        return F.Cast(x, dtype=self._dtype)


class ToTensor(Block):
    """HWC uint8 [0,255] -> CHW float32 [0,1] (parity: image_random to_tensor)."""

    def forward(self, x):
        arr = x.asnumpy() if isinstance(x, NDArray) else np.asarray(x)
        arr = arr.astype(np.float32) / 255.0
        if arr.ndim == 3:
            arr = arr.transpose(2, 0, 1)
        elif arr.ndim == 4:
            arr = arr.transpose(0, 3, 1, 2)
        return NDArray(arr)


class Normalize(Block):
    def __init__(self, mean, std):
        super().__init__()
        self._mean = np.asarray(mean, dtype=np.float32)
        self._std = np.asarray(std, dtype=np.float32)

    def forward(self, x):
        arr = x.asnumpy() if isinstance(x, NDArray) else np.asarray(x)
        shape = (-1, 1, 1) if arr.ndim == 3 else (1, -1, 1, 1)
        return NDArray((arr - self._mean.reshape(shape)) /
                       self._std.reshape(shape))


class Resize(Block):
    def __init__(self, size, keep_ratio=False, interpolation=1):
        super().__init__()
        self._size = size if isinstance(size, (tuple, list)) else (size, size)

    def forward(self, x):
        return _image.imresize(x, self._size[0], self._size[1])


class CenterCrop(Block):
    def __init__(self, size, interpolation=1):
        super().__init__()
        self._size = size if isinstance(size, (tuple, list)) else (size, size)

    def forward(self, x):
        return _image.center_crop(x, self._size)[0]


class RandomResizedCrop(Block):
    def __init__(self, size, scale=(0.08, 1.0), ratio=(3.0 / 4.0, 4.0 / 3.0),
                 interpolation=1):
        super().__init__()
        self._size = size if isinstance(size, (tuple, list)) else (size, size)
        self._scale = scale
        self._ratio = ratio

    def forward(self, x):
        return _image.random_size_crop(x, self._size, self._scale[0],
                                       self._ratio)[0]


class RandomFlipLeftRight(Block):
    def forward(self, x):
        if np.random.rand() < 0.5:
            arr = x.asnumpy() if isinstance(x, NDArray) else x
            return NDArray(arr[:, ::-1].copy())
        return x


class RandomFlipTopBottom(Block):
    def forward(self, x):
        if np.random.rand() < 0.5:
            arr = x.asnumpy() if isinstance(x, NDArray) else x
            return NDArray(arr[::-1].copy())
        return x


class RandomBrightness(Block):
    def __init__(self, brightness):
        super().__init__()
        self._args = (max(0, 1 - brightness), 1 + brightness)

    def forward(self, x):
        alpha = np.random.uniform(*self._args)
        arr = x.asnumpy() if isinstance(x, NDArray) else np.asarray(x)
        return NDArray(np.clip(arr * alpha, 0, 255).astype(arr.dtype))


class RandomContrast(Block):
    def __init__(self, contrast):
        super().__init__()
        self._args = (max(0, 1 - contrast), 1 + contrast)

    def forward(self, x):
        alpha = np.random.uniform(*self._args)
        arr = x.asnumpy().astype(np.float32)
        gray = arr.mean()
        return NDArray(np.clip(gray + alpha * (arr - gray), 0, 255))


class RandomSaturation(Block):
    def __init__(self, saturation):
        super().__init__()
        self._args = (max(0, 1 - saturation), 1 + saturation)

    def forward(self, x):
        alpha = np.random.uniform(*self._args)
        arr = x.asnumpy().astype(np.float32)
        gray = arr.mean(axis=-1, keepdims=True)
        return NDArray(np.clip(gray + alpha * (arr - gray), 0, 255))


class RandomHue(Block):
    def __init__(self, hue):
        super().__init__()
        self._hue = hue

    def forward(self, x):
        # cheap HSV-free approximation: channel-rotation jitter
        alpha = np.random.uniform(-self._hue, self._hue)
        arr = x.asnumpy().astype(np.float32)
        rotated = np.roll(arr, 1, axis=-1)
        return NDArray(np.clip((1 - abs(alpha)) * arr + abs(alpha) * rotated,
                               0, 255))


class RandomColorJitter(Block):
    def __init__(self, brightness=0, contrast=0, saturation=0, hue=0):
        super().__init__()
        self._transforms = []
        if brightness:
            self._transforms.append(RandomBrightness(brightness))
        if contrast:
            self._transforms.append(RandomContrast(contrast))
        if saturation:
            self._transforms.append(RandomSaturation(saturation))
        if hue:
            self._transforms.append(RandomHue(hue))

    def forward(self, x):
        for t in self._transforms:
            x = t(x)
        return x


class RandomLighting(Block):
    def __init__(self, alpha):
        super().__init__()
        self._alpha = alpha

    def forward(self, x):
        alpha = np.random.normal(0, self._alpha, 3)
        eigval = np.array([55.46, 4.794, 1.148])
        eigvec = np.array([[-0.5675, 0.7192, 0.4009],
                           [-0.5808, -0.0045, -0.8140],
                           [-0.5836, -0.6948, 0.4203]])
        rgb = eigvec @ (alpha * eigval)
        arr = x.asnumpy().astype(np.float32)
        return NDArray(np.clip(arr + rgb, 0, 255))
