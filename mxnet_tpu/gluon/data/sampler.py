"""Samplers (parity: python/mxnet/gluon/data/sampler.py).

Beyond parity: samplers carry a resumable cursor for the fault-tolerant
training runtime (parallel/resilient.py). A seeded `RandomSampler` draws
each epoch's permutation from `(seed, epoch)` only, so after a preemption
a relaunched worker that restores `state_dict()` regenerates the exact
epoch order and fast-forwards to the batch it died at — index generation
only, no dataset access.
"""
from __future__ import annotations

import numpy as np


class Sampler:
    def __iter__(self):
        raise NotImplementedError

    def __len__(self):
        raise NotImplementedError

    # resumable-cursor protocol: stateless samplers return {} and ignore
    # restores; epoch-aware samplers override (see RandomSampler)
    def state_dict(self):
        return {}

    def load_state_dict(self, state):
        pass

    def set_epoch(self, epoch):
        pass


class SequentialSampler(Sampler):
    def __init__(self, length):
        self._length = length

    def __iter__(self):
        return iter(range(self._length))

    def __len__(self):
        return self._length


class RandomSampler(Sampler):
    """Shuffled indices. With `seed=None` (default) each pass draws from
    the global numpy RNG (legacy behavior, not resumable). With an integer
    seed the pass-`e` permutation is a pure function of `(seed, e)` —
    the resume contract the fault-tolerant runtime needs."""

    def __init__(self, length, seed=None):
        self._length = length
        self._seed = seed
        self._epoch = 0           # epoch index the NEXT __iter__ will use

    @property
    def epoch(self):
        return self._epoch

    def set_epoch(self, epoch):
        self._epoch = int(epoch)

    def __iter__(self):
        if self._seed is None:
            self._epoch += 1
            indices = np.arange(self._length)
            np.random.shuffle(indices)
            return iter(indices.tolist())
        rs = np.random.RandomState((int(self._seed) + self._epoch)
                                   & 0xFFFFFFFF)
        self._epoch += 1
        return iter(rs.permutation(self._length).tolist())

    def state_dict(self):
        if self._seed is None:
            # fail at the FIRST checkpoint, not at restore time — a
            # seedless shuffle draws from the global numpy RNG and its
            # order is unrecoverable, so saved cursors would be unusable
            raise ValueError(
                "RandomSampler(seed=None) is not resumable — construct "
                "it (or the DataLoader, via seed=) with an integer seed "
                "to make the data cursor checkpointable")
        return {"epoch": self._epoch, "seed": self._seed,
                "length": self._length}

    def load_state_dict(self, state):
        if self._seed is None:
            raise ValueError(
                "RandomSampler(seed=None) is not resumable — construct it "
                "with an integer seed to restore a data cursor")
        if state.get("seed") is not None and \
                int(state["seed"]) != int(self._seed):
            raise ValueError(
                "sampler seed mismatch: checkpoint has %r, sampler has %r "
                "— resuming would replay a different shuffle order"
                % (state["seed"], self._seed))
        if state.get("length") is not None and \
                int(state["length"]) != int(self._length):
            # a grown/shrunk dataset regenerates an unrelated permutation;
            # the cursor would silently land on different samples
            raise ValueError(
                "sampler length mismatch: checkpoint was taken over %s "
                "samples but the dataset now has %d — the resumed shuffle "
                "order would not match the interrupted run"
                % (state["length"], self._length))
        self._epoch = int(state["epoch"])

    def __len__(self):
        return self._length


class BatchSampler(Sampler):
    def __init__(self, sampler, batch_size, last_batch="keep"):
        self._sampler = sampler
        self._batch_size = batch_size
        self._last_batch = last_batch
        self._prev = []
        self._pass_carry = []  # the carry the CURRENT pass started with

    def __iter__(self):
        self._pass_carry = list(self._prev)
        batch, self._prev = self._prev, []
        for i in self._sampler:
            batch.append(i)
            if len(batch) == self._batch_size:
                yield batch
                batch = []
        if batch:
            if self._last_batch == "keep":
                yield batch
            elif self._last_batch == "discard":
                return
            elif self._last_batch == "rollover":
                self._prev = batch
            else:
                raise ValueError(
                    "last_batch must be one of 'keep', 'discard', or "
                    "'rollover', but got %s" % self._last_batch)

    def state_dict(self):
        """Sampler cursor + the rollover carries: `prev` is the partial
        batch this pass hands the NEXT epoch; `pass_carry` is what the
        CURRENT pass started with — a mid-pass resume must replay the
        pass with the same starting carry or every batch boundary
        shifts."""
        return {"sampler": self._sampler.state_dict(),
                "prev": [int(i) for i in self._prev],
                "pass_carry": [int(i) for i in self._pass_carry]}

    def load_state_dict(self, state):
        self._sampler.load_state_dict(state.get("sampler", {}))
        self._prev = [int(i) for i in state.get("prev", [])]
        self._pass_carry = [int(i) for i in state.get("pass_carry", [])]

    def rewind_to_pass_start(self):
        """Re-arm the carry consumed at the interrupted pass's start so
        the regenerated pass yields identical batch boundaries
        (DataLoader.load_state_dict calls this for mid-pass cursors)."""
        self._prev = list(self._pass_carry)

    def set_epoch(self, epoch):
        self._sampler.set_epoch(epoch)

    def __len__(self):
        if self._last_batch == "keep":
            return (len(self._sampler) + self._batch_size - 1) // \
                self._batch_size
        if self._last_batch == "discard":
            return len(self._sampler) // self._batch_size
        if self._last_batch == "rollover":
            return (len(self._prev) + len(self._sampler)) // self._batch_size
        raise ValueError(
            "last_batch must be one of 'keep', 'discard', or 'rollover', "
            "but got %s" % self._last_batch)


class IntervalSampler(Sampler):
    """Sample i, i+interval, i+2*interval, ... for each offset i (parity:
    gluon/contrib/data/sampler.py IntervalSampler; rollover=True starts at
    every offset, False only at 0)."""

    def __init__(self, length, interval, rollover=True):
        assert interval <= length, (
            "interval %d must not be larger than length %d"
            % (interval, length))
        self._length = length
        self._interval = interval
        self._rollover = rollover

    def __iter__(self):
        for start in range(self._interval if self._rollover else 1):
            for i in range(start, self._length, self._interval):
                yield i

    def __len__(self):
        # parity quirk: like the reference contrib sampler, len() reports
        # the full dataset length even with rollover=False (which yields
        # only ceil(length/interval) indices)
        return self._length
