"""Gluon data API (parity: python/mxnet/gluon/data/)."""
from .dataset import Dataset, ArrayDataset, SimpleDataset, RecordFileDataset
from .sampler import Sampler, SequentialSampler, RandomSampler, BatchSampler
from .dataloader import (DataLoader, default_batchify_fn,
                         default_mp_batchify_fn)
from . import vision
