"""DataLoader.

Parity: reference `python/mxnet/gluon/data/dataloader.py:72-94` — batching +
shuffling + multiprocess workers over POSIX shared memory.

TPU-native redesign: workers use a thread pool — batch assembly is numpy
(releases the GIL) and the expensive device transfer is XLA's async
host→HBM DMA, so processes+shm buy little; `num_workers>0` maps to an
N-thread pool that assembles batches concurrently and hands them off in
sampler order (the PrefetcherIter capability, iter_prefetcher.h).
"""
from __future__ import annotations

import numpy as np

from ...ndarray import NDArray
from .sampler import SequentialSampler, RandomSampler, BatchSampler


def default_batchify_fn(data):
    """Stack samples into a batch (parity: dataloader default_batchify_fn)."""
    if isinstance(data[0], NDArray):
        import jax.numpy as jnp
        return NDArray(jnp.stack([d._data for d in data]))
    if isinstance(data[0], tuple):
        data = zip(*data)
        return [default_batchify_fn(i) for i in data]
    data = np.asarray(data)
    if data.dtype == np.float64:
        data = data.astype(np.float32)
    return NDArray(data)


# parity alias (reference dataloader.py default_mp_batchify_fn): the
# reference's mp variant stacks into shared memory for its worker->main
# NDArray pickler; here workers hand back numpy and stacking is identical
default_mp_batchify_fn = default_batchify_fn


def prefetch_to_device(iterable, size=2, device=None):
    """Stage upcoming batches in accelerator memory while the current one
    computes.

    `jax.device_put` is an asynchronous host→HBM DMA, so holding `size`
    batches in flight overlaps the input transfer with the training step
    — the TPU input-pipeline pattern the reference approximates with
    engine-async `PrefetcherIter` (iter_prefetcher.h). Works on any
    iterable of NDArray / array / (nested) tuple-list batches; yields
    batches with device-resident buffers in original order.
    """
    import jax
    from collections import deque

    if device is None:
        device = jax.devices()[0]

    def put(b):
        if isinstance(b, NDArray):
            return NDArray(jax.device_put(b._data, device))
        if isinstance(b, tuple) and hasattr(b, "_fields"):  # namedtuple
            return type(b)(*(put(x) for x in b))
        if isinstance(b, (list, tuple)):
            return type(b)(put(x) for x in b)
        return jax.device_put(b, device)

    window = deque()
    for batch in iterable:
        window.append(put(batch))
        if len(window) > max(1, size):
            yield window.popleft()
    while window:
        yield window.popleft()


class DataLoader:
    """Batched iteration with an optional resumable cursor.

    Resume contract (fault-tolerant runtime, parallel/resilient.py): pass
    `seed=` (or a seeded `RandomSampler`) and the loader exposes
    `state_dict()/load_state_dict()` — a tiny `(epoch, batch, seed)`
    cursor. After `load_state_dict`, the NEXT `__iter__` regenerates the
    interrupted epoch's shuffle order and fast-forwards to the saved
    batch index by skipping index lists only (no dataset reads, no
    batchify work for the skipped prefix). The cursor counts batches the
    consumer actually received — worker prefetch can't over-advance it.
    """

    def __init__(self, dataset, batch_size=None, shuffle=False, sampler=None,
                 last_batch=None, batch_sampler=None, batchify_fn=None,
                 num_workers=0, pin_memory=False, prefetch=None,
                 device_prefetch=0, seed=None):
        self._dataset = dataset
        if batch_sampler is None:
            if batch_size is None:
                raise ValueError("batch_size must be specified unless "
                                 "batch_sampler is specified")
            if sampler is None:
                if shuffle:
                    sampler = RandomSampler(len(dataset), seed=seed)
                else:
                    sampler = SequentialSampler(len(dataset))
            elif shuffle:
                raise ValueError("shuffle must not be specified if sampler "
                                 "is specified")
            batch_sampler = BatchSampler(
                sampler, batch_size, last_batch if last_batch else "keep")
        elif batch_size is not None or shuffle or sampler is not None or \
                last_batch is not None:
            raise ValueError("batch_size, shuffle, sampler and last_batch "
                             "must not be specified if batch_sampler is "
                             "specified.")
        self._batch_sampler = batch_sampler
        self._num_workers = num_workers
        self._prefetch = max(0, int(prefetch) if prefetch is not None
                             else 2 * num_workers)
        self._device_prefetch = max(0, int(device_prefetch))
        self._batchify_fn = batchify_fn or default_batchify_fn
        self._epoch = 0          # epoch index of the pass in progress
        self._batch_cursor = 0   # batches YIELDED in the current pass
        self._resume_skip = 0    # batches to fast-forward on next __iter__

    def _make_batch(self, indices):
        return self._batchify_fn([self._dataset[i] for i in indices])

    # -- resumable cursor ---------------------------------------------------
    def state_dict(self):
        """Cursor of the pass in progress: safe to snapshot between
        batches (the fault-tolerant loop checkpoints at step boundaries,
        so `batch` counts exactly the batches already consumed)."""
        if not hasattr(self._batch_sampler, "state_dict"):
            # fail at the FIRST save with guidance, not with a silently
            # wrong cursor at resume time
            raise ValueError(
                "this DataLoader's custom batch_sampler (%s) has no "
                "state_dict()/load_state_dict() — it is not resumable. "
                "Implement the cursor protocol (see gluon.data.sampler."
                "BatchSampler) or construct the DataLoader with "
                "batch_size/shuffle/seed." % type(self._batch_sampler)
                .__name__)
        return {"epoch": self._epoch, "batch": self._batch_cursor,
                "batch_sampler": self._batch_sampler.state_dict()}

    def load_state_dict(self, state):
        """Restore a cursor; takes effect at the NEXT `__iter__`, which
        re-derives the epoch's order and skips the consumed prefix."""
        self._epoch = int(state["epoch"])
        self._batch_cursor = int(state["batch"])
        self._resume_skip = self._batch_cursor
        bs_state = dict(state.get("batch_sampler", {}))
        # the saved sampler epoch is where the INTERRUPTED pass started
        # +1; rewind so the next pass regenerates that same permutation
        if hasattr(self._batch_sampler, "load_state_dict"):
            self._batch_sampler.load_state_dict(bs_state)
        if hasattr(self._batch_sampler, "set_epoch"):
            self._batch_sampler.set_epoch(self._epoch)
        if self._batch_cursor > 0 and \
                hasattr(self._batch_sampler, "rewind_to_pass_start"):
            # mid-pass resume replays the interrupted pass from its
            # start: restore the rollover carry that pass consumed
            self._batch_sampler.rewind_to_pass_start()

    def __iter__(self):
        skip = self._resume_skip
        inner = self._iter_host()
        if self._device_prefetch:
            inner = prefetch_to_device(inner, self._device_prefetch)
        return self._tracked(inner, skip)

    def _tracked(self, it, skip):
        """Cursor bookkeeping at the SINGLE point batches reach the
        consumer — worker pools and the device-prefetch window both pull
        ahead of the training loop, and a cursor advanced at their pull
        time would make a resume skip batches that were never trained
        on."""
        if skip == 0:
            sampler = getattr(self._batch_sampler, "_sampler", None)
            self._epoch = getattr(sampler, "epoch", self._epoch)
            self._batch_cursor = 0
        else:
            self._batch_cursor = skip
        for batch in it:
            self._batch_cursor += 1
            yield batch
        self._epoch += 1
        self._batch_cursor = 0

    def _iter_host(self):
        skip, self._resume_skip = self._resume_skip, 0
        index_iter = iter(self._batch_sampler)
        for _ in range(skip):
            # fast-forward: consume index lists only — no dataset access
            next(index_iter, None)
        if self._num_workers == 0:
            for batch in index_iter:
                yield self._make_batch(batch)
            return
        # N-worker prefetching pool with ordered hand-off: batches are
        # assembled concurrently (numpy/image decode release the GIL) but
        # yielded in sampler order, keeping at most `prefetch` in flight
        from concurrent.futures import ThreadPoolExecutor
        from collections import deque
        pool = ThreadPoolExecutor(self._num_workers)
        window = deque()
        try:
            for batch in index_iter:
                window.append(pool.submit(self._make_batch, batch))
                if len(window) >= max(2, self._prefetch):
                    yield window.popleft().result()
            while window:
                yield window.popleft().result()
        finally:
            for f in window:
                f.cancel()
            pool.shutdown(wait=False)

    def __len__(self):
        return len(self._batch_sampler)
