"""Gluon Trainer.

Parity: reference `python/mxnet/gluon/trainer.py:27` — creates a kvstore
(:112), step = allreduce + update (:160,206,247), allreduce_grads, lr
scheduling, save/load optimizer states, gradient compression knob.

TPU-native redesign: parameters have ONE logical buffer (not per-device
copies), so _allreduce_grads is a no-op on a single chip and a mesh psum
under data parallelism (kvstore type 'tpu'/'dist_*'). The update path calls
the pure optimizer rules; for the fully-fused XLA train step (forward + loss
+ backward + update in one compiled program with donation), see
mxnet_tpu.parallel.TrainStep which reuses the same optimizer rules.
"""
from __future__ import annotations

from ..base import MXNetError
from .. import optimizer as opt
from .. import kvstore as kvs
from .parameter import ParameterDict, Parameter
from ..ndarray.sparse import RowSparseNDArray


class Trainer:
    def __init__(self, params, optimizer, optimizer_params=None, kvstore="device",
                 compression_params=None, update_on_kvstore=None):
        if isinstance(params, (dict, ParameterDict)):
            params = list(params.values())
        if not isinstance(params, (list, tuple)):
            raise ValueError(
                "First argument must be a list or dict of Parameters, "
                "got %s." % (type(params)))
        self._params = []
        for param in params:
            if not isinstance(param, Parameter):
                raise ValueError(
                    "First argument must be a list or dict of Parameters, "
                    "got list of %s." % (type(param)))
            self._params.append(param)
        self._compression_params = compression_params
        optimizer_params = optimizer_params if optimizer_params else {}
        self._scale = float(optimizer_params.get("rescale_grad", 1.0))
        self._init_optimizer(optimizer, optimizer_params)
        self._kv_type = kvstore
        self._kvstore = None
        self._update_on_kvstore = update_on_kvstore
        self._kv_initialized = False
        self._params_to_init = []

    def _init_optimizer(self, optimizer, optimizer_params):
        param_dict = {i: param for i, param in enumerate(self._params)}
        if isinstance(optimizer, opt.Optimizer):
            assert not optimizer_params, \
                "optimizer_params must be None if optimizer is an Optimizer " \
                "instance"
            self._optimizer = optimizer
            self._optimizer.param_dict = param_dict
        else:
            self._optimizer = opt.create(optimizer, param_dict=param_dict,
                                         **optimizer_params)
        self._updaters = [opt.get_updater(self._optimizer)]

    def _init_kvstore(self):
        if isinstance(self._kv_type, kvs.KVStore):
            self._kvstore = self._kv_type
        elif self._kv_type is None:
            self._kvstore = None
        else:
            self._kvstore = kvs.create(self._kv_type)
        if self._kvstore is not None and self._compression_params:
            self._kvstore.set_gradient_compression(self._compression_params)
        self._distributed = self._kvstore is not None and \
            self._kvstore.num_workers > 1
        self._kv_initialized = True

    @property
    def learning_rate(self):
        return self._optimizer.lr if self._optimizer.lr_scheduler is None \
            else self._optimizer.lr_scheduler(self._optimizer.num_update)

    def set_learning_rate(self, lr):
        self._optimizer.set_learning_rate(lr)

    def _row_sparse_pull(self, parameter, out, row_id):
        if not self._kv_initialized:
            self._init_kvstore()
        idx = self._params.index(parameter)
        if self._kvstore is not None:
            key = "param_%d" % idx
            if key not in self._kvstore._store:
                self._kvstore.init(key, parameter.data())
            self._kvstore.row_sparse_pull(key, out=out, row_ids=row_id)

    def step(self, batch_size, ignore_stale_grad=False):
        """allreduce + optimizer update (parity: trainer.py:160)."""
        if not self._kv_initialized:
            self._init_kvstore()
        self._optimizer.rescale_grad = self._scale / batch_size
        self._allreduce_grads()
        self._update(ignore_stale_grad)

    def allreduce_grads(self):
        if not self._kv_initialized:
            self._init_kvstore()
        self._allreduce_grads()

    def _allreduce_grads(self):
        # single logical buffer per param: nothing to reduce locally.
        # multi-host data parallelism: psum grads over the process mesh.
        if self._kvstore is not None and self._kvstore.num_workers > 1:
            for param in self._params:
                if param.grad_req != "null":
                    g = param.grad()
                    g._data = kvs._multihost_psum(g._data) / \
                        self._kvstore.num_workers

    def _update(self, ignore_stale_grad=False):
        updater = self._updaters[0]
        for i, param in enumerate(self._params):
            if param.grad_req == "null":
                continue
            if param._data is None:
                continue
            grad = param._grad
            if grad is None:
                if ignore_stale_grad:
                    continue
                raise MXNetError(
                    "Gradient of Parameter `%s` not found. Call backward "
                    "first." % param.name)
            updater(i, grad, param.data())

    def update(self, batch_size, ignore_stale_grad=False):
        if not self._kv_initialized:
            self._init_kvstore()
        self._optimizer.rescale_grad = self._scale / batch_size
        self._update(ignore_stale_grad)

    def save_states(self, fname):
        assert self._optimizer is not None
        with open(fname, "wb") as fout:
            fout.write(self._updaters[0].get_states(dump_optimizer=False))

    def load_states(self, fname):
        if not self._kv_initialized:
            self._init_kvstore()
        with open(fname, "rb") as f:
            states = f.read()
        self._updaters[0].set_states(states)
        self._updaters[0].optimizer = self._optimizer
