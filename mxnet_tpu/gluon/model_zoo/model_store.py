"""Pretrained-model file resolution.

Parity: reference `python/mxnet/gluon/model_zoo/model_store.py` (sha1-keyed
download cache). No network egress in this environment: files must already
exist under root (~/.mxnet/models); otherwise an informative error is raised.
"""
from __future__ import annotations

import os

_DEFAULT_ROOT = os.path.join("~", ".mxnet", "models")

# sha1 prefixes keyed by model name (parity: the reference's
# _model_sha1 table keys the download cache; with no egress the table's
# role here is the short_hash naming contract for cached files)
_model_sha1 = {}


def short_hash(name):
    """8-char sha1 prefix for a model's cached filename (parity:
    model_store.py short_hash)."""
    if name not in _model_sha1:
        raise ValueError(
            "Pretrained model for %s is not available "
            "(no published hash registered)" % name)
    return _model_sha1[name][:8]


def get_model_file(name, root=_DEFAULT_ROOT):
    root = os.path.expanduser(root or _DEFAULT_ROOT)
    search = [root]
    # parity: MXNET_GLUON_REPO overrides the model source. A local path is
    # honored as an extra directory to resolve from; an http(s)/file URL
    # becomes a download base fetched with retry+backoff (utils.retry via
    # gluon.utils.download — transient repo hiccups must not fail a job
    # that is about to train for hours).
    extra = os.environ.get("MXNET_GLUON_REPO")
    repo_url = None
    if extra and extra.startswith(("http://", "https://", "file://")):
        repo_url = extra.rstrip("/")
    elif extra:
        search.append(os.path.expanduser(extra))
    # resolve both this package's plain naming and the reference's
    # hash-suffixed cache naming (name-<short_hash>.params) when a hash
    # is registered
    candidates = [name + ".params"]
    if name in _model_sha1:
        candidates.append("%s-%s.params" % (name, short_hash(name)))
    for base in search:
        for fname in candidates:
            file_path = os.path.join(base, fname)
            if os.path.exists(file_path):
                return file_path
    if repo_url is not None:
        from ..utils import download
        sha1 = _model_sha1.get(name)
        return download("%s/%s" % (repo_url, candidates[-1]),
                        path=os.path.join(root, candidates[-1]),
                        sha1_hash=sha1, retries=5)
    raise IOError(
        "Pretrained weights %s.params not found under %s and cannot be "
        "downloaded (no MXNET_GLUON_REPO url configured). Train from "
        "scratch or place the file there." % (name, " or ".join(search)))


def purge(root=_DEFAULT_ROOT):
    root = os.path.expanduser(root)
    if os.path.exists(root):
        for f in os.listdir(root):
            if f.endswith(".params"):
                os.remove(os.path.join(root, f))
