"""Pretrained-model file resolution.

Parity: reference `python/mxnet/gluon/model_zoo/model_store.py` (sha1-keyed
download cache). No network egress in this environment: files must already
exist under root (~/.mxnet/models); otherwise an informative error is raised.
"""
from __future__ import annotations

import os

_DEFAULT_ROOT = os.path.join("~", ".mxnet", "models")


def get_model_file(name, root=_DEFAULT_ROOT):
    root = os.path.expanduser(root or _DEFAULT_ROOT)
    search = [root]
    # parity: MXNET_GLUON_REPO overrides the model source; with no network
    # egress it is honored as an extra local directory to resolve from
    extra = os.environ.get("MXNET_GLUON_REPO")
    if extra and not extra.startswith(("http://", "https://")):
        search.append(os.path.expanduser(extra))
    for base in search:
        file_path = os.path.join(base, name + ".params")
        if os.path.exists(file_path):
            return file_path
    raise IOError(
        "Pretrained weights %s.params not found under %s and cannot be "
        "downloaded (no network egress). Train from scratch or place the "
        "file there." % (name, " or ".join(search)))


def purge(root=_DEFAULT_ROOT):
    root = os.path.expanduser(root)
    if os.path.exists(root):
        for f in os.listdir(root):
            if f.endswith(".params"):
                os.remove(os.path.join(root, f))
