"""Pretrained-model file resolution.

Parity: reference `python/mxnet/gluon/model_zoo/model_store.py` (sha1-keyed
download cache). No network egress in this environment: files must already
exist under root (~/.mxnet/models); otherwise an informative error is raised.
"""
from __future__ import annotations

import os

_DEFAULT_ROOT = os.path.join("~", ".mxnet", "models")


def get_model_file(name, root=_DEFAULT_ROOT):
    root = os.path.expanduser(root or _DEFAULT_ROOT)
    file_path = os.path.join(root, name + ".params")
    if os.path.exists(file_path):
        return file_path
    raise IOError(
        "Pretrained weights %s.params not found under %s and cannot be "
        "downloaded (no network egress). Train from scratch or place the "
        "file there." % (name, root))


def purge(root=_DEFAULT_ROOT):
    root = os.path.expanduser(root)
    if os.path.exists(root):
        for f in os.listdir(root):
            if f.endswith(".params"):
                os.remove(os.path.join(root, f))
