"""Attribute scoping (parity: python/mxnet/attribute.py — AttrScope used to
attach attrs like ctx_group / lr_mult to symbols created inside the scope)."""
from __future__ import annotations

import threading


class AttrScope:
    _current = threading.local()

    def __init__(self, **kwargs):
        self._attr = {str(k): str(v) for k, v in kwargs.items()}
        self._old_scope = None

    def get(self, attr=None):
        if self._attr:
            ret = self._attr.copy()
            if attr:
                ret.update(attr)
            return ret
        return attr if attr else {}

    def __enter__(self):
        if not hasattr(AttrScope._current, "value"):
            AttrScope._current.value = AttrScope()
        self._old_scope = AttrScope._current.value
        attr = AttrScope._current.value._attr.copy()
        attr.update(self._attr)
        self._attr = attr
        AttrScope._current.value = self
        return self

    def __exit__(self, ptype, value, trace):
        AttrScope._current.value = self._old_scope


AttrScope._current.value = AttrScope()


def current():
    if not hasattr(AttrScope._current, "value"):
        AttrScope._current.value = AttrScope()
    return AttrScope._current.value
