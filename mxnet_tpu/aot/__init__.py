"""mxnet_tpu.aot — persistent ahead-of-time executable cache.

Seated at the compile watchdog's `lower().compile()` choke point
(telemetry/introspect.py): every framework jit's compiled executable is
serialized to a content-addressed disk entry, and a restarted engine,
respawned replica, or freshly scaled-out warm replica loads it back with
ZERO fresh XLA compilation — bit-identical logits, compile-once fleet.
See cache.py for key anatomy and docs/OBSERVABILITY.md ("Compile-once
fleet") for the operator story.
"""
from .cache import (AOTCache, CorruptEntry, atomic_publish, cache,
                    cache_dir, configure, fingerprint, key_for,
                    load_executable, placement_key,
                    serialize_executable_blob)

__all__ = [
    "AOTCache", "CorruptEntry", "atomic_publish", "cache", "cache_dir",
    "configure", "fingerprint", "key_for", "load_executable",
    "placement_key", "serialize_executable_blob",
]
