"""Persistent AOT executable cache (ISSUE 16).

The compile watchdog (telemetry/introspect.py) already funnels every
framework jit through ONE `lower().compile()` choke point; this module
makes that choke point durable. A compiled executable is serialized via
jax's AOT serialization (`jax.experimental.serialize_executable` — the
same compile-once idea `predict.py`'s `.mxtpu` artifacts prove for
exported models) and published to a disk directory keyed by a content
hash of everything that determines the program:

  * the environment **fingerprint**: jax / jaxlib / framework versions,
    backend platform, device kind and count, compiler-flag env
    (`XLA_FLAGS`, `LIBTPU_INIT_ARGS`) and the lowering-relevant
    `MXNET_*` env vars;
  * the watchdog **site** and the traced **signature** (shapes, dtypes,
    shardings, static values — exactly the watchdog's cache key);
  * the **placement**: the sorted device ids the call's committed
    arguments live on (two tp replicas on different device windows
    compile different programs from identical shapes — the identity-free
    sharding description deliberately can't tell them apart, this can);
  * an explicit **variant** tag from the instrument site (the gather and
    paged decode jits share one site and can share a signature — the
    tag plus the lowered-text hash below make a wrong-executable hit
    structurally impossible);
  * the sha256 of the deterministic **lowered StableHLO text** — the
    program's actual content, the belt under every brace above.

Entries are single zip files published by atomic rename (first writer
wins, a racing loser discards its temp file and reuses the published
entry), with sha256 digests over the payload verified on every load.
A corrupt, truncated, or stale entry is NEVER an error: the loader
quarantines it and the caller falls back to a fresh compile — the cache
switches where an executable comes from, never what it computes.

Surface: `MXNET_AOT_CACHE_DIR` env, `configure(path)` (what
`Engine(aot_cache=...)` and `serve --aot-cache` call), and
`tools/aot_warm.py` for pre-populating/verifying a directory.
"""
from __future__ import annotations

import contextlib
import hashlib
import json
import os
import pickle
import threading
import time
import zipfile

from ..base import MXNetError

#: entry format version — bumped on any layout change (old entries then
#: fail the meta check and are recompiled, never misread)
FORMAT = 1

#: entry file suffix (one zip per executable)
SUFFIX = ".mxaot"

#: env vars that change what XLA is asked to build — part of the key's
#: environment fingerprint (flags switch placement/codegen, never logits,
#: so a mismatch is a MISS, not an error)
_FLAG_ENV = ("XLA_FLAGS", "LIBTPU_INIT_ARGS", "JAX_ENABLE_X64",
             "MXNET_PAGED_ATTENTION", "MXNET_PALLAS_INTERPRET",
             "MXNET_SERVING_TP", "MXNET_QUANTIZED_KV",
             "MXNET_QUANTIZED_WEIGHTS")


class CorruptEntry(MXNetError):
    """A cache entry failed its sha256 / format verification (truncated
    write, bit flip, stale layout). The loader quarantines the file and
    the caller recompiles — corruption costs a compile, never an error
    or a wrong executable."""


def fingerprint():
    """The environment part of every cache key: anything here changing
    invalidates the whole cache (by missing, not by erroring)."""
    import jax
    fp = {"jax": getattr(jax, "__version__", "?")}
    try:
        import jaxlib
        fp["jaxlib"] = getattr(jaxlib, "__version__", "?")
    except Exception:                                    # pragma: no cover
        fp["jaxlib"] = "?"
    try:
        from ..libinfo import __version__ as fw
        fp["framework"] = fw
    except Exception:                                    # pragma: no cover
        fp["framework"] = "?"
    try:
        devs = jax.devices()
        fp["platform"] = devs[0].platform
        fp["device_kind"] = devs[0].device_kind
        fp["device_count"] = len(devs)
    except Exception:                                    # pragma: no cover
        fp["platform"] = fp["device_kind"] = "?"
        fp["device_count"] = 0
    fp["env"] = {k: os.environ.get(k, "") for k in _FLAG_ENV}
    return fp


def placement_key(args):
    """Sorted device ids the call's COMMITTED argument leaves live on.
    Host/uncommitted inputs contribute nothing; a call with no committed
    leaf keys on the default device (where it will execute). This is
    what distinguishes two tp replicas' device windows — their shapes,
    dtypes, and identity-free sharding descriptions are all equal."""
    import jax
    ids = set()
    for leaf in jax.tree.leaves(args):
        s = getattr(leaf, "sharding", None)
        if s is None or not getattr(leaf, "_committed", True):
            continue
        try:
            ids.update(d.id for d in s.device_set)
        except Exception:                                # pragma: no cover
            pass
    if not ids:
        try:
            ids = {jax.devices()[0].id}
        except Exception:                                # pragma: no cover
            return ()
    return tuple(sorted(ids))


def key_for(site, sig, lowered_text, variant=None, placement=(),
            fp=None):
    """The content-hash key of one executable. Any component changing —
    version, device topology, signature/sharding, compiler flags, the
    lowered program itself — produces a different key, so staleness is
    structurally a MISS: the cache can serve the wrong-vintage
    executable only if sha256 collides."""
    fp = fingerprint() if fp is None else fp
    h = hashlib.sha256()
    h.update(json.dumps(fp, sort_keys=True).encode())
    h.update(b"\x00site:" + site.encode())
    h.update(b"\x00variant:" + repr(variant).encode())
    h.update(b"\x00placement:" + repr(tuple(placement)).encode())
    h.update(b"\x00sig:" + repr(sig).encode())
    h.update(b"\x00hlo:")
    h.update(hashlib.sha256(lowered_text.encode()).digest())
    return h.hexdigest()[:40]


# ---------------------------------------------------------------------------
# executable (de)serialization — the version-portable seam
# ---------------------------------------------------------------------------


def _serializers():
    """(serialize, deserialize_and_load) or None when this jax build
    can't round-trip executables — caching then silently disables (the
    flag switches persistence, never behavior)."""
    try:
        from jax.experimental.serialize_executable import (
            serialize, deserialize_and_load)
        return serialize, deserialize_and_load
    except Exception:                                    # pragma: no cover
        return None


def serialize_executable_blob(compiled):
    """(payload bytes, pickled (in_tree, out_tree)) for a compiled
    executable, or None when serialization is unavailable/unsupported
    for this executable."""
    sz = _serializers()
    if sz is None:                                       # pragma: no cover
        return None
    payload, in_tree, out_tree = sz[0](compiled)
    return bytes(payload), pickle.dumps((in_tree, out_tree))


def load_executable(payload, in_tree, out_tree):
    """Rehydrate a serialized executable into a callable taking the
    original dynamic arguments — zero XLA compilation."""
    sz = _serializers()
    if sz is None:                                       # pragma: no cover
        raise CorruptEntry("executable serialization unavailable")
    return sz[1](payload, in_tree, out_tree)


# ---------------------------------------------------------------------------
# the on-disk cache
# ---------------------------------------------------------------------------


@contextlib.contextmanager
def atomic_publish(path):
    """Write-to-temp + atomic-rename publish: yields the temp path to
    write, renames over `path` on success, removes the temp on failure.
    Readers never observe a half-written file (predict.py's artifact
    writers share this)."""
    tmp = "%s.tmp.%d.%x" % (path, os.getpid(), threading.get_ident())
    try:
        yield tmp
        os.replace(tmp, path)
    finally:
        with contextlib.suppress(OSError):
            os.remove(tmp)


class AOTCache:
    """One cache directory: load / store / verify over `.mxaot` entry
    zips. Thread- and process-safe by construction — every publish is
    an atomic rename and every load verifies digests, so concurrent
    writers and readers need no locks."""

    def __init__(self, path):
        self.path = str(path)
        os.makedirs(self.path, exist_ok=True)

    def entry_path(self, site_sane, key):
        return os.path.join(self.path, "%s-%s%s" % (site_sane, key,
                                                    SUFFIX))

    # -- store ---------------------------------------------------------------

    def store(self, site_sane, key, payload, trees, extra=None):
        """Publish one entry. First writer wins: if the entry already
        exists (another replica/process got there first) nothing is
        written and False is returned — the loser simply reuses the
        published copy on its next load."""
        final = self.entry_path(site_sane, key)
        if os.path.exists(final):
            return False
        meta = {"format": FORMAT, "key": key, "site": site_sane,
                "payload_sha256": hashlib.sha256(payload).hexdigest(),
                "trees_sha256": hashlib.sha256(trees).hexdigest(),
                "created": time.time()}
        if extra:
            meta.update(extra)
        tmp = "%s.tmp.%d.%x" % (final, os.getpid(),
                                threading.get_ident())
        try:
            with zipfile.ZipFile(tmp, "w") as z:
                z.writestr("meta.json", json.dumps(meta))
                z.writestr("payload.bin", payload)
                z.writestr("trees.pkl", trees)
            if os.path.exists(final):        # lost the race mid-write
                return False
            os.replace(tmp, final)
            return True
        finally:
            with contextlib.suppress(OSError):
                os.remove(tmp)

    # -- load ----------------------------------------------------------------

    def load(self, site_sane, key):
        """(payload, in_tree, out_tree, meta) for a verified entry, None
        on a miss, CorruptEntry (after quarantining the file) on any
        verification failure — the caller recompiles either way."""
        path = self.entry_path(site_sane, key)
        if not os.path.exists(path):
            return None
        try:
            with zipfile.ZipFile(path) as z:
                meta = json.loads(z.read("meta.json"))
                payload = z.read("payload.bin")
                trees = z.read("trees.pkl")
        except Exception as e:
            self._quarantine(path)
            raise CorruptEntry("unreadable cache entry %s: %s"
                               % (os.path.basename(path), e))
        if meta.get("format") != FORMAT \
                or meta.get("payload_sha256") \
                != hashlib.sha256(payload).hexdigest() \
                or meta.get("trees_sha256") \
                != hashlib.sha256(trees).hexdigest():
            self._quarantine(path)
            raise CorruptEntry("cache entry %s failed sha256/format "
                               "verification"
                               % os.path.basename(path))
        try:
            in_tree, out_tree = pickle.loads(trees)
        except Exception as e:
            self._quarantine(path)
            raise CorruptEntry("cache entry %s has undecodable trees: %s"
                               % (os.path.basename(path), e))
        return payload, in_tree, out_tree, meta

    def invalidate(self, site_sane, key):
        """Quarantine one entry whose payload deserialized but failed to
        load as an executable (a hash-valid but unusable vintage)."""
        self._quarantine(self.entry_path(site_sane, key))

    def _quarantine(self, path):
        with contextlib.suppress(OSError):
            os.remove(path)

    # -- inventory -----------------------------------------------------------

    def entries(self):
        """Sorted entry file names currently published."""
        try:
            names = os.listdir(self.path)
        except OSError:
            return []
        return sorted(n for n in names if n.endswith(SUFFIX))

    def verify(self):
        """Non-destructive re-hash of every entry: (ok names, corrupt
        names). `tools/aot_warm.py --verify` renders this."""
        ok, bad = [], []
        for name in self.entries():
            path = os.path.join(self.path, name)
            try:
                with zipfile.ZipFile(path) as z:
                    meta = json.loads(z.read("meta.json"))
                    payload = z.read("payload.bin")
                    trees = z.read("trees.pkl")
                good = (meta.get("format") == FORMAT
                        and meta.get("payload_sha256")
                        == hashlib.sha256(payload).hexdigest()
                        and meta.get("trees_sha256")
                        == hashlib.sha256(trees).hexdigest())
            except Exception:
                good = False
            (ok if good else bad).append(name)
        return ok, bad


# ---------------------------------------------------------------------------
# process-wide configuration: configure() override > MXNET_AOT_CACHE_DIR
# ---------------------------------------------------------------------------

_ENV = object()          # sentinel: defer to the env var
_override = _ENV
_cache_lock = threading.Lock()
_caches = {}             # dir -> AOTCache (memoized: makedirs once)


def configure(path=_ENV):
    """Set the process-wide cache directory (`Engine(aot_cache=...)` /
    `serve --aot-cache` land here). `None` disables caching regardless
    of the env var; calling with no argument restores env-var control
    (MXNET_AOT_CACHE_DIR)."""
    global _override
    _override = str(path) if path not in (None, _ENV) else path


def cache_dir():
    """The resolved cache directory, or None when caching is off."""
    if _override is not _ENV:
        return _override
    return os.environ.get("MXNET_AOT_CACHE_DIR") or None


def cache():
    """The process-wide AOTCache, or None when caching is off (no dir
    configured, or this jax can't serialize executables)."""
    d = cache_dir()
    if not d or _serializers() is None:
        return None
    with _cache_lock:
        c = _caches.get(d)
        if c is None:
            try:
                c = _caches[d] = AOTCache(d)
            except OSError:
                return None
        return c
