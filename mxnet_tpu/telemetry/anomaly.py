"""Training anomaly detection: EWMA z-scores on loss and grad-norm.

The bad-step guard (parallel/resilient.py) catches NaN/Inf — the
*infinite* failure. This module is its finite-but-wrong complement
(ISSUE 14): a loss spike or a grad-norm explosion that is numerically
valid but statistically impossible against the run's own history is
invisible to the guard and, without this, invisible to the operator
until the curve diverges hours later.

`EwmaDetector` keeps an exponentially-weighted mean and variance per
signal and scores each new observation against the *previous* state:

    z = (x - m) / sqrt(v + eps)        # m, v BEFORE seeing x
    d = x - m
    m' = m + alpha * d
    v' = (1 - alpha) * (v + alpha * d^2)

(the standard incremental EW mean/variance pair — tests pin the math
against hand-computed sequences). An observation only *flags* once the
detector has warmed up (`warmup` observations) and |z| exceeds the
threshold; flagged or not, the state always updates, so a sustained
level shift re-baselines instead of flagging forever.

`AnomalyDetector` is the step-seam wrapper `ResilientLoop` drives: one
EWMA per signal (loss, grad_norm), a flight-flagged
`train_anomalies_total` counter, a `train.anomaly` flight event naming
the signal/value/z/step, and `train_<signal>_zscore` gauges — all
no-ops under `MXNET_TELEMETRY=0` except the pure math (which is
behavior and stays testable).

Knobs (docs/ENV_VARS.md): `MXNET_ANOMALY_DETECT` (default off — the
detector forces the loss onto the host each step),
`MXNET_ANOMALY_ALPHA` (EWMA weight, default 0.05),
`MXNET_ANOMALY_ZSCORE` (flag threshold, default 6.0),
`MXNET_ANOMALY_WARMUP` (observations before flagging, default 20).
"""
from __future__ import annotations

import math
import os

from .metrics import enabled, default_registry

#: metric-name templates (docs/OBSERVABILITY.md; the doc-drift check
#: resolves `<signal>` against the %s template)
ANOMALIES_TOTAL = "train_anomalies_total"
SIGNAL_ZSCORE = "train_%s_zscore"

_EPS = 1e-12


def detect_enabled():
    """MXNET_ANOMALY_DETECT=1 arms the loop-level detector (default
    off: scoring the loss costs a device->host sync per step)."""
    return os.environ.get("MXNET_ANOMALY_DETECT", "0") == "1"


def _env_float(name, default, lo=None, hi=None):
    raw = os.environ.get(name)
    if raw is None or raw == "":
        return default
    try:
        v = float(raw)
    except ValueError:
        raise ValueError("%s must be a number, got %r" % (name, raw))
    if (lo is not None and v < lo) or (hi is not None and v > hi):
        raise ValueError("%s must be in [%s, %s], got %r"
                         % (name, lo, hi, raw))
    return v


def anomaly_alpha():
    v = _env_float("MXNET_ANOMALY_ALPHA", 0.05, None, 1.0)
    if v <= 0.0:
        # exclusive lower bound: alpha=0 would freeze the EWMA, and the
        # lazy EwmaDetector would otherwise reject it mid-training with
        # an error that never names the knob
        raise ValueError("MXNET_ANOMALY_ALPHA must be in (0, 1], got %r"
                         % (v,))
    return v


def anomaly_zscore():
    return _env_float("MXNET_ANOMALY_ZSCORE", 6.0, 0.0)


def anomaly_warmup():
    return int(_env_float("MXNET_ANOMALY_WARMUP", 20, 0))


class EwmaDetector:
    """One signal's exponentially-weighted mean/variance + z-scoring."""

    def __init__(self, alpha=0.05, zscore=6.0, warmup=20):
        if not 0.0 < alpha <= 1.0:
            raise ValueError("alpha must be in (0, 1], got %r" % (alpha,))
        self.alpha = float(alpha)
        self.zscore = float(zscore)
        self.warmup = int(warmup)
        self.mean = None
        self.var = 0.0
        self.n = 0

    def observe(self, x):
        """Score `x` against the state BEFORE it, then fold it in.
        Returns (z, flagged): z is None for the very first observation
        (no history to score against) and for non-finite inputs (the
        guard's territory, not statistics'); flagged requires warmup."""
        x = float(x)
        if not math.isfinite(x):
            return None, False
        if self.mean is None:
            self.mean = x
            self.n = 1
            return None, False
        z = (x - self.mean) / math.sqrt(self.var + _EPS)
        d = x - self.mean
        self.mean += self.alpha * d
        self.var = (1.0 - self.alpha) * (self.var + self.alpha * d * d)
        self.n += 1
        flagged = self.n > self.warmup and abs(z) > self.zscore
        return z, flagged


class AnomalyDetector:
    """The step-seam detector: one EWMA per named signal, recording
    flags as metrics + flight events. Pure math (z-scores, counts)
    works regardless of MXNET_TELEMETRY; only recording is gated."""

    def __init__(self, alpha=None, zscore=None, warmup=None,
                 registry=None):
        self.alpha = anomaly_alpha() if alpha is None else float(alpha)
        self.z_thresh = anomaly_zscore() if zscore is None \
            else float(zscore)
        self.warmup = anomaly_warmup() if warmup is None else int(warmup)
        self._signals = {}
        self._registry = registry
        self.anomalies = 0            # functional count (tests/statusz)
        self.last = {}                # signal -> last (value, z)

    def _ewma(self, signal):
        e = self._signals.get(signal)
        if e is None:
            e = self._signals[signal] = EwmaDetector(
                self.alpha, self.z_thresh, self.warmup)
        return e

    def observe(self, step, **signals):
        """Score one step's named signals; returns the list of flagged
        signal names. `ResilientLoop` calls
        `observe(t, loss=..., grad_norm=...)` at the step boundary."""
        reg = self._registry or default_registry()
        flagged_names = []
        for signal, value in signals.items():
            if value is None:
                continue
            z, flagged = self._ewma(signal).observe(value)
            if z is None:
                continue
            # copy-on-write: `last` is read by the train console's HTTP
            # thread mid-iteration — replace the dict atomically rather
            # than resizing one a reader may be walking
            self.last = dict(self.last, **{signal: (float(value), z)})
            if enabled():
                reg.gauge(SIGNAL_ZSCORE % signal,
                          help="EWMA z-score of %s, last step" % signal
                          ).set(z)
            if flagged:
                flagged_names.append(signal)
                self.anomalies += 1
                if enabled():
                    reg.counter(
                        ANOMALIES_TOTAL, flight=True,
                        help="finite-but-statistically-impossible "
                             "loss/grad-norm steps (EWMA z-score over "
                             "MXNET_ANOMALY_ZSCORE)"
                    ).inc(signal=signal, step=step)
                    from .flight import flight
                    flight().record("event", "train.anomaly",
                                    signal=signal, value=float(value),
                                    z=round(z, 3), step=step)
        return flagged_names
