"""Process-wide metrics registry: counters, gauges, fixed-bucket histograms.

The measurement substrate every scaling PR reports through (ISSUE 7):
one `MetricsRegistry` holds named metrics labeled by `host`/`replica`,
readable two ways —

  * `snapshot()`  — a plain JSON-able dict (the test observable and the
    payload bench.py attaches to each emitted line);
  * `prometheus_text()` — Prometheus text exposition (version 0.0.4),
    what the serving HTTP `/metrics` endpoint serves under
    `Accept: text/plain`.

Histograms are FIXED-BUCKET: `observe(v)` increments one bucket counter
plus a running sum/count, so p50/p95/p99 come from linear interpolation
inside the owning bucket — O(buckets) memory, no per-sample storage, and
the exposition is exactly Prometheus' cumulative `_bucket{le=...}` form.

There is one process-global default registry (`default_registry()`); the
serving stack builds a private registry per `ServingMetrics` so parallel
servers (and tests) never share counters. `MXNET_TELEMETRY=0` turns every
mutation into a no-op (reads still work: snapshots are just empty/zero).
"""
from __future__ import annotations

import bisect
import math
import os
import re
import threading
import time


def enabled():
    """Telemetry master switch: MXNET_TELEMETRY, default on (the
    instruments are a few ns each; production visibility should not be
    opt-in). `0` disables every metric mutation, span record, and flight
    event at the recording site."""
    return os.environ.get("MXNET_TELEMETRY", "1") != "0"


def _host_label():
    """This process's `host` label: MXNET_HOST_ID wins (the emulated
    multi-host drill sets it), else jax's process index if jax is
    already imported (never import it just for a label), else 0."""
    env = os.environ.get("MXNET_HOST_ID")
    if env is not None:
        return env
    import sys
    jax = sys.modules.get("jax")
    if jax is not None:
        try:
            return str(jax.process_index())
        except Exception:
            pass
    return "0"


def _replica_label():
    return os.environ.get("MXNET_REPLICA_ID", "0")


_NAME_RE = re.compile(r"[^a-zA-Z0-9_:]")


def _sane(name):
    """Prometheus metric-name charset: [a-zA-Z_:][a-zA-Z0-9_:]*."""
    name = _NAME_RE.sub("_", name)
    if name and name[0].isdigit():
        name = "_" + name
    return name


def _fmt(v):
    """Prometheus sample-value formatting (no pythonic 'inf'/'nan')."""
    if v != v:
        return "NaN"
    if v == float("inf"):
        return "+Inf"
    if v == float("-inf"):
        return "-Inf"
    if float(v) == int(v) and abs(v) < 1e15:
        return str(int(v))
    return repr(float(v))


#: default histogram buckets: seconds-scale latencies from 100 µs to
#: ~2 min (exponential, factor ~2.5) — wide enough for decode steps,
#: train steps, and checkpoint publishes alike.
DEFAULT_BUCKETS = (0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01,
                   0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0,
                   30.0, 60.0, 120.0)


class _Metric:
    kind = None

    def __init__(self, registry, name, help=""):
        self.registry = registry
        self.name = name
        self.help = help


class Counter(_Metric):
    """Monotonic counter. `flight=True` mirrors every increment into the
    process flight recorder (the bad-step/retry/preemption events the
    post-mortem timeline is made of)."""

    kind = "counter"

    def __init__(self, registry, name, help="", flight=False):
        super().__init__(registry, name, help)
        self._value = 0.0
        self._flight = flight

    def inc(self, delta=1, **attrs):
        if not enabled():
            return
        with self.registry._lock:
            self._value += delta
        if self._flight:
            from .flight import flight
            flight().record("metric", self.name, delta=delta,
                            value=self._value, **attrs)

    @property
    def value(self):
        return self._value


class Gauge(_Metric):
    kind = "gauge"

    def __init__(self, registry, name, help=""):
        super().__init__(registry, name, help)
        self._value = 0.0

    def set(self, value):
        if not enabled():
            return
        with self.registry._lock:
            self._value = float(value)

    def inc(self, delta=1):
        if not enabled():
            return
        with self.registry._lock:
            self._value += delta

    def dec(self, delta=1):
        self.inc(-delta)

    @property
    def value(self):
        return self._value


class Histogram(_Metric):
    """Fixed cumulative buckets + sum/count: quantiles without samples."""

    kind = "histogram"

    def __init__(self, registry, name, help="", buckets=DEFAULT_BUCKETS):
        super().__init__(registry, name, help)
        self.buckets = tuple(sorted(float(b) for b in buckets))
        if not self.buckets:
            raise ValueError("histogram %r needs at least one bucket"
                             % name)
        self._counts = [0] * (len(self.buckets) + 1)   # +1 = +Inf
        self.sum = 0.0
        self.count = 0

    def observe(self, value):
        if not enabled():
            return
        value = float(value)
        i = bisect.bisect_left(self.buckets, value)
        with self.registry._lock:
            self._counts[i] += 1
            self.sum += value
            self.count += 1

    def quantile(self, q):
        """Estimated q-quantile (0..1) by linear interpolation inside
        the owning bucket; None when empty. The +Inf bucket clamps to
        the largest finite bound (nothing better is known)."""
        if self.count == 0:
            return None
        rank = q * self.count
        cum = 0
        lo = 0.0
        for i, bound in enumerate(self.buckets):
            prev = cum
            cum += self._counts[i]
            if cum >= rank:
                frac = ((rank - prev) / self._counts[i]
                        if self._counts[i] else 0.0)
                # clamp: float interpolation must not overshoot the
                # bucket's own upper bound
                return min(bound,
                           lo + (bound - lo) * min(1.0, max(0.0, frac)))
            lo = bound
        return self.buckets[-1]

    def count_below(self, value):
        """Estimated number of observations <= `value`, interpolating
        linearly inside the owning bucket (the same convention as
        `quantile`, run in the other direction) — the SLO engine's
        good-event count for a latency threshold. Observations in the
        +Inf bucket are assumed to exceed any finite threshold."""
        value = float(value)
        cum = 0.0
        lo = 0.0
        for i, bound in enumerate(self.buckets):
            c = self._counts[i]
            if value >= bound:
                cum += c
                lo = bound
                continue
            if value > lo and c and bound > lo:
                cum += c * (value - lo) / (bound - lo)
            return cum
        return cum

    @property
    def mean(self):
        return self.sum / self.count if self.count else None


class MetricsRegistry:
    """Named metrics with common labels. Metric creation is idempotent:
    asking for an existing name returns the existing instance (so
    instrumentation sites never need creation-order coordination), but a
    kind mismatch raises."""

    def __init__(self, labels=None):
        self._lock = threading.RLock()
        self._metrics = {}
        self._labels = dict(labels or {})

    # -- creation ------------------------------------------------------------
    def _get(self, cls, name, **kwargs):
        name = _sane(name)
        with self._lock:
            m = self._metrics.get(name)
            if m is not None:
                if not isinstance(m, cls):
                    raise ValueError(
                        "metric %r already registered as %s, not %s"
                        % (name, m.kind, cls.kind))
                return m
            m = cls(self, name, **kwargs)
            self._metrics[name] = m
            return m

    def counter(self, name, help="", flight=False):
        return self._get(Counter, name, help=help, flight=flight)

    def gauge(self, name, help=""):
        return self._get(Gauge, name, help=help)

    def histogram(self, name, help="", buckets=DEFAULT_BUCKETS):
        return self._get(Histogram, name, help=help, buckets=buckets)

    # -- reading -------------------------------------------------------------
    def labels(self):
        out = {"host": _host_label(), "replica": _replica_label()}
        out.update(self._labels)
        return out

    def _label_str(self):
        return ",".join('%s="%s"' % (k, str(v).replace('"', '\\"'))
                        for k, v in sorted(self.labels().items()))

    def snapshot(self):
        """JSON-able view: {name: {...}} plus the label set. Histograms
        carry count/sum/mean/p50/p95/p99 and the raw bucket counts (the
        BENCH_* artifact payload)."""
        out = {"labels": self.labels(), "metrics": {}}
        with self._lock:
            metrics = dict(self._metrics)
        for name, m in sorted(metrics.items()):
            if m.kind == "histogram":
                out["metrics"][name] = {
                    "kind": "histogram",
                    "count": m.count,
                    "sum": m.sum,
                    "mean": m.mean,
                    "p50": m.quantile(0.50),
                    "p95": m.quantile(0.95),
                    "p99": m.quantile(0.99),
                    "buckets": {_fmt(b): c for b, c in
                                zip(list(m.buckets) + [float("inf")],
                                    m._counts)},
                }
            else:
                out["metrics"][name] = {"kind": m.kind, "value": m.value}
        return out

    def prometheus_text(self):
        """Prometheus text exposition format 0.0.4. The format is pinned
        by tests: HELP/TYPE comment pairs, label set on every sample,
        cumulative `_bucket{le=...}` + `_sum`/`_count` for histograms,
        trailing newline."""
        return merged_prometheus_text([self])

    def _sample_lines(self, lines, name, m):
        """Append one metric's sample lines (no HELP/TYPE) under this
        registry's label set."""
        labels = self._label_str()
        if m.kind == "histogram":
            cum = 0
            for bound, c in zip(list(m.buckets) + [float("inf")],
                                m._counts):
                cum += c
                lab = '%s,le="%s"' % (labels, _fmt(bound)) if labels \
                    else 'le="%s"' % _fmt(bound)
                lines.append("%s_bucket{%s} %d" % (name, lab, cum))
            lines.append("%s_sum{%s} %s" % (name, labels, _fmt(m.sum)))
            lines.append("%s_count{%s} %d" % (name, labels, m.count))
        else:
            lines.append("%s{%s} %s" % (name, labels, _fmt(m.value)))

    def reset(self):
        """Drop every metric (tests and bench.py's per-config isolation)."""
        with self._lock:
            self._metrics.clear()


def merged_prometheus_text(registries):
    """One Prometheus exposition over several registries — the
    multi-replica serving front door's `/metrics`: each engine replica
    records into a private registry labeled `replica="<i>"`, and the
    router merges them so every metric name appears ONCE with HELP/TYPE
    and one sample (or histogram series) per replica. Same-name metrics
    must agree on kind (first registry wins the HELP text)."""
    per = []
    for reg in registries:
        with reg._lock:
            per.append((reg, dict(reg._metrics)))
    names = sorted({n for _, ms in per for n in ms})
    lines = []
    for name in names:
        kinds = {ms[name].kind for _, ms in per if name in ms}
        if len(kinds) > 1:
            raise ValueError("metric %r registered with mixed kinds %r "
                             "across registries" % (name, sorted(kinds)))
        meta_done = False
        for reg, ms in per:
            m = ms.get(name)
            if m is None:
                continue
            if not meta_done:
                if m.help:
                    lines.append("# HELP %s %s" % (name, m.help))
                lines.append("# TYPE %s %s" % (name, m.kind))
                meta_done = True
            reg._sample_lines(lines, name, m)
    return "\n".join(lines) + "\n"


_default = MetricsRegistry()


def default_registry():
    """The process-global registry (training loop, checkpoint IO, bench
    instrumentation). Serving builds per-server registries instead."""
    return _default
