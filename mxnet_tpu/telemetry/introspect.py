"""Compile watchdog + executable memory accounting (ISSUE 9).

Every jit entry point the framework owns — `TrainStep`'s fused step, the
serving engine's prefill-chunk/decode executables, the full-forward
serving adapters, `predict.export_*` — registers through ONE seam:
`instrument(jax.jit(fn), site=...)`. The wrapper owns the executable
cache (signature -> `lower().compile()` AOT executable), so every
compilation is an explicit, observable event instead of a silent stall
inside jax's dispatch:

  * **Signature-diff attribution**: each compile is diffed against the
    site's cached signatures — which argument's shape / dtype / sharding
    / static flag changed, rendered as a human-readable reason
    ("tables: shape (1, 1) -> (1, 2) (axis 1)"). Sites are
    PROCESS-GLOBAL while executable caches are per-instance, so an
    engine restart that recompiles an already-seen signature is
    attributed as a `duplicate` (the cold-executable-cache gap the
    ROADMAP item-5 AOT cache exists to close), and a tp restart with
    unchanged shapes is attributed to the sharding diff.
  * **Recording**: a `compile` span (wall-time into the `compile_seconds`
    histogram), a flight-recorder event, a global `compile_total` and a
    per-site `compile_<site>_total` counter — all on the default
    registry, all no-ops under `MXNET_TELEMETRY=0` (signature tracking
    and the engine's recompile counters stay functional: they are
    behavior, not telemetry).
  * **Memory & cost accounting**: after each compile the executable's
    `memory_analysis()` / `cost_analysis()` (version-portable, absent
    gracefully on older jax) land in per-site gauges —
    `exec_<site>_{argument,output,temp,code,hbm}_bytes` and
    `exec_<site>_flops` — exported through the Prometheus exposition
    and every flight dump.
  * **Collective-comms ledger** (ISSUE 14): the compiled HLO is walked
    once per compile and every collective instruction (all-reduce,
    reduce-scatter, all-gather, all-to-all, collective-permute) is
    attributed to its site as per-kind byte/op gauges —
    `comms_<site>_<kind>_bytes` / `comms_<site>_<kind>_ops` — plus a
    derived `comms_<site>_fraction` (collective payload over the
    executable's total `bytes accessed`). Bytes are the per-device
    LOGICAL payload of each instruction, max(operand, result) — a
    reduce-scatter counts its full input, an all-gather its full
    output, so the ZeRO-1 train step's reduce-scatter/all-gather both
    read ≈ param bytes (the analytic pin in
    tests/test_train_observability.py) — not the ring-wire traffic
    (which is topology-dependent: 2(N−1)/N× for a ring all-reduce).
  * **Budgets**: `MXNET_COMPILE_BUDGET=<n>[:warn|:raise]` turns the
    (n+1)-th compile at any one site into a warning or a raise — a
    recompile storm fails loudly instead of silently eating throughput.
    `MXNET_HBM_BUDGET_GB=<gb>[:raise|:warn]` is a pre-flight check: an
    executable whose compiled footprint (arguments + outputs + temps +
    generated code) exceeds the budget is refused BEFORE dispatch
    (default) or warned about, instead of dying as an opaque device OOM
    mid-serve.

`watchdog().events()` is the in-process record (what tests and
`bench.py`'s `compile_s` / `exec_hbm_bytes` fields read);
`tools/postmortem.py` renders the flight-recorder copies.
"""
from __future__ import annotations

import contextlib
import functools
import os
import re
import threading
import time
import warnings
from collections import deque

from ..base import MXNetError
from .metrics import enabled, default_registry, _sane


class CompileBudgetExceeded(MXNetError):
    """MXNET_COMPILE_BUDGET=<n>:raise tripped: one site compiled more
    than <n> distinct programs — a recompile storm (an unstable shape
    bucket, a sharding flapping between configs) that would otherwise
    just eat throughput silently."""


class HbmBudgetExceeded(MXNetError):
    """MXNET_HBM_BUDGET_GB pre-flight refusal: the compiled executable's
    footprint exceeds the declared budget; refusing before dispatch
    beats an opaque device OOM mid-request."""


# -- metric-name templates (docs/OBSERVABILITY.md lists these; the static
# -- doc-drift check resolves `<site>` placeholders against them) ----------
COMPILE_SECONDS = "compile_seconds"
COMPILE_TOTAL = "compile_total"
COMPILE_DUPLICATE_TOTAL = "compile_duplicate_total"
COMPILE_OVERRUNS_TOTAL = "compile_budget_overruns_total"
SITE_COMPILE_TOTAL = "compile_%s_total"
COMPILE_CACHE_HITS = "compile_cache_hits"
COMPILE_CACHE_MISSES = "compile_cache_misses"
COMPILE_CACHE_STORES = "compile_cache_stores"
COMPILE_CACHE_LOAD_SECONDS = "compile_cache_load_seconds"
COMPILE_CACHE_CORRUPT_TOTAL = "compile_cache_corrupt_total"
EXEC_ARG_BYTES = "exec_%s_argument_bytes"
EXEC_OUT_BYTES = "exec_%s_output_bytes"
EXEC_TEMP_BYTES = "exec_%s_temp_bytes"
EXEC_CODE_BYTES = "exec_%s_code_bytes"
EXEC_HBM_BYTES = "exec_%s_hbm_bytes"
EXEC_FLOPS = "exec_%s_flops"
COMMS_BYTES = "comms_%s_%s_bytes"
COMMS_OPS = "comms_%s_%s_ops"
COMMS_FRACTION = "comms_%s_fraction"

#: compile-seconds histogram buckets: traces take ms, XLA compiles of a
#: fused train step take seconds to minutes
_COMPILE_BUCKETS = (0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0,
                    10.0, 30.0, 60.0, 120.0, 300.0, 600.0)

#: warm-load buckets: deserializing a cached executable is disk + PJRT
#: load work — milliseconds to a few seconds, never an XLA compile
_CACHE_LOAD_BUCKETS = (0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
                       0.25, 0.5, 1.0, 2.5, 5.0, 10.0)


def _parse_budget(env_var, default_policy, convert):
    """`<value>[:warn|:raise]` -> (converted value, policy) or
    (None, None). Any malformed part raises MXNetError NAMING the env
    var — this parse runs deep inside a compile, where a bare
    int()/float() ValueError would say nothing about its origin."""
    raw = os.environ.get(env_var)
    if not raw:
        return None, None
    value, _, policy = raw.partition(":")
    policy = policy or default_policy
    if policy not in ("warn", "raise"):
        raise MXNetError("%s policy must be warn or raise, got %r"
                         % (env_var, policy))
    try:
        value = convert(value)
    except ValueError:
        raise MXNetError("%s must be <number>[:warn|:raise], got %r"
                         % (env_var, raw))
    return value, policy


def compile_budget():
    """MXNET_COMPILE_BUDGET=<n>[:warn|:raise] — max distinct compilations
    per site; overruns warn by default. Returns (n, policy) or
    (None, None). Read at each compile, so it can be tightened live."""
    return _parse_budget("MXNET_COMPILE_BUDGET", "warn", int)


def hbm_budget_bytes():
    """MXNET_HBM_BUDGET_GB=<gb>[:raise|:warn] — pre-flight executable
    footprint ceiling; overruns refuse dispatch by default. Returns
    (bytes, policy) or (None, None)."""
    value, policy = _parse_budget("MXNET_HBM_BUDGET_GB", "raise", float)
    if value is None:
        return None, None
    return value * (1024.0 ** 3), policy


# ---------------------------------------------------------------------------
# signatures: what distinguishes one compiled program from another
# ---------------------------------------------------------------------------


try:
    from jax.sharding import NamedSharding as _NamedSharding
except Exception:                                        # pragma: no cover
    _NamedSharding = ()


@functools.lru_cache(maxsize=512)
def _sharding_desc_cached(s):
    """Stable string for a placement. NamedShardings render by mesh axis
    sizes + spec (two engines over equal-shaped meshes of different Mesh
    objects must produce EQUAL signatures, or every restart would read
    as a sharding diff) — the cache key is the sharding OBJECT, but the
    rendered value is identity-free, so unequal objects with the same
    placement still collide to one signature on a cache miss. signature()
    runs on EVERY dispatch; without the memo this rendering dominates
    the per-call cost."""
    if isinstance(s, _NamedSharding):
        axes = ",".join("%s=%d" % kv for kv in s.mesh.shape.items())
        # normalize the spec: P(None, 'tp', None) and P(None, 'tp')
        # are the same placement, but jit outputs trim trailing
        # Nones while device_put placements keep them — a raw repr
        # would misread every round-trip as a sharding change
        spec = tuple(s.spec)
        while spec and spec[-1] is None:
            spec = spec[:-1]
        return "NamedSharding({%s}, %s)" % (axes, spec)
    return type(s).__name__


def _sharding_desc(v):
    s = getattr(v, "sharding", None)
    if s is None or not getattr(v, "_committed", True):
        # numpy/python inputs and UNCOMMITTED device arrays produce the
        # same executable (jax's own cache treats them alike) — both
        # must read "host", or an engine feeding numpy decode batches
        # would recompile programs its jnp prefill args already built
        return "host"
    try:
        return _sharding_desc_cached(s)
    except Exception:                                    # pragma: no cover
        return type(s).__name__       # unhashable exotic sharding


@functools.lru_cache(maxsize=64)
def _dtype_str(dt):
    return str(dt)


def _leaf_sig(v):
    shape = getattr(v, "shape", None)
    if shape is None:
        # a python static (bool flag, enum string): its VALUE is part of
        # the program identity, unlike a dynamic array argument's
        return ("static", type(v).__name__, repr(v))
    try:
        dtype = _dtype_str(getattr(v, "dtype", "?"))
    except TypeError:                                    # pragma: no cover
        dtype = str(v.dtype)
    return (tuple(shape), dtype, _sharding_desc(v))


def signature(args):
    """Per-top-level-argument signature tuple for a positional call."""
    import jax
    return tuple(tuple(_leaf_sig(l) for l in jax.tree.leaves(a))
                 for a in args)


def _axes_changed(a, b):
    if len(a) != len(b):
        return "rank %d -> %d" % (len(a), len(b))
    axes = [i for i, (x, y) in enumerate(zip(a, b)) if x != y]
    return "axis " + ",".join(str(i) for i in axes) if axes else ""


def _leaf_diff(old_leaf, new_leaf):
    """One leaf's human-readable change."""
    if old_leaf[0] == "static" or new_leaf[0] == "static":
        return "static %s -> %s" % (old_leaf[-1], new_leaf[-1])
    parts = []
    if old_leaf[0] != new_leaf[0]:
        extra = _axes_changed(old_leaf[0], new_leaf[0])
        parts.append("shape %s -> %s%s"
                     % (old_leaf[0], new_leaf[0],
                        " (%s)" % extra if extra else ""))
    if old_leaf[1] != new_leaf[1]:
        parts.append("dtype %s -> %s" % (old_leaf[1], new_leaf[1]))
    if old_leaf[2] != new_leaf[2]:
        parts.append("sharding %s -> %s" % (old_leaf[2], new_leaf[2]))
    return ", ".join(parts) or "changed"


def _arg_diff(old_arg, new_arg):
    if len(old_arg) != len(new_arg):
        return "structure %d -> %d leaves" % (len(old_arg), len(new_arg))
    diffs = [i for i, (o, n) in enumerate(zip(old_arg, new_arg)) if o != n]
    if not diffs:
        return "unchanged"
    text = _leaf_diff(old_arg[diffs[0]], new_arg[diffs[0]])
    if len(old_arg) > 1:
        text = "leaf %d: %s" % (diffs[0], text)
    if len(diffs) > 1:
        text += " (+%d more leaves)" % (len(diffs) - 1)
    return text


def diff_reason(argnames, cached_sigs, new_sig):
    """Attribute a new signature to the smallest diff against the site's
    cached signatures: which ARGUMENT changed, and how. Returns the
    human-readable reason string the compile event carries."""
    candidates = [s for s in cached_sigs if len(s) == len(new_sig)]
    if not candidates:
        if cached_sigs:
            return ("argument structure changed (%d args -> %d args)"
                    % (len(next(iter(cached_sigs))), len(new_sig)))
        return "first compilation at this site"
    # nearest neighbor: fewest differing arguments
    def ndiff(s):
        return sum(1 for o, n in zip(s, new_sig) if o != n)
    best = min(candidates, key=ndiff)
    parts = []
    for i, (o, n) in enumerate(zip(best, new_sig)):
        if o == n:
            continue
        name = (argnames[i] if argnames and i < len(argnames)
                else "arg%d" % i)
        parts.append("%s: %s" % (name, _arg_diff(o, n)))
    return "; ".join(parts) if parts else "identical signature"


# ---------------------------------------------------------------------------
# the watchdog
# ---------------------------------------------------------------------------


class CompileSite:
    """One named compile seam. Signature history is PROCESS-wide (so a
    restarted engine diffs against its predecessor's signatures);
    executable caches live on the InstrumentedJit instances."""

    def __init__(self, name):
        self.name = name
        self.sane = _sane(name.replace(".", "_"))
        self.signatures = {}          # sig -> first-seen event seq
        self.compiles = 0             # process-wide compiles at this site
        self.duplicates = 0           # same-sig recompiles (cold caches)
        self.cache_hits = 0           # executables warm-loaded from disk
        self.comms = None             # latest executable's comms ledger


def _analyses(compiled):
    """(memory dict, flops, bytes accessed) from a compiled executable —
    the version-portable seam: every accessor is optional and a missing
    or failing one degrades to None, never to an exception (older jax /
    backends without CompiledMemoryStats)."""
    memory = None
    try:
        ma = compiled.memory_analysis()
        memory = {
            "argument_bytes": int(getattr(ma, "argument_size_in_bytes", 0)),
            "output_bytes": int(getattr(ma, "output_size_in_bytes", 0)),
            "temp_bytes": int(getattr(ma, "temp_size_in_bytes", 0)),
            "code_bytes": int(getattr(ma, "generated_code_size_in_bytes",
                                      0)),
            "alias_bytes": int(getattr(ma, "alias_size_in_bytes", 0)),
        }
        # aliased (donated) buffers overlap the argument set; don't
        # double-count them in the footprint
        memory["hbm_bytes"] = (memory["argument_bytes"]
                               + memory["output_bytes"]
                               - memory["alias_bytes"]
                               + memory["temp_bytes"]
                               + memory["code_bytes"])
    except Exception:
        memory = None
    flops = None
    bytes_accessed = None
    try:
        cost = compiled.cost_analysis()
        if isinstance(cost, (list, tuple)):
            cost = cost[0] if cost else {}
        flops = float(cost.get("flops", 0.0)) or None
        bytes_accessed = float(cost.get("bytes accessed", 0.0)) or None
    except Exception:
        flops = None
    return memory, flops, bytes_accessed


# ---------------------------------------------------------------------------
# collective-comms ledger: bytes per collective kind, read off the HLO
# ---------------------------------------------------------------------------

#: the collective opcodes the ledger attributes (gauge-name kinds are the
#: underscored forms: all_reduce, reduce_scatter, ...)
COLLECTIVE_KINDS = ("all_reduce", "reduce_scatter", "all_gather",
                    "all_to_all", "collective_permute")

_DTYPE_BYTES = {"pred": 1, "s4": 1, "u4": 1, "s8": 1, "u8": 1,
                "f8e4m3fn": 1, "f8e5m2": 1, "s16": 2, "u16": 2,
                "f16": 2, "bf16": 2, "s32": 4, "u32": 4, "f32": 4,
                "s64": 8, "u64": 8, "f64": 8, "c64": 8, "c128": 16}

_SHAPE_RE = re.compile(r"\b([a-z][a-z0-9]*)\[([0-9,]*)\]")
#: one collective instruction: `%name = RESULT opcode(OPERANDS...`,
#: where RESULT is a shape or a tuple of shapes. `-start` matches the
#: async forms; the paired `-done` (which would double-count) does not.
_COLLECTIVE_RE = re.compile(
    r"=\s+(\([^)]*\)|\S+)\s+"
    r"(all-reduce|reduce-scatter|all-gather|all-to-all|"
    r"collective-permute)((?:-start)?)\(([^)]*)")


def _shape_bytes(text):
    """Summed byte size of every `dtype[dims]` shape token in `text`."""
    total = 0
    for dtype, dims in _SHAPE_RE.findall(text):
        n = 1
        for d in dims.split(","):
            if d:
                n *= int(d)
        total += n * _DTYPE_BYTES.get(dtype, 4)
    return total


def comms_from_hlo(hlo_text):
    """{kind: {"bytes": b, "ops": n}} over the collective instructions
    of one (per-device SPMD) HLO module. Bytes are the instruction's
    logical payload — max(summed operand shapes, summed result shapes)
    — so a reduce-scatter counts its full input and an all-gather its
    full output: exactly the hand-computable ZeRO-1 sizes (≈ param
    bytes each), independent of which side the partitioner sharded.

    Known limit: this is a STATIC walk — a collective inside a
    while/scan body (e.g. ring attention's per-ring-step ppermute)
    counts once, not once per iteration, so the ledger is a lower
    bound for loop-heavy programs (trip counts are not recoverable
    from HLO text in general; docs/OBSERVABILITY.md discloses this)."""
    kinds = {}
    for result, opcode, started, operands in \
            _COLLECTIVE_RE.findall(hlo_text):
        in_bytes = _shape_bytes(operands)
        out_bytes = _shape_bytes(result)
        if started:
            # async form: the result tuple is (aliased input, real
            # output[, contexts]) — max(in, raw out) would double-count
            # the alias, while the bare operand undercounts an
            # all-gather-start (whose operand is the 1/N shard). The
            # real output side is result minus the aliased input.
            payload = max(in_bytes, out_bytes - in_bytes)
        else:
            payload = max(in_bytes, out_bytes)
        k = kinds.setdefault(opcode.replace("-", "_"),
                             {"bytes": 0, "ops": 0})
        k["bytes"] += payload
        k["ops"] += 1
    return kinds


def comms_ledger(compiled, bytes_accessed=None):
    """The per-executable collective ledger dict the watchdog records:
    {"kinds": {...}, "total_bytes", "bytes_accessed", "fraction"}.
    Returns None when the executable exposes no HLO text (an exported
    artifact observed `owned=False` never reaches here)."""
    try:
        txt = compiled.as_text()
    except Exception:
        return None
    if not txt:
        return None
    kinds = comms_from_hlo(txt)
    total = sum(k["bytes"] for k in kinds.values())
    fraction = None
    if bytes_accessed:
        # comms fraction of the step: collective payload over the
        # executable's total traffic ("bytes accessed", same per-device
        # cost model) — the at-a-glance "is this step collective-bound"
        # gauge. Payload is max(in, out) <= in + out, so it can't
        # exceed the traffic that contains it.
        fraction = total / float(bytes_accessed)
    return {"kinds": kinds, "total_bytes": total,
            "bytes_accessed": bytes_accessed, "fraction": fraction}


class Watchdog:
    """Process-wide compile observatory: named sites, a bounded event
    ring, and the metric/span/flight recording every compile flows
    through."""

    def __init__(self, registry=None):
        self._lock = threading.RLock()
        self._sites = {}
        self._events = deque(maxlen=512)
        self._seq = 0
        self._registry = registry
        self.total_seconds = 0.0

    def registry(self):
        return self._registry or default_registry()

    def site(self, name):
        with self._lock:
            s = self._sites.get(name)
            if s is None:
                s = self._sites[name] = CompileSite(name)
            return s

    def sites(self):
        with self._lock:
            return dict(self._sites)

    # -- budget gate (checked BEFORE paying a compile) ----------------------
    def check_budget(self, site):
        budget, policy = compile_budget()
        if budget is None or site.compiles + site.duplicates < budget:
            return
        msg = ("compile budget overrun at site %r: %d compilations "
               "already recorded, MXNET_COMPILE_BUDGET=%d (%s) — a "
               "recompile storm; see watchdog().events() for the "
               "signature diffs" % (site.name,
                                    site.compiles + site.duplicates,
                                    budget, policy))
        if enabled():
            self.registry().counter(
                COMPILE_OVERRUNS_TOTAL, flight=True,
                help="compile-budget overruns (MXNET_COMPILE_BUDGET)"
            ).inc(site=site.name)
        if policy == "raise":
            raise CompileBudgetExceeded(msg)
        warnings.warn(msg, RuntimeWarning, stacklevel=3)

    # -- recording ----------------------------------------------------------
    def record(self, site, sig, reason, seconds, phase=None, memory=None,
               flops=None, duplicate=False, start_us=None, comms=None):
        """Record one compile event (the seam `InstrumentedJit` and
        `compile_region` report through). Returns the event dict."""
        with self._lock:
            self._seq += 1
            seq = self._seq
            if duplicate:
                site.duplicates += 1
            else:
                site.compiles += 1
                if sig is not None:
                    site.signatures.setdefault(sig, seq)
            if comms is not None:
                site.comms = comms
            self.total_seconds += seconds
            ev = {"seq": seq, "site": site.name, "reason": reason,
                  "seconds": seconds, "phase": phase,
                  "duplicate": bool(duplicate), "t": time.time()}
            if memory:
                ev["memory"] = dict(memory)
                ev["hbm_bytes"] = memory.get("hbm_bytes")
            if flops:
                ev["flops"] = flops
            if comms is not None:
                ev["comms"] = comms
            self._events.append(ev)
        if enabled():
            reg = self.registry()
            reg.histogram(
                COMPILE_SECONDS, buckets=_COMPILE_BUCKETS,
                help="wall time per watchdog-observed compilation "
                     "(trace + XLA compile)").observe(seconds)
            reg.counter(COMPILE_TOTAL,
                        help="compilations across all watchdog sites"
                        ).inc()
            reg.counter(SITE_COMPILE_TOTAL % site.sane,
                        help="compilations at site %s" % site.name).inc()
            if duplicate:
                reg.counter(
                    COMPILE_DUPLICATE_TOTAL,
                    help="recompiles of an already-seen signature (cold "
                         "executable cache, e.g. an engine restart)"
                    ).inc()
            if memory:
                reg.gauge(EXEC_ARG_BYTES % site.sane,
                          help="argument bytes, latest executable"
                          ).set(memory["argument_bytes"])
                reg.gauge(EXEC_OUT_BYTES % site.sane,
                          help="output bytes, latest executable"
                          ).set(memory["output_bytes"])
                reg.gauge(EXEC_TEMP_BYTES % site.sane,
                          help="temp (live-activation) bytes, latest "
                               "executable").set(memory["temp_bytes"])
                reg.gauge(EXEC_CODE_BYTES % site.sane,
                          help="generated-code bytes, latest executable"
                          ).set(memory["code_bytes"])
                reg.gauge(EXEC_HBM_BYTES % site.sane,
                          help="total device footprint (args + outputs "
                               "- aliased + temps + code), latest "
                               "executable").set(memory["hbm_bytes"])
            if flops:
                reg.gauge(EXEC_FLOPS % site.sane,
                          help="declared flops, latest executable"
                          ).set(flops)
            if comms is not None:
                for kind, k in comms["kinds"].items():
                    reg.gauge(COMMS_BYTES % (site.sane, kind),
                              help="per-device %s payload bytes per "
                                   "step, latest executable"
                              % kind.replace("_", "-")).set(k["bytes"])
                    reg.gauge(COMMS_OPS % (site.sane, kind),
                              help="%s instructions in the latest "
                                   "executable"
                              % kind.replace("_", "-")).set(k["ops"])
                # the gauges claim "latest executable": a recompile
                # whose lowering DROPPED a kind must zero that kind's
                # existing gauges, not leave them advertising
                # collectives the running program no longer contains
                for kind in COLLECTIVE_KINDS:
                    if kind in comms["kinds"]:
                        continue
                    for tmpl in (COMMS_BYTES, COMMS_OPS):
                        name = tmpl % (site.sane, kind)
                        if name in reg._metrics:
                            reg.gauge(name).set(0)
                if comms["fraction"] is not None:
                    reg.gauge(COMMS_FRACTION % site.sane,
                              help="collective payload / total bytes "
                                   "accessed, latest executable"
                              ).set(comms["fraction"])
            if start_us is None:
                start_us = time.perf_counter_ns() // 1000 \
                    - int(seconds * 1e6)
            from .tracing import record_span
            record_span("compile", start_us, int(seconds * 1e6),
                        category="compile", to_flight=False,
                        site=site.name, reason=reason, phase=phase)
            from .flight import flight
            flight().record("event", "compile", site=site.name,
                            reason=reason, seconds=round(seconds, 4),
                            duplicate=bool(duplicate))
        return ev

    # -- AOT-cache recording (ISSUE 16) -------------------------------------
    def record_cache_hit(self, site, sig, seconds, phase=None):
        """One executable warm-loaded from the persistent AOT cache
        (mxnet_tpu/aot): the signature registers at the site (it IS now
        compiled in this process) but neither `compiles` nor
        `duplicates` advances — a warm load is the ABSENCE of the
        recompile the duplicate counter measures."""
        with self._lock:
            self._seq += 1
            seq = self._seq
            site.cache_hits += 1
            if sig is not None:
                site.signatures.setdefault(sig, seq)
            self.total_seconds += seconds
            ev = {"seq": seq, "site": site.name,
                  "reason": "warm-loaded from the AOT executable cache",
                  "seconds": seconds, "phase": phase, "duplicate": False,
                  "cache_hit": True, "t": time.time()}
            self._events.append(ev)
        if enabled():
            reg = self.registry()
            reg.counter(COMPILE_CACHE_HITS,
                        help="executables warm-loaded from the "
                             "persistent AOT cache (no XLA compile)"
                        ).inc()
            reg.histogram(COMPILE_CACHE_LOAD_SECONDS,
                          buckets=_CACHE_LOAD_BUCKETS,
                          help="wall time to load + rehydrate one "
                               "cached executable").observe(seconds)
            from .flight import flight
            flight().record("event", "compile_cache_hit", site=site.name,
                            seconds=round(seconds, 4))
        return ev

    def record_cache_miss(self, site):
        """A keyed lookup found no (valid) entry — the compile that
        follows will try to store one."""
        if enabled():
            self.registry().counter(
                COMPILE_CACHE_MISSES,
                help="AOT-cache lookups that fell through to a fresh "
                     "XLA compile").inc()

    def record_cache_store(self, site):
        if enabled():
            self.registry().counter(
                COMPILE_CACHE_STORES,
                help="executables serialized and published to the AOT "
                     "cache (atomic first-wins rename)").inc()

    def record_cache_corrupt(self, site):
        """A truncated/bit-flipped/stale entry failed verification: the
        file was quarantined and the caller recompiles — corruption
        costs one compile, never an error or a wrong executable."""
        if enabled():
            self.registry().counter(
                COMPILE_CACHE_CORRUPT_TOTAL, flight=True,
                help="AOT-cache entries that failed sha256/format/load "
                     "verification (quarantined, recompiled)").inc()
            from .flight import flight
            flight().record("event", "compile_cache_corrupt",
                            site=site.name)

    def check_hbm_budget(self, site, memory):
        """Pre-flight footprint gate, called after compile and BEFORE
        the first dispatch of a new executable."""
        if not memory:
            return
        budget, policy = hbm_budget_bytes()
        if budget is None or memory["hbm_bytes"] <= budget:
            return
        msg = ("executable at site %r needs %.3f GB of device memory "
               "(args %.3f + out %.3f - aliased %.3f + temp %.3f + code "
               "%.3f) but MXNET_HBM_BUDGET_GB=%.3f (%s)"
               % (site.name, memory["hbm_bytes"] / 1024.0 ** 3,
                  memory["argument_bytes"] / 1024.0 ** 3,
                  memory["output_bytes"] / 1024.0 ** 3,
                  memory["alias_bytes"] / 1024.0 ** 3,
                  memory["temp_bytes"] / 1024.0 ** 3,
                  memory["code_bytes"] / 1024.0 ** 3,
                  budget / 1024.0 ** 3, policy))
        if enabled():
            from .flight import flight
            flight().record("event", "hbm_budget_overrun", site=site.name,
                            hbm_bytes=memory["hbm_bytes"])
        if policy == "raise":
            raise HbmBudgetExceeded(msg)
        warnings.warn(msg, RuntimeWarning, stacklevel=3)

    # -- reading ------------------------------------------------------------
    def events(self, site=None):
        with self._lock:
            out = list(self._events)
        if site is not None:
            out = [e for e in out if e["site"] == site]
        return out

    def mark(self):
        """Opaque marker for `since()` — bench.py brackets one config."""
        with self._lock:
            return self._seq

    def since(self, mark):
        """(compile seconds, peak executable hbm_bytes or None) over the
        events recorded after `mark`."""
        evs = [e for e in self.events() if e["seq"] > mark]
        seconds = sum(e["seconds"] for e in evs)
        peaks = [e["hbm_bytes"] for e in evs if e.get("hbm_bytes")]
        return seconds, (max(peaks) if peaks else None)


#: per-thread count of compiles PAID by this thread's dispatches — the
#: attribution seam for callers (the serving engine) that share
#: instrumented jits across instances: bracket your own call with
#: `dispatch_mark()`/`dispatch_compiles_since()` and you count exactly
#: the compilations your call triggered, never a sibling's on another
#: thread (when two threads race to compile one signature, only the
#: winner's count advances — the loser dispatched a cached executable)
_dispatch_tls = threading.local()


def dispatch_mark():
    """Opaque marker for `dispatch_compiles_since` (thread-local)."""
    return getattr(_dispatch_tls, "count", 0)


def dispatch_compiles_since(mark):
    """Compiles this thread paid inside instrumented-jit dispatches
    since `mark` (survives MXNET_TELEMETRY=0: attribution is behavior,
    not telemetry)."""
    return getattr(_dispatch_tls, "count", 0) - mark


def dispatch_warm_mark():
    """Opaque marker for `dispatch_warm_loads_since` (thread-local):
    executables this thread warm-loaded from the AOT cache instead of
    compiling — the counterpart attribution seam to `dispatch_mark`."""
    return getattr(_dispatch_tls, "warm", 0)


def dispatch_warm_loads_since(mark):
    """Warm AOT-cache loads this thread's dispatches performed since
    `mark` (like compiles, attribution is behavior, not telemetry)."""
    return getattr(_dispatch_tls, "warm", 0) - mark


_watchdog = None
_watchdog_lock = threading.Lock()


def watchdog():
    """The process-wide watchdog (created on first use)."""
    global _watchdog
    if _watchdog is None:
        with _watchdog_lock:
            if _watchdog is None:
                _watchdog = Watchdog()
    return _watchdog


def reset():
    """Drop all sites/events (tests). Instances created before the reset
    keep recording into the OLD watchdog's sites."""
    global _watchdog
    with _watchdog_lock:
        _watchdog = None


# ---------------------------------------------------------------------------
# the instrumented jit wrapper
# ---------------------------------------------------------------------------


class InstrumentedJit:
    """Owns a jitted callable's executable cache so compiles are explicit.

    `owned=True` (default): a new signature triggers `lower().compile()`
    — the compile is timed WITHOUT the first execution, the executable's
    memory/cost analyses are pulled, the HBM pre-flight check runs, and
    subsequent same-signature calls dispatch the cached executable
    directly. `owned=False` observes a callable the wrapper can't AOT
    (e.g. a deserialized `jax.export` artifact): a first-seen signature
    is timed as compile+run (disclosed on the event) and no memory
    analysis is available.

    `.lower` and `.__wrapped__` delegate to the underlying jit, so AOT
    consumers (bench cost probes, bytes reports, `export_train_step`)
    keep working on the wrapped object.

    Dispatch cost: owning the cache means recomputing the signature on
    every call — O(leaves) Python work (measured ~0.3 ms for a 160-leaf
    train step, ~25 us for a 2-arg serving step, with the sharding/dtype
    rendering memoized). That is host-side work a real device step
    overlaps; the alternative (let jax dispatch and observe), would lose
    the pre-flight HBM gate (which must run BEFORE the first dispatch)
    and compile timing isolated from the first execution.

    Per-instance `compiles` / `compiles_by_phase` are the FUNCTIONAL
    counters (the serving engine's `prefill_compilations` /
    `decode_compilations` read them); they advance regardless of
    `MXNET_TELEMETRY` — only the recording is telemetry.
    """

    def __init__(self, jitted, site, argnames=None, phase=None,
                 owned=True, static_argnums=(), variant=None):
        self._jitted = jitted
        self._site = watchdog().site(site)
        self._argnames = tuple(argnames) if argnames else None
        self._phase = phase
        self._owned = owned
        # AOT-cache variant tag: two jits can share one site AND one
        # signature (the gather and paged decode steps do) — the tag,
        # with the lowered-text hash, keeps their disk entries apart
        self._variant = variant
        # a lowered executable takes only the DYNAMIC arguments; static
        # ones (part of the signature, so part of the cache key) must be
        # stripped at dispatch
        self._static = frozenset(static_argnums)
        self._compiled = {}            # sig -> executable (or jitted)
        # RLock: _compile_and_call runs UNDER it (two serving threads
        # sharing one adapter must not both pay the same XLA compile —
        # plain jax.jit was internally thread-safe here) and
        # _record_instance_compile re-enters it
        self._lock = threading.RLock()
        self.compiles = 0
        self.compiles_by_phase = {}
        # warm loads are counted APART from compiles: the engine's
        # recompile-bound tests (<=2 prefill / <=6 decode) stay
        # meaningful with the cache on, and `warm_loads` is the
        # restart-MTTR signal (how much XLA work the cache absorbed)
        self.warm_loads = 0
        self.warm_loads_by_phase = {}

    @property
    def site(self):
        return self._site.name

    @property
    def __wrapped__(self):
        return self._jitted.__wrapped__

    def lower(self, *args, **kwargs):
        return self._jitted.lower(*args, **kwargs)

    def _cache_size(self):
        """Distinct executables this instance holds (mirrors jax's
        `jit._cache_size`, which the wrapper replaces as cache owner)."""
        return len(self._compiled)

    def _record_instance_compile(self, phase):
        _dispatch_tls.count = getattr(_dispatch_tls, "count", 0) + 1
        with self._lock:
            self.compiles += 1
            if phase:
                self.compiles_by_phase[phase] = \
                    self.compiles_by_phase.get(phase, 0) + 1

    def _record_instance_warm_load(self, phase):
        _dispatch_tls.warm = getattr(_dispatch_tls, "warm", 0) + 1
        with self._lock:
            self.warm_loads += 1
            if phase:
                self.warm_loads_by_phase[phase] = \
                    self.warm_loads_by_phase.get(phase, 0) + 1

    def _dynamic(self, args):
        if not self._static:
            return args
        return tuple(a for i, a in enumerate(args) if i not in self._static)

    def __call__(self, *args, _phase=None):
        sig = signature(args)
        entry = self._compiled.get(sig)
        if entry is None:
            with self._lock:
                entry = self._compiled.get(sig)     # racing thread won?
                if entry is None:
                    if not self._owned:
                        # can't AOT: timed WITH the first execution,
                        # which therefore stays under the lock
                        return self._observe_first_call(sig, args,
                                                        _phase
                                                        or self._phase)
                    entry = self._compile(sig, args,
                                          _phase or self._phase)
            # the fresh executable's FIRST run happens outside the
            # lock — other signatures' compiles must not queue behind
            # this one's execution
        # an unowned entry is the jit itself: it takes every arg
        return entry(*(self._dynamic(args) if self._owned else args))

    def _diff_and_gate(self, wd, sig, gate=True):
        site = self._site
        with wd._lock:
            duplicate = sig in site.signatures
            cached = tuple(site.signatures)
        reason = ("signature already compiled in this process — cold "
                  "executable cache (engine restart / new instance)"
                  if duplicate
                  else diff_reason(self._argnames, cached, sig))
        if gate:
            wd.check_budget(site)
        return duplicate, reason

    # -- persistent AOT cache hooks (ISSUE 16) ------------------------------
    def _cache_key(self, site, sig, args):
        """(cache, key, lowered) for this call, or (None, None, None)
        when caching is off or this program can't be content-keyed (no
        deterministic lowered text) — an unkeyable program is simply
        never cached, it cannot hit a wrong entry."""
        if not self._owned:
            return None, None, None
        from .. import aot
        c = aot.cache()
        if c is None:
            return None, None, None
        try:
            lowered = self._jitted.lower(*args)
            text = lowered.as_text()
        except Exception:
            return None, None, None
        if not text:
            return None, None, None
        try:
            key = aot.key_for(site.name, sig, text,
                              variant=self._variant,
                              placement=aot.placement_key(args))
        except Exception:
            return None, None, None
        return c, key, lowered

    def _cache_load(self, wd, cache, site, key, sig, phase):
        """Warm-load one verified entry: corrupt/stale/undeserializable
        entries are quarantined and read as a miss (NEVER an error —
        the cache switches where the executable comes from, not what it
        computes)."""
        from .. import aot
        t0 = time.perf_counter()
        try:
            rec = cache.load(site.sane, key)
        except aot.CorruptEntry:
            wd.record_cache_corrupt(site)
            rec = None
        if rec is None:
            wd.record_cache_miss(site)
            return None
        payload, in_tree, out_tree, meta = rec
        try:
            compiled = aot.load_executable(payload, in_tree, out_tree)
        except Exception:
            cache.invalidate(site.sane, key)
            wd.record_cache_corrupt(site)
            wd.record_cache_miss(site)
            return None
        wd.record_cache_hit(site, sig, time.perf_counter() - t0,
                            phase=phase)
        self._record_instance_warm_load(phase)
        # the stored memory analysis re-arms the HBM pre-flight: a warm
        # load must refuse an over-budget executable exactly like the
        # compile that produced it did
        return self._gate_entry(wd, site, sig, compiled,
                                meta.get("memory"))

    def _cache_store(self, wd, cache, site, key, compiled, memory):
        try:
            from .. import aot
            blob = aot.serialize_executable_blob(compiled)
            if blob is None:
                return
            payload, trees = blob
            if cache.store(site.sane, key, payload, trees,
                           extra={"watchdog_site": site.name,
                                  "variant": self._variant,
                                  "memory": memory}):
                wd.record_cache_store(site)
        except Exception:
            # persistence must never break the serving/train path: an
            # unserializable executable just stays process-local
            pass

    def _gate_entry(self, wd, site, sig, compiled, memory):
        """HBM pre-flight + executable-cache insert, shared by the
        fresh-compile and warm-load paths."""
        try:
            # pre-flight: refuse (or warn about) an over-budget
            # executable BEFORE its first dispatch
            wd.check_hbm_budget(site, memory)
        except HbmBudgetExceeded:
            # cache a re-checking refuser, not nothing: a same-sig retry
            # must neither pay the compile again nor read as a
            # `duplicate` (the engine-restart signal) — and a budget
            # lifted live re-admits the already-built executable
            def entry(*dyn, _c=compiled, _m=memory, _s=site, _sig=sig):
                wd.check_hbm_budget(_s, _m)          # still over: raises
                self._compiled[_sig] = _c            # budget lifted
                return _c(*dyn)
        else:
            entry = compiled
        self._compiled[sig] = entry
        return entry

    def _compile(self, sig, args, phase):
        # caller holds self._lock: one compile per signature, fleet-wide
        wd = watchdog()
        site = self._site
        duplicate, reason = self._diff_and_gate(wd, sig, gate=False)
        cache, key, lowered = self._cache_key(site, sig, args)
        if cache is not None:
            entry = self._cache_load(wd, cache, site, key, sig, phase)
            if entry is not None:
                return entry
        # the compile budget gates only REAL compiles: a warm load
        # costs no XLA work, so it must neither consume
        # MXNET_COMPILE_BUDGET nor trip it
        wd.check_budget(site)
        t0_us = time.perf_counter_ns() // 1000
        t0 = time.perf_counter()
        if lowered is None:
            lowered = self._jitted.lower(*args)
        compiled = lowered.compile()
        seconds = time.perf_counter() - t0
        memory, flops, bytes_accessed = _analyses(compiled)
        # the ledger walk is pure telemetry (an HLO-text pass per
        # compile); under MXNET_TELEMETRY=0 it never runs
        comms = comms_ledger(compiled, bytes_accessed) if enabled() \
            else None
        wd.record(site, sig, reason, seconds, phase=phase,
                  memory=memory, flops=flops, duplicate=duplicate,
                  start_us=t0_us, comms=comms)
        self._record_instance_compile(phase)
        if cache is not None:
            self._cache_store(wd, cache, site, key, compiled, memory)
        return self._gate_entry(wd, site, sig, compiled, memory)

    def _observe_first_call(self, sig, args, phase):
        wd = watchdog()
        duplicate, reason = self._diff_and_gate(wd, sig)
        t0_us = time.perf_counter_ns() // 1000
        t0 = time.perf_counter()
        out = self._jitted(*args)
        wd.record(self._site, sig,
                  reason + " (timed with first execution)",
                  time.perf_counter() - t0, phase=phase,
                  duplicate=duplicate, start_us=t0_us)
        self._record_instance_compile(phase)
        self._compiled[sig] = self._jitted
        return out


def instrument(jitted, site, argnames=None, phase=None, owned=True,
               static_argnums=(), variant=None):
    """Register a jitted callable at a watchdog site. The one-line seam
    every framework jit entry point goes through. `static_argnums` must
    restate the jit's own (jax doesn't expose them on the jitted
    object): the lowered executable takes only the dynamic arguments.
    `variant` tags this instance's entries in the persistent AOT cache
    (mxnet_tpu/aot) — required disambiguation when two different jits
    register at one site and can trace identical signatures."""
    return InstrumentedJit(jitted, site, argnames=argnames, phase=phase,
                           owned=owned, static_argnums=static_argnums,
                           variant=variant)


@contextlib.contextmanager
def compile_region(site, phase=None, **attrs):
    """Time an explicit whole-compile region (jax.export in
    `predict.export_model` / `export_train_step`) as one watchdog
    compile event — no signature cache, every entry is a compile."""
    wd = watchdog()
    s = wd.site(site)
    wd.check_budget(s)
    t0_us = time.perf_counter_ns() // 1000
    t0 = time.perf_counter()
    # no try/finally: a region that RAISES produced no executable, so
    # recording it would masquerade the failure as a normal compile
    # (and bench's compile_s would absorb the aborted attempt's wall
    # time); the exception itself is the loud signal
    yield
    wd.record(s, None,
              "explicit compile region%s"
              % (" (%s)" % ", ".join("%s=%s" % kv
                                     for kv in sorted(attrs.items()))
                 if attrs else ""),
              time.perf_counter() - t0, phase=phase, start_us=t0_us)


def compile_events(site=None):
    """Recorded compile events, oldest first (`site=` filters)."""
    return watchdog().events(site)


def site_comms(site):
    """The latest compiled executable's collective-comms ledger at a
    site — {"kinds": {kind: {"bytes", "ops"}}, "total_bytes",
    "bytes_accessed", "fraction"} — or None before the first compile
    there (or under MXNET_TELEMETRY=0, where the HLO walk never runs)."""
    s = watchdog().sites().get(site)
    return s.comms if s is not None else None
