"""SLO engine + per-request lifecycle ledger (ISSUE 13).

Two halves of "what does the fleet owe its tenants, and is it paying?":

* **Objectives & burn rates** — declare service-level objectives (TTFT,
  inter-token latency, availability) fleet-wide or per tenant via the
  `MXNET_SLO_*` env knobs (docs/ENV_VARS.md). An `SLOTracker` rides each
  `ServingMetrics` registry and derives, FROM THE EXISTING HISTOGRAMS
  (no second measurement path): per-objective attainment (fraction of
  observations meeting the threshold), multi-window burn rates (SRE
  convention: observed bad fraction over the window divided by the
  error budget `1 - target`; a burn rate of 1.0 spends the budget
  exactly at the objective's horizon, >> 1 is an alarm), and
  error-budget-remaining gauges. All of it lands in the registry
  (`slo_<objective>_attainment`, `slo_<objective>_burn_rate_<window>s`,
  `slo_<objective>_budget_remaining`) so the merged Prometheus
  exposition carries it, and in the `/statusz` JSON endpoint both
  serving fronts expose.

* **Request lifecycle ledger** — every request's life (queued →
  shed/admitted → prefill chunks → first token → per-decode-step ITL →
  failover replay → finish/expire) streams as sampled JSONL to
  `MXNET_REQUEST_LOG` (sample fraction `MXNET_REQUEST_LOG_SAMPLE`,
  deterministic per trace id so one request's events are all-or-nothing
  even across a failover hop). The schema is pinned
  (`REQUEST_LOG_EVENTS` / `REQUEST_LOG_REQUIRED`, tests/test_slo.py).
  Failover-implicated requests additionally mirror their coarse
  lifecycle events into the crash flight recorder, so a postmortem
  timeline shows the victims' lifecycles interleaved with the faults
  that moved them (tools/chaos_serve.py pins this).

Token accounting (the goodput ledger the /statusz identity test pins):
every request is classified EXACTLY ONCE, at its terminal state —
delivered tokens are *goodput* (met the SLO) or *slow* (delivered but
SLO-violating), refused work is *shed* (admission-time unmeetable
deadline, brownout), *expired* (deadline/queue timeout passed while
queued), or *failed* (engine fault, orphaned). `submitted` increments by
the same amount at the same moment, so
``submitted == goodput + slow + shed + expired + failed`` holds at every
instant (with no SLO configured, `slow` is zero and the four-term
identity of ISSUE 13 holds verbatim). Failover replays additionally
count their salvaged tokens as *replayed* — extra work performed, not a
fifth terminal class.
"""
from __future__ import annotations

import bisect
import json
import os
import threading
import time
import zlib
from collections import deque

from .metrics import enabled

#: default burn-rate windows (seconds) — the SRE multi-window pattern:
#: a fast window pages, a slow window tickets. MXNET_SLO_WINDOWS
#: overrides ("60,300,3600").
DEFAULT_WINDOWS = (60, 300, 3600)

#: objective kinds and their histogram/counter sources + default targets
_KINDS = {
    "ttft": {"target": 0.95},
    "itl": {"target": 0.99},
    "availability": {"target": 0.999},
}

#: gauge-name templates (docs/OBSERVABILITY.md names these with
#: `<objective>`/`<window>` placeholders; the doc-drift check maps them
#: back onto these literals)
_ATTAIN = "slo_%s_attainment"
_BURN = "slo_%s_burn_rate_%ss"
_BUDGET = "slo_%s_budget_remaining"

#: the pinned request-log schema (tests/test_slo.py): every line is one
#: JSON object carrying at least REQUEST_LOG_REQUIRED, with `event` in
#: REQUEST_LOG_EVENTS
REQUEST_LOG_VERSION = 1
REQUEST_LOG_EVENTS = ("queued", "admitted", "shed", "expired",
                      "prefill_chunk", "first_token", "decode",
                      "failover", "finish")
REQUEST_LOG_REQUIRED = ("ts", "event", "request", "trace", "tenant")

#: coarse lifecycle events mirrored into the flight recorder for
#: failover-implicated requests (per-decode-step events would evict the
#: bounded ring's history — the black box keeps transitions, not tokens)
_FLIGHT_EVENTS = ("queued", "admitted", "first_token", "failover",
                  "finish", "shed", "expired")


def _sane_tenant(name):
    from .metrics import _sane
    return _sane(str(name))


class Objective:
    """One declared SLO: `kind` in ('ttft', 'itl', 'availability'),
    `threshold_s` (None for availability — its unit is outcomes, not
    latency), `target` the required good fraction, `tenant` None for
    fleet-wide."""

    def __init__(self, kind, threshold_s=None, target=None, tenant=None):
        if kind not in _KINDS:
            raise ValueError("unknown SLO kind %r (know %s)"
                             % (kind, ", ".join(sorted(_KINDS))))
        self.kind = kind
        self.threshold_s = (float(threshold_s)
                            if threshold_s is not None else None)
        self.target = float(target if target is not None
                            else _KINDS[kind]["target"])
        if not 0.0 < self.target < 1.0:
            raise ValueError("SLO target must be in (0, 1), got %r"
                             % target)
        self.tenant = str(tenant) if tenant is not None else None

    @property
    def budget(self):
        """Error budget: the tolerable bad fraction."""
        return 1.0 - self.target

    @property
    def key(self):
        """Sanitized metric-name stem: `ttft`, `itl_tenant_acme`, …"""
        if self.tenant is None:
            return self.kind
        return "%s_tenant_%s" % (self.kind, _sane_tenant(self.tenant))

    def describe(self):
        return {"objective": self.kind, "tenant": self.tenant,
                "threshold_ms": (round(self.threshold_s * 1e3, 3)
                                 if self.threshold_s is not None
                                 else None),
                "target": self.target}


def _parse_entries(name, raw, latency):
    """Entries out of one MXNET_SLO_* value: comma-separated
    `[tenant=]threshold_ms[:target]` (latency kinds) or
    `[tenant=]target` (availability). Raises naming the env var on
    malformed values — a half-armed SLO must fail loudly at
    construction, not silently report no burn."""
    out = []
    for entry in str(raw).split(","):
        entry = entry.strip()
        if not entry:
            continue
        tenant = None
        if "=" in entry:
            tenant, entry = entry.split("=", 1)
            tenant = tenant.strip() or None
        try:
            if latency:
                parts = entry.split(":")
                if len(parts) > 2:
                    raise ValueError(entry)
                threshold_s = float(parts[0]) / 1e3
                target = float(parts[1]) if len(parts) == 2 else None
            else:
                threshold_s, target = None, float(entry)
            if target is not None and not 0.0 < target < 1.0:
                # out-of-range targets fail HERE so the error names the
                # knob (99.9 is a percent, not a fraction — the most
                # likely operator slip)
                raise ValueError(entry)
            out.append((tenant, threshold_s, target))
        except ValueError:
            raise ValueError(
                "%s must be comma-separated %s entries with target a "
                "fraction in (0, 1), got %r"
                % (name, "[tenant=]<threshold_ms>[:<target>]" if latency
                   else "[tenant=]<target>", raw))
    return out


def parse_slo_env(environ=None):
    """The declared objectives: MXNET_SLO_TTFT_MS / MXNET_SLO_ITL_MS
    (comma-separated `[tenant=]threshold_ms[:target]`; default targets
    0.95 / 0.99) and MXNET_SLO_AVAILABILITY (`[tenant=]target`,
    fraction of terminal requests that must complete without error).
    Unset knobs declare nothing — the SLO layer then only keeps the
    token ledger."""
    env = os.environ if environ is None else environ
    objectives = []
    for kind, var, latency in (("ttft", "MXNET_SLO_TTFT_MS", True),
                               ("itl", "MXNET_SLO_ITL_MS", True),
                               ("availability", "MXNET_SLO_AVAILABILITY",
                                False)):
        raw = env.get(var)
        if not raw:
            continue
        for tenant, threshold_s, target in _parse_entries(var, raw,
                                                          latency):
            objectives.append(Objective(kind, threshold_s=threshold_s,
                                        target=target, tenant=tenant))
    return objectives


def burn_rate(good, total, budget):
    """Burn rate over one window: observed bad fraction / error budget
    (1.0 spends the budget exactly at the window's horizon; an empty
    window burns nothing). THE formula — gauges, /statusz payloads, and
    the fleet merge all call this one definition."""
    return ((total - good) / total / budget) if total else 0.0


def budget_remaining(good, total, budget):
    """Lifetime error budget left: 1 = untouched, <= 0 = spent (may go
    negative — overspend is information). No observations = untouched."""
    return (1.0 - (total - good) / (total * budget)) if total else 1.0


def parse_windows(environ=None):
    """Burn-rate windows in seconds (MXNET_SLO_WINDOWS, default
    60,300,3600)."""
    env = os.environ if environ is None else environ
    raw = env.get("MXNET_SLO_WINDOWS")
    if not raw:
        return DEFAULT_WINDOWS
    try:
        windows = tuple(sorted({int(w) for w in str(raw).split(",")
                                if w.strip()}))
        if not windows or any(w <= 0 for w in windows):
            raise ValueError(raw)
    except ValueError:
        raise ValueError("MXNET_SLO_WINDOWS must be comma-separated "
                         "positive seconds, got %r" % raw)
    return windows


class SLOTracker:
    """Burn-rate accounting for one ServingMetrics registry.

    `counts_fn(objective)` returns the objective's LIFETIME
    `(good, total)` — derived from the registry's own histograms and
    counters, so /statusz can never disagree with /metrics. `update()`
    (called on every read path) snapshots those counts into a bounded
    time ring and refreshes the attainment / burn-rate /
    budget-remaining gauges; `payload()` renders the /statusz block,
    including the raw per-window good/total deltas so a multi-replica
    front door can SUM trackers and recompute fleet burn exactly
    (`merge_slo`)."""

    def __init__(self, registry, counts_fn, objectives=None,
                 windows=None):
        self.registry = registry
        self.counts_fn = counts_fn
        self.objectives = (parse_slo_env() if objectives is None
                           else list(objectives))
        self.windows = tuple(parse_windows() if windows is None
                             else windows)
        self._lock = threading.Lock()
        self._ring = deque()          # (t, {key: (good, total)})
        self._gauges = {}

    def _gauge(self, name, help=""):
        g = self._gauges.get(name)
        if g is None:
            g = self._gauges[name] = self.registry.gauge(name, help=help)
        return g

    def ttft_threshold(self, tenant):
        """The TTFT objective governing `tenant` (tenant-scoped wins
        over fleet-wide), or None — the goodput classifier's question."""
        fleet = None
        for obj in self.objectives:
            if obj.kind != "ttft":
                continue
            if obj.tenant == tenant:
                return obj.threshold_s
            if obj.tenant is None:
                fleet = obj.threshold_s
        return fleet

    def update(self, now=None):
        """Snapshot lifetime counts and refresh every SLO gauge."""
        self._refresh(now)

    def _refresh(self, now=None):
        """ONE pass per read: compute lifetime counts, append the ring
        sample, derive per-window deltas, set every gauge — and return
        {key: (good, total, {window: (good_d, total_d, span_s)})} so
        payload() never recomputes what the gauges were just set from
        (a 3600s window scraped at 1 Hz makes the ring scan real
        work)."""
        if not self.objectives:
            return {}
        now = time.time() if now is None else now
        counts = {obj.key: self.counts_fn(obj) for obj in self.objectives}
        with self._lock:
            self._ring.append((now, counts))
            horizon = now - max(self.windows) - 60.0
            while len(self._ring) > 1 and self._ring[0][0] < horizon:
                self._ring.popleft()
            ring = list(self._ring)
        # ONE ring copy per refresh, window bases found by bisecting
        # the (time-sorted) timestamps — at 1 Hz scrapes a 3600s window
        # holds ~3700 samples, and a linear scan per objective per
        # window would be real work on the serving host
        ts = [t for t, _ in ring]
        out = {}
        for obj in self.objectives:
            good, total = counts[obj.key]
            # no observations yet -> nothing violated: attainment 1.0
            # (a cold replica must not read as burning)
            attain = (good / total) if total else 1.0
            self._gauge(_ATTAIN % obj.key,
                        help="fraction of observations meeting the "
                             "%s objective" % obj.kind).set(attain)
            self._gauge(_BUDGET % obj.key,
                        help="error budget remaining (1 = untouched, "
                             "<= 0 = spent)").set(
                budget_remaining(good, total, obj.budget))
            deltas = self._window_deltas(obj, now, ring, ts)
            for w, (gd, td, _span) in deltas.items():
                self._gauge(_BURN % (obj.key, w),
                            help="error-budget burn rate over the "
                                 "window (1.0 spends the budget at the "
                                 "horizon)").set(
                    burn_rate(gd, td, obj.budget))
            out[obj.key] = (good, total, deltas)
        return out

    def _window_deltas(self, obj, now, ring=None, ts=None):
        """{window_s: (good_delta, total_delta, actual_span_s)} against
        the oldest ring sample inside each window (the ring may be
        younger than the window — the actual span is reported so
        /statusz never overstates its evidence). `ring`/`ts` are the
        caller's pre-copied snapshot (one copy per refresh, shared by
        every objective); bases are found by bisect on the time-sorted
        timestamps."""
        if ring is None:
            with self._lock:
                ring = list(self._ring)
            ts = [t for t, _ in ring]
        if not ring:
            return {w: (0, 0, 0.0) for w in self.windows}
        t_now, cur = ring[-1]
        out = {}
        for w in self.windows:
            i = bisect.bisect_left(ts, t_now - w)
            base_t, base = ring[min(i, len(ring) - 1)]
            g0, t0 = base.get(obj.key, (0, 0))
            g1, t1 = cur.get(obj.key, (0, 0))
            out[w] = (max(0.0, g1 - g0), max(0.0, t1 - t0),
                      max(0.0, t_now - base_t))
        return out

    def payload(self, now=None):
        """The /statusz `slo` block: one dict per objective with
        attainment, budget remaining, and per-window burn (carrying the
        raw good/total deltas for exact fleet merging)."""
        if not self.objectives:
            return []
        computed = self._refresh(now)
        out = []
        for obj in self.objectives:
            good, total, deltas = computed[obj.key]
            d = obj.describe()
            d.update(good=round(good, 3), total=round(total, 3),
                     attainment=(round(good / total, 6) if total
                                 else None),
                     budget_remaining=round(
                         budget_remaining(good, total, obj.budget), 6),
                     burn={})
            for w, (gd, td, span) in deltas.items():
                d["burn"]["%ss" % w] = {
                    "rate": round(burn_rate(gd, td, obj.budget), 6),
                    "good": round(gd, 3),
                    "total": round(td, 3), "span_s": round(span, 3)}
            out.append(d)
        return out


def merge_slo(payloads):
    """Fleet view over several replicas' /statusz `slo` blocks: same
    objective (kind + tenant + threshold + target) sums its lifetime
    and per-window good/total across replicas, and burn/attainment are
    recomputed from the sums — NOT averaged, so an idle replica can't
    dilute a burning one."""
    merged = {}
    for block in payloads:
        for d in block or []:
            key = (d.get("objective"), d.get("tenant"),
                   d.get("threshold_ms"), d.get("target"))
            m = merged.get(key)
            if m is None:
                m = merged[key] = {
                    "objective": d.get("objective"),
                    "tenant": d.get("tenant"),
                    "threshold_ms": d.get("threshold_ms"),
                    "target": d.get("target"),
                    "good": 0.0, "total": 0.0, "burn": {}}
            m["good"] += d.get("good") or 0
            m["total"] += d.get("total") or 0
            for w, b in (d.get("burn") or {}).items():
                mw = m["burn"].setdefault(
                    w, {"good": 0.0, "total": 0.0, "span_s": 0.0})
                mw["good"] += b.get("good") or 0
                mw["total"] += b.get("total") or 0
                mw["span_s"] = max(mw["span_s"], b.get("span_s") or 0)
    out = []
    for m in merged.values():
        budget = 1.0 - float(m["target"])
        total = m["total"]
        m["attainment"] = (round(m["good"] / total, 6) if total
                           else None)
        m["budget_remaining"] = round(
            budget_remaining(m["good"], total, budget), 6)
        for w, b in m["burn"].items():
            b["rate"] = round(burn_rate(b["good"], b["total"], budget),
                              6)
        out.append(m)
    out.sort(key=lambda m: (m["objective"], m["tenant"] or ""))
    return out


# ---------------------------------------------------------------------------
# request lifecycle ledger: sampled JSONL + flight mirroring
# ---------------------------------------------------------------------------


class RequestLog:
    """Append-only JSONL stream of request lifecycle events.

    Enabled by `MXNET_REQUEST_LOG=<path>`; `MXNET_REQUEST_LOG_SAMPLE`
    (default 1.0) keeps that fraction of requests, decided
    DETERMINISTICALLY from the trace id (crc32), so a sampled request
    stays sampled across replicas and failover hops and an unsampled
    one never leaves half a lifecycle. Env is re-read per event, so the
    log can be pointed somewhere (or off) mid-process; the file handle
    is cached per path and writes are line-atomic under a lock."""

    def __init__(self):
        self._lock = threading.Lock()
        self._path = None
        self._fh = None

    @property
    def enabled(self):
        return bool(os.environ.get("MXNET_REQUEST_LOG"))

    def sample_rate(self):
        raw = os.environ.get("MXNET_REQUEST_LOG_SAMPLE")
        if not raw:
            return 1.0
        try:
            rate = float(raw)
        except ValueError:
            rate = -1.0
        if not 0.0 <= rate <= 1.0:
            # "50" meaning 50% must fail loudly, not silently clamp to
            # full-volume logging (same contract as the MXNET_SLO_*
            # percent-vs-fraction guard)
            raise ValueError("MXNET_REQUEST_LOG_SAMPLE must be a "
                             "fraction in [0, 1], got %r" % raw)
        return rate

    def sampled(self, trace):
        try:
            rate = self.sample_rate()
        except ValueError:
            # the knob is validated LOUDLY at ServingMetrics
            # construction; a malformed value flipped in mid-process is
            # downgraded here to full sampling + a one-time warning —
            # event() runs on the serving thread, where a config typo
            # must never read as a loop death
            if not getattr(self, "_warned_sample", False):
                self._warned_sample = True
                import warnings
                warnings.warn("malformed MXNET_REQUEST_LOG_SAMPLE %r "
                              "ignored (logging every request)"
                              % os.environ.get(
                                  "MXNET_REQUEST_LOG_SAMPLE"))
            rate = 1.0
        if rate >= 1.0:
            return True
        if rate <= 0.0:
            return False
        h = zlib.crc32(str(trace).encode()) & 0xffffffff
        return h / 4294967296.0 < rate

    def event(self, event, req, replica=None, **fields):
        """Append one lifecycle event for `req` (needs .id/.trace/
        .tenant). Silently a no-op when the log is off, the request is
        unsampled, or telemetry is killed; a failing write disables
        nothing but never raises into the serving loop."""
        if not enabled():
            return None
        path = os.environ.get("MXNET_REQUEST_LOG")
        if not path:
            return None
        trace = getattr(req, "trace", None)
        if not self.sampled(trace):
            return None
        rec = {"ts": time.time(), "event": str(event),
               "request": getattr(req, "id", None), "trace": trace,
               "tenant": getattr(req, "tenant", None)}
        if replica is not None:
            rec["replica"] = replica
        for k, v in fields.items():
            if v is not None:
                rec[k] = v
        line = json.dumps(rec, default=str) + "\n"
        try:
            with self._lock:
                if self._fh is None or self._path != path:
                    if self._fh is not None:
                        self._fh.close()
                    self._fh = open(path, "a")
                    self._path = path
                self._fh.write(line)
                self._fh.flush()
        except OSError:
            return None
        return rec


_log = None
_log_lock = threading.Lock()


def request_log():
    """The process-wide request log (created on first use)."""
    global _log
    if _log is None:
        with _log_lock:
            if _log is None:
                _log = RequestLog()
    return _log


def request_event(event, req, replica=None, **fields):
    """One lifecycle transition: streamed to the sampled JSONL request
    log, and — for failover-implicated requests (the event is the hop
    itself, or the request already spent a hop) — mirrored as a coarse
    event into the crash flight recorder, so `tools/postmortem.py`
    timelines show the victims' lifecycles next to the faults that
    moved them."""
    if not enabled():
        return
    request_log().event(event, req, replica=replica, **fields)
    if event in _FLIGHT_EVENTS and (
            event == "failover" or getattr(req, "failovers", 0)):
        from .flight import flight
        flight().record("event", "request.%s" % event,
                        request=getattr(req, "id", None),
                        trace=getattr(req, "trace", None),
                        tenant=getattr(req, "tenant", None),
                        replica=replica, **fields)
