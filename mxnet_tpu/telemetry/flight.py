"""Crash flight recorder: a black box for training and serving processes.

A bounded ring buffer of recent happenings — closed spans, flagged metric
increments (bad steps, rollbacks, retries, engine failures), chaos fault
injections — that costs a deque append while the process is healthy and
dumps to disk the moment something goes wrong:

  * SIGTERM / preemption notice (`PreemptionWatcher` dumps before the
    drain even starts, so a drain that wedges still leaves a record);
  * an unhandled serving-loop fault (`LMServer._loop`);
  * `/healthz` wedge detection (first `health()` call that observes a
    dead-or-stalled loop);
  * explicitly, via `flight().dump(reason)`.

Dumps land in `MXNET_FLIGHT_RECORDER_DIR` as one JSON file per dump
(`flight-host<h>-pid<p>-<n>.<reason>.json`) carrying the ring, the
process labels, and a snapshot of the default metrics registry — enough
for `tools/postmortem.py` to render a human-readable timeline of a dead
pod's last seconds. With the env var unset, recording still happens (the
in-process ring is readable by tests/tools) but nothing is written to
disk unless a dump path is passed explicitly.

Ring size: `MXNET_FLIGHT_RECORDER_RING` (default 512 events).
"""
from __future__ import annotations

import json
import os
import threading
import time
from collections import deque

from .metrics import enabled, _host_label, default_registry


class FlightRecorder:
    def __init__(self, capacity=None):
        if capacity is None:
            capacity = int(os.environ.get("MXNET_FLIGHT_RECORDER_RING",
                                          "512"))
        self.capacity = int(capacity)
        self._ring = deque(maxlen=self.capacity)
        # REENTRANT: dump() runs inside signal handlers (PreemptionWatcher
        # SIGTERM), which Python executes on the main thread — possibly
        # interrupting a record() that already holds this lock. A plain
        # Lock would deadlock the handler; with an RLock the re-entry is
        # safe (the guarded deque ops are single C calls a signal can't
        # split).
        self._lock = threading.RLock()
        self._dumps = 0
        self._wall0 = time.time()
        self._perf0 = time.perf_counter()

    # -- recording -----------------------------------------------------------
    def record(self, kind, name, **data):
        """Append one event. `kind` is 'span' | 'metric' | 'event' |
        'fault'; `data` must be JSON-able (the dump writes it as-is)."""
        if not enabled():
            return
        ev = {"t": time.time(), "kind": kind, "name": name}
        if data:
            ev.update(data)
        with self._lock:
            self._ring.append(ev)

    def events(self):
        with self._lock:
            return list(self._ring)

    def clear(self):
        with self._lock:
            self._ring.clear()

    # -- dumping -------------------------------------------------------------
    def dump_dir(self):
        return os.environ.get("MXNET_FLIGHT_RECORDER_DIR")

    def dump(self, reason, path=None):
        """Write the black box to disk. Returns the path, or None when no
        directory is configured and no explicit path was given. Never
        raises: a failing dump must not mask the fault being dumped."""
        try:
            if path is None:
                d = self.dump_dir()
                if not d:
                    return None
                os.makedirs(d, exist_ok=True)
                with self._lock:
                    self._dumps += 1
                    n = self._dumps
                path = os.path.join(
                    d, "flight-host%s-pid%d-%d.%s.json"
                    % (_host_label(), os.getpid(), n,
                       "".join(c if c.isalnum() or c in "-_" else "_"
                               for c in str(reason))))
            doc = {
                "reason": str(reason),
                "host": _host_label(),
                "pid": os.getpid(),
                "dumped_at": time.time(),
                "ring_capacity": self.capacity,
                "events": self.events(),
                "metrics": default_registry().snapshot(),
            }
            tmp = path + ".tmp-%d" % os.getpid()
            with open(tmp, "w") as f:
                json.dump(doc, f)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, path)
            return path
        except Exception:
            return None


_flight = None
_flight_lock = threading.Lock()


def flight():
    """The process-wide flight recorder (created on first use)."""
    global _flight
    if _flight is None:
        with _flight_lock:
            if _flight is None:
                _flight = FlightRecorder()
    return _flight
