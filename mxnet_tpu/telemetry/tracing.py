"""Span tracing: one request's (or train step's) life as a connected trace.

`span(name, trace=..., **attrs)` is a context manager timing a region.
Every closed span is recorded three ways:

  * the legacy chrome-trace recorder (`profiler.record_event`, when the
    profiler is running) — so existing `profiler.dump()` traces gain the
    serving/training spans alongside the op-level events;
  * the in-process span ring (bounded; `export_perfetto()` turns it into
    a Perfetto-loadable JSON trace where every trace id is its own row);
  * the flight recorder ring (`telemetry.flight`) — the post-mortem
    record of "what was this process doing right before it died".

Trace ids connect spans: the serving stack uses the request id, so one
request's submit → queue → prefill chunks → decode steps all share an id
and render as a single row. Ids propagate implicitly to nested spans via
a thread-local (set once at the root span, inherited below), or
explicitly with `span(..., trace=id)` / `record_span(..., trace=id)` for
regions timed outside a `with` block (e.g. one decode step fanned out to
every sequence it advanced).
"""
from __future__ import annotations

import itertools
import json
import os
import re
import threading
import time
import uuid
from collections import deque

import zlib

from .. import profiler
from .metrics import enabled, default_registry, _host_label

_ids = itertools.count(1)
_tls = threading.local()

#: closed spans, newest last. Bounded: tracing must be always-on-able
#: without growing without bound; export before the ring wraps (or raise
#: MXNET_TELEMETRY_SPAN_RING).
_ring_size = int(os.environ.get("MXNET_TELEMETRY_SPAN_RING", "8192"))
_spans = deque(maxlen=_ring_size)
_lock = threading.Lock()
#: highest span id the last export_perfetto() saw: an overwrite of a
#: NEWER span is a drop the operator never got to see (ISSUE 13 — drops
#: were silent before; now they land on `spans_dropped_total` and the
#: ring fill rides the `span_ring_occupancy` gauge)
_exported_upto = 0


#: cached (counter, gauge) pair — record_span runs once per request per
#: decode step, so it must not pay a locked registry lookup per span.
#: Invalidated when the default registry is reset (bench.py's
#: per-config isolation): the cached counter identity is checked
#: against the registry's current entry with one plain dict read.
_ring_cache = None
_occupancy_last = -1


def _ring_instruments():
    global _ring_cache
    reg = default_registry()
    cached = _ring_cache
    if cached is not None and cached[0] is reg and \
            reg._metrics.get("spans_dropped_total") is cached[1]:
        return cached[1], cached[2]
    ctr = reg.counter("spans_dropped_total",
                      help="spans evicted from the bounded span ring "
                           "before any export_perfetto() saw them "
                           "(raise MXNET_TELEMETRY_SPAN_RING or "
                           "export more often)")
    gauge = reg.gauge("span_ring_occupancy",
                      help="span-ring fill fraction (len / capacity)")
    _ring_cache = (reg, ctr, gauge)
    return ctr, gauge


# -- W3C trace context (traceparent) ----------------------------------------

#: traceparent: version "-" trace-id "-" parent-id "-" flags
#: (https://www.w3.org/TR/trace-context/); version ff is forbidden and
#: all-zero trace/parent ids are invalid
_TRACEPARENT_RE = re.compile(
    r"^([0-9a-f]{2})-([0-9a-f]{32})-([0-9a-f]{16})-([0-9a-f]{2})$")


def new_trace_id():
    """A fresh 32-hex W3C-compatible trace id."""
    return uuid.uuid4().hex


def parse_traceparent(value):
    """The trace id out of a W3C `traceparent` header, or None for
    anything malformed (wrong field count, bad charset, all-zero ids,
    the forbidden ff version, bytes, whitespace garbage …). Callers
    MUST treat None as "start a fresh trace", never as an error — a
    client sending garbage must not be able to 500 the frontend."""
    try:
        m = _TRACEPARENT_RE.match(str(value).strip().lower())
    except Exception:
        return None
    if m is None:
        return None
    version, trace_id, parent_id, _flags = m.groups()
    if version == "ff":
        return None
    if trace_id == "0" * 32 or parent_id == "0" * 16:
        return None
    return trace_id


def format_traceparent(trace, parent_id=None, sampled=True):
    """Render a trace id back into a `traceparent` header value. A
    trace id that is not already 32-hex (an in-process id) is folded
    into one deterministically, so the emitted header is always
    well-formed."""
    t = str(trace).lower()
    if not re.match(r"^[0-9a-f]{32}$", t):
        t = uuid.uuid5(uuid.NAMESPACE_OID, str(trace)).hex
    if parent_id is None:
        parent_id = uuid.uuid4().hex[:16]
    return "00-%s-%s-%s" % (t, parent_id, "01" if sampled else "00")


def current_trace():
    """The thread's active trace id, or None."""
    return getattr(_tls, "trace", None)


def set_trace(trace):
    """Set the thread's trace id; returns the previous value (restore it
    when the propagation scope ends)."""
    prev = getattr(_tls, "trace", None)
    _tls.trace = trace
    return prev


def _now_us():
    return time.perf_counter_ns() // 1000


def record_span(name, start_us, dur_us, trace=None, category="trace",
                to_profiler=True, to_flight=True, **attrs):
    """Record one already-timed span. The seam for fan-out: a batched
    decode step is timed once but attributed to every request it
    advanced, so each request's row stays connected. The per-request
    copies only matter to the span ring (their Perfetto rows):
    `to_profiler=False` keeps them out of the chrome trace and
    `to_flight=False` out of the flight-recorder ring, where B duplicate
    copies per decode step would evict the history the black box exists
    to keep (the batch-level span covers the interval in both)."""
    if not enabled():
        return
    if trace is None:
        trace = current_trace()
    rec = {"id": next(_ids), "name": name, "cat": category,
           "trace": trace, "ts": start_us, "dur": dur_us,
           "pid": os.getpid(), "tid": threading.get_ident()}
    if attrs:
        rec["attrs"] = attrs
    global _occupancy_last
    dropped, occupancy = 0, 0.0
    with _lock:
        if len(_spans) == _spans.maxlen \
                and _spans[0]["id"] > _exported_upto:
            # the ring is about to overwrite a span no export has seen:
            # a silent gap in the next Perfetto row (satellite, ISSUE 13)
            dropped = 1
        _spans.append(rec)
        occupancy = len(_spans) / float(_spans.maxlen or 1)
    # quantize the occupancy gauge so a full (or slowly-filling) ring
    # doesn't pay a locked gauge.set per span on the decode hot path;
    # a registry reset (bench.py per-config isolation) drops the cached
    # instruments, so the staleness check below re-creates AND re-sets
    # them even at a steady quantized fill
    cache = _ring_cache
    reg = default_registry()
    stale = (cache is None or cache[0] is not reg or
             reg._metrics.get("spans_dropped_total") is not cache[1])
    occ_q = int(occupancy * 128)
    if dropped or stale or occ_q != _occupancy_last:
        ctr, gauge = _ring_instruments()
        if dropped:
            ctr.inc()
        gauge.set(occupancy)
        _occupancy_last = occ_q
    if to_profiler:
        profiler.record_event(name, category, start_us, dur_us,
                              dict(attrs, trace=trace) if attrs
                              else {"trace": trace})
    if to_flight:
        from .flight import flight
        flight().record("span", name, trace=trace, dur_us=dur_us,
                        **attrs)
    return rec


class span:
    """Time a region and record it as a span. Usage:

        with telemetry.span("serving.prefill", trace=req.id, chunk=3):
            ...

    `trace=None` inherits the thread's current trace id; passing an
    explicit id also makes it the thread's current id for the duration
    (nested spans connect automatically)."""

    def __init__(self, name, trace=None, category="trace", **attrs):
        self.name = name
        self.category = category
        self.attrs = attrs
        self._trace = trace
        self._prev = None

    def __enter__(self):
        if self._trace is not None:
            self._prev = set_trace(self._trace)
        self._t0 = _now_us()
        return self

    def __exit__(self, exc_type, exc, tb):
        t1 = _now_us()
        if exc_type is not None:
            self.attrs.setdefault("error", exc_type.__name__)
        record_span(self.name, self._t0, t1 - self._t0,
                    trace=self._trace, category=self.category,
                    **self.attrs)
        if self._trace is not None:
            set_trace(self._prev)
        return False


def spans(trace=None):
    """Recorded spans, oldest first; `trace=` filters to one id."""
    with _lock:
        out = list(_spans)
    if trace is not None:
        out = [s for s in out if s["trace"] == trace]
    return out


def clear():
    """Drop the ring (tests)."""
    global _exported_upto, _occupancy_last
    with _lock:
        _spans.clear()
        _exported_upto = 0
    _occupancy_last = -1


def host_pid(host, pid):
    """Fold a host label into the numeric pid a Perfetto row keys on.
    Traces merged across a pod's hosts (tools/postmortem.py --perfetto)
    can carry the SAME OS pid on different hosts (containers all start
    at pid 1), which would silently merge their rows; folding the host
    into the high digits keeps every host's rows distinct while the low
    digits stay the recognizable OS pid."""
    try:
        h = int(host)
    except (TypeError, ValueError):
        h = zlib.crc32(str(host).encode())
    # 1e9 host slots: numeric pod indices never wrap, and crc32 string
    # labels collide only at ~1/1e9 per pair (the residual window is
    # disclosed here; pids stay well inside exact-int JSON range)
    return (h % 1_000_000_000) * 1_000_000 + int(pid) % 1_000_000


def export_perfetto(path=None):
    """Write the span ring as Perfetto-compatible chrome-trace JSON.

    Each distinct trace id becomes its own thread row (`tid` = trace id,
    named by a thread_name metadata event), so loading the file in
    Perfetto/chrome://tracing shows one request's whole life — queue,
    prefill chunks, decode steps — as a single connected row; untraced
    spans keep their real thread id. The process row folds
    MXNET_HOST_ID into the pid (`host_pid`) and is named
    `host <h> pid <p>`, so exports from different pod hosts can be
    merged without their rows colliding. Returns the trace dict (and
    writes it to `path` when given)."""
    global _exported_upto
    with _lock:
        recs = list(_spans)
        if recs:    # spans up to here have been exported: only younger
            # ones count as dropped if the ring overwrites them
            _exported_upto = max(_exported_upto, recs[-1]["id"])
    host = _host_label()
    events = []
    rows = {}
    pids = {}
    for r in recs:
        tid = r["tid"]
        if r["trace"] is not None:
            # stable small row ids: first-seen order per trace id
            tid = rows.setdefault(r["trace"], 1_000_000 + len(rows))
        pid = host_pid(host, r["pid"])
        pids[pid] = r["pid"]
        ev = {"name": r["name"], "cat": r["cat"], "ph": "X",
              "ts": r["ts"], "dur": r["dur"], "pid": pid,
              "tid": tid,
              "args": dict(r.get("attrs") or {}, trace=r["trace"],
                           span_id=r["id"], host=host)}
        events.append(ev)
    this_pid = host_pid(host, os.getpid())
    pids.setdefault(this_pid, os.getpid())
    for trace, tid in rows.items():
        events.append({"name": "thread_name", "ph": "M",
                       "pid": this_pid, "tid": tid,
                       "args": {"name": "trace %s" % (trace,)}})
    for pid, os_pid in pids.items():
        events.append({"name": "process_name", "ph": "M", "pid": pid,
                       "tid": 0,
                       "args": {"name": "host %s pid %s"
                                % (host, os_pid)}})
    events.sort(key=lambda e: e.get("ts", 0))
    doc = {"traceEvents": events, "displayTimeUnit": "ms"}
    if path is not None:
        with open(path, "w") as f:
            json.dump(doc, f)
    return doc
