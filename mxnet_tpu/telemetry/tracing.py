"""Span tracing: one request's (or train step's) life as a connected trace.

`span(name, trace=..., **attrs)` is a context manager timing a region.
Every closed span is recorded three ways:

  * the legacy chrome-trace recorder (`profiler.record_event`, when the
    profiler is running) — so existing `profiler.dump()` traces gain the
    serving/training spans alongside the op-level events;
  * the in-process span ring (bounded; `export_perfetto()` turns it into
    a Perfetto-loadable JSON trace where every trace id is its own row);
  * the flight recorder ring (`telemetry.flight`) — the post-mortem
    record of "what was this process doing right before it died".

Trace ids connect spans: the serving stack uses the request id, so one
request's submit → queue → prefill chunks → decode steps all share an id
and render as a single row. Ids propagate implicitly to nested spans via
a thread-local (set once at the root span, inherited below), or
explicitly with `span(..., trace=id)` / `record_span(..., trace=id)` for
regions timed outside a `with` block (e.g. one decode step fanned out to
every sequence it advanced).
"""
from __future__ import annotations

import itertools
import json
import os
import threading
import time
from collections import deque

from .. import profiler
from .metrics import enabled

_ids = itertools.count(1)
_tls = threading.local()

#: closed spans, newest last. Bounded: tracing must be always-on-able
#: without growing without bound; export before the ring wraps (or raise
#: MXNET_TELEMETRY_SPAN_RING).
_ring_size = int(os.environ.get("MXNET_TELEMETRY_SPAN_RING", "8192"))
_spans = deque(maxlen=_ring_size)
_lock = threading.Lock()


def current_trace():
    """The thread's active trace id, or None."""
    return getattr(_tls, "trace", None)


def set_trace(trace):
    """Set the thread's trace id; returns the previous value (restore it
    when the propagation scope ends)."""
    prev = getattr(_tls, "trace", None)
    _tls.trace = trace
    return prev


def _now_us():
    return time.perf_counter_ns() // 1000


def record_span(name, start_us, dur_us, trace=None, category="trace",
                to_profiler=True, to_flight=True, **attrs):
    """Record one already-timed span. The seam for fan-out: a batched
    decode step is timed once but attributed to every request it
    advanced, so each request's row stays connected. The per-request
    copies only matter to the span ring (their Perfetto rows):
    `to_profiler=False` keeps them out of the chrome trace and
    `to_flight=False` out of the flight-recorder ring, where B duplicate
    copies per decode step would evict the history the black box exists
    to keep (the batch-level span covers the interval in both)."""
    if not enabled():
        return
    if trace is None:
        trace = current_trace()
    rec = {"id": next(_ids), "name": name, "cat": category,
           "trace": trace, "ts": start_us, "dur": dur_us,
           "pid": os.getpid(), "tid": threading.get_ident()}
    if attrs:
        rec["attrs"] = attrs
    with _lock:
        _spans.append(rec)
    if to_profiler:
        profiler.record_event(name, category, start_us, dur_us,
                              dict(attrs, trace=trace) if attrs
                              else {"trace": trace})
    if to_flight:
        from .flight import flight
        flight().record("span", name, trace=trace, dur_us=dur_us,
                        **attrs)
    return rec


class span:
    """Time a region and record it as a span. Usage:

        with telemetry.span("serving.prefill", trace=req.id, chunk=3):
            ...

    `trace=None` inherits the thread's current trace id; passing an
    explicit id also makes it the thread's current id for the duration
    (nested spans connect automatically)."""

    def __init__(self, name, trace=None, category="trace", **attrs):
        self.name = name
        self.category = category
        self.attrs = attrs
        self._trace = trace
        self._prev = None

    def __enter__(self):
        if self._trace is not None:
            self._prev = set_trace(self._trace)
        self._t0 = _now_us()
        return self

    def __exit__(self, exc_type, exc, tb):
        t1 = _now_us()
        if exc_type is not None:
            self.attrs.setdefault("error", exc_type.__name__)
        record_span(self.name, self._t0, t1 - self._t0,
                    trace=self._trace, category=self.category,
                    **self.attrs)
        if self._trace is not None:
            set_trace(self._prev)
        return False


def spans(trace=None):
    """Recorded spans, oldest first; `trace=` filters to one id."""
    with _lock:
        out = list(_spans)
    if trace is not None:
        out = [s for s in out if s["trace"] == trace]
    return out


def clear():
    """Drop the ring (tests)."""
    with _lock:
        _spans.clear()


def export_perfetto(path=None):
    """Write the span ring as Perfetto-compatible chrome-trace JSON.

    Each distinct trace id becomes its own thread row (`tid` = trace id,
    named by a thread_name metadata event), so loading the file in
    Perfetto/chrome://tracing shows one request's whole life — queue,
    prefill chunks, decode steps — as a single connected row; untraced
    spans keep their real thread id. Returns the trace dict (and writes
    it to `path` when given)."""
    with _lock:
        recs = list(_spans)
    events = []
    rows = {}
    for r in recs:
        tid = r["tid"]
        if r["trace"] is not None:
            # stable small row ids: first-seen order per trace id
            tid = rows.setdefault(r["trace"], 1_000_000 + len(rows))
        ev = {"name": r["name"], "cat": r["cat"], "ph": "X",
              "ts": r["ts"], "dur": r["dur"], "pid": r["pid"],
              "tid": tid,
              "args": dict(r.get("attrs") or {}, trace=r["trace"],
                           span_id=r["id"])}
        events.append(ev)
    for trace, tid in rows.items():
        events.append({"name": "thread_name", "ph": "M",
                       "pid": os.getpid(), "tid": tid,
                       "args": {"name": "trace %s" % (trace,)}})
    events.sort(key=lambda e: e.get("ts", 0))
    doc = {"traceEvents": events, "displayTimeUnit": "ms"}
    if path is not None:
        with open(path, "w") as f:
            json.dump(doc, f)
    return doc
