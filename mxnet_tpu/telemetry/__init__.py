"""Unified telemetry: metrics registry, span tracing, crash flight recorder.

The common measurement substrate for training and serving (ISSUE 7; see
docs/OBSERVABILITY.md):

  * `metrics` — counters / gauges / fixed-bucket histograms labeled by
    host/replica, with Prometheus text exposition and a JSON snapshot
    (`default_registry()` is the process-wide instance; the serving
    stack builds one per server).
  * `tracing` — `span(name, trace=..., **attrs)` context manager; spans
    sharing a trace id (serving: the request id) render as one connected
    row in the Perfetto JSON export and feed the legacy chrome-trace
    recorder (`profiler.dump()`).
  * `flight` — a bounded ring of recent spans/flagged-metric/fault
    events that dumps to `MXNET_FLIGHT_RECORDER_DIR` on SIGTERM,
    serving-loop death, or /healthz wedge detection; rendered by
    `tools/postmortem.py`.

Master switch: `MXNET_TELEMETRY` (default on; `0` turns every recording
site into a no-op).
"""
from . import metrics
from . import tracing
from . import flight as _flight_mod
from . import introspect
from . import slo
from . import anomaly

from .metrics import (enabled, MetricsRegistry, default_registry,
                      DEFAULT_BUCKETS, merged_prometheus_text)
from .tracing import (span, record_span, current_trace, set_trace,
                      spans, export_perfetto, new_trace_id,
                      parse_traceparent, format_traceparent)
from .flight import FlightRecorder, flight
from .introspect import (watchdog, instrument, compile_events,
                         compile_region, site_comms,
                         CompileBudgetExceeded, HbmBudgetExceeded)
from .slo import (Objective, SLOTracker, parse_slo_env, parse_windows,
                  merge_slo, request_log, request_event)
from .anomaly import EwmaDetector, AnomalyDetector


def counter(name, help="", flight=False):
    """Counter on the default registry."""
    return default_registry().counter(name, help=help, flight=flight)


def gauge(name, help=""):
    """Gauge on the default registry."""
    return default_registry().gauge(name, help=help)


def histogram(name, help="", buckets=DEFAULT_BUCKETS):
    """Histogram on the default registry."""
    return default_registry().histogram(name, help=help, buckets=buckets)


def snapshot():
    """JSON snapshot of the default registry."""
    return default_registry().snapshot()


def prometheus_text():
    """Prometheus exposition of the default registry."""
    return default_registry().prometheus_text()
