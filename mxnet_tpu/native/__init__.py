"""ctypes bindings to the native C++ runtime (native/mxtpu_native.cc).

The reference's runtime around the compute path is C++ (src/engine/,
src/io/, dmlc recordio); this package is its TPU-framework counterpart:
  NativeEngine      threaded dependency engine (var-queue protocol)
  RecWriter/Reader  recordio framing, bit-compatible with recordio.py
  NativeImageIter   parallel JPEG decode + augment + batch (the
                    ImageRecordIter hot loop, iter_image_recordio_2.cc)

The shared library builds on first import (g++, ~2s) and is cached next to
the source. If the toolchain/libjpeg is unavailable, AVAILABLE is False and
pure-Python fallbacks in recordio.py / io.py take over.
"""
from __future__ import annotations

import ctypes
import os
import subprocess

import numpy as np

_HERE = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_HERE, "..", "..", "native", "mxtpu_native.cc")
_SO = os.path.join(_HERE, "..", "..", "native", "libmxtpu_native.so")

AVAILABLE = False
_lib = None


def _build():
    cmd = ["g++", "-O2", "-std=c++17", "-fPIC", "-shared", "-o", _SO, _SRC,
           "-ljpeg", "-lpthread"]
    subprocess.run(cmd, check=True, capture_output=True)


def _load():
    global _lib, AVAILABLE
    src_mtime = os.path.getmtime(_SRC) if os.path.exists(_SRC) else 0
    if (not os.path.exists(_SO)) or os.path.getmtime(_SO) < src_mtime:
        try:
            _build()
        except (subprocess.CalledProcessError, FileNotFoundError) as e:
            out = getattr(e, "stderr", b"")
            import logging
            logging.getLogger(__name__).warning(
                "native build failed, using pure-python fallbacks: %s",
                out.decode() if isinstance(out, bytes) else out)
            return
    try:
        lib = ctypes.CDLL(_SO)
    except OSError as e:  # stale/ABI-incompatible .so: fall back, don't crash
        import logging
        logging.getLogger(__name__).warning(
            "native library unloadable, using pure-python fallbacks: %s", e)
        return

    lib.EngineCreate.restype = ctypes.c_void_p
    lib.EngineCreate.argtypes = [ctypes.c_int]
    lib.EngineFree.argtypes = [ctypes.c_void_p]
    lib.EngineNewVar.restype = ctypes.c_void_p
    lib.EngineNewVar.argtypes = [ctypes.c_void_p]
    lib.EnginePush.argtypes = [
        ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
        ctypes.POINTER(ctypes.c_void_p), ctypes.c_int,
        ctypes.POINTER(ctypes.c_void_p), ctypes.c_int, ctypes.c_uint64]
    lib.EngineWaitAll.argtypes = [ctypes.c_void_p]
    lib.EngineOutstanding.restype = ctypes.c_int64
    lib.EngineOutstanding.argtypes = [ctypes.c_void_p]
    lib.EngineDrainDone.restype = ctypes.c_int64
    lib.EngineDrainDone.argtypes = [ctypes.c_void_p,
                                    ctypes.POINTER(ctypes.c_uint64),
                                    ctypes.c_int64]

    lib.RecWriterCreate.restype = ctypes.c_void_p
    lib.RecWriterCreate.argtypes = [ctypes.c_char_p]
    lib.RecWriterTell.restype = ctypes.c_int64
    lib.RecWriterTell.argtypes = [ctypes.c_void_p]
    lib.RecWriterWrite.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                   ctypes.c_uint64]
    lib.RecWriterClose.argtypes = [ctypes.c_void_p]
    lib.RecReaderCreate.restype = ctypes.c_void_p
    lib.RecReaderCreate.argtypes = [ctypes.c_char_p]
    lib.RecReaderSeek.argtypes = [ctypes.c_void_p, ctypes.c_int64]
    lib.RecReaderTell.restype = ctypes.c_int64
    lib.RecReaderTell.argtypes = [ctypes.c_void_p]
    lib.RecReaderRead.restype = ctypes.c_int64
    lib.RecReaderRead.argtypes = [ctypes.c_void_p,
                                  ctypes.POINTER(ctypes.c_char_p)]
    lib.RecReaderClose.argtypes = [ctypes.c_void_p]

    lib.ImgIterCreate.restype = ctypes.c_void_p
    lib.ImgIterCreate.argtypes = [ctypes.c_char_p] + [ctypes.c_int] * 8 + \
        [ctypes.c_uint]
    lib.ImgIterSize.restype = ctypes.c_int64
    lib.ImgIterSize.argtypes = [ctypes.c_void_p]
    lib.ImgIterReset.argtypes = [ctypes.c_void_p]
    lib.ImgIterNext.restype = ctypes.c_int
    lib.ImgIterNext.argtypes = [ctypes.c_void_p,
                                ctypes.POINTER(ctypes.c_float),
                                ctypes.POINTER(ctypes.c_float)]
    lib.ImgIterFree.argtypes = [ctypes.c_void_p]

    _lib = lib
    AVAILABLE = True


_load()

_ENGINE_CB = ctypes.CFUNCTYPE(None, ctypes.c_void_p)


def _env_nthreads(num_threads):
    """Explicit count wins; otherwise MXNET_CPU_WORKER_NTHREADS (parity:
    docs/faq/env_var.md) sizes the pool; 0 falls through to
    hardware_concurrency in C++. Bad values are ignored with the variable
    named, not a bare ValueError from deep inside a constructor."""
    if num_threads > 0:
        return num_threads
    raw = os.environ.get("MXNET_CPU_WORKER_NTHREADS", "0")
    try:
        return int(raw)
    except ValueError:
        import warnings
        warnings.warn("ignoring non-integer MXNET_CPU_WORKER_NTHREADS=%r"
                      % raw)
        return 0


class NativeEngine:
    """Threaded dependency engine (parity: Engine::PushAsync semantics —
    include/mxnet/engine.h:96-295). Python callables run on C++ worker
    threads; vars serialize writers and share readers."""

    _DRAIN_BUF_CAP = 1024

    def __init__(self, num_threads=0):
        assert AVAILABLE, "native library unavailable"
        self._h = _lib.EngineCreate(_env_nthreads(num_threads))
        self._keepalive = {}
        self._token = 0
        self._drain_buf = (ctypes.c_uint64 * self._DRAIN_BUF_CAP)()

    def new_var(self):
        return _lib.EngineNewVar(self._h)

    def _drain_done(self):
        # Free ffi closures whose callbacks have fully returned. The C++
        # side records each token strictly AFTER invoking the callback (see
        # EnginePush in mxtpu_native.cc), so freeing the CFUNCTYPE here can
        # never unmap a closure stub still on a worker thread's stack —
        # unlike a trampoline popping itself, which is a use-after-free.
        # Draining on every push also bounds memory under sustained streams
        # that never go idle.
        while True:
            n = _lib.EngineDrainDone(self._h, self._drain_buf,
                                     self._DRAIN_BUF_CAP)
            for i in range(n):
                self._keepalive.pop(self._drain_buf[i], None)
            if n < self._DRAIN_BUF_CAP:
                break

    def push(self, fn, read_vars=(), write_vars=()):
        self._drain_done()
        token = self._token
        self._token += 1

        def trampoline(_arg):
            fn()

        cb = _ENGINE_CB(trampoline)
        self._keepalive[token] = cb
        n_r, n_w = len(read_vars), len(write_vars)
        r = (ctypes.c_void_p * max(n_r, 1))(*read_vars)
        w = (ctypes.c_void_p * max(n_w, 1))(*write_vars)
        _lib.EnginePush(self._h, ctypes.cast(cb, ctypes.c_void_p), None,
                        r, n_r, w, n_w, token)

    def wait_all(self):
        _lib.EngineWaitAll(self._h)
        # all ops completed => all tokens recorded; drain frees everything
        self._drain_done()

    def close(self):
        if self._h:
            _lib.EngineFree(self._h)
            self._h = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


class RecWriter:
    def __init__(self, path):
        assert AVAILABLE
        self._h = _lib.RecWriterCreate(path.encode())
        if not self._h:
            raise IOError("cannot open %s" % path)

    def tell(self):
        return _lib.RecWriterTell(self._h)

    def write(self, buf):
        _lib.RecWriterWrite(self._h, buf, len(buf))

    def close(self):
        if self._h:
            _lib.RecWriterClose(self._h)
            self._h = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


class RecReader:
    def __init__(self, path):
        assert AVAILABLE
        self._h = _lib.RecReaderCreate(path.encode())
        if not self._h:
            raise IOError("cannot open %s" % path)

    def seek(self, pos):
        _lib.RecReaderSeek(self._h, pos)

    def tell(self):
        return _lib.RecReaderTell(self._h)

    def read(self):
        data = ctypes.c_char_p()
        n = _lib.RecReaderRead(self._h, ctypes.byref(data))
        if n < 0:
            return None
        return ctypes.string_at(data, n)

    def close(self):
        if self._h:
            _lib.RecReaderClose(self._h)
            self._h = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


class NativeImageIter:
    """Parallel JPEG decode pipeline over a .rec file (parity:
    ImageRecordIOParser2, src/io/iter_image_recordio_2.cc:50-147).
    Yields (data[batch,c,h,w] float32, label[batch] float32, n)."""

    def __init__(self, rec_path, batch_size, data_shape, shuffle=False,
                 num_threads=0, rand_crop=False, rand_mirror=False, seed=0):
        assert AVAILABLE
        c, h, w = data_shape
        self.batch_size = batch_size
        self.data_shape = data_shape
        num_threads = _env_nthreads(num_threads)
        self._h = _lib.ImgIterCreate(rec_path.encode(), batch_size, h, w, c,
                                     int(shuffle), num_threads,
                                     int(rand_crop), int(rand_mirror),
                                     seed)
        if not self._h:
            raise IOError("cannot open %s" % rec_path)
        self._data = np.empty((batch_size, c, h, w), np.float32)
        self._label = np.empty((batch_size,), np.float32)

    def __len__(self):
        return int(_lib.ImgIterSize(self._h))

    def reset(self):
        _lib.ImgIterReset(self._h)

    def next_batch(self):
        n = _lib.ImgIterNext(
            self._h,
            self._data.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
            self._label.ctypes.data_as(ctypes.POINTER(ctypes.c_float)))
        if n == 0:
            return None
        # copies: the internal buffers are refilled by the next call, and
        # jnp.asarray can be zero-copy on CPU (silent aliasing otherwise)
        return self._data.copy(), self._label.copy(), n

    def close(self):
        if self._h:
            _lib.ImgIterFree(self._h)
            self._h = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass
