"""BucketingModule (parity: python/mxnet/module/bucketing_module.py:36,65 —
per-bucket executors sharing parameters; the reference's answer to variable
sequence lengths, and ours: one jit specialization per bucket shape, which is
exactly jax.jit's shape-keyed cache behind each bucket's Executor)."""
from __future__ import annotations

import logging

from .base_module import BaseModule
from .module import Module
from ..base import MXNetError


class BucketingModule(BaseModule):
    def __init__(self, sym_gen, default_bucket_key=None, logger=logging,
                 context=None, work_load_list=None, fixed_param_names=None,
                 state_names=None, group2ctxs=None, compression_params=None,
                 mesh=None, data_axis="dp"):
        super().__init__(logger=logger)
        assert default_bucket_key is not None
        self._default_bucket_key = default_bucket_key
        self._sym_gen = sym_gen
        self._context = context
        self._fixed_param_names = fixed_param_names
        self._state_names = state_names
        self._compression_params = compression_params
        self._mesh = mesh
        self._data_axis = data_axis
        self._buckets = {}
        self._curr_module = None
        self._curr_bucket_key = None
        self._params_dirty = False
        self._opt_config = None
        self._opt_owner = None  # the Module whose optimizer all buckets share

    def _call_sym_gen(self, bucket_key):
        return self._sym_gen(bucket_key)

    @property
    def data_names(self):
        if self.binded:
            return self._curr_module.data_names
        _, data_names, _ = self._call_sym_gen(self._default_bucket_key)
        return data_names

    @property
    def output_names(self):
        if self.binded:
            return self._curr_module.output_names
        symbol, _, _ = self._call_sym_gen(self._default_bucket_key)
        return symbol.list_outputs()

    @property
    def data_shapes(self):
        assert self.binded
        return self._curr_module.data_shapes

    @property
    def label_shapes(self):
        assert self.binded
        return self._curr_module.label_shapes

    @property
    def output_shapes(self):
        assert self.binded
        return self._curr_module.output_shapes

    def get_params(self):
        assert self.params_initialized
        self._curr_module._params_dirty = self._params_dirty
        params = self._curr_module.get_params()
        self._params_dirty = False
        return params

    def init_params(self, initializer=None, arg_params=None, aux_params=None,
                    allow_missing=False, force_init=False, allow_extra=False):
        if self.params_initialized and not force_init:
            return
        assert self.binded
        self._curr_module.init_params(initializer=initializer,
                                      arg_params=arg_params,
                                      aux_params=aux_params,
                                      allow_missing=allow_missing,
                                      force_init=force_init,
                                      allow_extra=allow_extra)
        self.params_initialized = True
        self._params_dirty = False

    def bind(self, data_shapes, label_shapes=None, for_training=True,
             inputs_need_grad=False, force_rebind=False, shared_module=None,
             grad_req="write"):
        assert shared_module is None, \
            "shared_module for BucketingModule is not supported"
        if self.binded and not force_rebind:
            self.logger.warning("Already bound, ignoring bind()")
            return
        self.for_training = for_training
        self.inputs_need_grad = inputs_need_grad
        self.binded = True
        symbol, data_names, label_names = self._call_sym_gen(
            self._default_bucket_key)
        module = Module(symbol, data_names, label_names, logger=self.logger,
                        context=self._context,
                        fixed_param_names=self._fixed_param_names,
                        state_names=self._state_names,
                        compression_params=self._compression_params,
                        mesh=self._mesh, data_axis=self._data_axis)
        module.bind(data_shapes, label_shapes, for_training,
                    inputs_need_grad, force_rebind=False, grad_req=grad_req)
        self._curr_module = module
        self._curr_bucket_key = self._default_bucket_key
        self._buckets[self._default_bucket_key] = module

    def switch_bucket(self, bucket_key, data_shapes, label_shapes=None):
        assert self.binded, "call bind before switching bucket"
        if bucket_key not in self._buckets:
            symbol, data_names, label_names = self._call_sym_gen(bucket_key)
            module = Module(symbol, data_names, label_names,
                            logger=self.logger, context=self._context,
                            fixed_param_names=self._fixed_param_names,
                            state_names=self._state_names,
                            compression_params=self._compression_params,
                            mesh=self._mesh, data_axis=self._data_axis)
            module.bind(data_shapes, label_shapes, self.for_training,
                        self.inputs_need_grad, force_rebind=False)
            # share parameters with the master bucket
            default_mod = self._buckets[self._default_bucket_key]
            if default_mod.params_initialized:
                arg, aux = default_mod.get_params()
                module.init_params(arg_params=arg, aux_params=aux,
                                   allow_missing=False, force_init=True)
            self._buckets[bucket_key] = module
        self._curr_module = self._buckets[bucket_key]
        self._curr_bucket_key = bucket_key
        if self._opt_config is not None and \
                not self._curr_module.optimizer_initialized:
            # every bucket advances ONE optimizer (reference
            # borrow_optimizer): fresh per-bucket moments would make e.g.
            # Adam diverge when batches alternate between buckets
            assert self._opt_owner is not None  # set with _opt_config
            self._curr_module.borrow_optimizer(self._opt_owner)

    def forward(self, data_batch, is_train=None):
        assert self.binded and self.params_initialized
        if data_batch.bucket_key != self._curr_bucket_key and \
                data_batch.bucket_key is not None:
            # sync params from current bucket into the new one
            arg, aux = self._curr_module.get_params() \
                if self._curr_module.params_initialized else (None, None)
            self.switch_bucket(data_batch.bucket_key,
                               data_batch.provide_data,
                               data_batch.provide_label)
            if arg is not None:
                self._curr_module.init_params(arg_params=arg, aux_params=aux,
                                              force_init=True)
        self._curr_module.forward(data_batch, is_train=is_train)

    def backward(self, out_grads=None):
        self._curr_module.backward(out_grads=out_grads)

    def update(self):
        self._params_dirty = True
        self._curr_module.update()
        # propagate updated params to the default module lazily at get_params

    def get_outputs(self, merge_multi_context=True):
        return self._curr_module.get_outputs(merge_multi_context)

    def get_input_grads(self, merge_multi_context=True):
        return self._curr_module.get_input_grads(merge_multi_context)

    def update_metric(self, eval_metric, labels):
        self._curr_module.update_metric(eval_metric, labels)

    def init_optimizer(self, kvstore="local", optimizer="sgd",
                       optimizer_params=(("learning_rate", 0.01),),
                       force_init=False):
        assert self.binded and self.params_initialized
        self._opt_config = dict(kvstore=kvstore, optimizer=optimizer,
                                optimizer_params=optimizer_params,
                                force_init=force_init)
        self._curr_module.init_optimizer(kvstore=kvstore, optimizer=optimizer,
                                         optimizer_params=optimizer_params,
                                         force_init=force_init)
        self._opt_owner = self._curr_module
        # buckets bound before init_optimizer must share this optimizer too
        for mod in self._buckets.values():
            if mod is not self._curr_module:
                mod.borrow_optimizer(self._opt_owner)
        self.optimizer_initialized = True

    @property
    def symbol(self):
        assert self.binded
        return self._curr_module.symbol

    def install_monitor(self, mon):
        for mod in self._buckets.values():
            mod.install_monitor(mon)

    def get_states(self, merge_multi_context=True):
        """States of the current bucket's module (parity:
        bucketing_module.py get_states)."""
        assert self._curr_module is not None, "bind and forward first"
        return self._curr_module.get_states(
            merge_multi_context=merge_multi_context)

    def set_states(self, states=None, value=None):
        """Set states on the current bucket's module (parity:
        bucketing_module.py set_states)."""
        assert self._curr_module is not None, "bind and forward first"
        self._curr_module.set_states(states=states, value=value)

    def prepare(self, data_batch, sparse_row_id_fn=None):
        """Ensure the batch's bucket executor exists, then RESTORE the
        current bucket (parity: bucketing_module.py prepare — the
        reference switches back so outputs of the in-flight bucket stay
        readable; forward() performs the real switch)."""
        original = self._curr_bucket_key
        self.switch_bucket(data_batch.bucket_key,
                           data_batch.provide_data,
                           data_batch.provide_label)
        if original is not None and original != data_batch.bucket_key:
            self.switch_bucket(original, None, None)
