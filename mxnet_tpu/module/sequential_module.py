"""SequentialModule (parity: python/mxnet/module/sequential_module.py —
chain modules, feeding outputs to the next module's data)."""
from __future__ import annotations

import logging

from .base_module import BaseModule
from ..io import DataBatch


class SequentialModule(BaseModule):
    META_TAKE_LABELS = "take_labels"
    META_AUTO_WIRING = "auto_wiring"

    def __init__(self, logger=logging):
        super().__init__(logger=logger)
        self._modules = []
        self._metas = []
        self._label_shapes = None
        self._data_shapes = None
        self._meta_keys = set([getattr(SequentialModule, x) for x in
                               dir(SequentialModule) if x.startswith("META_")])

    def add(self, module, **kwargs):
        self._modules.append(module)
        for key in kwargs:
            assert key in self._meta_keys, \
                "Unknown meta \"%s\", a typo?" % key
        self._metas.append(kwargs)
        self.binded = False
        self.params_initialized = False
        self.optimizer_initialized = False
        return self

    @property
    def data_names(self):
        if len(self._modules) > 0:
            return self._modules[0].data_names
        return []

    @property
    def output_names(self):
        if len(self._modules) > 0:
            return self._modules[-1].output_names
        return []

    @property
    def data_shapes(self):
        assert self.binded
        return self._modules[0].data_shapes

    @property
    def label_shapes(self):
        assert self.binded
        return self._label_shapes

    @property
    def output_shapes(self):
        assert self.binded
        return self._modules[-1].output_shapes

    def get_params(self):
        assert self.binded and self.params_initialized
        arg_params = dict()
        aux_params = dict()
        for module in self._modules:
            arg, aux = module.get_params()
            arg_params.update(arg)
            aux_params.update(aux)
        return (arg_params, aux_params)

    def init_params(self, initializer=None, arg_params=None, aux_params=None,
                    allow_missing=False, force_init=False, allow_extra=False):
        if self.params_initialized and not force_init:
            return
        assert self.binded
        for module in self._modules:
            module.init_params(initializer=initializer, arg_params=arg_params,
                               aux_params=aux_params,
                               allow_missing=True,
                               force_init=force_init, allow_extra=True)
        self.params_initialized = True

    def bind(self, data_shapes, label_shapes=None, for_training=True,
             inputs_need_grad=False, force_rebind=False, shared_module=None,
             grad_req="write"):
        if self.binded and not force_rebind:
            self.logger.warning("Already bound, ignoring bind()")
            return
        assert len(self._modules) > 0
        self.for_training = for_training
        self.inputs_need_grad = inputs_need_grad
        self._label_shapes = label_shapes
        my_data_shapes = data_shapes
        for i_layer, (meta, module) in enumerate(zip(self._metas,
                                                     self._modules)):
            meta_take_labels = meta.get(SequentialModule.META_TAKE_LABELS,
                                        False)
            my_label_shapes = label_shapes if meta_take_labels else None
            my_inputs_need_grad = inputs_need_grad if i_layer == 0 else True
            if meta.get(SequentialModule.META_AUTO_WIRING, False):
                data_names = module.data_names
                assert len(data_names) == len(my_data_shapes)
                my_data_shapes = [(new_name, shape) for (new_name,
                                  (_, shape)) in zip(data_names,
                                                     my_data_shapes)]
            module.bind(data_shapes=my_data_shapes,
                        label_shapes=my_label_shapes,
                        for_training=for_training,
                        inputs_need_grad=my_inputs_need_grad,
                        force_rebind=force_rebind, grad_req=grad_req)
            module.init_params()
            my_data_shapes = [(n, s) for n, s in zip(
                module.output_names,
                [o.shape for o in module._exec.outputs]
                if getattr(module, "_exec", None) and module._exec.outputs
                else [s for _, s in module.output_shapes or []])] \
                if module.output_shapes else \
                [(n, s) for n, s in zip(module.output_names, [])]
            # simpler: infer output shapes via a dry forward at first use
            my_data_shapes = [(n, s) for n, s in (module.output_shapes or [])]
        self.binded = True

    def forward(self, data_batch, is_train=None):
        assert self.binded and self.params_initialized
        batch = DataBatch(data=data_batch.data, label=data_batch.label,
                          pad=data_batch.pad)
        for i_layer, (meta, module) in enumerate(zip(self._metas,
                                                     self._modules)):
            module.forward(batch, is_train=is_train)
            if i_layer + 1 == len(self._modules):
                break
            out = module.get_outputs()
            label = batch.label if self._metas[i_layer + 1].get(
                SequentialModule.META_TAKE_LABELS, False) else None
            batch = DataBatch(data=out, label=label, pad=batch.pad)

    def backward(self, out_grads=None):
        assert self.binded and self.params_initialized
        for i_layer, module in reversed(list(enumerate(self._modules))):
            module.backward(out_grads=out_grads)
            if i_layer == 0:
                break
            out_grads = module.get_input_grads()

    def update(self):
        for module in self._modules:
            module.update()

    def init_optimizer(self, kvstore="local", optimizer="sgd",
                       optimizer_params=(("learning_rate", 0.01),),
                       force_init=False):
        assert self.binded and self.params_initialized
        for module in self._modules:
            module.init_optimizer(kvstore=kvstore, optimizer=optimizer,
                                  optimizer_params=optimizer_params,
                                  force_init=force_init)
        self.optimizer_initialized = True

    def get_outputs(self, merge_multi_context=True):
        return self._modules[-1].get_outputs(merge_multi_context)

    def get_input_grads(self, merge_multi_context=True):
        return self._modules[0].get_input_grads(merge_multi_context)

    def update_metric(self, eval_metric, labels):
        for meta, module in zip(self._metas, self._modules):
            if meta.get(SequentialModule.META_TAKE_LABELS, False):
                module.update_metric(eval_metric, labels)

    def install_monitor(self, mon):
        for module in self._modules:
            module.install_monitor(mon)
