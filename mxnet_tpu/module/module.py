"""Module: symbol + one jit-compiled executor.

Parity: reference `python/mxnet/module/module.py:40` (bind:364 →
DataParallelExecutorGroup, init_optimizer:473 wiring KVStore).

TPU-native redesign: the reference sliced each batch across N GPU executors
(`executor_group.py:129`); here ONE executor runs the whole batch and
multi-chip data parallelism is mesh sharding (mxnet_tpu.parallel) — the XLA
partitioner plays the role of DataParallelExecutorGroup, so there is no
per-device replica bookkeeping to manage.
"""
from __future__ import annotations

import logging

import numpy as np

from .base_module import BaseModule
from ..base import MXNetError
from ..context import cpu
from ..executor import Executor
from ..ndarray import NDArray
from .. import ndarray as nd
from .. import optimizer as opt
from .. import kvstore as kvs


class Module(BaseModule):
    def __init__(self, symbol, data_names=("data",), label_names=("softmax_label",),
                 logger=logging, context=None, work_load_list=None,
                 fixed_param_names=None, state_names=None, group2ctxs=None,
                 compression_params=None, mesh=None, data_axis="dp"):
        """mesh/data_axis: multi-chip data parallelism for the symbolic path.
        The reference sliced each batch across N per-GPU executors
        (DataParallelExecutorGroup, executor_group.py:129); here pass a
        `jax.sharding.Mesh` and the ONE executor's inputs are sharded over
        `data_axis` — GSPMD partitions compute and inserts the gradient
        all-reduce, playing the role of kvstore type 'device'."""
        super().__init__(logger=logger)
        if context is None:
            context = cpu()
        if isinstance(context, (list, tuple)):
            context = context[0]  # devices = sharding, one logical executor
        self._context = context
        self._mesh = mesh
        self._data_axis = data_axis
        self._symbol = symbol
        data_names = list(data_names) if data_names is not None else []
        label_names = list(label_names) if label_names is not None else []
        arg_names = symbol.list_arguments()
        input_names = data_names + label_names + (list(state_names) if
                                                  state_names else [])
        self._param_names = [x for x in arg_names if x not in input_names]
        self._fixed_param_names = list(fixed_param_names or [])
        self._aux_names = symbol.list_auxiliary_states()
        self._data_names = data_names
        self._label_names = label_names
        self._state_names = list(state_names or [])
        self._output_names = symbol.list_outputs()
        self._arg_params = None
        self._aux_params = None
        self._params_dirty = False
        self._compression_params = compression_params
        self._optimizer = None
        self._kvstore = None
        self._update_on_kvstore = None
        self._updater = None
        self._exec = None
        self._data_shapes = None
        self._label_shapes = None
        self._static_output_shapes = None

    @staticmethod
    def load(prefix, epoch, load_optimizer_states=False, **kwargs):
        from ..model import load_checkpoint
        sym, args, auxs = load_checkpoint(prefix, epoch)
        mod = Module(symbol=sym, **kwargs)
        mod._arg_params = args
        mod._aux_params = auxs
        mod.params_initialized = True
        if load_optimizer_states:
            mod._preload_opt_states = "%s-%04d.states" % (prefix, epoch)
        return mod

    def save_checkpoint(self, prefix, epoch, save_optimizer_states=False):
        self._symbol.save("%s-symbol.json" % prefix)
        param_name = "%s-%04d.params" % (prefix, epoch)
        arg_params, aux_params = self.get_params()
        save_dict = {("arg:%s" % k): v for k, v in arg_params.items()}
        save_dict.update({("aux:%s" % k): v for k, v in aux_params.items()})
        from ..utils import serialization
        serialization.save_ndarrays(param_name, save_dict)
        if save_optimizer_states:
            self.save_optimizer_states("%s-%04d.states" % (prefix, epoch))

    # -- properties ---------------------------------------------------------
    @property
    def data_names(self):
        return self._data_names

    @property
    def label_names(self):
        return self._label_names

    @property
    def output_names(self):
        return self._output_names

    @property
    def data_shapes(self):
        assert self.binded
        return self._data_shapes

    @property
    def label_shapes(self):
        assert self.binded
        return self._label_shapes

    @property
    def output_shapes(self):
        assert self.binded
        if self._exec.outputs:
            return [(n, o.shape) for n, o in
                    zip(self._output_names, self._exec.outputs)]
        # no forward yet: infer statically from the symbol (SequentialModule
        # wires the next module's data shapes from this before any forward)
        if self._static_output_shapes is None:
            try:
                shapes = dict(self._data_shapes + (self._label_shapes or []))
                _, out_shapes, _ = self._symbol.infer_shape(**shapes)
            except MXNetError:
                return None  # e.g. stateful symbols with unknowable shapes
            self._static_output_shapes = [
                (n, s) for n, s in zip(self._output_names, out_shapes)]
        return self._static_output_shapes

    # -- params -------------------------------------------------------------
    def get_params(self):
        assert self.binded and self.params_initialized
        arg = {n: self._exec.arg_dict[n].copy() for n in self._param_names}
        aux = {n: self._exec.aux_dict[n].copy() for n in self._aux_names}
        return arg, aux

    def init_params(self, initializer=None, arg_params=None, aux_params=None,
                    allow_missing=False, force_init=False, allow_extra=False):
        if self.params_initialized and not force_init:
            return
        assert self.binded, "call bind before initializing the parameters"
        from ..initializer import Uniform, InitDesc
        initializer = initializer if initializer is not None else Uniform(0.01)

        for name in self._param_names:
            arr = self._exec.arg_dict[name]
            if arg_params is not None and name in arg_params:
                arr._data = arg_params[name]._data.reshape(arr.shape).astype(
                    arr._data.dtype)
            elif self._arg_params is not None and name in self._arg_params:
                arr._data = self._arg_params[name]._data.reshape(
                    arr.shape).astype(arr._data.dtype)
            elif initializer is not None:
                initializer(InitDesc(name), arr)
            elif not allow_missing:
                raise MXNetError("no initializer for %s" % name)
        for name in self._aux_names:
            arr = self._exec.aux_dict[name]
            if aux_params is not None and name in aux_params:
                arr._data = aux_params[name]._data.reshape(arr.shape)
            elif self._aux_params is not None and name in self._aux_params:
                arr._data = self._aux_params[name]._data.reshape(arr.shape)
        if self._mesh is not None:
            # freshly-assigned buffers are single-device; restore replication
            self._replicate_params_on_mesh()
        self.params_initialized = True
        self._params_dirty = False

    # -- bind ---------------------------------------------------------------
    def bind(self, data_shapes, label_shapes=None, for_training=True,
             inputs_need_grad=False, force_rebind=False, shared_module=None,
             grad_req="write"):
        if self.binded and not force_rebind:
            self.logger.warning("Already bound, ignoring bind()")
            return
        if self.binded and self.params_initialized:
            # rebind/reshape: capture trained params from the executor being
            # discarded so the re-sync below restores them, not stale/random
            # values (parity: exec_group.set_params on rebind)
            self._arg_params, self._aux_params = self.get_params()
        self.for_training = for_training
        self.inputs_need_grad = inputs_need_grad
        self._static_output_shapes = None
        shapes = {}
        norm_data = []
        for d in data_shapes:
            name, shape = (d.name, d.shape) if hasattr(d, "name") else d
            shapes[name] = tuple(shape)
            norm_data.append((name, tuple(shape)))
        self._data_shapes = norm_data
        norm_label = []
        if label_shapes:
            for d in label_shapes:
                name, shape = (d.name, d.shape) if hasattr(d, "name") else d
                shapes[name] = tuple(shape)
                norm_label.append((name, tuple(shape)))
        self._label_shapes = norm_label

        req = {}
        for n in self._symbol.list_arguments():
            if n in self._param_names and n not in self._fixed_param_names:
                req[n] = grad_req if for_training else "null"
            elif n in self._data_names and inputs_need_grad:
                req[n] = grad_req
            else:
                req[n] = "null"
        self._exec = Executor.simple_bind(self._symbol, self._context,
                                          grad_req=req, **shapes)
        self.binded = True
        if shared_module is not None and shared_module.params_initialized:
            arg, aux = shared_module.get_params()
            self._exec.copy_params_from(arg, aux)
            if self._mesh is not None:
                self._replicate_params_on_mesh()
            self.params_initialized = True
        elif self.params_initialized:
            # Module.load flow: loaded _arg/_aux_params predate this bind —
            # re-sync them into the fresh executor (parity: module.py:364
            # exec_group.set_params after bind)
            self.init_params(force_init=True)

    def _replicate_params_on_mesh(self):
        """Place every param/aux buffer replicated on the mesh so sharded
        data feeds partition the compiled program instead of forcing a
        cross-device transfer."""
        from ..parallel.mesh import replicate
        for d in (self._exec.arg_dict, self._exec.aux_dict):
            for name, arr in d.items():
                if name not in self._data_names + self._label_names:
                    arr._data = replicate(self._mesh, arr._data)

    def _shard_feed(self, arr):
        from ..parallel.mesh import shard_batch
        v = arr._data if isinstance(arr, NDArray) else arr
        return NDArray(shard_batch(self._mesh, v, self._data_axis))

    # -- compute ------------------------------------------------------------
    def forward(self, data_batch, is_train=None):
        assert self.binded and self.params_initialized
        if is_train is None:
            is_train = self.for_training
        feeds = {}
        for name, arr in zip(self._data_names, data_batch.data):
            feeds[name] = arr
        if data_batch.label is not None:
            for name, arr in zip(self._label_names, data_batch.label):
                if name in self._exec.arg_dict:
                    feeds[name] = arr
        if self._mesh is not None:
            feeds = {n: self._shard_feed(a) for n, a in feeds.items()}
        self._exec.forward(is_train=is_train, **feeds)

    def backward(self, out_grads=None):
        assert self.binded and self.params_initialized
        self._exec.backward(out_grads=out_grads)

    def get_outputs(self, merge_multi_context=True):
        assert self.binded and self.params_initialized
        return self._exec.outputs

    def get_input_grads(self, merge_multi_context=True):
        assert self.binded and self.params_initialized and \
            self.inputs_need_grad
        return [self._exec.grad_dict.get(n) for n in self._data_names]

    # -- optimizer ----------------------------------------------------------
    def init_optimizer(self, kvstore="local", optimizer="sgd",
                       optimizer_params=(("learning_rate", 0.01),),
                       force_init=False):
        assert self.binded and self.params_initialized
        if self.optimizer_initialized and not force_init:
            self.logger.warning("optimizer already initialized, ignoring...")
            return
        if isinstance(optimizer, str):
            # default rescale_grad = 1/batch (parity: module.py:497 — loss
            # heads emit unnormalized grads; the optimizer rescales)
            batch_size = self._data_shapes[0][1][0] if self._data_shapes \
                else 1
            idx2name = {i: n for i, n in enumerate(self._param_names)}
            optimizer_params = dict(optimizer_params)
            optimizer_params.setdefault("rescale_grad", 1.0 / batch_size)
            optimizer = opt.create(optimizer, param_idx2name=idx2name,
                                   sym=self._symbol, **optimizer_params)
        self._optimizer = optimizer
        if isinstance(kvstore, str):
            kvstore = kvs.create(kvstore) if kvstore else None
        self._kvstore = kvstore
        self._update_on_kvstore = kvstore is not None
        if kvstore is not None:
            if self._compression_params:
                kvstore.set_gradient_compression(self._compression_params)
            kvstore.set_optimizer(self._optimizer)
            for i, name in enumerate(self._param_names):
                kvstore.init(i, self._exec.arg_dict[name])
        else:
            self._updater = opt.get_updater(self._optimizer)
        self.optimizer_initialized = True

    def borrow_optimizer(self, shared_module):
        """Share the optimizer AND its state (updater/kvstore) with another
        module — all BucketingModule buckets must advance one set of
        optimizer moments (parity: module.py borrow_optimizer; without this
        each bucket's Adam/momentum state sees only its own subset of the
        updates and training diverges under bucket switching)."""
        assert shared_module.optimizer_initialized
        # updater/kvstore state is keyed by param INDEX — orderings must
        # match or moments silently cross-apply between parameters
        assert shared_module._param_names == self._param_names, \
            "borrow_optimizer requires identical parameter orderings"
        self._optimizer = shared_module._optimizer
        self._kvstore = shared_module._kvstore
        self._update_on_kvstore = shared_module._update_on_kvstore
        self._updater = shared_module._updater
        self.optimizer_initialized = True

    def update(self):
        """Push grads / apply optimizer (parity: module.py:631 + model.py:126).

        With a kvstore the update runs "server-side" in the store (the
        reference's dist path); without one, a local Updater applies it."""
        assert self.binded and self.params_initialized and \
            self.optimizer_initialized
        self._params_dirty = True
        for i, name in enumerate(self._param_names):
            if self._exec._grad_req.get(name, "null") == "null":
                continue
            grad = self._exec.grad_dict.get(name)
            if grad is None:
                continue
            weight = self._exec.arg_dict[name]
            if self._kvstore is not None:
                self._kvstore.push(i, grad)
                self._kvstore.pull(i, out=weight)
            else:
                self._updater(i, grad, weight)

    def update_metric(self, eval_metric, labels):
        eval_metric.update(labels, self.get_outputs())

    def get_states(self, merge_multi_context=True):
        return [self._exec.arg_dict[n] for n in self._state_names]

    def set_states(self, states=None, value=None):
        for n, s in zip(self._state_names, states or []):
            self._exec.arg_dict[n]._data = s._data

    def install_monitor(self, mon):
        mon.install(self._exec)

    def save_optimizer_states(self, fname):
        assert self.optimizer_initialized
        if self._kvstore is not None:
            self._kvstore.save_optimizer_states(fname)
        else:
            with open(fname, "wb") as fout:
                fout.write(self._updater.get_states())

    def load_optimizer_states(self, fname):
        assert self.optimizer_initialized
        if self._kvstore is not None:
            self._kvstore.load_optimizer_states(fname)
        else:
            with open(fname, "rb") as f:
                self._updater.set_states(f.read())

    def reshape(self, data_shapes, label_shapes=None):
        self.bind(data_shapes, label_shapes, self.for_training,
                  self.inputs_need_grad, force_rebind=True)
