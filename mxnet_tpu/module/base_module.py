"""BaseModule: the fit/score/predict training-loop surface.

API parity with the reference's `python/mxnet/module/base_module.py`
(fit/score/predict/iter_predict/forward_backward and the abstract
bind/init/forward/update contract), re-built around this framework's
execution model:

- All three evaluation entry points (score, predict, iter_predict) drain
  one shared `_eval_batches` generator — a single place owns the
  reset / batch-limit / eval-forward / pad-trim protocol.
- `fit` is a plain loop over the data iterator. The reference interleaved
  a one-batch lookahead with the engine's async dispatch to overlap IO
  with compute (base_module.py:507-519); here overlap is owned by the IO
  layer (PrefetchingIter / DevicePrefetchIter stage batches host- and
  device-side), so the training loop stays sequential and readable.
- Batch callbacks receive a BatchEndParams record (same attribute names
  the reference's Speedometer-style callbacks read).
"""
from __future__ import annotations

import logging
import time

import numpy as np

from .. import metric as metric_mod
from ..base import MXNetError
from ..ndarray import NDArray


class BatchEndParams:
    """What a batch/score callback sees; attribute-compatible with the
    reference's namedtuple (epoch, nbatch, eval_metric, locals)."""

    __slots__ = ("epoch", "nbatch", "eval_metric", "locals")

    def __init__(self, epoch, nbatch, eval_metric, locals=None):
        self.epoch = epoch
        self.nbatch = nbatch
        self.eval_metric = eval_metric
        self.locals = locals or {}


_BatchEndParam = BatchEndParams  # back-compat alias


def _callbacks(cbs):
    """Normalize a callback argument (None | callable | list) to a list."""
    if cbs is None:
        return []
    if isinstance(cbs, (list, tuple)):
        return list(cbs)
    return [cbs]


def _trim_pad(outputs, pad):
    """Drop the iterator's fill-up rows from the tail of each output."""
    if not pad:
        return list(outputs)
    return [out[:out.shape[0] - pad] for out in outputs]


class BaseModule:
    def __init__(self, logger=logging):
        self.logger = logger
        self.binded = False
        self.for_training = False
        self.params_initialized = False
        self.optimizer_initialized = False
        self._symbol = None

    # -- high-level API -----------------------------------------------------
    def forward_backward(self, data_batch):
        self.forward(data_batch, is_train=True)
        self.backward()

    def prepare(self, data_batch, sparse_row_id_fn=None):
        """Pre-batch hook (parity: base_module.py prepare). The reference
        used it to row_sparse-pull the rows a batch touches from the
        kvstore before forward; here parameters live device-resident and
        sparse gradients flow through the kvstore at update time, so
        there is nothing to prefetch — the hook exists for API
        compatibility and subclass extension."""

    def _require_ready(self):
        if not (self.binded and self.params_initialized):
            raise MXNetError("module is not ready: call bind() and "
                             "init_params() (or fit()) first")

    def _eval_batches(self, eval_data, num_batch, reset):
        """Shared evaluation drain: inference-mode forward over up to
        `num_batch` batches, yielding (nbatch, batch, pad). Consumers that
        want outputs call get_outputs() themselves (score never does, so
        the drain must not pay for trimming)."""
        self._require_ready()
        if reset:
            eval_data.reset()
        for nbatch, batch in enumerate(eval_data):
            if num_batch is not None and nbatch >= num_batch:
                return
            self.forward(batch, is_train=False)
            yield nbatch, batch, getattr(batch, "pad", 0)

    def score(self, eval_data, eval_metric, num_batch=None,
              batch_end_callback=None, score_end_callback=None, reset=True,
              epoch=0, sparse_row_id_fn=None):
        """Run `eval_metric` over the eval set; returns name/value pairs."""
        if not isinstance(eval_metric, metric_mod.EvalMetric):
            eval_metric = metric_mod.create(eval_metric)
        eval_metric.reset()
        seen = 0
        for nbatch, batch, _ in self._eval_batches(eval_data, num_batch,
                                                   reset):
            self.update_metric(eval_metric, batch.label)
            seen = nbatch + 1
            for cb in _callbacks(batch_end_callback):
                cb(BatchEndParams(epoch, nbatch, eval_metric, locals()))
        for cb in _callbacks(score_end_callback):
            cb(BatchEndParams(epoch, seen, eval_metric, locals()))
        return eval_metric.get_name_value()

    def iter_predict(self, eval_data, num_batch=None, reset=True):
        """Yield (outputs, nbatch, batch) per evaluation batch."""
        for nbatch, batch, pad in self._eval_batches(eval_data,
                                                     num_batch, reset):
            yield _trim_pad(self.get_outputs(), pad), nbatch, batch

    def predict(self, eval_data, num_batch=None, merge_batches=True,
                reset=True, always_output_list=False):
        """Collect forward outputs over the eval set.

        merge_batches=True concatenates along the batch axis and unwraps a
        single output (unless always_output_list); False returns the raw
        per-batch list-of-lists."""
        collected = [_trim_pad(self.get_outputs(), pad) for _, _, pad in
                     self._eval_batches(eval_data, num_batch, reset)]
        if not collected:
            return collected
        if not merge_batches:
            return collected
        width = len(collected[0])
        if any(len(outputs) != width for outputs in collected):
            raise MXNetError(
                "predict(merge_batches=True) needs every mini-batch to "
                "produce the same number of outputs; got a varying count "
                "(bucketed executors do this — pass merge_batches=False)")
        from .. import ndarray as nd
        merged = [nd.concatenate([outputs[i] for outputs in collected])
                  for i in range(width)]
        if width == 1 and not always_output_list:
            return merged[0]
        return merged

    def fit(self, train_data, eval_data=None, eval_metric="acc",
            epoch_end_callback=None, batch_end_callback=None,
            kvstore="local", optimizer="sgd",
            optimizer_params=(("learning_rate", 0.01),),
            eval_end_callback=None, eval_batch_end_callback=None,
            initializer=None, arg_params=None, aux_params=None,
            allow_missing=False, force_rebind=False, force_init=False,
            begin_epoch=0, num_epoch=None, validation_metric=None,
            monitor=None, sparse_row_id_fn=None):
        """The reference's one-call training loop (API parity:
        base_module.py fit): bind -> init params/optimizer -> epochs of
        forward_backward/update with metric + callback plumbing."""
        if num_epoch is None:
            raise ValueError("fit() needs num_epoch")
        from ..initializer import Uniform

        self.bind(data_shapes=train_data.provide_data,
                  label_shapes=train_data.provide_label, for_training=True,
                  force_rebind=force_rebind)
        if monitor is not None:
            self.install_monitor(monitor)
        self.init_params(initializer=initializer or Uniform(0.01),
                         arg_params=arg_params, aux_params=aux_params,
                         allow_missing=allow_missing, force_init=force_init)
        self.init_optimizer(kvstore=kvstore, optimizer=optimizer,
                            optimizer_params=dict(optimizer_params))

        if not isinstance(eval_metric, metric_mod.EvalMetric):
            eval_metric = metric_mod.create(eval_metric)
        validation_metric = validation_metric or eval_metric

        for epoch in range(begin_epoch, num_epoch):
            started = time.time()
            eval_metric.reset()
            for nbatch, batch in enumerate(train_data):
                if monitor is not None:
                    monitor.tic()
                self.prepare(batch, sparse_row_id_fn=sparse_row_id_fn)
                self.forward_backward(batch)
                self.update()
                self.update_metric(eval_metric, batch.label)
                if monitor is not None:
                    monitor.toc_print()
                for cb in _callbacks(batch_end_callback):
                    cb(BatchEndParams(epoch, nbatch, eval_metric, locals()))

            for name, val in eval_metric.get_name_value():
                self.logger.info("Epoch[%d] Train-%s=%f", epoch, name, val)
            self.logger.info("Epoch[%d] Time cost=%.3f", epoch,
                             time.time() - started)

            # materialize the epoch's parameters host-side: checkpoints
            # written by epoch callbacks must not hold donated buffers
            arg_now, aux_now = self.get_params()
            self.set_params(arg_now, aux_now)
            for cb in _callbacks(epoch_end_callback):
                cb(epoch, self.symbol, arg_now, aux_now)

            if eval_data is not None:
                for name, val in self.score(
                        eval_data, validation_metric, epoch=epoch,
                        batch_end_callback=eval_batch_end_callback,
                        score_end_callback=eval_end_callback):
                    self.logger.info("Epoch[%d] Validation-%s=%f", epoch,
                                     name, val)

            train_data.reset()

    # -- properties / abstract ----------------------------------------------
    @property
    def symbol(self):
        return self._symbol

    @property
    def data_names(self):
        raise NotImplementedError()

    @property
    def output_names(self):
        raise NotImplementedError()

    @property
    def data_shapes(self):
        raise NotImplementedError()

    @property
    def label_shapes(self):
        raise NotImplementedError()

    @property
    def output_shapes(self):
        raise NotImplementedError()

    def get_params(self):
        raise NotImplementedError()

    def init_params(self, initializer=None, arg_params=None, aux_params=None,
                    allow_missing=False, force_init=False,
                    allow_extra=False):
        raise NotImplementedError()

    def set_params(self, arg_params, aux_params, allow_missing=False,
                   force_init=True, allow_extra=False):
        self.init_params(initializer=None, arg_params=arg_params,
                         aux_params=aux_params, allow_missing=allow_missing,
                         force_init=force_init, allow_extra=allow_extra)

    def save_params(self, fname):
        arg_params, aux_params = self.get_params()
        save_dict = {("arg:%s" % k): v for k, v in arg_params.items()}
        save_dict.update({("aux:%s" % k): v for k, v in aux_params.items()})
        from ..utils import serialization
        serialization.save_ndarrays(fname, save_dict)

    def load_params(self, fname):
        from ..utils import serialization
        arg_params, aux_params = {}, {}
        for key, value in serialization.load_ndarrays(fname).items():
            kind, _, name = key.partition(":")
            if kind == "arg":
                arg_params[name] = value
            elif kind == "aux":
                aux_params[name] = value
            else:
                raise ValueError(
                    "%s is not a module parameter file: entry %r is "
                    "neither arg: nor aux:" % (fname, key))
        self.set_params(arg_params, aux_params)

    def forward(self, data_batch, is_train=None):
        raise NotImplementedError()

    def backward(self, out_grads=None):
        raise NotImplementedError()

    def get_outputs(self, merge_multi_context=True):
        raise NotImplementedError()

    def get_input_grads(self, merge_multi_context=True):
        raise NotImplementedError()

    def update(self):
        raise NotImplementedError()

    def update_metric(self, eval_metric, labels):
        raise NotImplementedError()

    def bind(self, data_shapes, label_shapes=None, for_training=True,
             inputs_need_grad=False, force_rebind=False, shared_module=None,
             grad_req="write"):
        raise NotImplementedError()

    def init_optimizer(self, kvstore="local", optimizer="sgd",
                       optimizer_params=(("learning_rate", 0.01),),
                       force_init=False):
        raise NotImplementedError()

    def install_monitor(self, mon):
        raise NotImplementedError()
