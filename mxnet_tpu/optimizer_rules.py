"""Pure optimizer update rules shared by every execution path.

Parity: the reference implements each optimizer twice — python math
(`python/mxnet/optimizer.py:35-1453`) and fused C++ kernels
(`src/operator/optimizer_op-inl.h`). Here there is ONE implementation per
optimizer: a pure (weight, grad, state) -> (new_weight, new_state) function
in jnp. The eager classes in optimizer.py delegate their dense paths to
these rules, and parallel.trainer.TrainStep closes them into the donated
fused XLA step — so the fused path supports every registered optimizer and
matches the eager path exactly (tested in tests/test_trainstep_optimizers.py).

Signatures:
    init(w, h)                          -> tuple of state arrays (may be ())
    apply(w, g, state, lr, wd, t, h, key=None) -> (new_w, new_state)

where `g` is the incoming gradient with rescale/clipping already applied
(NOT weight decay — each rule applies wd the way its reference class does),
`lr`/`wd`/`t` may be tracers (t is the 1-based update count), `h` is a dict
of static hyper-parameters, and `key` is a PRNG key for stochastic rules
(SGLD). All state is carried in the returned tuple — including Nadam's
m_schedule, which the reference keeps as a single Python float shared by
every parameter (a cross-parameter leak); here it is per-parameter state,
the mathematically intended form.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp


def _zeros(w):
    return jnp.zeros_like(w)


# ---------------------------------------------------------------------------
# rules
# ---------------------------------------------------------------------------

def _sgd_init(w, h):
    return (_zeros(w),) if h.get("momentum", 0.0) else ()


def _sgd_apply(w, g, state, lr, wd, t, h, key=None):
    g = g + wd * w
    if state:
        m = h["momentum"] * state[0] - lr * g
        return w + m, (m,)
    return w - lr * g, state


def _signum_init(w, h):
    return (_zeros(w),) if h.get("momentum", 0.0) else ()


def _signum_apply(w, g, state, lr, wd, t, h, key=None):
    wd_lh = h.get("wd_lh", 0.0)
    if state:
        m = h["momentum"] * state[0] - (1 - h["momentum"]) * (g + wd * w)
        return (1 - lr * wd_lh) * w + lr * jnp.sign(m), (m,)
    return (1 - lr * (wd + wd_lh)) * w - lr * jnp.sign(g), state


def _ftml_init(w, h):
    return (_zeros(w), _zeros(w), _zeros(w))  # d, v, z


def _ftml_apply(w, g, state, lr, wd, t, h, key=None):
    b1, b2, eps = h.get("beta1", 0.6), h.get("beta2", 0.999), \
        h.get("epsilon", 1e-8)
    g = g + wd * w
    d, v, z = state
    v_t = b2 * v + (1 - b2) * jnp.square(g)
    d_t = (1 - b1 ** t) / lr * (jnp.sqrt(v_t / (1 - b2 ** t)) + eps)
    sigma_t = d_t - b1 * d
    z_t = b1 * z + (1 - b1) * g - sigma_t * w
    return -z_t / d_t, (d_t, v_t, z_t)


def _lbsgd_init(w, h):
    return (_zeros(w),)


def _lbsgd_apply(w, g, state, lr, wd, t, h, key=None):
    warm_steps = h.get("warmup_epochs", 5) * h.get("updates_per_epoch", 32)
    lr = lr * jnp.minimum(t / max(1, warm_steps), 1.0)
    wnorm = jnp.linalg.norm(w)
    gnorm = jnp.linalg.norm(g)
    phi = jnp.where((wnorm > 0) & (gnorm > 0),
                    wnorm / (gnorm + wd * wnorm + 1e-12), 1.0)
    g = g + wd * w
    m = h.get("momentum", 0.0) * state[0] - lr * phi * g
    return w + m, (m,)


def _dcasgd_init(w, h):
    mom = (_zeros(w),) if h.get("momentum", 0.0) else ()
    return mom + (w + 0,)  # (momentum?, prev_weight)


def _dcasgd_apply(w, g, state, lr, wd, t, h, key=None):
    lamda = h.get("lamda", 0.04)
    prev = state[-1]
    comp = g + wd * w + lamda * g * g * (w - prev)
    if len(state) == 2:
        m = h["momentum"] * state[0] - lr * comp
        return w + m, (m, w)
    return w - lr * comp, (w,)


def _nag_init(w, h):
    return (_zeros(w),) if h.get("momentum", 0.0) else ()


def _nag_apply(w, g, state, lr, wd, t, h, key=None):
    g = g + wd * w
    if state:
        m = h["momentum"] * state[0] + g
        return w - lr * (g + h["momentum"] * m), (m,)
    return w - lr * g, state


def _sgld_init(w, h):
    return ()


def _sgld_apply(w, g, state, lr, wd, t, h, key=None):
    g = g + wd * w
    noise = jax.random.normal(key, w.shape, dtype=w.dtype) * jnp.sqrt(lr)
    return w - lr / 2 * g + noise, state


def _adam_init(w, h):
    return (_zeros(w), _zeros(w))  # mean, var


def _adam_apply(w, g, state, lr, wd, t, h, key=None):
    b1, b2, eps = h.get("beta1", 0.9), h.get("beta2", 0.999), \
        h.get("epsilon", 1e-8)
    g = g + wd * w
    m, v = state
    m = b1 * m + (1 - b1) * g
    v = b2 * v + (1 - b2) * jnp.square(g)
    lr_t = lr * jnp.sqrt(1 - b2 ** t) / (1 - b1 ** t)
    return w - lr_t * m / (jnp.sqrt(v) + eps), (m, v)


def _adagrad_init(w, h):
    return (_zeros(w),)


def _adagrad_apply(w, g, state, lr, wd, t, h, key=None):
    eps = h.get("eps", 1e-7)
    g = g + wd * w
    n = state[0] + jnp.square(g)
    return w - lr * g / jnp.sqrt(n + eps), (n,)


def _rmsprop_init(w, h):
    if h.get("centered", False):
        return (_zeros(w), _zeros(w), _zeros(w))  # n, g_bar, delta
    return (_zeros(w),)


def _rmsprop_apply(w, g, state, lr, wd, t, h, key=None):
    g1, g2 = h.get("gamma1", 0.9), h.get("gamma2", 0.9)
    eps = h.get("epsilon", 1e-8)
    clip_w = h.get("clip_weights", None)
    g = g + wd * w
    if h.get("centered", False):
        n, gbar, delta = state
        n = (1 - g1) * jnp.square(g) + g1 * n
        gbar = (1 - g1) * g + g1 * gbar
        delta = g2 * delta - lr * g / jnp.sqrt(
            n - jnp.square(gbar) + eps)
        new_w, new_state = w + delta, (n, gbar, delta)
    else:
        n = (1 - g1) * jnp.square(g) + g1 * state[0]
        new_w, new_state = w - lr * g / jnp.sqrt(n + eps), (n,)
    if clip_w:
        new_w = jnp.clip(new_w, -clip_w, clip_w)
    return new_w, new_state


def _adadelta_init(w, h):
    return (_zeros(w), _zeros(w))  # acc_g, acc_delta


def _adadelta_apply(w, g, state, lr, wd, t, h, key=None):
    rho, eps = h.get("rho", 0.90), h.get("epsilon", 1e-5)
    g = g + wd * w
    acc_g, acc_d = state
    acc_g = rho * acc_g + (1 - rho) * jnp.square(g)
    delta = jnp.sqrt(acc_d + eps) / jnp.sqrt(acc_g + eps) * g
    acc_d = rho * acc_d + (1 - rho) * jnp.square(delta)
    return w - delta, (acc_g, acc_d)


def _ftrl_init(w, h):
    return (_zeros(w), _zeros(w))  # z, n


def _ftrl_apply(w, g, state, lr, wd, t, h, key=None):
    lamda1, beta = h.get("lamda1", 0.01), h.get("beta", 1)
    z, n = state
    sigma = (jnp.sqrt(n + jnp.square(g)) - jnp.sqrt(n)) / lr
    z = z + g - sigma * w
    n = n + jnp.square(g)
    new_w = jnp.where(
        jnp.abs(z) <= lamda1, 0.0,
        -(z - jnp.sign(z) * lamda1) / ((beta + jnp.sqrt(n)) / lr + wd))
    return new_w, (z, n)


def _adamax_init(w, h):
    return (_zeros(w), _zeros(w))  # m, u


def _adamax_apply(w, g, state, lr, wd, t, h, key=None):
    b1, b2 = h.get("beta1", 0.9), h.get("beta2", 0.999)
    lr = lr / (1.0 - b1 ** t)
    g = g + wd * w
    m, u = state
    m = b1 * m + (1 - b1) * g
    u = jnp.maximum(b2 * u, jnp.abs(g))
    return w - lr * m / (u + 1e-8), (m, u)


def _nadam_init(w, h):
    # per-parameter m_schedule (see module docstring re: reference quirk)
    return (_zeros(w), _zeros(w), jnp.ones((), dtype=w.dtype))


def _nadam_apply(w, g, state, lr, wd, t, h, key=None):
    b1, b2, eps = h.get("beta1", 0.9), h.get("beta2", 0.999), \
        h.get("epsilon", 1e-8)
    sd = h.get("schedule_decay", 0.004)
    g = g + wd * w
    m, v, m_sched = state
    mom_t = b1 * (1.0 - 0.5 * 0.96 ** (t * sd))
    mom_tp1 = b1 * (1.0 - 0.5 * 0.96 ** ((t + 1) * sd))
    m_sched = m_sched * mom_t
    m_sched_next = m_sched * mom_tp1
    gp = g / (1.0 - m_sched)
    m = b1 * m + (1 - b1) * g
    v = b2 * v + (1 - b2) * jnp.square(g)
    m_hat = m / (1.0 - m_sched_next)
    v_hat = v / (1.0 - b2 ** t)
    m_bar = (1.0 - mom_t) * gp + mom_tp1 * m_hat
    return w - lr * m_bar / (jnp.sqrt(v_hat) + eps), (m, v, m_sched)


def _test_init(w, h):
    return (_zeros(w),)


def _test_apply(w, g, state, lr, wd, t, h, key=None):
    new_w = w + g
    return new_w, (new_w,)


RULES = {
    "sgd": (_sgd_init, _sgd_apply),
    "ccsgd": (_sgd_init, _sgd_apply),
    "signum": (_signum_init, _signum_apply),
    "ftml": (_ftml_init, _ftml_apply),
    "lbsgd": (_lbsgd_init, _lbsgd_apply),
    "dcasgd": (_dcasgd_init, _dcasgd_apply),
    "nag": (_nag_init, _nag_apply),
    "sgld": (_sgld_init, _sgld_apply),
    "adam": (_adam_init, _adam_apply),
    "adagrad": (_adagrad_init, _adagrad_apply),
    "rmsprop": (_rmsprop_init, _rmsprop_apply),
    "adadelta": (_adadelta_init, _adadelta_apply),
    "ftrl": (_ftrl_init, _ftrl_apply),
    "adamax": (_adamax_init, _adamax_apply),
    "nadam": (_nadam_init, _nadam_apply),
    "test": (_test_init, _test_apply),
}

STOCHASTIC = {"sgld"}


def get(name):
    """Return (init, apply) for a registered optimizer name."""
    key = name.lower()
    if key not in RULES:
        raise ValueError("no pure update rule for optimizer %r" % name)
    return RULES[key]
