"""Imperative autograd: record/pause scopes, mark_variables, backward.

Parity: reference `python/mxnet/autograd.py` (record:122/pause:146/
mark_variables:197/backward:243/grad:270/Function:363) on top of
`src/imperative/imperative.cc` (RecordOp tape, Backward graph construction).

TPU-native redesign: instead of building an nnvm graph and re-dispatching
node-by-node through a C++ engine, the tape stores each op's pure JAX
function plus the concrete input buffers; backward walks the tape in reverse
topological order calling jax.vjp per node. Stochastic ops snapshot their
PRNG key so forward/backward see identical masks. XLA's async dispatch
provides the engine's compute overlap; the tape provides the dependency
order.
"""
from __future__ import annotations

import contextlib
import threading
from collections import defaultdict

import numpy as np
import jax
import jax.numpy as jnp

from . import random as _random
from .base import MXNetError


class _Scope(threading.local):
    def __init__(self):
        super().__init__()
        self.recording = False
        self.training = False


_SCOPE = _Scope()


def is_recording():
    return _SCOPE.recording


def is_training():
    return _SCOPE.training


def set_recording(is_record):
    prev = _SCOPE.recording
    _SCOPE.recording = bool(is_record)
    return prev


def set_training(train_mode):
    prev = _SCOPE.training
    _SCOPE.training = bool(train_mode)
    return prev


class _RecordingStateScope:
    def __init__(self, is_record, train_mode):
        self._enter_is_record = is_record
        self._enter_train_mode = train_mode
        self._prev_is_record = None
        self._prev_train_mode = None

    def __enter__(self):
        if self._enter_is_record is not None:
            self._prev_is_record = set_recording(self._enter_is_record)
        if self._enter_train_mode is not None:
            self._prev_train_mode = set_training(self._enter_train_mode)
        return self

    def __exit__(self, *exc):
        if self._enter_is_record is not None:
            set_recording(self._prev_is_record)
        if self._enter_train_mode is not None:
            set_training(self._prev_train_mode)


def record(train_mode=True):
    """Scope that records ops onto the tape (parity: autograd.record)."""
    return _RecordingStateScope(True, train_mode)


def pause(train_mode=False):
    return _RecordingStateScope(False, train_mode)


def train_mode():
    return _RecordingStateScope(None, True)


def predict_mode():
    return _RecordingStateScope(None, False)


# ---------------------------------------------------------------------------
# tape nodes
# ---------------------------------------------------------------------------


class VariableEntry:
    """A leaf marked via mark_variables/attach_grad."""
    __slots__ = ("array", "grad_req")

    def __init__(self, array, grad_req):
        self.array = array  # the NDArray whose .grad accumulates
        self.grad_req = grad_req


# Keyed cache of jitted vjp programs, mirroring the eager forward's
# _JIT_CACHE (ndarray.py): without it, backward runs jax.vjp EAGERLY —
# for scan-carrying ops (fused RNN) eager linearization compiles the scan
# inside op-by-op dispatch on every backward call, turning a word-LM
# backward from milliseconds into minutes-per-batch. Keyed on the same
# static specialization tuple the forward jit used plus the
# input/cotangent avals, so one compile serves every batch of the same
# shape. Stochastic nodes pass their PRNG key as a traced argument
# (trace_key_scope installs tracers fine — TrainStep does the same), so
# the cached program is key-independent.
_VJP_CACHE = {}
_VJP_CACHE_CAP = 8192  # same bound as the forward _JIT_CACHE
_VJP_BLACKLIST = set()


class OpNode:
    """One recorded op application (parity: nnvm node on the imperative tape,
    src/imperative/imperative.cc:182 RecordOp)."""
    __slots__ = ("fn", "kwargs", "parent_entries", "input_vals", "num_outputs",
                 "out_avals", "rng_key", "train_flag", "custom_backward",
                 "differentiable", "jit_key")

    def __init__(self, fn, kwargs, parent_entries, input_vals, num_outputs,
                 out_avals, rng_key, train_flag, differentiable=True,
                 custom_backward=None, jit_key=None):
        self.fn = fn
        self.kwargs = kwargs
        self.parent_entries = parent_entries  # list of entries or None
        self.input_vals = input_vals          # jax arrays at record time
        self.num_outputs = num_outputs
        self.out_avals = out_avals            # (shape, dtype) per output
        self.rng_key = rng_key
        self.train_flag = train_flag
        self.differentiable = differentiable
        self.custom_backward = custom_backward
        self.jit_key = jit_key                # hashable static spec or None

    def run_vjp(self, out_grads):
        """Compute input cotangents given output cotangents (list, no Nones)."""
        if self.custom_backward is not None:
            return self.custom_backward(out_grads, self.input_vals, self.kwargs)
        kwargs = self.kwargs

        def pure(*ins):
            out = self.fn(*ins, **kwargs)
            return out if isinstance(out, tuple) else (out,)

        def run():
            _, vjp_fn = jax.vjp(pure, *self.input_vals)
            return vjp_fn(tuple(out_grads))

        has_rng = self.rng_key is not None
        scope = _RecordingStateScope(False, self.train_flag)
        with scope:
            ck = None
            if self.jit_key is not None:
                ck = (self.jit_key, self.train_flag, has_rng,
                      tuple((v.shape, str(v.dtype))
                            for v in self.input_vals),
                      tuple((g.shape, str(g.dtype)) for g in out_grads))
            # Cache hits are always served; the cap bounds only how many NEW
            # programs may be inserted (mirrors _jitted_op in ndarray.py —
            # gating lookups at cap would silently revert every backward to
            # eager per-op jax.vjp once the cache fills).
            jitted = _VJP_CACHE.get(ck) if ck is not None else None
            if ck is not None and ck not in _VJP_BLACKLIST and \
                    (jitted is not None or len(_VJP_CACHE) < _VJP_CACHE_CAP):
                fresh = jitted is None
                if fresh:
                    # arguments flow through vjp as tracers, so the cached
                    # program is reusable across nodes with the same key;
                    # the rng key is an argument too, not a baked constant.
                    # Close over ONLY self.fn/kwargs (static values) — not
                    # `pure`/`self`, which would pin the node and its whole
                    # upstream tape (first batch's activations) in the
                    # module-global cache forever.
                    def vjp_apply(ins, gs, key, _fn=self.fn, _kw=kwargs):
                        def _pure(*xs):
                            out = _fn(*xs, **_kw)
                            return out if isinstance(out, tuple) else (out,)
                        ctx = _random.trace_key_scope(key) if key is not None \
                            else contextlib.nullcontext()
                        with ctx:
                            _, vjp_fn = jax.vjp(_pure, *ins)
                            return vjp_fn(tuple(gs))
                    jitted = jax.jit(vjp_apply,
                                     static_argnums=() if has_rng else (2,))
                try:
                    res = jitted(tuple(self.input_vals), tuple(out_grads),
                                 self.rng_key)
                    _VJP_CACHE[ck] = jitted
                    return res
                except Exception:
                    # First call of a NEW program = trace/compile time, where
                    # backward jits a wider surface than the forward
                    # _jitted_op saw (host syncs, callbacks, plugin quirks):
                    # blacklist the specialization and fall through to the
                    # eager path below — if the op is genuinely broken the
                    # eager retry raises the real error. A failure from an
                    # already-validated CACHED program is an execution-time
                    # error (OOM, transient runtime): propagate it rather
                    # than silently demoting the specialization forever.
                    if not fresh:
                        raise
                    _VJP_BLACKLIST.add(ck)
                    _VJP_CACHE.pop(ck, None)
            if has_rng:
                with _random.trace_key_scope(self.rng_key):
                    return run()
            return run()


def record_op(opdef, input_ndarrays, input_vals, outputs, kwargs,
              rng_key=None, custom_backward=None, fn=None, jit_key=None):
    """Append an op to the tape; sets ._entry on each output NDArray."""
    parent_entries = [getattr(a, "_entry", None) for a in input_ndarrays]
    if custom_backward is None and (
            not opdef.differentiable or
            (all(e is None for e in parent_entries))):
        return  # nothing upstream requires grad
    out_avals = [(o.shape, o.dtype) for o in
                 (outputs if isinstance(outputs, (list, tuple)) else [outputs])]
    node = OpNode(fn or opdef.fn, {} if fn is not None else dict(kwargs),
                  parent_entries, list(input_vals),
                  len(out_avals), out_avals, rng_key, is_training(),
                  opdef.differentiable, custom_backward, jit_key=jit_key)
    outs = outputs if isinstance(outputs, (list, tuple)) else [outputs]
    for i, o in enumerate(outs):
        o._entry = (node, i)


def mark_variables(variables, gradients, grad_reqs="write"):
    """Attach gradient buffers to leaves (parity: autograd.mark_variables)."""
    if isinstance(grad_reqs, str):
        grad_reqs = [grad_reqs] * len(variables)
    for var, grad, req in zip(variables, gradients, grad_reqs):
        var._grad = grad
        var._entry = VariableEntry(var, req)


# ---------------------------------------------------------------------------
# backward
# ---------------------------------------------------------------------------


def _toposort(head_entries):
    """Reverse-topological order of OpNodes reachable from the heads."""
    visited = {}
    order = []
    stack = [e[0] for e in head_entries if isinstance(e, tuple)]
    # iterative DFS with post-order append
    work = [(n, False) for n in stack]
    while work:
        node, processed = work.pop()
        if processed:
            order.append(node)
            continue
        if id(node) in visited:
            continue
        visited[id(node)] = node
        work.append((node, True))
        for ent in node.parent_entries:
            if isinstance(ent, tuple) and id(ent[0]) not in visited:
                work.append((ent[0], False))
    order.reverse()  # heads first
    return order


def backward(heads, head_grads=None, retain_graph=False, train_mode=True):
    """Run backward over the tape from `heads` (parity: autograd.backward).

    Gradients accumulate into the .grad buffers attached by
    attach_grad/mark_variables according to each leaf's grad_req.
    """
    from .ndarray import NDArray

    if isinstance(heads, NDArray):
        heads = [heads]
    if head_grads is None:
        head_grads = [None] * len(heads)
    elif isinstance(head_grads, NDArray):
        head_grads = [head_grads]

    grads = defaultdict(dict)  # id(node) -> {out_idx: jax array}
    touched = set()            # grad buffers already written this backward
    entries = []
    for h, hg in zip(heads, head_grads):
        ent = getattr(h, "_entry", None)
        if ent is None:
            continue
        g = hg._data if hg is not None else jnp.ones(h.shape, dtype=h._data.dtype)
        if isinstance(ent, VariableEntry):
            _accumulate_leaf(ent, g, touched)
            continue
        node, idx = ent
        cur = grads[id(node)].get(idx)
        grads[id(node)][idx] = g if cur is None else cur + g
        entries.append(ent)

    if not entries and not any(isinstance(getattr(h, "_entry", None), VariableEntry)
                               for h in heads):
        raise MXNetError("cannot differentiate: outputs are not on the tape "
                         "(call inside autograd.record())")

    order = _toposort(entries)
    for node in order:
        node_grads = grads.pop(id(node), None)
        if node_grads is None:
            continue
        out_grads = []
        for i in range(node.num_outputs):
            g = node_grads.get(i)
            if g is None:
                shape, dtype = node.out_avals[i]
                g = jnp.zeros(shape, dtype=dtype)
            out_grads.append(g)
        if not node.differentiable and node.custom_backward is None:
            continue
        in_grads = node.run_vjp(out_grads)
        for ent, ig in zip(node.parent_entries, in_grads):
            if ent is None or ig is None:
                continue
            if getattr(ig, "dtype", None) == jax.dtypes.float0:
                continue  # cotangent of an integer input
            if isinstance(ent, VariableEntry):
                _accumulate_leaf(ent, ig, touched)
            else:
                pnode, pidx = ent
                cur = grads[id(pnode)].get(pidx)
                grads[id(pnode)][pidx] = ig if cur is None else cur + ig
        if not retain_graph:
            node.input_vals = None  # free buffers


def _accumulate_leaf(ent, g, touched):
    var = ent.array
    if ent.grad_req == "null" or var._grad is None:
        return
    g = g.astype(var._grad._data.dtype)
    if g.shape != var._grad.shape:
        g = g.reshape(var._grad.shape)
    if ent.grad_req == "add" or id(var._grad) in touched:
        var._grad._data = var._grad._data + g
    else:  # grad_req == 'write': first touch this backward overwrites
        var._grad._data = g
    touched.add(id(var._grad))
    var._grad._version += 1


def grad(heads, variables, head_grads=None, retain_graph=None,
         create_graph=False, train_mode=True):
    """Return gradients of heads w.r.t. variables (parity: autograd.grad:270).

    create_graph=True (higher-order) is supported by re-deriving through
    jax.grad on the replayed subgraph — round 1 supports first order.
    """
    from .ndarray import NDArray
    if isinstance(variables, NDArray):
        variables = [variables]
    saved = [(v._grad, getattr(v, "_entry", None)) for v in variables]
    zeros = []
    for v in variables:
        z = NDArray(jnp.zeros(v.shape, dtype=v._data.dtype), ctx=v.context)
        zeros.append(z)
    mark_variables(variables, zeros, "write")
    try:
        backward(heads, head_grads, retain_graph=bool(retain_graph),
                 train_mode=train_mode)
        return [v._grad for v in variables]
    finally:
        for v, (g, e) in zip(variables, saved):
            v._grad = g
            if e is not None:
                v._entry = e


def get_symbol(x):  # parity shim: reference returns the recorded symbol
    return None


class Function:
    """Custom differentiable function (parity: autograd.Function:363).

    Subclass and override forward(self, *inputs) / backward(self, *out_grads),
    both operating on NDArrays.
    """

    def __init__(self):
        self.saved_tensors = ()

    def save_for_backward(self, *args):
        self.saved_tensors = args

    def forward(self, *inputs):
        raise NotImplementedError

    def backward(self, *out_grads):
        raise NotImplementedError

    def __call__(self, *inputs):
        from .ndarray import NDArray
        with pause():
            outputs = self.forward(*inputs)
        single = not isinstance(outputs, (list, tuple))
        outs = [outputs] if single else list(outputs)
        if is_recording():
            func = self

            def custom_backward(out_grads, input_vals, kwargs):
                gs = [NDArray(g) for g in out_grads]
                with pause():
                    igs = func.backward(*gs)
                if not isinstance(igs, (list, tuple)):
                    igs = [igs]
                return [g._data if g is not None else None for g in igs]

            class _FakeOpDef:
                fn = None
                differentiable = True

            record_op(_FakeOpDef, list(inputs), [i._data for i in inputs],
                      outs, {}, custom_backward=custom_backward)
        return outs[0] if single else outs
