"""Checkpoint helpers + legacy FeedForward estimator.

Parity: reference `python/mxnet/model.py` — save_checkpoint:365 /
load_checkpoint:395 (symbol JSON + params), FeedForward:433 (pre-Module
estimator, thin wrapper over Module here).
"""
from __future__ import annotations

import logging

from .utils import serialization
from . import symbol as sym_mod


def save_checkpoint(prefix, epoch, symbol, arg_params, aux_params):
    """Parity: model.py:365 — writes prefix-symbol.json + prefix-%04d.params."""
    if symbol is not None:
        symbol.save("%s-symbol.json" % prefix)
    save_dict = {("arg:%s" % k): v for k, v in arg_params.items()}
    save_dict.update({("aux:%s" % k): v for k, v in aux_params.items()})
    param_name = "%s-%04d.params" % (prefix, epoch)
    serialization.save_ndarrays(param_name, save_dict)
    logging.info("Saved checkpoint to \"%s\"", param_name)


def load_checkpoint(prefix, epoch):
    """Parity: model.py:395."""
    symbol = sym_mod.load("%s-symbol.json" % prefix)
    save_dict = serialization.load_ndarrays("%s-%04d.params" % (prefix, epoch))
    arg_params, aux_params = serialization.split_arg_aux(save_dict)
    return (symbol, arg_params, aux_params)


class FeedForward:
    """Legacy estimator (parity: model.py:433) as a thin Module wrapper."""

    def __init__(self, symbol, ctx=None, num_epoch=None, epoch_size=None,
                 optimizer="sgd", initializer=None, numpy_batch_size=128,
                 arg_params=None, aux_params=None, allow_extra_params=False,
                 begin_epoch=0, **kwargs):
        from .initializer import Uniform
        self.symbol = symbol
        self.ctx = ctx
        self.num_epoch = num_epoch
        self.optimizer = optimizer
        self.initializer = initializer or Uniform(0.01)
        self.arg_params = arg_params
        self.aux_params = aux_params
        self.begin_epoch = begin_epoch
        self.kwargs = kwargs
        self._module = None

    def fit(self, X, y=None, eval_data=None, eval_metric="acc",
            epoch_end_callback=None, batch_end_callback=None,
            kvstore="local", logger=None):
        from .module import Module
        mod = Module(self.symbol, context=self.ctx)
        self._module = mod
        mod.fit(X, eval_data=eval_data, eval_metric=eval_metric,
                epoch_end_callback=epoch_end_callback,
                batch_end_callback=batch_end_callback, kvstore=kvstore,
                optimizer=self.optimizer,
                optimizer_params=self.kwargs.get("optimizer_params",
                                                 (("learning_rate", 0.01),)),
                initializer=self.initializer, arg_params=self.arg_params,
                aux_params=self.aux_params, begin_epoch=self.begin_epoch,
                num_epoch=self.num_epoch)
        self.arg_params, self.aux_params = mod.get_params()

    def _ensure_module(self, X):
        """Bind a Module on demand so predict/score work on
        checkpoint-loaded models that never called fit (reference
        model.py:724 builds the predictor from arg_params)."""
        if self._module is not None:
            return self._module
        from .module import Module
        mod = Module(self.symbol, context=self.ctx)
        mod.bind(data_shapes=X.provide_data,
                 label_shapes=getattr(X, "provide_label", None),
                 for_training=False)
        assert self.arg_params is not None, \
            "no parameters: call fit() or load() first"
        mod.set_params(self.arg_params, self.aux_params or {},
                       allow_missing=False)
        self._module = mod
        return mod

    def predict(self, X, num_batch=None, return_data=False, reset=True):
        return self._ensure_module(X).predict(X, num_batch=num_batch,
                                              reset=reset)

    def score(self, X, eval_metric="acc", num_batch=None):
        """Parity: model.py FeedForward.score — returns the metric value
        list (all values for composite metrics, reference model.py:773)."""
        from . import metric as metric_mod
        mod = self._ensure_module(X)
        if isinstance(eval_metric, str):
            eval_metric = metric_mod.create(eval_metric)
        mod.score(X, eval_metric, num_batch=num_batch)
        return eval_metric.get()[1]

    def save(self, prefix, epoch=None):
        if epoch is None:
            epoch = self.num_epoch or 0
        save_checkpoint(prefix, epoch, self.symbol, self.arg_params,
                        self.aux_params)

    @staticmethod
    def load(prefix, epoch, ctx=None, **kwargs):
        symbol, arg_params, aux_params = load_checkpoint(prefix, epoch)
        return FeedForward(symbol, ctx=ctx, arg_params=arg_params,
                           aux_params=aux_params, begin_epoch=epoch, **kwargs)

    @staticmethod
    def create(symbol, X, y=None, ctx=None, num_epoch=None, **kwargs):
        model = FeedForward(symbol, ctx=ctx, num_epoch=num_epoch, **kwargs)
        model.fit(X, y)
        return model
