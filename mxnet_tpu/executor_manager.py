"""Legacy multi-device executor manager (parity: reference
python/mxnet/executor_manager.py — `_split_input_slice`,
`DataParallelExecutorGroup`, `DataParallelExecutorManager`, the engine
under the pre-Module `FeedForward` estimator's multi-device loop).

TPU-native note: new code should use `Module` (mesh-sharded single
executor) or `parallel.TrainStep`; this manager exists for source
compatibility with reference scripts that drive executors directly. Each
context gets its own bound executor over a batch slice; parameters and
gradients are exposed as per-device lists exactly like the reference, so
the caller's updater/kvstore loop works unchanged.
"""
from __future__ import annotations

import logging

import numpy as np

from .base import MXNetError
from .executor import Executor
from .ndarray import NDArray


def _split_input_slice(batch_size, work_load_list):
    """Proportional batch slices per device (reference
    executor_manager.py:31); raises when a device would get zero rows."""
    total = sum(work_load_list)
    slices = []
    start = 0
    for i, w in enumerate(work_load_list):
        end = batch_size if i == len(work_load_list) - 1 else \
            start + int(round(batch_size * w / total))
        if end <= start:
            raise MXNetError(
                "too many slices: batch size %d cannot feed %d devices"
                % (batch_size, len(work_load_list)))
        slices.append(slice(start, end))
        start = end
    return slices


def _load_general(src, targets):
    """Copy source arrays into target (array, slice) pairs."""
    for arr, targets_for_arr in zip(src, targets):
        a = arr.asnumpy() if isinstance(arr, NDArray) else np.asarray(arr)
        for dst, sl in targets_for_arr:
            dst._data = NDArray(a[sl])._data
            dst._version += 1


class DataParallelExecutorGroup:
    """One executor per context over a batch slice (reference
    executor_manager.py:204)."""

    def __init__(self, sym, arg_names, param_names, ctx, slices, train_data,
                 shared_group=None):
        self.param_names = list(param_names)
        self.arg_names = list(arg_names)
        self.aux_names = sym.list_auxiliary_states()

        def _desc(d):
            return (d.name, tuple(d.shape)) if hasattr(d, "name") \
                else (d[0], tuple(d[1]))

        descs = [_desc(d) for d in
                 list(train_data.provide_data) +
                 list(train_data.provide_label)]
        data_shapes = dict(descs)
        self.data_names = [_desc(d)[0] for d in train_data.provide_data]
        self.label_names = [_desc(d)[0] for d in train_data.provide_label]
        grad_req = {n: ("write" if n in set(param_names) else "null")
                    for n in arg_names}
        self.train_execs = []
        for i, c in enumerate(ctx):
            shapes = {}
            for name, shape in data_shapes.items():
                n_rows = slices[i].stop - slices[i].start
                shapes[name] = (n_rows,) + tuple(shape[1:])
            shared = shared_group.train_execs[i] if shared_group else None
            exe = Executor.simple_bind(sym, c, grad_req=grad_req, **shapes)
            if shared is not None:
                # bucketing shares parameter/grad storage with the master
                for n in self.param_names:
                    exe.arg_dict[n] = shared.arg_dict[n]
                    if n in shared.grad_dict:
                        exe.grad_dict[n] = shared.grad_dict[n]
                for n in self.aux_names:
                    exe.aux_dict[n] = shared.aux_dict[n]
            self.train_execs.append(exe)
        self.slices = slices
        # per-parameter lists of per-device arrays (the reference layout
        # consumed by _update_params / kvstore loops)
        self.param_arrays = [[e.arg_dict[n] for e in self.train_execs]
                             for n in self.param_names]
        self.grad_arrays = [[e.grad_dict.get(n) for e in self.train_execs]
                            for n in self.param_names]
        self.aux_arrays = [[e.aux_dict[n] for e in self.train_execs]
                           for n in self.aux_names]
        self.data_arrays = [[(e.arg_dict[n], sl) for e, sl in
                             zip(self.train_execs, self.slices)]
                            for n in self.data_names]
        self.label_arrays = [[(e.arg_dict[n], sl) for e, sl in
                              zip(self.train_execs, self.slices)]
                             for n in self.label_names]

    def load_data_batch(self, data_batch):
        _load_general(data_batch.data, self.data_arrays)
        if data_batch.label:
            _load_general(data_batch.label, self.label_arrays)

    def forward(self, is_train=False):
        for e in self.train_execs:
            e.forward(is_train=is_train)

    def backward(self):
        for e in self.train_execs:
            e.backward()

    def update_metric(self, metric, labels):
        for e, sl in zip(self.train_execs, self.slices):
            metric.update([NDArray(np.asarray(l.asnumpy()[sl]))
                           for l in labels], e.outputs)


class DataParallelExecutorManager:
    """Reference executor_manager.py:295 — the FeedForward-era manager;
    supports plain symbols and `sym_gen` bucketing."""

    def __init__(self, symbol, ctx, train_data, arg_names, param_names,
                 aux_names, work_load_list=None, logger=None, sym_gen=None):
        logger = logger or logging
        num_device = len(ctx)
        logger.info("Start training with %s", str(ctx))
        if work_load_list is None:
            work_load_list = [1] * num_device
        if len(work_load_list) != num_device:
            raise MXNetError("work_load_list must match the context count")
        self.slices = _split_input_slice(train_data.batch_size,
                                         work_load_list)
        self.arg_names = arg_names
        self.param_names = param_names
        self.aux_names = aux_names
        self.ctx = ctx
        self.symbol = symbol
        self.sym_gen = sym_gen
        self.execgrp = DataParallelExecutorGroup(
            symbol, arg_names, param_names, ctx, self.slices, train_data)
        self.curr_execgrp = None
        if sym_gen is not None:
            self.execgrp_bucket = {
                train_data.default_bucket_key: self.execgrp}

    def install_monitor(self, monitor):
        if self.sym_gen is not None:
            raise NotImplementedError(
                "monitoring is not implemented for bucketing")
        for e in self.execgrp.train_execs:
            monitor.install(e)

    def set_params(self, arg_params, aux_params):
        for e in self.execgrp.train_execs:
            e.copy_params_from(arg_params, aux_params)

    def copy_to(self, arg_params, aux_params):
        """Device-averaged weights/aux into the given dicts (reference
        executor_manager.py copy_to). Params are identical across devices
        when the caller synchronizes updates, but aux states (BatchNorm
        moving stats) genuinely diverge per device-slice — averaging is
        the reference's reconciliation."""
        execs = self.execgrp.train_execs
        for name in self.param_names:
            mean = sum(e.arg_dict[name]._data for e in execs) / len(execs)
            arg_params[name] = NDArray(mean)
        for name in self.aux_names:
            mean = sum(e.aux_dict[name]._data for e in execs) / len(execs)
            aux_params[name] = NDArray(mean)

    @property
    def param_arrays(self):
        return self.execgrp.param_arrays

    @property
    def grad_arrays(self):
        return self.execgrp.grad_arrays

    @property
    def aux_arrays(self):
        return self.execgrp.aux_arrays

    def load_data_batch(self, data_batch):
        if self.sym_gen is not None:
            key = data_batch.bucket_key
            if key not in self.execgrp_bucket:
                self.execgrp_bucket[key] = DataParallelExecutorGroup(
                    self.sym_gen(key), self.arg_names, self.param_names,
                    self.ctx, self.slices, data_batch,
                    shared_group=self.execgrp)
            self.curr_execgrp = self.execgrp_bucket[key]
        else:
            self.curr_execgrp = self.execgrp
        self.curr_execgrp.load_data_batch(data_batch)

    def forward(self, is_train=False):
        self.curr_execgrp.forward(is_train=is_train)

    def backward(self):
        self.curr_execgrp.backward()

    def update_metric(self, metric, labels):
        self.curr_execgrp.update_metric(metric, labels)
