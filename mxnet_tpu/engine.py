"""Execution engine surface.

Parity: reference `src/engine/` — the threaded dependency engine
(`include/mxnet/engine.h:96-295`) that topologically dispatches op closures
when their read/write vars clear, giving async execution and compute/comm
overlap.

TPU-native redesign: XLA's async dispatch IS the engine. Every jnp/lax call
returns immediately with a future-backed buffer; data dependencies are
tracked by the runtime; `wait_to_read`/`waitall` are the synchronization
points; donation replaces in-place write scheduling; streams/priorities are
XLA's concern. This module keeps the reference's *API surface* (bulk scopes,
engine-type query, WaitAll) as thin shims so user code ports cleanly, and
documents the ordering guarantees:
  - ops on the same buffers execute in program order (functional dataflow);
  - host reads (asnumpy/asscalar/wait_to_read) block until ready;
  - exceptions surface at the blocking read, like the reference's
    propagation to WaitForVar (threaded_engine.cc:361-369).
"""
from __future__ import annotations

import contextlib
import os
import weakref

import jax


def current_engine_type():
    """Parity: MXNET_ENGINE_TYPE (src/engine/engine.cc:32-58). 'XLAAsync' is
    the only engine; 'Naive' semantics (fully synchronous, for debugging) can
    be requested via MXNET_ENGINE_TYPE=NaiveEngine which makes every op block."""
    return os.environ.get("MXNET_ENGINE_TYPE", "XLAAsync")


_naive = current_engine_type() == "NaiveEngine"


def maybe_sync(data):
    """Called by the invoke path when Naive (sync) mode is requested."""
    if _naive and hasattr(data, "block_until_ready"):
        data.block_until_ready()
    return data


# Buffers the framework dispatched since the last wait_all. Weak values:
# collected buffers need no sync and drop out automatically. jax.Array is
# unhashable, so a WeakSet can't hold it — key by id instead.
_PENDING = weakref.WeakValueDictionary()


def note(data):
    """Record a dispatched device buffer (called from NDArray creation) so
    wait_all syncs exactly the framework's outstanding work."""
    try:
        _PENDING[id(data)] = data
    except TypeError:
        pass  # non-weakref-able host value: nothing async to wait on


def wait_all():
    """Parity: Engine::WaitForAll / mx.nd.waitall.

    Blocks on the buffers this framework dispatched (deterministic scope),
    not on every live array in the process — another library's arrays are
    not this engine's business."""
    pending = list(_PENDING.values())
    _PENDING.clear()
    for d in pending:
        if getattr(d, "is_deleted", lambda: False)():
            continue  # donated buffer: its consumer already completed it
        if hasattr(d, "block_until_ready"):
            # real async failures (OOM, collective errors) surface here,
            # as the module contract promises — never swallowed
            d.block_until_ready()


@contextlib.contextmanager
def bulk(size):
    """Parity: engine bulk scope (threaded_engine.h:398-472). XLA fuses
    adjacent ops automatically under jit; eager ops are already batched by
    async dispatch, so this is a no-op scope kept for API compatibility."""
    yield


def set_bulk_size(size):
    return size
