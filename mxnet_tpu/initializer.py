"""Weight initializers.

Parity: reference `python/mxnet/initializer.py` (Uniform/Normal/Xavier/
MSRAPrelu/Orthogonal/Bilinear/LSTMBias/One/Zero/Constant/Load/Mixed +
InitDesc attribute protocol and the registry).
"""
from __future__ import annotations

import json
import re

import numpy as np
import jax.numpy as jnp

from . import random as _random
from .base import dtype_np
from .registry import get_register_func, get_create_func, get_alias_func


class InitDesc(str):
    """Name + attrs descriptor passed to initializers (parity: InitDesc)."""

    def __new__(cls, name, attrs=None, global_init=None):
        ret = super().__new__(cls, name)
        ret.attrs = attrs or {}
        ret.global_init = global_init
        return ret


class Initializer:
    def __init__(self, **kwargs):
        self._kwargs = kwargs
        self._verbose = False
        self._print_func = None

    def set_verbosity(self, verbose=False, print_func=None):
        self._verbose = verbose
        self._print_func = print_func
        return self

    def dumps(self):
        return json.dumps([self.__class__.__name__.lower(), self._kwargs])

    def __call__(self, desc, arr):
        if not isinstance(desc, str):
            raise TypeError("desc must be a string or InitDesc")
        if getattr(desc, "global_init", None) is None and \
                isinstance(desc, InitDesc):
            desc.global_init = self
        init = desc.attrs.get("__init__", "") if isinstance(desc, InitDesc) else ""
        if init:
            create(init)._init_weight(desc, arr)
            return
        name = desc.lower()
        if name.endswith("weight"):
            self._init_weight(desc, arr)
        elif name.endswith("bias"):
            self._init_bias(desc, arr)
        elif name.endswith("gamma"):
            self._init_gamma(desc, arr)
        elif name.endswith("beta"):
            self._init_beta(desc, arr)
        elif name.endswith("moving_mean") or name.endswith("running_mean"):
            self._init_zero(desc, arr)
        elif name.endswith("moving_var") or name.endswith("running_var"):
            self._init_one(desc, arr)
        elif name.endswith("moving_inv_var"):
            self._init_zero(desc, arr)
        elif name.endswith("moving_avg"):
            self._init_zero(desc, arr)
        else:
            self._init_default(desc, arr)

    def init_weight(self, name, arr):
        self._init_weight(name, arr)

    def _set(self, arr, value):
        arr._data = jnp.asarray(np.asarray(value), dtype=arr._data.dtype)
        arr._version += 1

    def _init_zero(self, _, arr):
        self._set(arr, np.zeros(arr.shape))

    def _init_one(self, _, arr):
        self._set(arr, np.ones(arr.shape))

    def _init_bias(self, _, arr):
        self._init_zero(_, arr)

    def _init_gamma(self, _, arr):
        self._init_one(_, arr)

    def _init_beta(self, _, arr):
        self._init_zero(_, arr)

    def _init_weight(self, name, arr):
        raise NotImplementedError

    def _init_default(self, name, arr):
        self._init_weight(name, arr)


_register = get_register_func(Initializer, "initializer")
register = _register
create = get_create_func(Initializer, "initializer")
alias = get_alias_func(Initializer, "initializer")


@register
class Zero(Initializer):
    def _init_weight(self, _, arr):
        self._init_zero(_, arr)


alias("zeros")(Zero)


@register
class One(Initializer):
    def _init_weight(self, _, arr):
        self._init_one(_, arr)


alias("ones")(One)


@register
class Constant(Initializer):
    def __init__(self, value=0.0):
        super().__init__(value=value)
        self.value = value

    def _init_weight(self, _, arr):
        self._set(arr, np.full(arr.shape, self.value))


@register
class Uniform(Initializer):
    def __init__(self, scale=0.07):
        super().__init__(scale=scale)
        self.scale = scale

    def _init_weight(self, _, arr):
        self._set(arr, np.random.uniform(-self.scale, self.scale, arr.shape))


@register
class Normal(Initializer):
    def __init__(self, sigma=0.01):
        super().__init__(sigma=sigma)
        self.sigma = sigma

    def _init_weight(self, _, arr):
        self._set(arr, np.random.normal(0.0, self.sigma, arr.shape))


@register
class Xavier(Initializer):
    """Parity: initializer.py Xavier (gaussian/uniform; avg/in/out)."""

    def __init__(self, rnd_type="uniform", factor_type="avg", magnitude=3):
        super().__init__(rnd_type=rnd_type, factor_type=factor_type,
                         magnitude=magnitude)
        self.rnd_type = rnd_type
        self.factor_type = factor_type
        self.magnitude = float(magnitude)

    def _init_weight(self, name, arr):
        shape = arr.shape
        hw_scale = 1.0
        if len(shape) < 2:
            raise ValueError("Xavier requires at least 2d shape for %s" % name)
        if len(shape) > 2:
            hw_scale = float(np.prod(shape[2:]))
        fan_in, fan_out = shape[1] * hw_scale, shape[0] * hw_scale
        factor = {"avg": (fan_in + fan_out) / 2.0, "in": fan_in,
                  "out": fan_out}[self.factor_type]
        scale = np.sqrt(self.magnitude / factor)
        if self.rnd_type == "uniform":
            self._set(arr, np.random.uniform(-scale, scale, shape))
        else:
            self._set(arr, np.random.normal(0, scale, shape))


@register
class MSRAPrelu(Xavier):
    def __init__(self, factor_type="avg", slope=0.25):
        magnitude = 2.0 / (1 + slope ** 2)
        super().__init__("gaussian", factor_type, magnitude)
        self._kwargs = {"factor_type": factor_type, "slope": slope}


@register
class Orthogonal(Initializer):
    def __init__(self, scale=1.414, rand_type="uniform"):
        super().__init__(scale=scale, rand_type=rand_type)
        self.scale = scale
        self.rand_type = rand_type

    def _init_weight(self, _, arr):
        nout = arr.shape[0]
        nin = int(np.prod(arr.shape[1:]))
        if self.rand_type == "uniform":
            tmp = np.random.uniform(-1.0, 1.0, (nout, nin))
        else:
            tmp = np.random.normal(0.0, 1.0, (nout, nin))
        u, _, v = np.linalg.svd(tmp, full_matrices=False)
        q = u if u.shape == (nout, nin) else v
        self._set(arr, self.scale * q.reshape(arr.shape))


@register
class Bilinear(Initializer):
    def _init_weight(self, _, arr):
        shape = arr.shape
        weight = np.zeros(int(np.prod(shape)), dtype=np.float32)
        f = np.ceil(shape[3] / 2.0)
        c = (2 * f - 1 - f % 2) / (2.0 * f)
        for i in range(int(np.prod(shape))):
            x = i % shape[3]
            y = (i // shape[3]) % shape[2]
            weight[i] = (1 - abs(x / f - c)) * (1 - abs(y / f - c))
        self._set(arr, weight.reshape(shape))


@register
class LSTMBias(Initializer):
    """Forget-gate bias init (parity: initializer.py LSTMBias)."""

    def __init__(self, forget_bias=1.0):
        super().__init__(forget_bias=forget_bias)
        self.forget_bias = forget_bias

    def _init_weight(self, name, arr):
        b = np.zeros(arr.shape, dtype=np.float32)
        num_hidden = arr.shape[0] // 4
        b[num_hidden:2 * num_hidden] = self.forget_bias  # i, f, g, o order
        self._set(arr, b)


@register
class FusedRNN(Initializer):
    def __init__(self, init=None, num_hidden=0, num_layers=1, mode="lstm",
                 bidirectional=False, forget_bias=1.0):
        super().__init__()
        if isinstance(init, str):
            klass, kwargs = json.loads(init)
            init = create(klass, **kwargs)
        self._init = init or Uniform()
        self._forget_bias = forget_bias

    def _init_weight(self, desc, arr):
        self._init._init_weight(desc, arr)


@register
class Load(Initializer):
    def __init__(self, param, default_init=None, verbose=False):
        super().__init__()
        self.param = {k.split(":", 1)[-1]: v for k, v in param.items()}
        self.default_init = default_init

    def __call__(self, name, arr):
        if name in self.param:
            arr._data = self.param[name]._data.reshape(arr.shape)
        elif self.default_init is not None:
            self.default_init(name, arr)
        else:
            raise ValueError("Cannot Initialize parameter: %s" % name)


@register
class Mixed(Initializer):
    def __init__(self, patterns, initializers):
        super().__init__()
        self.map = list(zip([re.compile(p) for p in patterns], initializers))

    def __call__(self, name, arr):
        for prog, init in self.map:
            if prog.match(str(name)):
                init(name, arr)
                return
        raise ValueError("no initializer matches %s" % name)


class init:
    """Namespace alias (parity: mx.init.*)."""
    Initializer = Initializer
    InitDesc = InitDesc
    Zero = Zero
    One = One
    Constant = Constant
    Uniform = Uniform
    Normal = Normal
    Xavier = Xavier
    MSRAPrelu = MSRAPrelu
    Orthogonal = Orthogonal
    Bilinear = Bilinear
    LSTMBias = LSTMBias
    FusedRNN = FusedRNN
    Load = Load
    Mixed = Mixed
    # registry surface (parity: @mx.init.register custom initializers)
    register = staticmethod(register)
    create = staticmethod(create)
