"""Base utilities: errors, dtype handling, string/registry helpers.

Capability parity with the reference's `python/mxnet/base.py` (error type,
registry glue) and dmlc-core's logging/param machinery, redesigned for a
pure-Python + JAX stack (no C ABI marshalling needed).
"""
from __future__ import annotations

import ctypes
import os
import numpy as np

import jax.numpy as jnp


class MXNetError(RuntimeError):
    """Error raised by the framework (parity: reference MXNetError)."""


# ---------------------------------------------------------------------------
# dtype registry (parity: mshadow type codes used across the reference C ABI)
# ---------------------------------------------------------------------------
_DTYPE_NP_TO_CODE = {
    np.dtype(np.float32): 0,
    np.dtype(np.float64): 1,
    np.dtype(np.float16): 2,
    np.dtype(np.uint8): 3,
    np.dtype(np.int32): 4,
    np.dtype(np.int8): 5,
    np.dtype(np.int64): 6,
    jnp.bfloat16.dtype: 7,
    np.dtype(np.bool_): 8,
}
_DTYPE_CODE_TO_NP = {v: k for k, v in _DTYPE_NP_TO_CODE.items()}


def dtype_np(dtype):
    """Normalize a user dtype spec (str/np.dtype/jnp dtype) to a numpy dtype."""
    if dtype is None:
        return np.dtype(np.float32)
    if dtype == "bfloat16" or dtype is jnp.bfloat16:
        return jnp.bfloat16.dtype
    return np.dtype(dtype)


def default_dtype():
    return np.dtype(np.float32)


def getenv_int(name, default):
    try:
        return int(os.environ.get(name, default))
    except (TypeError, ValueError):
        return default


def getenv_bool(name, default=False):
    v = os.environ.get(name)
    if v is None:
        return default
    return v not in ("0", "false", "False", "")


def check_call(ret):  # parity shim: no C ABI, nothing to check
    return ret


class classproperty:
    def __init__(self, f):
        self.f = f

    def __get__(self, obj, owner):
        return self.f(owner)


# ---------------------------------------------------------------------------
# reference-API compatibility surface (parity: base.py) — exceptions, the
# ctypes helpers reference-era extension code calls, and doc utilities.
# There is no libmxnet C handle here, so the ctypes helpers are generic
# array/buffer conversions.
# ---------------------------------------------------------------------------


class NotImplementedForSymbol(MXNetError):
    """An NDArray-only API was called on a Symbol (parity: base.py)."""

    def __init__(self, function, alias=None, *args):
        super().__init__()
        self.function = getattr(function, "__name__", str(function))
        self.alias = alias
        self.args_rep = str(args)

    def __str__(self):
        msg = "Function %s is not implemented for Symbol" % self.function
        if self.alias:
            msg += " (use %s instead)" % self.alias
        return msg


class NotSupportedForSparseNDArray(MXNetError):
    """A dense-only API was called on a sparse ndarray (parity: base.py)."""

    def __init__(self, function, alias=None, *args):
        super().__init__()
        self.function = getattr(function, "__name__", str(function))
        self.alias = alias

    def __str__(self):
        msg = "Function %s is not supported for sparse ndarray" \
            % self.function
        if self.alias:
            msg += " (use %s instead)" % self.alias
        return msg


class MXCallbackList(ctypes.Structure):
    """C callback-list struct layout (parity: base.py MXCallbackList);
    kept for source compatibility with reference extension code."""
    _fields_ = [("num_callbacks", ctypes.c_int),
                ("callbacks", ctypes.POINTER(ctypes.CFUNCTYPE(
                    ctypes.c_int))),
                ("contexts", ctypes.POINTER(ctypes.c_void_p))]


def c_str(string):
    return ctypes.c_char_p(string.encode("utf-8"))


def c_str_array(strings):
    return (ctypes.c_char_p * len(strings))(
        *[s.encode("utf-8") for s in strings])


def c_array(ctype, values):
    """Create a ctypes array from a Python sequence (parity: base.py)."""
    out = (ctype * len(values))()
    out[:] = values
    return out


def c_array_buf(ctype, buf):
    """Create a ctypes array from a buffer (parity: base.py)."""
    return (ctype * len(buf)).from_buffer(buf)


def c_handle_array(objs):
    """Array of the objects' .handle fields (parity: base.py); handles
    here are opaque void pointers (may be None for pure-Python objects)."""
    arr = (ctypes.c_void_p * len(objs))()
    arr[:] = [getattr(o, "handle", None) for o in objs]
    return arr


def ctypes2buffer(cptr, length):
    """Copy a ctypes char pointer to a Python bytearray (parity)."""
    if not isinstance(cptr, ctypes.POINTER(ctypes.c_char)):
        raise TypeError("expected char pointer")
    res = bytearray(length)
    rptr = (ctypes.c_char * length).from_buffer(res)
    if not ctypes.memmove(rptr, cptr, length):
        raise RuntimeError("memmove failed")
    return res


def ctypes2numpy_shared(cptr, shape):
    """View a ctypes float pointer as a shared numpy array (parity)."""
    import numpy as _np
    if not isinstance(cptr, ctypes.POINTER(ctypes.c_float)):
        raise TypeError("expected float pointer")
    size = 1
    for s in shape:
        size *= s
    dbuffer = (ctypes.c_float * size).from_address(
        ctypes.addressof(cptr.contents))
    return _np.frombuffer(dbuffer, dtype=_np.float32).reshape(shape)


def build_param_doc(arg_names, arg_types, arg_descs, remove_dup=True):
    """Assemble a numpydoc Parameters section (parity: base.py)."""
    param_keys = set()
    lines = ["Parameters", "----------"]
    for name, ptype, desc in zip(arg_names, arg_types, arg_descs):
        if name in param_keys and remove_dup:
            continue
        if name == "num_args":
            continue
        param_keys.add(name)
        lines.append("%s : %s" % (name, ptype))
        if desc:
            lines.append("    " + desc)
    return "\n".join(lines)


def add_fileline_to_docstring(module, incursive=True):
    """Append 'From:file:line' to the docstrings of a module's functions
    (parity: base.py; best-effort — objects without source stay as-is)."""
    import inspect

    def _add(obj):
        try:
            fname = inspect.getsourcefile(obj)
            _, line = inspect.getsourcelines(obj)
        except (TypeError, OSError):
            return
        if obj.__doc__ and "From:" not in obj.__doc__:
            obj.__doc__ += "\n\nFrom:%s:%d" % (fname, line)

    if isinstance(module, str):
        import sys as _sys
        module = _sys.modules[module]
    for _, obj in module.__dict__.items():
        if inspect.isfunction(obj) and obj.__module__ == module.__name__:
            _add(obj)
        elif inspect.isclass(obj) and incursive:
            for _, m in obj.__dict__.items():
                if inspect.isfunction(m):
                    _add(m)


def with_metaclass(meta, *bases):
    """py2/3 metaclass shim the reference API exposed (parity: base.py)."""
    class _Meta(meta):
        def __new__(cls, name, this_bases, d):
            return meta(name, bases, d)
    return type.__new__(_Meta, "temporary_class", (), {})
