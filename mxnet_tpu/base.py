"""Base utilities: errors, dtype handling, string/registry helpers.

Capability parity with the reference's `python/mxnet/base.py` (error type,
registry glue) and dmlc-core's logging/param machinery, redesigned for a
pure-Python + JAX stack (no C ABI marshalling needed).
"""
from __future__ import annotations

import os
import numpy as np

import jax.numpy as jnp


class MXNetError(RuntimeError):
    """Error raised by the framework (parity: reference MXNetError)."""


# ---------------------------------------------------------------------------
# dtype registry (parity: mshadow type codes used across the reference C ABI)
# ---------------------------------------------------------------------------
_DTYPE_NP_TO_CODE = {
    np.dtype(np.float32): 0,
    np.dtype(np.float64): 1,
    np.dtype(np.float16): 2,
    np.dtype(np.uint8): 3,
    np.dtype(np.int32): 4,
    np.dtype(np.int8): 5,
    np.dtype(np.int64): 6,
    jnp.bfloat16.dtype: 7,
    np.dtype(np.bool_): 8,
}
_DTYPE_CODE_TO_NP = {v: k for k, v in _DTYPE_NP_TO_CODE.items()}


def dtype_np(dtype):
    """Normalize a user dtype spec (str/np.dtype/jnp dtype) to a numpy dtype."""
    if dtype is None:
        return np.dtype(np.float32)
    if dtype == "bfloat16" or dtype is jnp.bfloat16:
        return jnp.bfloat16.dtype
    return np.dtype(dtype)


def default_dtype():
    return np.dtype(np.float32)


def getenv_int(name, default):
    try:
        return int(os.environ.get(name, default))
    except (TypeError, ValueError):
        return default


def getenv_bool(name, default=False):
    v = os.environ.get(name)
    if v is None:
        return default
    return v not in ("0", "false", "False", "")


def check_call(ret):  # parity shim: no C ABI, nothing to check
    return ret


class classproperty:
    def __init__(self, f):
        self.f = f

    def __get__(self, obj, owner):
        return self.f(owner)
