"""Model factories for the BASELINE exercise configs (SURVEY §6):
  1. LeNet/MLP on MNIST (Module API)        -> lenet.get_lenet / get_mlp
  2. ResNet-50 ImageNet (Gluon hybridize)   -> gluon.model_zoo resnet50_v1
  3. LSTM word language model               -> word_lm.RNNModel
  4. SSD object detection (multibox ops)    -> ssd.SSDLite
  5. Sparse linear classification           -> sparse_linear.SparseLinear
"""
from .lenet import get_lenet, get_mlp, get_resnetish, LeNet
from .word_lm import RNNModel
from .ssd import SSDLite
from .sparse_linear import SparseLinear
from .fm import FactorizationMachine

# mesh-first transformer LM (capability upgrade: dp/tp/sp/ep parallelism)
from .transformer import (TransformerConfig, init_transformer_params,
                          transformer_apply, transformer_shardings,
                          make_train_step as make_transformer_train_step,
                          lm_loss)
