"""Factorization machine over sparse features (parity: reference
example/sparse/factorization_machine + contrib FM operators' role).

score(x) = w.x + b + 0.5 * sum_f [(x V)_f^2 - (x.x)(V.V)_f]

Everything sparse stays sparse: both the forward products and the factor
gradient run through the csr / csr^T segment-sum kernels
(ops/sparse.py), and the weight/factor gradients are row-sparse over the
features present in the batch — the same lazy-update flow as
SparseLinear.
"""
from __future__ import annotations

import numpy as np

from ..ndarray import NDArray
from ..ndarray.sparse import (CSRNDArray, RowSparseNDArray,
                              dot as sparse_dot, touched_rows)
from .. import optimizer as opt


class FactorizationMachine:
    """Binary FM classifier trained with logistic loss."""

    def __init__(self, num_features, num_factors=8, optimizer="sgd",
                 learning_rate=0.1, seed=0):
        rng = np.random.RandomState(seed)
        self.num_features = num_features
        self.num_factors = num_factors
        self.w = NDArray(np.zeros((num_features, 1), dtype=np.float32))
        self.v = NDArray((rng.randn(num_features, num_factors) * 0.05)
                         .astype(np.float32))
        self.b = NDArray(np.zeros((1,), dtype=np.float32))
        self._opt = opt.create(optimizer, learning_rate=learning_rate)
        self._updater = opt.get_updater(self._opt)

    def _squared(self, x):
        """Element-squared csr with the same sparsity structure."""
        return CSRNDArray(x._values * x._values, x._indices, x._indptr,
                          x.shape)

    def forward(self, x):
        import jax.numpy as jnp
        s1 = sparse_dot(x, self.v)._data                 # (n, k)
        s2 = sparse_dot(self._squared(x),
                        NDArray(self.v._data ** 2))._data
        pair = 0.5 * jnp.sum(s1 * s1 - s2, axis=1)
        lin = sparse_dot(x, self.w)._data[:, 0]
        return lin + pair + self.b._data[0], s1

    def loss_grad(self, x, y):
        """Logistic loss + row-sparse grads for w and V."""
        import jax
        import jax.numpy as jnp
        score, s1 = self.forward(x)
        yv = y._data if isinstance(y, NDArray) else jnp.asarray(y)
        prob = jax.nn.sigmoid(score)
        loss = -jnp.mean(yv * jnp.log(prob + 1e-12) +
                         (1 - yv) * jnp.log(1 - prob + 1e-12))
        g = (prob - yv) / score.shape[0]                 # dL/dscore, (n,)
        # w grad: x^T g            (features, 1)
        wgrad = sparse_dot(x, NDArray(g[:, None]), transpose_a=True)._data
        # V grad: x^T (g*s1) - V * ((x.x)^T g)
        t1 = sparse_dot(x, NDArray(g[:, None] * s1), transpose_a=True)._data
        t2 = self.v._data * sparse_dot(self._squared(x),
                                       NDArray(g[:, None]),
                                       transpose_a=True)._data
        vgrad = t1 - t2
        bgrad = jnp.sum(g)[None]
        touched = touched_rows(x)
        return (float(loss),
                RowSparseNDArray(touched.astype(np.int32), wgrad[touched],
                                 wgrad.shape),
                RowSparseNDArray(touched.astype(np.int32), vgrad[touched],
                                 vgrad.shape),
                NDArray(bgrad))

    def step(self, x, y):
        loss, wg, vg, bg = self.loss_grad(x, y)
        self._updater("w", wg, self.w)
        self._updater("v", vg, self.v)
        self._updater("b", bg, self.b)
        return loss

    def predict(self, x):
        import jax
        score, _ = self.forward(x)
        return np.asarray(jax.nn.sigmoid(score))
