"""Sparse linear classifier (parity: reference example/sparse/
linear_classification/train.py — BASELINE config 5: CSR data dot
row-sparse-updated weights, dist kvstore row_sparse push/pull)."""
from __future__ import annotations

import numpy as np

from ..ndarray import NDArray
from ..ndarray.sparse import (CSRNDArray, RowSparseNDArray,
                              dot as sparse_dot, touched_rows)
from .. import ndarray as nd
from .. import kvstore as kvs
from .. import optimizer as opt


class SparseLinear:
    """Logistic-regression-style linear model over sparse features, trained
    with row-sparse gradient push/pull through a KVStore."""

    def __init__(self, num_features, num_classes=2, kvstore=None,
                 optimizer="sgd", learning_rate=0.1):
        self.num_features = num_features
        self.num_classes = num_classes
        self.weight = NDArray(np.zeros((num_features, num_classes),
                                       dtype=np.float32))
        self.bias = NDArray(np.zeros((num_classes,), dtype=np.float32))
        self._kv = kvs.create(kvstore) if isinstance(kvstore, str) else kvstore
        self._opt = opt.create(optimizer, learning_rate=learning_rate)
        self._updater = opt.get_updater(self._opt)
        if self._kv is not None:
            self._kv.init("weight", self.weight)
            self._kv.set_optimizer(self._opt)

    def forward(self, x):
        if isinstance(x, CSRNDArray):
            scores = sparse_dot(x, self.weight)
        else:
            scores = nd.dot(x, self.weight)
        return scores + self.bias

    def loss_grad(self, x, y):
        """Softmax CE loss + row-sparse weight gradient."""
        import jax.numpy as jnp
        import jax
        scores = self.forward(x)
        n = scores.shape[0]
        logp = jax.nn.log_softmax(scores._data, axis=-1)
        yi = y._data.astype(jnp.int32) if isinstance(y, NDArray) else \
            jnp.asarray(y, dtype=jnp.int32)
        loss = -jnp.mean(jnp.take_along_axis(logp, yi[:, None], axis=1))
        prob = jax.nn.softmax(scores._data, axis=-1)
        dscore = (prob - jax.nn.one_hot(yi, self.num_classes)) / n
        if isinstance(x, CSRNDArray):
            # csr^T . dense via the segment-sum kernel — never densifies x
            wgrad_dense = sparse_dot(x, NDArray(dscore),
                                     transpose_a=True)._data
            touched = touched_rows(x)
        else:
            wgrad_dense = x._data.T @ dscore
            touched = np.nonzero(np.asarray(jnp.any(x._data != 0, axis=0)))[0]
        bgrad = jnp.sum(dscore, axis=0)
        # only feature rows present in the batch received gradient
        wgrad = RowSparseNDArray(jnp.asarray(touched, dtype=jnp.int32),
                                 wgrad_dense[touched],
                                 wgrad_dense.shape)
        return float(loss), wgrad, NDArray(bgrad)

    def step(self, x, y):
        loss, wgrad, bgrad = self.loss_grad(x, y)
        if self._kv is not None:
            self._kv.push("weight", wgrad)
            self._kv.pull("weight", out=self.weight)
        else:
            self._updater("weight", wgrad, self.weight)
        self._updater("bias", bgrad, self.bias)
        return loss

    def row_sparse_pull(self, row_ids):
        """Pull only the rows needed for a batch (parity: row_sparse_pull)."""
        if self._kv is None:
            return RowSparseNDArray.from_dense(self.weight).retain(row_ids)
        out = RowSparseNDArray.from_dense(self.weight)
        self._kv.row_sparse_pull("weight", out=out, row_ids=row_ids)
        return out
