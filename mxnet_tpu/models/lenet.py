"""LeNet + MLP (parity: reference example/image-classification/symbols/
lenet.py and mlp.py — exercised by train_mnist.py / BASELINE config 1)."""
from __future__ import annotations

from .. import symbol as sym
from ..gluon import nn, HybridBlock


def get_mlp(num_classes=10):
    """Symbol-API MLP (parity: example/image-classification/symbols/mlp.py)."""
    data = sym.Variable("data")
    data = sym.Flatten(data, name="flatten")
    fc1 = sym.FullyConnected(data, name="fc1", num_hidden=128)
    act1 = sym.Activation(fc1, name="relu1", act_type="relu")
    fc2 = sym.FullyConnected(act1, name="fc2", num_hidden=64)
    act2 = sym.Activation(fc2, name="relu2", act_type="relu")
    fc3 = sym.FullyConnected(act2, name="fc3", num_hidden=num_classes)
    return sym.SoftmaxOutput(fc3, name="softmax")


def get_lenet(num_classes=10):
    """Symbol-API LeNet (parity: symbols/lenet.py)."""
    data = sym.Variable("data")
    conv1 = sym.Convolution(data, name="conv1", kernel=(5, 5), num_filter=20)
    tanh1 = sym.Activation(conv1, name="tanh1", act_type="tanh")
    pool1 = sym.Pooling(tanh1, name="pool1", pool_type="max", kernel=(2, 2),
                        stride=(2, 2))
    conv2 = sym.Convolution(pool1, name="conv2", kernel=(5, 5), num_filter=50)
    tanh2 = sym.Activation(conv2, name="tanh2", act_type="tanh")
    pool2 = sym.Pooling(tanh2, name="pool2", pool_type="max", kernel=(2, 2),
                        stride=(2, 2))
    flatten = sym.Flatten(pool2, name="flatten")
    fc1 = sym.FullyConnected(flatten, name="fc1", num_hidden=500)
    tanh3 = sym.Activation(fc1, name="tanh3", act_type="tanh")
    fc2 = sym.FullyConnected(tanh3, name="fc2", num_hidden=num_classes)
    return sym.SoftmaxOutput(fc2, name="softmax")


class LeNet(HybridBlock):
    """Gluon LeNet for the imperative path. `dropout>0` inserts a Dropout
    between the dense layers — the classic regularized variant, and the
    RNG-dependent fixture the fault-tolerance suite uses to prove that a
    resumed run replays the exact per-step dropout masks."""

    def __init__(self, num_classes=10, dropout=0.0, **kwargs):
        super().__init__(**kwargs)
        with self.name_scope():
            self.conv1 = nn.Conv2D(20, kernel_size=5, activation="tanh")
            self.pool1 = nn.MaxPool2D(pool_size=2, strides=2)
            self.conv2 = nn.Conv2D(50, kernel_size=5, activation="tanh")
            self.pool2 = nn.MaxPool2D(pool_size=2, strides=2)
            self.flatten = nn.Flatten()
            self.fc1 = nn.Dense(500, activation="tanh")
            self.drop = nn.Dropout(dropout) if dropout > 0 else None
            self.fc2 = nn.Dense(num_classes)

    def hybrid_forward(self, F, x):
        x = self.pool1(self.conv1(x))
        x = self.pool2(self.conv2(x))
        x = self.flatten(x)
        x = self.fc1(x)
        if self.drop is not None:
            x = self.drop(x)
        return self.fc2(x)


def get_resnetish(classes=10, prefix="rn_"):
    """Small ResNet-shaped Gluon net (7x7 stride-2 stem, BN, maxpool,
    stride-2 + stride-1 conv blocks, global pool): the shared fixture for
    multi-chip sharding equality checks (strided convs + BatchNorm are
    where GSPMD sharding bugs live). Deferred init: run a (2,3,64,64)
    batch through it after initialize()."""
    from ..gluon import nn

    net = nn.HybridSequential(prefix=prefix)
    with net.name_scope():
        net.add(nn.Conv2D(8, 7, strides=2, padding=3))
        net.add(nn.BatchNorm())
        net.add(nn.Activation("relu"))
        net.add(nn.MaxPool2D(pool_size=3, strides=2, padding=1))
        net.add(nn.Conv2D(16, 3, strides=2, padding=1))
        net.add(nn.BatchNorm())
        net.add(nn.Activation("relu"))
        net.add(nn.Conv2D(16, 3, strides=1, padding=1))
        net.add(nn.BatchNorm())
        net.add(nn.Activation("relu"))
        net.add(nn.GlobalAvgPool2D())
        net.add(nn.Flatten())
        net.add(nn.Dense(classes))
    return net
