"""SSD object detector (parity: reference example/ssd — BASELINE config 4:
multibox prior/target/detection ops behind a compact VGG-ish backbone)."""
from __future__ import annotations

import numpy as np

from ..gluon import nn, HybridBlock
from ..ndarray import NDArray
from .. import ndarray as F


class SSDLite(HybridBlock):
    """Compact SSD: 3 feature scales, the full multibox pipeline."""

    def __init__(self, num_classes=20, sizes=((0.2,), (0.4,), (0.7,)),
                 ratios=((1.0, 2.0, 0.5),) * 3, **kwargs):
        super().__init__(**kwargs)
        self.num_classes = num_classes
        self.sizes = sizes
        self.ratios = ratios
        self._anchors_per_cell = [len(s) + len(r) - 1
                                  for s, r in zip(sizes, ratios)]
        with self.name_scope():
            self.stem = nn.HybridSequential(prefix="stem_")
            for ch in (32, 64):
                self.stem.add(nn.Conv2D(ch, 3, padding=1, use_bias=False))
                self.stem.add(nn.BatchNorm())
                self.stem.add(nn.Activation("relu"))
                self.stem.add(nn.MaxPool2D(2))
            self.blocks = []
            self.cls_heads = []
            self.loc_heads = []
            for i, a in enumerate(self._anchors_per_cell):
                blk = nn.HybridSequential(prefix="blk%d_" % i)
                blk.add(nn.Conv2D(64, 3, strides=2, padding=1,
                                  use_bias=False))
                blk.add(nn.BatchNorm())
                blk.add(nn.Activation("relu"))
                cls = nn.Conv2D(a * (num_classes + 1), 3, padding=1,
                                prefix="cls%d_" % i)
                loc = nn.Conv2D(a * 4, 3, padding=1, prefix="loc%d_" % i)
                self.blocks.append(blk)
                self.cls_heads.append(cls)
                self.loc_heads.append(loc)
                setattr(self, "blk%d" % i, blk)
                setattr(self, "cls%d" % i, cls)
                setattr(self, "loc%d" % i, loc)

    def forward(self, x):
        """Returns (anchors [1,A,4], cls_preds [N,C+1,A], loc_preds [N,A*4])."""
        feats = self.stem(x)
        anchors, cls_preds, loc_preds = [], [], []
        for i, blk in enumerate(self.blocks):
            feats = blk(feats)
            anchors.append(F.contrib.MultiBoxPrior(
                feats, sizes=self.sizes[i], ratios=self.ratios[i]))
            c = self.cls_heads[i](feats)
            n = c.shape[0]
            cls_preds.append(
                c.transpose((0, 2, 3, 1)).reshape(
                    (n, -1, self.num_classes + 1)))
            l = self.loc_heads[i](feats)
            loc_preds.append(l.transpose((0, 2, 3, 1)).reshape((n, -1)))
        anchors = F.Concat(*anchors, dim=1)
        cls_preds = F.Concat(*cls_preds, dim=1).transpose((0, 2, 1))
        loc_preds = F.Concat(*loc_preds, dim=1)
        return anchors, cls_preds, loc_preds

    def targets(self, anchors, labels, cls_preds):
        """Training targets via MultiBoxTarget."""
        return F.contrib.MultiBoxTarget(anchors, labels, cls_preds,
                                        overlap_threshold=0.5,
                                        negative_mining_ratio=3.0)

    def detect(self, cls_preds, loc_preds, anchors, nms_threshold=0.45):
        probs = F.softmax(cls_preds, axis=1)
        return F.contrib.MultiBoxDetection(probs, loc_preds, anchors,
                                           nms_threshold=nms_threshold)
