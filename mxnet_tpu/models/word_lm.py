"""LSTM word language model (parity: reference example/rnn/word_lm/model.py —
BASELINE config 3: embedding -> multilayer LSTM -> tied/untied decoder)."""
from __future__ import annotations

from ..gluon import nn, rnn, HybridBlock


class RNNModel(HybridBlock):
    def __init__(self, mode="lstm", vocab_size=10000, num_embed=200,
                 num_hidden=200, num_layers=2, dropout=0.5, tie_weights=False,
                 fused=None, **kwargs):
        super().__init__(**kwargs)
        self._mode = mode
        self._num_hidden = num_hidden
        with self.name_scope():
            self.drop = nn.Dropout(dropout)
            self.encoder = nn.Embedding(vocab_size, num_embed)
            # fused: None honors MXNET_FUSED_RNN; True/False pin the
            # persistent Pallas scan kernel (ops/pallas_rnn.py)
            if mode == "lstm":
                self.rnn = rnn.LSTM(num_hidden, num_layers, dropout=dropout,
                                    input_size=num_embed, fused=fused)
            elif mode == "gru":
                self.rnn = rnn.GRU(num_hidden, num_layers, dropout=dropout,
                                   input_size=num_embed, fused=fused)
            else:
                self.rnn = rnn.RNN(num_hidden, num_layers, dropout=dropout,
                                   input_size=num_embed, fused=fused,
                                   activation="relu" if mode == "rnn_relu"
                                   else "tanh")
            if tie_weights:
                assert num_embed == num_hidden
                self.decoder = nn.Dense(vocab_size, flatten=False,
                                        params=self.encoder.params)
            else:
                self.decoder = nn.Dense(vocab_size, flatten=False,
                                        in_units=num_hidden)

    def begin_state(self, batch_size):
        return self.rnn.begin_state(batch_size)

    def forward(self, inputs, hidden=None):
        # inputs: [T, N] int tokens
        emb = self.drop(self.encoder(inputs))
        if hidden is None:
            output = self.rnn(emb)
            output = self.drop(output)
            decoded = self.decoder(output.reshape((-1, self._num_hidden)))
            return decoded
        output, hidden = self.rnn(emb, hidden)
        output = self.drop(output)
        decoded = self.decoder(output.reshape((-1, self._num_hidden)))
        return decoded, hidden
