"""Transformer language model, built mesh-first.

Capability upgrade over the reference (which predates transformers — its
sequence stack is fused RNNs + bucketing, SURVEY §5.7). This model is the
showcase for the framework's parallelism axes:

  dp  batch sharding (GSPMD inserts the gradient psum)
  tp  Megatron-style sharded attention heads + FFN (column→row parallel)
  sp  ring attention over the sequence axis (parallel/ring_attention.py)
  ep  expert-parallel mixture-of-experts FFN (gate-weighted dense dispatch;
      expert weights sharded over 'ep', GSPMD inserts the all_to_all-
      equivalent collectives)

The model is functional (params dict + pure apply) — the idiomatic form for
pjit over a Mesh; the Gluon API remains the imperative front door for the
reference's own model families.
"""
from __future__ import annotations

import dataclasses
import functools
import os

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P


@dataclasses.dataclass(frozen=True)
class TransformerConfig:
    vocab: int = 256
    d_model: int = 64
    n_heads: int = 4
    n_layers: int = 2
    d_ff: int = 128
    n_experts: int = 0          # 0 => dense FFN; >0 => MoE
    moe_top_k: int = 0          # 0 => dense dispatch; >0 => top-k routing
    capacity_factor: float = 1.25  # per-expert buffer over the even share
    moe_group_size: int = 4096  # GShard token grouping; <=0 => one group
    max_len: int = 128
    dtype: object = jnp.float32


def init_transformer_params(rng, cfg):
    """Returns a flat dict name -> array."""
    keys = iter(jax.random.split(rng, 4 + 5 * cfg.n_layers))
    scale = 0.02
    p = {}

    def w(shape):
        return (scale * jax.random.normal(next(keys), shape)).astype(
            cfg.dtype)

    p["embed"] = w((cfg.vocab, cfg.d_model))
    p["pos_embed"] = w((cfg.max_len, cfg.d_model))
    for i in range(cfg.n_layers):
        pre = "layer%d_" % i
        p[pre + "ln1_g"] = jnp.ones((cfg.d_model,), cfg.dtype)
        p[pre + "ln1_b"] = jnp.zeros((cfg.d_model,), cfg.dtype)
        p[pre + "wqkv"] = w((cfg.d_model, 3 * cfg.d_model))
        p[pre + "wo"] = w((cfg.d_model, cfg.d_model))
        p[pre + "ln2_g"] = jnp.ones((cfg.d_model,), cfg.dtype)
        p[pre + "ln2_b"] = jnp.zeros((cfg.d_model,), cfg.dtype)
        if cfg.n_experts:
            p[pre + "wg"] = w((cfg.d_model, cfg.n_experts))
            p[pre + "w1"] = w((cfg.n_experts, cfg.d_model, cfg.d_ff))
            p[pre + "w2"] = w((cfg.n_experts, cfg.d_ff, cfg.d_model))
        else:
            p[pre + "w1"] = w((cfg.d_model, cfg.d_ff))
            p[pre + "w2"] = w((cfg.d_ff, cfg.d_model))
    p["lnf_g"] = jnp.ones((cfg.d_model,), cfg.dtype)
    p["lnf_b"] = jnp.zeros((cfg.d_model,), cfg.dtype)
    p["head"] = w((cfg.d_model, cfg.vocab))
    return p


def transformer_shardings(cfg):
    """name -> PartitionSpec over mesh axes ('tp', 'ep'); everything else
    replicated (batch/sequence sharding is on the activations)."""
    s = {"embed": P(), "pos_embed": P(), "head": P(None, "tp"),
         "lnf_g": P(), "lnf_b": P()}
    for i in range(cfg.n_layers):
        pre = "layer%d_" % i
        s[pre + "ln1_g"] = P()
        s[pre + "ln1_b"] = P()
        s[pre + "wqkv"] = P(None, "tp")   # column parallel
        s[pre + "wo"] = P("tp", None)     # row parallel
        s[pre + "ln2_g"] = P()
        s[pre + "ln2_b"] = P()
        if cfg.n_experts:
            s[pre + "wg"] = P()
            s[pre + "w1"] = P("ep", None, "tp")
            s[pre + "w2"] = P("ep", "tp", None)
        else:
            s[pre + "w1"] = P(None, "tp")
            s[pre + "w2"] = P("tp", None)
    return s


def _layer_norm(x, g, b, eps=1e-5):
    mu = jnp.mean(x, -1, keepdims=True)
    var = jnp.var(x, -1, keepdims=True)
    return (x - mu) / jnp.sqrt(var + eps) * g + b


def _attention(x, wqkv, wo, cfg, mesh=None, sp_axis="sp", causal=True):
    B, S, D = x.shape
    H = cfg.n_heads
    qkv = x @ wqkv                      # (B, S, 3D)
    q, k, v = jnp.split(qkv, 3, axis=-1)

    def heads(t):  # (B, S, D) -> (B, H, S, Dh)
        return t.reshape(B, S, H, D // H).transpose(0, 2, 1, 3)

    q, k, v = heads(q), heads(k), heads(v)
    if mesh is not None and sp_axis in mesh.shape and \
            mesh.shape[sp_axis] > 1:
        from ..parallel.ring_attention import ring_attention_sharded
        out = ring_attention_sharded(mesh, q, k, v, axis_name=sp_axis,
                                     causal=causal)
    elif mesh is None and \
            os.environ.get("MXNET_FLASH_ATTENTION", "0") == "1":
        # OPT-IN Pallas path: the 2026-07-31 v5e sweep (BENCH_FLASH_SWEEP
        # .jsonl) measured 0.96-1.06x vs XLA attention at seq 1024/2048/
        # 4096 — below the >=1.2x bar for a default-path kernel, so XLA
        # attention is the default and MXNET_FLASH_ATTENTION=1 enables the
        # kernel (VMEM-streamed online softmax; falls back to XLA when
        # shapes don't tile into the blocks). Single-device only: a
        # pallas_call has no GSPMD partitioning rule, so under a dp/tp
        # mesh it would force replication — the sharded paths go through
        # ring attention / the partitionable XLA reference instead
        from ..ops.pallas_attention import flash_attention
        out = flash_attention(q, k, v, causal=causal)
    else:
        from ..parallel.ring_attention import attention_reference
        out = attention_reference(q, k, v, causal=causal)
    out = out.transpose(0, 2, 1, 3).reshape(B, S, D)
    return out @ wo


def _moe_ffn(x, wg, w1, w2):
    """Gate-weighted dense-dispatch MoE; expert dim sharded over 'ep' by
    GSPMD. Every expert sees every token, outputs weighted by the full
    softmax gate — the exact function _moe_ffn_topk approximates (and
    reproduces when k == n_experts with ample capacity)."""
    gates = jax.nn.softmax(x @ wg, axis=-1)           # (B, S, E)
    h = jnp.einsum("bsd,edf->besf", x, w1)
    h = jax.nn.relu(h)
    y = jnp.einsum("besf,efd->besd", h, w2)
    return jnp.einsum("bse,besd->bsd", gates, y)


def _route_group_topk(xg, wg, w1, w2, k, capacity):
    """Route ONE token group (Tg, D) through top-k capacity-bounded
    experts; returns (out (Tg, D), aux scalar). Static shapes, einsums
    over one-hot masks only — no dynamic-extent gather/scatter, so the
    expert dim shards over 'ep' and dispatch/combine lower to
    all-to-alls under GSPMD."""
    Tg, D = xg.shape
    E = w1.shape[0]
    gates = jax.nn.softmax(xg @ wg, axis=-1)              # (Tg, E)
    topv, topi = jax.lax.top_k(gates, k)                  # (Tg, k)
    # renormalize over the selected experts
    topv = topv / jnp.maximum(jnp.sum(topv, -1, keepdims=True), 1e-9)

    # routing bookkeeping in int32: under bf16 activations a float
    # cumsum of token counts goes inexact past 256 and capacity slots
    # would silently collide — only the masks cast to xg.dtype, at the
    # einsum boundary
    sel_i = jax.nn.one_hot(topi, E, dtype=jnp.int32)      # (Tg, k, E)
    # position of each (token, choice) within its expert's buffer:
    # cumulative count of prior selections of that expert, counting
    # choice slots in priority order (k=0 first, matching GShard)
    flat = sel_i.transpose(1, 0, 2).reshape(k * Tg, E)    # (k*Tg, E)
    pos_flat = jnp.cumsum(flat, axis=0) - flat            # prior count
    pos = pos_flat.reshape(k, Tg, E).transpose(1, 0, 2)   # (Tg, k, E)
    in_cap = ((pos < capacity) & (sel_i > 0)).astype(xg.dtype)  # kept
    pos_idx = jnp.sum(pos * sel_i, -1).astype(jnp.int32)  # (Tg, k)

    # dispatch mask (Tg, E, C) -> one-hot over capacity slots
    cap_hot = jax.nn.one_hot(pos_idx, capacity, dtype=xg.dtype)  # (Tg,k,C)
    dispatch = jnp.einsum("tke,tkc->tec", in_cap, cap_hot)       # (Tg,E,C)
    expert_in = jnp.einsum("tec,td->ecd", dispatch, xg)          # (E,C,D)

    h = jax.nn.relu(jnp.einsum("ecd,edf->ecf", expert_in, w1))
    expert_out = jnp.einsum("ecf,efd->ecd", h, w2)               # (E,C,D)

    combine = jnp.einsum("tke,tk,tkc->tec", in_cap, topv, cap_hot)
    out = jnp.einsum("tec,ecd->td", combine, expert_out)

    # Switch/GShard load-balancing auxiliary: E * sum_e f_e * P_e, where
    # f_e = fraction of tokens whose TOP choice is expert e (hard count)
    # and P_e = mean softmax gate mass on e. Minimized at uniform
    # routing (value 1); without it top-k training collapses experts.
    f = jnp.mean(sel_i[:, 0, :].astype(jnp.float32), axis=0)     # (E,)
    p = jnp.mean(gates.astype(jnp.float32), axis=0)              # (E,)
    aux = E * jnp.sum(f * p)
    return out, aux


def _moe_groups(tokens, group_size):
    """Number of routing groups: smallest G dividing `tokens` with
    tokens/G <= group_size (G=1 when tokens already fit). The divisor
    hunt is bounded to 2x the ideal count — for prime-ish token counts
    it would otherwise degenerate to per-token groups (capacity == k,
    aux loss meaningless); such counts fall back to a single group."""
    if group_size <= 0 or tokens <= group_size:
        return 1
    ideal = (tokens + group_size - 1) // group_size
    for g in range(ideal, min(2 * ideal, tokens) + 1):
        if tokens % g == 0:
            return g
    return 1

def _moe_ffn_topk(x, wg, w1, w2, k, capacity_factor=1.25,
                  group_size=4096):
    """Top-k sparse-dispatch MoE (Switch/GShard style) with static
    shapes throughout — XLA/GSPMD friendly.

    GShard-style token grouping: the B*S tokens are split into G
    independent routing groups of Tg = B*S/G tokens (smallest G with
    Tg <= group_size), each with its own capacity
    C = ceil(capacity_factor * Tg * k / E). The dispatch/combine
    one-hot masks are (Tg, E, C) per group — O(T * E * C_group) total
    instead of the single-group O(T^2 * k * cf / E) blowup (at
    T = 8192, E = 8, k = 2 a single group's f32 dispatch tensor alone
    is ~2.7 GB; grouped at 4096 it is 2 x ~0.7 GB and scales linearly
    in T from there). Per token: softmax gate over E experts, keep the
    top k; overflow tokens past an expert's capacity drop to the
    residual path (the standard capacity trade). Combine weights are
    renormalized over the kept experts. The aux loss is the mean of the
    per-group Switch/GShard load-balancing terms.

    Reference seam: the reference's sparse embedding/expert flows ride
    row_sparse KVStore pulls (reference python/mxnet/kvstore.py
    row_sparse_pull); here routing is part of the one compiled step.
    """
    B, S, D = x.shape
    E = w1.shape[0]
    tokens = B * S
    G = _moe_groups(tokens, group_size)
    tg = tokens // G
    capacity = max(int(np.ceil(capacity_factor * tg * k / E)), k)

    xg = x.reshape(G, tg, D)
    out, aux = jax.vmap(
        lambda g: _route_group_topk(g, wg, w1, w2, k, capacity))(xg)
    return out.reshape(B, S, D), jnp.mean(aux)


def transformer_apply(params, tokens, cfg, mesh=None, causal=True,
                      return_aux=False):
    """tokens: (B, S) int32 -> logits (B, S, vocab).

    With return_aux=True also returns the summed MoE load-balancing
    auxiliary (0.0 for dense-dispatch / non-MoE configs)."""
    B, S = tokens.shape
    aux_total = jnp.float32(0.0)
    x = params["embed"][tokens] + params["pos_embed"][:S][None]
    for i in range(cfg.n_layers):
        pre = "layer%d_" % i
        h = _layer_norm(x, params[pre + "ln1_g"], params[pre + "ln1_b"])
        x = x + _attention(h, params[pre + "wqkv"], params[pre + "wo"],
                           cfg, mesh=mesh, causal=causal)
        h = _layer_norm(x, params[pre + "ln2_g"], params[pre + "ln2_b"])
        if cfg.n_experts and cfg.moe_top_k:
            moe_out, aux = _moe_ffn_topk(h, params[pre + "wg"],
                                         params[pre + "w1"],
                                         params[pre + "w2"],
                                         cfg.moe_top_k,
                                         cfg.capacity_factor,
                                         cfg.moe_group_size)
            x = x + moe_out
            aux_total = aux_total + aux
        elif cfg.n_experts:
            x = x + _moe_ffn(h, params[pre + "wg"], params[pre + "w1"],
                             params[pre + "w2"])
        else:
            x = x + jax.nn.relu(h @ params[pre + "w1"]) @ params[pre + "w2"]
    x = _layer_norm(x, params["lnf_g"], params["lnf_b"])
    logits = x @ params["head"]
    if return_aux:
        return logits, aux_total
    return logits


def lm_loss(params, tokens, cfg, mesh=None, aux_coef=0.01):
    """Next-token cross entropy. Runs attention on the full (sp-shardable)
    sequence and shifts in loss space, so the sequence axis stays divisible
    by the 'sp' mesh axis. Top-k MoE configs add the load-balancing
    auxiliary (Switch-style, coefficient `aux_coef`)."""
    logits, aux = transformer_apply(params, tokens, cfg, mesh=mesh,
                                    return_aux=True)
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    ll = jnp.take_along_axis(logp[:, :-1],
                             tokens[:, 1:][..., None], axis=-1)[..., 0]
    return -jnp.mean(ll) + aux_coef * aux


def make_train_step(mesh, cfg, lr=0.1, seed=0):
    """Build (step_fn, params) with params placed per transformer_shardings
    and the batch sharded over ('dp', 'sp'). step_fn is jitted with donated
    params; GSPMD inserts every collective (grad psum over dp, activation
    all_gathers for tp, expert collectives for ep; ring attention's
    ppermutes come from the explicit shard_map)."""
    params = init_transformer_params(jax.random.PRNGKey(seed), cfg)
    shardings = transformer_shardings(cfg)
    params = {k: jax.device_put(v, NamedSharding(mesh, shardings[k]))
              for k, v in params.items()}

    batch_spec = P("dp", "sp") if "sp" in mesh.shape else P("dp")

    @functools.partial(jax.jit, donate_argnums=0)
    def step(params, tokens):
        loss, grads = jax.value_and_grad(lm_loss)(params, tokens, cfg,
                                                  mesh=mesh)
        new_params = {k: v - lr * grads[k] for k, v in params.items()}
        return new_params, loss

    def run(params, tokens_np):
        tokens = jax.device_put(jnp.asarray(tokens_np, jnp.int32),
                                NamedSharding(mesh, batch_spec))
        return step(params, tokens)

    return run, params
